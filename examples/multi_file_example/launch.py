"""Launcher for the multi-file project: point run() at train.py; the whole
directory (data_util.py included) lands in the build context."""

import os

import cloud_tpu
from cloud_tpu.core.containerize import DockerConfig


def main(dry_run: bool = False):
    return cloud_tpu.run(
        entry_point=os.path.join(os.path.dirname(__file__), "train.py"),
        chief_config=cloud_tpu.COMMON_MACHINE_CONFIGS["TPU"],
        docker_config=DockerConfig(image="gcr.io/my-project/multifile:demo"),
        dry_run=dry_run,
    )


if __name__ == "__main__":
    main()
