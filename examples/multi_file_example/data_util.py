"""Sibling module imported by train.py — proves multi-file shipping."""

import numpy as np

from cloud_tpu.training import data


def make_dataset(n=256, batch_size=64, seed=0):
    rng = np.random.default_rng(seed)
    images = rng.normal(size=(n, 28, 28)).astype(np.float32)
    labels = np.clip(
        ((images.mean(axis=(1, 2)) + 0.5) * 10).astype(np.int32), 0, 9
    )
    return data.ArrayDataset({"image": images, "label": labels}, batch_size)
