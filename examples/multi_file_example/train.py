"""Entry point of a multi-file project (reference
core/tests/examples/multi_file_example): run() ships the entry point's
whole directory, so sibling modules import normally in the container."""

import jax
import optax

from data_util import make_dataset  # sibling module, shipped with the entry

from cloud_tpu import parallel
from cloud_tpu.models import mnist
from cloud_tpu.training import trainer


def main():
    t = trainer.Trainer(
        mnist.loss_fn, optax.adam(1e-3), mnist.init,
        mesh=parallel.get_global_mesh(),
        logical_axes=mnist.param_logical_axes(),
    )
    t.init_state(jax.random.PRNGKey(0))
    return t.fit(make_dataset(), epochs=2)


if __name__ == "__main__":
    main()
