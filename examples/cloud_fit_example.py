"""Fit an in-memory model remotely with cloud_fit.

Reference analogue: experimental/cloud_fit (client.py:45): serialize the
trainer spec + data + callbacks to a remote dir, submit a job whose
container deserializes and fits.  Here the model is the in-memory object —
no entry-point script at all.
"""

import optax

from cloud_tpu.cloud_fit import client
from cloud_tpu.cloud_fit.serialization import TrainerSpec
from cloud_tpu.core.containerize import DockerConfig
from cloud_tpu.models import mnist
from cloud_tpu.training import trainer

import numpy as np


def main(remote_dir="gs://my-bucket/cloud_fit_demo", dry_run: bool = False,
         **overrides):
    rng = np.random.default_rng(0)
    images = rng.normal(size=(512, 28, 28)).astype(np.float32)
    labels = np.clip(
        ((images.mean(axis=(1, 2)) + 0.5) * 10).astype(np.int32), 0, 9
    )

    spec = TrainerSpec(
        loss_fn=mnist.loss_fn,
        optimizer=optax.adam(1e-3),
        init_fn=mnist.init,
        logical_axes=mnist.param_logical_axes(),
    )
    return client.cloud_fit(
        spec,
        remote_dir,
        train_data={"image": images, "label": labels},
        callbacks=[trainer.ProgressLogger(every_n_steps=10)],
        epochs=2,
        batch_size=64,
        docker_config=DockerConfig(image="gcr.io/my-project/cloudfit:demo"),
        dry_run=dry_run,
        **overrides,
    )


if __name__ == "__main__":
    main()
