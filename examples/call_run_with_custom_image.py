"""Use a custom parent image and ship extra requirements.

Reference analogue: core/tests/examples/call_run_within_script_with_
autokeras.py:30-33 (custom base image for extra deps).  parent_image
replaces the default python base; requirements_txt is pip-installed into
the image.
"""

import os

import cloud_tpu
from cloud_tpu.core.containerize import DockerConfig

TESTDATA = os.path.join(os.path.dirname(__file__), "..", "tests", "testdata")


def main(dry_run: bool = False):
    return cloud_tpu.run(
        entry_point=os.path.join(TESTDATA, "mnist_example_using_fit.py"),
        requirements_txt=os.path.join(TESTDATA, "requirements.txt"),
        chief_config=cloud_tpu.COMMON_MACHINE_CONFIGS["TPU"],
        docker_config=DockerConfig(
            image="gcr.io/my-project/mnist:custom-base",
            parent_image="python:3.12-slim",
        ),
        job_labels={"team": "research", "phase": "dev"},
        dry_run=dry_run,
    )


if __name__ == "__main__":
    main()
