"""Build the image with Cloud Build instead of a local docker daemon.

Reference analogue: core/tests/examples/call_run_*_with_cloud_build.py —
passing a GCS bucket switches the builder (containerize.py:386-507): the
build context is tarred to the bucket and built server-side, so no local
docker install is needed (the common case on Cloud TPU VMs).
"""

import os

import cloud_tpu
from cloud_tpu.core.containerize import DockerConfig

TESTDATA = os.path.join(os.path.dirname(__file__), "..", "tests", "testdata")


def main(dry_run: bool = False):
    return cloud_tpu.run(
        entry_point=os.path.join(TESTDATA, "mnist_example_using_fit.py"),
        chief_config=cloud_tpu.COMMON_MACHINE_CONFIGS["TPU"],
        docker_config=DockerConfig(
            image="gcr.io/my-project/mnist:cloudbuild",
            image_build_bucket="my-build-bucket",
        ),
        dry_run=dry_run,
    )


if __name__ == "__main__":
    main()
