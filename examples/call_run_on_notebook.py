"""Launch a .ipynb notebook as the training workload.

Reference analogue: core/tests/examples/call_run_on_notebook_*.py — run()
converts the notebook to a script (shell/magic lines stripped) before
containerizing (notebook.py, reference preprocess.py:169-187).
"""

import os

import cloud_tpu
from cloud_tpu.core.containerize import DockerConfig

TESTDATA = os.path.join(os.path.dirname(__file__), "..", "tests", "testdata")


def main(dry_run: bool = False):
    return cloud_tpu.run(
        entry_point=os.path.join(TESTDATA, "mnist_example_using_fit.ipynb"),
        chief_config=cloud_tpu.COMMON_MACHINE_CONFIGS["TPU"],
        docker_config=DockerConfig(image="gcr.io/my-project/mnist-nb:demo"),
        dry_run=dry_run,
    )


if __name__ == "__main__":
    main()
