"""Launch the checkpoint/restore/resume workload.

Reference analogue: core/tests/examples/call_run_on_script_with_keras_save_and_load.py
— run() pointed at testdata save_and_load.py (user-owned strategy +
chief-aware save paths).  The TPU-native version checkpoints with Orbax,
where every process writes its own shards, so the script works unchanged
from 1 chip to a pod.
"""

import os

import cloud_tpu
from cloud_tpu.core.containerize import DockerConfig

TESTDATA = os.path.join(os.path.dirname(__file__), "..", "tests", "testdata")


def main(dry_run: bool = False):
    return cloud_tpu.run(
        entry_point=os.path.join(TESTDATA, "save_and_load.py"),
        chief_config=cloud_tpu.COMMON_MACHINE_CONFIGS["TPU"],
        # The workload owns its mesh (builds one itself): opt out of the
        # planner, mirroring reference distribution_strategy=None
        # (validate.py:117-124).
        distribution_strategy=None,
        docker_config=DockerConfig(image="gcr.io/my-project/ckpt:demo"),
        dry_run=dry_run,
    )


if __name__ == "__main__":
    main()
