"""Scale to a multi-slice job with explicit parallelism hints.

Beyond reference capability (SURVEY.md §2.6: it topped out at DP +
TPUStrategy): pin mesh axes — tensor parallel within a slice, fsdp for the
rest — and add worker slices; the planner validates the factorization and
the bootstrap builds the same Mesh on every host.
"""

import os

import cloud_tpu
from cloud_tpu.core.containerize import DockerConfig
from cloud_tpu.parallel import ParallelismHints

TESTDATA = os.path.join(os.path.dirname(__file__), "..", "tests", "testdata")


def main(dry_run: bool = False):
    return cloud_tpu.run(
        entry_point=os.path.join(TESTDATA, "mnist_example_using_fit.py"),
        chief_config=cloud_tpu.COMMON_MACHINE_CONFIGS["TPU_V5E_16"],
        worker_count=1,  # one extra slice; dp spans slices over DCN
        parallelism_hints=ParallelismHints(tp=4, prefer_fsdp=True),
        docker_config=DockerConfig(image="gcr.io/my-project/big-run:demo"),
        dry_run=dry_run,
    )


if __name__ == "__main__":
    main()
