"""Long-context training with ring attention over the ``sp`` mesh axis.

The sequence dimension is sharded across devices: each chip holds T/sp
tokens, K/V blocks rotate around the ring over ICI, and attention memory
stays O(T/sp) per chip — the config that OOMs a single chip trains across
the slice.  On TPU each per-block fold runs the Pallas flash kernel
(parallel/ring_attention.py).

Run locally on the virtual CPU rig (no TPU needed):

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/long_context_ring_attention.py

On a real slice the same code runs under the planner-produced mesh — ask
for sequence parallelism with ``ParallelismHints(sp=...)`` when launching
through ``cloud_tpu.run()``.
"""

import functools

import jax
import numpy as np
import optax


def main():
    from cloud_tpu import parallel
    from cloud_tpu.models import transformer
    from cloud_tpu.training import Trainer, data

    n = jax.device_count()
    # fsdp x sp: parameters ZeRO-sharded over fsdp, sequence over sp.
    mesh = parallel.MeshSpec({"fsdp": max(n // 4, 1), "sp": 4}).build()
    print(f"mesh: {[f'{a}={s}' for a, s in mesh.shape.items() if s > 1]}")

    # zigzag_sp: causal attention runs as the LOAD-BALANCED zig-zag ring
    # (every rank folds the same causal mass per hop); data stays in
    # natural order — the model owns the layout permutation.
    # fused_ce + remat "dots": the long-context memory recipe — the
    # [B, T, V] logits tensor never materializes (chunked online-
    # logsumexp loss) and the scan saves only matmul outputs, so
    # activation memory stays O(T/sp) end to end, loss included.
    config = transformer.TINY.scaled(
        zigzag_sp=True, fused_ce=True, remat=True, remat_policy="dots"
    )
    seq_len = 128  # divisible by 2*sp=8 -> zig-zag chunks of 16

    trainer = Trainer(
        functools.partial(transformer.loss_fn, config=config, mesh=mesh),
        optax.adamw(1e-3),
        init_fn=functools.partial(transformer.init, config=config),
        mesh=mesh,
        logical_axes=transformer.param_logical_axes(config),
    )
    trainer.init_state(jax.random.PRNGKey(0))

    dataset = data.synthetic_tokens(
        vocab_size=config.vocab_size, seq_len=seq_len, batch_size=8,
        num_batches=4,
    )
    history = trainer.fit(dataset, epochs=3)
    losses = [round(x, 4) for x in history.history["loss"]]
    print(f"losses per epoch: {losses}")
    assert losses[-1] < losses[0], "loss should improve"
    print("ring-attention training ran end to end")


if __name__ == "__main__":
    main()
