"""A self-launching training script: run() called from inside the script.

Reference analogue: core/tests/examples/call_run_within_script.py — the
script-mode contract (SURVEY.md §3.2): locally, run() ships THIS file and
exits; inside the container, remote() is true, run() returns immediately,
and the training below executes under the bootstrap-installed mesh.
"""

import jax
import numpy as np
import optax

import cloud_tpu
from cloud_tpu.core.containerize import DockerConfig

cloud_tpu.run(
    # entry_point=None => script mode: sys.argv[0] (this file) is shipped.
    chief_config=cloud_tpu.COMMON_MACHINE_CONFIGS["TPU"],
    worker_count=0,
    docker_config=DockerConfig(image="gcr.io/my-project/self-launch:demo"),
)

# ---- everything below runs only in the cloud container ----
from cloud_tpu import parallel  # noqa: E402
from cloud_tpu.models import mnist  # noqa: E402
from cloud_tpu.training import data, trainer  # noqa: E402

rng = np.random.default_rng(0)
images = rng.normal(size=(512, 28, 28)).astype(np.float32)
labels = np.clip(((images.mean(axis=(1, 2)) + 0.5) * 10).astype(np.int32), 0, 9)

t = trainer.Trainer(
    mnist.loss_fn, optax.adam(1e-3), mnist.init,
    mesh=parallel.get_global_mesh(),
    logical_axes=mnist.param_logical_axes(),
)
t.init_state(jax.random.PRNGKey(0))
t.fit(data.ArrayDataset({"image": images, "label": labels}, 64), epochs=3)
