"""Launch a training script on a TPU slice — the simplest invocation.

Reference analogue: core/tests/examples/call_run_on_script_* (run() pointed
at a file, machine configs from the named catalog).
"""

import os

import cloud_tpu
from cloud_tpu.core.containerize import DockerConfig

TESTDATA = os.path.join(
    os.path.dirname(__file__), "..", "tests", "testdata"
)


def main(dry_run: bool = False):
    return cloud_tpu.run(
        entry_point=os.path.join(TESTDATA, "mnist_example_using_fit.py"),
        chief_config=cloud_tpu.COMMON_MACHINE_CONFIGS["TPU"],
        # Explicit image URI; omit to default to gcr.io/<project>/... via ADC.
        docker_config=DockerConfig(image="gcr.io/my-project/mnist:demo"),
        dry_run=dry_run,
    )


if __name__ == "__main__":
    main()
