"""Train a tiny CloudLM, then sample from it with the KV-cache decoder.

End-to-end inference flow: fit a character-level model on a toy corpus,
then generate continuations with ``cloud_tpu.models.generation`` —
greedy and nucleus sampling, ragged prompt lengths, eos stopping.  The
whole decode is one compiled ``lax.scan`` program.

Run locally on the virtual CPU rig (no TPU needed):

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/generate_text.py

Under a mesh the same call shards batch over dp/fsdp and heads over tp
(see README "Text generation").
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax


CORPUS = (
    "the quick brown fox jumps over the lazy dog. "
    "pack my box with five dozen liquor jugs. "
    "how vexingly quick daft zebras jump! "
) * 40


def main():
    from cloud_tpu.models import generation, transformer
    from cloud_tpu.training import Trainer, data

    vocab = 128  # raw ascii
    config = transformer.TINY.scaled(vocab_size=vocab, max_seq_len=64)
    seq_len = 32

    # Character-level windows over the corpus.
    codes = np.frombuffer(CORPUS.encode(), np.uint8).astype(np.int32)
    starts = np.arange(0, len(codes) - seq_len - 1, 7)
    tokens = np.stack([codes[s:s + seq_len] for s in starts])

    trainer = Trainer(
        functools.partial(transformer.loss_fn, config=config),
        optax.adamw(3e-3),
        init_fn=functools.partial(transformer.init, config=config),
    )
    trainer.init_state(jax.random.PRNGKey(0))
    ds = data.ArrayDataset({"tokens": tokens}, batch_size=32, shuffle=True)
    hist = trainer.fit(ds, epochs=3)
    losses = hist.history["loss"]
    print(f"train loss: {losses[0]:.3f} -> {losses[-1]:.3f}")
    assert losses[-1] < losses[0]

    # Ragged prompts, batched generation.
    prompts = ["the quick brown ", "pack my "]
    t_prompt = max(len(p) for p in prompts)
    prompt_tokens = np.zeros((len(prompts), t_prompt), np.int32)
    prompt_lens = np.asarray([len(p) for p in prompts], np.int32)
    for i, p in enumerate(prompts):
        prompt_tokens[i, : len(p)] = np.frombuffer(p.encode(), np.uint8)

    for name, sample in [
        ("greedy", generation.SampleConfig(temperature=0.0)),
        ("nucleus", generation.SampleConfig(temperature=0.8, top_p=0.9)),
    ]:
        out = generation.generate(
            trainer.state.params,
            jnp.asarray(prompt_tokens),
            jnp.asarray(prompt_lens),
            config,
            max_new_tokens=24,
            sample=sample,
            rng=jax.random.PRNGKey(1),
        )
        for i, p in enumerate(prompts):
            n_real = int(prompt_lens[i]) + 24
            text = bytes(
                int(c) for c in np.asarray(out["sequences"])[i][:n_real]
            ).decode(errors="replace")
            print(f"{name:8s} | {text!r}")

    beam = generation.beam_search(
        trainer.state.params,
        jnp.asarray(prompt_tokens),
        jnp.asarray(prompt_lens),
        config,
        num_beams=4,
        max_new_tokens=24,
    )
    for i, p in enumerate(prompts):
        text = bytes(
            int(c) for c in np.asarray(beam["tokens"])[i] if c
        ).decode(errors="replace")
        print(f"beam-4   | {p + text!r}  (score {float(beam['scores'][i]):.3f})")

    # Serving-weight quantization: int8 storage (~4x smaller), decode
    # bandwidth halves vs bf16; on a trained model greedy output stays
    # essentially the same.
    from cloud_tpu.models import quantization

    qparams = quantization.quantize_params(trainer.state.params)
    ratio = quantization.param_bytes(qparams) / quantization.param_bytes(
        trainer.state.params
    )
    qout = generation.generate(
        qparams, jnp.asarray(prompt_tokens), jnp.asarray(prompt_lens),
        config, max_new_tokens=24,
    )
    text = bytes(
        int(c) for c in np.asarray(qout["sequences"])[0][: int(prompt_lens[0]) + 24]
    ).decode(errors="replace")
    print(f"int8     | {text!r}  (params {ratio:.2f}x of full)")
    return trainer


if __name__ == "__main__":
    main()
