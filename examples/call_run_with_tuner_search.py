"""Launch a hyperparameter-search workload onto TPU workers.

Reference analogue: core/tests/examples/call_run_on_script_with_keras_tuner_search.py
— run() pointed at a tuner workload (testdata keras_tuner_cifar_example.py).
Here the shipped script drives CloudTuner over the MNIST dense model; each
submitted job is one tuner worker, and N invocations with a shared study
id give distributed search (SURVEY.md §2.6 "data-parallel HP search").
"""

import os

import cloud_tpu
from cloud_tpu.core.containerize import DockerConfig

TESTDATA = os.path.join(os.path.dirname(__file__), "..", "tests", "testdata")


def main(dry_run: bool = False):
    return cloud_tpu.run(
        entry_point=os.path.join(TESTDATA, "tuner_mnist_example.py"),
        chief_config=cloud_tpu.COMMON_MACHINE_CONFIGS["TPU"],
        docker_config=DockerConfig(image="gcr.io/my-project/tuner:demo"),
        # Trials coordinate through the study service, not the mesh —
        # parallelism comes from submitting this job several times.
        job_labels={"workload": "hp-search"},
        dry_run=dry_run,
    )


if __name__ == "__main__":
    main()
