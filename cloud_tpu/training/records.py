"""Streaming record input pipeline — BASELINE config 5.

The at-scale analogue of the reference's tf.data + TFRecord path (golden
workload ``core/tests/testdata/mnist_example_using_fit.py:31-49`` streams
tfds TFRecords).  TPU-native design: files are the unit of host sharding,
decode happens on host CPU, and a background prefetcher keeps device_put
ahead of the train step so the TPU never waits on the host.

Three layers, each usable alone:

* **Wire framing** — ``RecordWriter`` / ``read_records`` speak the TFRecord
  format (u64 length + masked crc32c, then payload + masked crc32c), so
  files written here load in ``tf.data.TFRecordDataset`` and reference
  TFRecord files stream here, without TensorFlow installed.
* **Codecs** — ``encode_tensor_record``/``decode_tensor_record`` (npz-framed
  dict-of-arrays; the fast native path) and ``encode_example``/
  ``decode_example`` (a hand-rolled ``tf.train.Example`` protobuf subset:
  bytes/float/int64 lists — enough to parse the reference's datasets).
* **Pipeline** — ``RecordDataset`` (per-host file shards via
  ``jax.process_index()``, shuffle buffer, batching; the zero-arg-callable
  contract ``Trainer.fit`` expects) and ``prefetch_to_device`` (background
  thread overlapping host decode + transfer with device compute — now
  owned by ``training.pipeline_io``, re-exported here).

Paths may be local (glob patterns supported) or ``gs://`` (listed and read
via google.cloud.storage, injectable for tests).
"""

from __future__ import annotations

import glob as glob_lib
import io
import itertools
import logging
import os
import struct
import threading
from typing import Callable, Dict, Iterator, List, Optional, Sequence, Union

import numpy as np

logger = logging.getLogger(__name__)

# ---------------------------------------------------------------------------
# crc32c (Castagnoli) — required by the TFRecord framing.  Hot path lives
# in the native library (training/cpp/records_native.cc, slicing-by-8,
# ctypes-bound, built lazily like monitoring's registry); the pure-Python
# table fallback keeps the format usable when no toolchain exists.
# ---------------------------------------------------------------------------

_CRC_POLY = 0x82F63B78
_CRC_TABLE: Optional[List[int]] = None

_CPP_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "cpp")
_LIB_PATH = os.path.join(_CPP_DIR, "libcloud_tpu_records.so")
_native_lib = None
_native_tried = False
_native_lock = threading.Lock()


def _native():
    """Load (building if stale) the native records library via the shared
    loader; None if that fails (pure-Python paths take over)."""
    global _native_lib, _native_tried
    if _native_tried:
        return _native_lib
    with _native_lock:
        if _native_tried:
            return _native_lib
        import ctypes

        from cloud_tpu.utils.native import load_native_lib

        lib = load_native_lib(_CPP_DIR, "libcloud_tpu_records.so",
                              what="native records hot path")
        if lib is not None:
            lib.ctpu_records_crc32c.restype = ctypes.c_uint32
            lib.ctpu_records_crc32c.argtypes = [
                ctypes.c_char_p, ctypes.c_uint64
            ]
            lib.ctpu_records_masked_crc32c.restype = ctypes.c_uint32
            lib.ctpu_records_masked_crc32c.argtypes = [
                ctypes.c_char_p, ctypes.c_uint64
            ]
            lib.ctpu_records_scan.restype = ctypes.c_int64
            lib.ctpu_records_scan.argtypes = [
                ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int,
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.c_int64,
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_int32),
            ]
        _native_lib = lib
        _native_tried = True
        return _native_lib


def _table() -> List[int]:
    global _CRC_TABLE
    if _CRC_TABLE is None:
        table = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ (_CRC_POLY if c & 1 else 0)
            table.append(c)
        _CRC_TABLE = table
    return _CRC_TABLE


def _crc32c_python(data: bytes) -> int:
    table = _table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def crc32c(data: bytes) -> int:
    lib = _native()
    if lib is not None:
        return lib.ctpu_records_crc32c(data, len(data))
    return _crc32c_python(data)


def masked_crc32c(data: bytes) -> int:
    """TFRecord's rotated+offset crc (format spec: tensorflow
    core/lib/hash/crc32c.h)."""
    lib = _native()
    if lib is not None:
        return lib.ctpu_records_masked_crc32c(data, len(data))
    crc = _crc32c_python(data)
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# Wire framing
# ---------------------------------------------------------------------------


def _is_gcs(path: str) -> bool:
    return path.startswith("gs://")


def _split_gcs(path: str):
    rest = path[len("gs://"):]
    bucket, _, name = rest.partition("/")
    return bucket, name


class RecordWriter:
    """Writes TFRecord-framed records to one local or ``gs://`` file.

    GCS writes buffer in memory and upload on close (records files are
    written shard-by-shard; one shard fits comfortably in host RAM).
    """

    def __init__(self, path: str, storage_client=None):
        self.path = path
        self._storage_client = storage_client
        if _is_gcs(path):
            self._buf: Optional[io.BytesIO] = io.BytesIO()
            self._file = self._buf
        else:
            import os

            parent = os.path.dirname(path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            self._buf = None
            self._file = open(path, "wb")

    def write(self, payload: bytes) -> None:
        header = struct.pack("<Q", len(payload))
        self._file.write(header)
        self._file.write(struct.pack("<I", masked_crc32c(header)))
        self._file.write(payload)
        self._file.write(struct.pack("<I", masked_crc32c(payload)))

    def close(self) -> None:
        if self._buf is not None:
            from google.cloud import storage

            client = self._storage_client or storage.Client()
            bucket, name = _split_gcs(self.path)
            client.bucket(bucket).blob(name).upload_from_string(
                self._buf.getvalue()
            )
            self._buf = None
        else:
            self._file.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


#: Refill size for the native read path — bounds peak memory at roughly
#: one chunk (+ one in-flight record) regardless of file size.
_SCAN_CHUNK_BYTES = 8 * 1024 * 1024


def _scan_records_native(f, path: str, verify: bool):
    """Stream frames from file-like ``f`` via the native batch scanner:
    read a chunk, parse every complete frame in ONE C call per 4096
    records (crc verification included), keep the partial tail for the
    next refill.  Constant memory in the file size; records larger than
    the chunk grow the buffer only until their frame completes.

    Error parity with the Python framing loop: frames scanned before a
    corruption are yielded first, then the error raises.
    """
    import ctypes

    lib = _native()
    batch = 4096
    offsets = (ctypes.c_uint64 * batch)()
    lengths = (ctypes.c_uint64 * batch)()
    consumed = ctypes.c_uint64()
    status = ctypes.c_int32()
    buf = bytearray()
    eof = False
    while True:
        if not eof:
            chunk = f.read(_SCAN_CHUNK_BYTES)
            if chunk:
                buf += chunk
            else:
                eof = True
        pos = 0
        # from_buffer: a pointer into the bytearray, no copy.  The buffer
        # is not resized while scanning this fill.  The export object is
        # held in a named variable and dropped explicitly below — the
        # tail-trim resize would raise BufferError while any export is
        # alive, and relying on CPython refcounting to collect an
        # anonymous temporary is not a portable guarantee.
        anchor = ctypes.c_char.from_buffer(buf) if buf else None
        base = ctypes.addressof(anchor) if anchor is not None else 0
        # One memoryview per fill, released before the tail-trim below (a
        # live export blocks bytearray resizing); slicing the view keeps
        # payload extraction at ONE copy instead of bytearray-slice + bytes.
        view = memoryview(buf) if buf else None
        try:
            while pos < len(buf):
                count = lib.ctpu_records_scan(
                    ctypes.c_void_p(base + pos), len(buf) - pos,
                    1 if verify else 0, offsets, lengths,
                    batch, ctypes.byref(consumed), ctypes.byref(status),
                )
                for i in range(count):
                    start = pos + offsets[i]
                    yield bytes(view[start:start + lengths[i]])
                if status.value == 1:
                    raise ValueError(f"corrupt record length crc in {path}")
                if status.value == 2:
                    raise ValueError(
                        f"corrupt record payload crc in {path}"
                    )
                pos += consumed.value
                if consumed.value == 0:
                    break  # partial frame — refill (or truncated at EOF)
        finally:
            if view is not None:
                view.release()
            del anchor  # drop the ctypes buffer export before resizing
        if pos:
            del buf[:pos]  # keep only the partial tail
        if eof:
            if buf:
                raise ValueError(f"truncated record in {path}")
            return


def read_records(
    path: str, *, verify: bool = False, storage_client=None
) -> Iterator[bytes]:
    """Stream raw record payloads from one TFRecord-framed file.

    With the native library available, frames are parsed and
    crc-verified by the batched C scanner over fixed-size refills
    (constant memory); the framing-loop fallback streams record by
    record in Python.
    """
    if _is_gcs(path):
        from google.cloud import storage

        client = storage_client or storage.Client()
        bucket, name = _split_gcs(path)
        f = io.BytesIO(client.bucket(bucket).blob(name).download_as_bytes())
    else:
        f = open(path, "rb")
    if _native() is not None:
        try:
            yield from _scan_records_native(f, path, verify)
        finally:
            f.close()
        return
    try:
        while True:
            header = f.read(8)
            if not header:
                return
            if len(header) != 8:
                raise ValueError(f"truncated record header in {path}")
            (length,) = struct.unpack("<Q", header)
            header_crc_bytes = f.read(4)
            if len(header_crc_bytes) != 4:
                raise ValueError(f"truncated record header crc in {path}")
            (header_crc,) = struct.unpack("<I", header_crc_bytes)
            if verify and masked_crc32c(header) != header_crc:
                raise ValueError(f"corrupt record length crc in {path}")
            payload = f.read(length)
            if len(payload) != length:
                raise ValueError(f"truncated record payload in {path}")
            payload_crc_bytes = f.read(4)
            if len(payload_crc_bytes) != 4:
                raise ValueError(f"truncated record payload crc in {path}")
            (payload_crc,) = struct.unpack("<I", payload_crc_bytes)
            if verify and masked_crc32c(payload) != payload_crc:
                raise ValueError(f"corrupt record payload crc in {path}")
            yield payload
    finally:
        f.close()


# ---------------------------------------------------------------------------
# Codecs
# ---------------------------------------------------------------------------


_TENSOR_MAGIC = b"CTR1"


def encode_tensor_record(tensors: Dict[str, np.ndarray]) -> bytes:
    """Native codec: one record = one example as a dict of arrays.

    Wire layout: magic, then a JSON header (name -> [dtype, shape]) length-
    prefixed, then each array's raw bytes in header order.  Chosen over
    npz because np.savez routes through zipfile — ~0.3 ms per example,
    which caps a streaming pipeline at ~3k examples/s; this framing
    decodes via zero-copy ``np.frombuffer`` an order of magnitude faster.
    """
    import json as json_lib

    header = {}
    chunks = []
    for name, value in tensors.items():
        # np.asarray, not ascontiguousarray: the latter promotes 0-d
        # scalars to shape (1,).  tobytes() already emits C order.
        arr = np.asarray(value)
        header[name] = [arr.dtype.str, list(arr.shape)]
        chunks.append(arr.tobytes())
    header_bytes = json_lib.dumps(header).encode()
    return b"".join(
        [_TENSOR_MAGIC, struct.pack("<I", len(header_bytes)), header_bytes]
        + chunks
    )


def decode_tensor_record(payload: bytes) -> Dict[str, np.ndarray]:
    import json as json_lib

    if payload[:4] != _TENSOR_MAGIC:
        # Back-compat: npz-framed records from earlier writers.
        with np.load(io.BytesIO(payload)) as npz:
            return {k: npz[k] for k in npz.files}
    (header_len,) = struct.unpack("<I", payload[4:8])
    header = json_lib.loads(payload[8 : 8 + header_len].decode())
    out = {}
    offset = 8 + header_len
    for name, (dtype_str, shape) in header.items():
        dtype = np.dtype(dtype_str)
        count = int(np.prod(shape)) if shape else 1
        out[name] = np.frombuffer(
            payload, dtype, count, offset=offset
        ).reshape(shape)
        offset += count * dtype.itemsize
    return out


# --- tf.train.Example protobuf subset (no TF, no protoc) -------------------
#
# Wire schema (tensorflow/core/example/{example,feature}.proto):
#   Example      { Features features = 1; }
#   Features     { map<string, Feature> feature = 1; }   (map entry: key=1, value=2)
#   Feature      { oneof { BytesList bytes_list = 1; FloatList float_list = 2;
#                          Int64List int64_list = 3; } }
#   BytesList    { repeated bytes value = 1; }
#   FloatList    { repeated float value = 1 [packed = true]; }
#   Int64List    { repeated int64 value = 1 [packed = true]; }


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_varint(data: bytes, pos: int):
    result = 0
    shift = 0
    while True:
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _len_delimited(field: int, payload: bytes) -> bytes:
    return _varint(field << 3 | 2) + _varint(len(payload)) + payload


def _encode_feature(values) -> bytes:
    arr = np.asarray(values)
    if arr.dtype.kind in ("S", "U", "O") or isinstance(values, (bytes, str)):
        items = values if isinstance(values, (list, tuple)) else [values]
        body = b"".join(
            _len_delimited(1, v.encode() if isinstance(v, str) else bytes(v))
            for v in items
        )
        return _len_delimited(1, body)  # bytes_list
    if arr.dtype.kind == "f":
        packed = arr.astype("<f4").ravel().tobytes()
        return _len_delimited(2, _len_delimited(1, packed))  # float_list
    if arr.dtype.kind in ("i", "u", "b"):
        body = b"".join(
            _varint(int(v) & 0xFFFFFFFFFFFFFFFF) for v in arr.ravel()
        )
        return _len_delimited(3, _len_delimited(1, body))  # int64_list
    raise TypeError(f"unsupported feature dtype: {arr.dtype}")


def encode_example(features: Dict[str, Union[np.ndarray, bytes, str, list]]) -> bytes:
    """Encode a flat feature dict as a serialized ``tf.train.Example``."""
    entries = []
    for name, values in features.items():
        entry = _len_delimited(1, name.encode()) + _len_delimited(
            2, _encode_feature(values)
        )
        entries.append(_len_delimited(1, entry))  # Features.feature map entry
    return _len_delimited(1, b"".join(entries))  # Example.features


def _decode_feature(data: bytes):
    tag, pos = _read_varint(data, 0)
    field = tag >> 3
    length, pos = _read_varint(data, pos)
    body = data[pos : pos + length]
    if field == 1:  # bytes_list
        out = []
        p = 0
        while p < len(body):
            _, p = _read_varint(body, p)  # tag (field 1, wire 2)
            n, p = _read_varint(body, p)
            out.append(body[p : p + n])
            p += n
        return out
    if field == 2:  # float_list (packed)
        p = 0
        floats = []
        while p < len(body):
            t, p = _read_varint(body, p)
            if t & 7 == 2:  # packed
                n, p = _read_varint(body, p)
                floats.append(np.frombuffer(body, "<f4", n // 4, offset=p))
                p += n
            else:  # unpacked single float
                floats.append(np.frombuffer(body, "<f4", 1, offset=p))
                p += 4
        return np.concatenate(floats) if floats else np.zeros(0, "<f4")
    if field == 3:  # int64_list (packed varints)
        p = 0
        ints = []
        while p < len(body):
            t, p = _read_varint(body, p)
            if t & 7 == 2:
                n, p = _read_varint(body, p)
                end = p + n
                while p < end:
                    v, p = _read_varint(body, p)
                    ints.append(v - (1 << 64) if v >> 63 else v)
            else:
                v, p = _read_varint(body, p)
                ints.append(v - (1 << 64) if v >> 63 else v)
        return np.array(ints, np.int64)
    raise ValueError(f"unknown Feature field {field}")


def decode_example(payload: bytes) -> Dict[str, object]:
    """Parse a serialized ``tf.train.Example`` into {name: values}.

    bytes_list -> list[bytes]; float_list -> float32 array; int64_list ->
    int64 array.
    """
    # Unwrap Example.features
    tag, pos = _read_varint(payload, 0)
    if tag >> 3 != 1:
        raise ValueError("not an Example proto")
    length, pos = _read_varint(payload, pos)
    features = payload[pos : pos + length]

    out: Dict[str, object] = {}
    p = 0
    while p < len(features):
        tag, p = _read_varint(features, p)  # map entry (field 1)
        n, p = _read_varint(features, p)
        entry = features[p : p + n]
        p += n
        # entry: key (field 1, string) + value (field 2, Feature)
        ep = 0
        name = None
        value = None
        while ep < len(entry):
            etag, ep = _read_varint(entry, ep)
            en, ep = _read_varint(entry, ep)
            chunk = entry[ep : ep + en]
            ep += en
            if etag >> 3 == 1:
                name = chunk.decode()
            else:
                value = _decode_feature(chunk)
        out[name] = value
    return out


# ---------------------------------------------------------------------------
# Pipeline
# ---------------------------------------------------------------------------


def _list_files(patterns: Sequence[str], storage_client=None) -> List[str]:
    import fnmatch

    files: List[str] = []
    for pattern in patterns:
        if _is_gcs(pattern):
            from google.cloud import storage

            client = storage_client or storage.Client()
            bucket, glob_pattern = _split_gcs(pattern)
            prefix = glob_pattern.split("*")[0]
            # list_blobs only prefixes; apply the full glob to each name so
            # e.g. sidecar files under the same prefix don't stream as
            # records.
            files.extend(
                f"gs://{bucket}/{blob.name}"
                for blob in client.bucket(bucket).list_blobs(prefix=prefix)
                if fnmatch.fnmatch(blob.name, glob_pattern)
            )
        elif glob_lib.has_magic(pattern):
            files.extend(sorted(glob_lib.glob(pattern)))
        else:
            import os as os_lib

            if not os_lib.path.exists(pattern):
                # Fail at construction like the glob branch, not from the
                # prefetch thread mid-training.
                raise ValueError(f"record file not found: {pattern}")
            files.append(pattern)
    if not files:
        raise ValueError(f"no record files match {list(patterns)}")
    return sorted(files)


class RecordDataset:
    """Re-iterable batched dataset streaming from record files.

    Sharding: with N hosts (``jax.process_count()``), host i reads files
    ``files[i::N]`` — disjoint shards, no coordination (the tf.data
    ``shard(num_shards, index)`` pattern the reference's input pipelines
    relied on).  When there are fewer files than hosts, records are strided
    instead (host i keeps records where ``record_idx % N == i``), trading
    read amplification for correctness.

    ``decode`` maps a raw payload to a {name: array} example; defaults to
    the native tensor codec.  Batches are stacked along a new leading axis.
    The instance is a zero-arg callable yielding a fresh iterator — the
    ``Trainer.fit`` contract.

    ``decode_threads`` runs decode in an ordered thread pool.  Leave at 0
    (serial) unless your decode RELEASES THE GIL — measured on this repo's
    pure-Python codecs the pool is ~30% slower (GIL-bound decode gains no
    parallelism, pays submit overhead).  The win case is C-backed
    decompression: JPEG/PNG decode, zlib, np-heavy augmentation.

    Resume: shuffle order (file order AND buffer draws) is derived per
    epoch from ``(seed, epoch)``, and ``state_dict()`` /
    ``load_state_dict()`` implement the exactly-once fast-forward
    contract shared with :class:`~cloud_tpu.training.data.ArrayDataset`
    — a restored trainer replays epoch E from its B-th batch with the
    identical stream an uninterrupted run would have produced.  Skipped
    batches are still decoded (the shuffle-buffer state must advance
    identically) but never collated or yielded.
    """

    def __init__(
        self,
        files: Union[str, Sequence[str]],
        batch_size: int,
        *,
        decode: Optional[Callable[[bytes], Dict[str, np.ndarray]]] = None,
        shuffle_buffer: int = 0,
        seed: int = 0,
        drop_remainder: bool = True,
        shard_by_process: bool = True,
        process_index: Optional[int] = None,
        process_count: Optional[int] = None,
        verify: bool = False,
        decode_threads: int = 0,
        storage_client=None,
    ):
        patterns = [files] if isinstance(files, str) else list(files)
        self.files = _list_files(patterns, storage_client)
        self.batch_size = batch_size
        self.decode = decode or decode_tensor_record
        self.decode_threads = decode_threads
        self.shuffle_buffer = shuffle_buffer
        self.drop_remainder = drop_remainder
        self.verify = verify
        self._storage_client = storage_client
        self.seed = int(seed)
        self._epoch = 0  # epochs issued so far (next __call__ uses this)
        self._skip = 0   # one-shot batch fast-forward for the next epoch
        if shard_by_process:
            if process_index is None or process_count is None:
                import jax

                process_index = jax.process_index()
                process_count = jax.process_count()
        else:
            process_index, process_count = 0, 1
        self.process_index = process_index
        self.process_count = process_count
        if len(self.files) >= self.process_count:
            self.shard_files = self.files[process_index::process_count]
            self._stride_records = False
        else:
            self.shard_files = list(self.files)
            self._stride_records = True

    def _payloads(self, rng: np.random.Generator) -> Iterator[bytes]:
        files = list(self.shard_files)
        # In record-striding mode the keep predicate depends on the GLOBAL
        # record index, which is only consistent across hosts when every
        # host walks the files in the same (canonical) order — shuffling
        # there would silently break shard disjointness for differently
        # seeded hosts.  Shuffling still happens via the example buffer.
        if self.shuffle_buffer and not self._stride_records:
            rng.shuffle(files)
        idx = 0
        for path in files:
            for payload in read_records(
                path, verify=self.verify, storage_client=self._storage_client
            ):
                keep = (
                    not self._stride_records
                    or idx % self.process_count == self.process_index
                )
                idx += 1
                if keep:
                    yield payload

    def _examples(self, rng: np.random.Generator, payloads=None
                  ) -> Iterator[Dict[str, np.ndarray]]:
        if payloads is None:
            payloads = self._payloads(rng)
        if self.decode_threads <= 0:
            for payload in payloads:
                yield self.decode(payload)
            return
        # Ordered parallel decode: submit up to threads*4 payloads ahead,
        # always yield the oldest future — order (and therefore multi-host
        # determinism) is preserved while decode overlaps file reads.
        import collections
        from concurrent.futures import ThreadPoolExecutor

        inflight: "collections.deque" = collections.deque()
        max_inflight = self.decode_threads * 4
        with ThreadPoolExecutor(max_workers=self.decode_threads) as pool:
            for payload in payloads:
                inflight.append(pool.submit(self.decode, payload))
                if len(inflight) >= max_inflight:
                    yield inflight.popleft().result()
            while inflight:
                yield inflight.popleft().result()

    def _shuffled(self, rng: np.random.Generator
                  ) -> Iterator[Dict[str, np.ndarray]]:
        if not self.shuffle_buffer:
            yield from self._examples(rng)
            return
        buf: List[Dict[str, np.ndarray]] = []
        for example in self._examples(rng):
            buf.append(example)
            if len(buf) >= self.shuffle_buffer:
                pick = rng.integers(len(buf))
                buf[pick], buf[-1] = buf[-1], buf[pick]
                yield buf.pop()
        rng.shuffle(buf)
        yield from buf

    def state_dict(self) -> Dict[str, int]:
        """Reproducibility state (the trainer records the authoritative
        consumed-batch position; this is the dataset-side complement)."""
        return {"epoch": self._epoch, "seed": self.seed}

    def load_state_dict(self, state: Dict) -> None:
        """Fast-forward: the next iterator produces epoch
        ``state["epoch"]`` with its first ``state["batches_consumed"]``
        batches skipped (positions come from the trainer-boundary count
        a checkpoint recorded, so prefetched-but-unconsumed batches are
        not marked done).  A ``seed`` in the state is ADOPTED (with a
        loud warning on mismatch): the position only names the right
        batches under the shuffle order it was recorded in."""
        saved = state.get("seed")
        if saved is not None and int(saved) != self.seed:
            logger.warning(
                "restored iterator position was recorded under shuffle "
                "seed %s but this dataset was built with seed %d; "
                "adopting the checkpoint's seed so the replayed stream "
                "is the one the position points into", saved, self.seed,
            )
            self.seed = int(saved)
        self._epoch = int(state.get("epoch", 0))
        self._skip = int(state.get("batches_consumed", 0))

    def __call__(self) -> Iterator[Dict[str, np.ndarray]]:
        # Epoch/skip captured eagerly so a prefetcher that builds the
        # iterator without pulling still advances the epoch counter.
        epoch = self._epoch
        self._epoch += 1
        skip, self._skip = self._skip, 0
        return self._iter_epoch(epoch, skip)

    def _iter_epoch(self, epoch: int, skip: int
                    ) -> Iterator[Dict[str, np.ndarray]]:
        # Pipeline throughput producers (default exporter telemetry, like
        # the trainer's MetricsCallback): per-batch counter bumps are a
        # ctypes call each — noise against decode cost — and the
        # examples/sec gauge updates via the shared windowed-rate helper
        # (one window = 32 batches), with the tail flushed at stream end.
        from time import perf_counter

        from cloud_tpu.monitoring import metrics as _metrics

        rate = _metrics.WindowedRate(
            "data/examples_per_sec", 32 * self.batch_size
        )
        rate.restart(perf_counter())

        def account(n: int) -> None:
            _metrics.counter_inc("data/batches")
            _metrics.counter_inc("data/examples", n)
            rate.add(perf_counter(), n)

        # account() runs BEFORE each yield and the flush sits in a
        # finally: a consumer that stops early (steps_per_epoch break,
        # abandoned prefetch) suspends the generator at the yield and
        # GCs it — counting after the yield would drop the last batch
        # and skip the tail flush.
        rng = np.random.default_rng((self.seed, epoch))
        skipped = 0
        if skip and not self.shuffle_buffer:
            # No shuffle-buffer state to advance: fast-forward at the
            # RECORD level instead of the example level.  The framing is
            # still read (crc verify and stride accounting unchanged) but
            # skipped batches are never decoded — at a deep resume point
            # that is the difference between a seek-speed fast-forward
            # and re-decoding hours of JPEG/zlib just to discard it.
            payloads = self._payloads(rng)
            for _ in itertools.islice(payloads, skip * self.batch_size):
                pass  # a stream shorter than the skip yields nothing, as before
            source = self._examples(rng, payloads)
            skipped = skip  # already skipped; the loop below starts live
        else:
            source = self._shuffled(rng)
        try:
            batch: List[Dict[str, np.ndarray]] = []
            for example in source:
                batch.append(example)
                if len(batch) == self.batch_size:
                    if skipped < skip:
                        # Resume fast-forward: the batch was already
                        # consumed by the interrupted run — advance the
                        # stream (shuffle state included) without
                        # collating, accounting, or yielding it.
                        skipped += 1
                        batch = []
                        continue
                    account(self.batch_size)
                    yield self._collate(batch)
                    batch = []
            if batch and not self.drop_remainder:
                if skipped < skip:
                    return
                account(len(batch))
                yield self._collate(batch)
        finally:
            rate.flush(perf_counter())

    @staticmethod
    def _collate(examples: List[Dict[str, np.ndarray]]) -> Dict[str, np.ndarray]:
        keys = examples[0].keys()
        return {k: np.stack([e[k] for e in examples]) for k in keys}


def write_records(
    path_template: str,
    examples: Iterator[Dict[str, np.ndarray]],
    *,
    num_shards: int = 1,
    encode: Callable[[Dict[str, np.ndarray]], bytes] = encode_tensor_record,
    storage_client=None,
) -> List[str]:
    """Write examples round-robin into ``num_shards`` TFRecord-framed files.

    ``path_template`` must contain ``{shard}`` when num_shards > 1, e.g.
    ``/data/train-{shard:05d}-of-00004.rec``.
    """
    if num_shards > 1 and "{shard" not in path_template:
        raise ValueError("path_template needs a {shard} placeholder")
    paths = [
        path_template.format(shard=i) if "{shard" in path_template
        else path_template
        for i in range(num_shards)
    ]
    writers = [RecordWriter(p, storage_client) for p in paths]
    try:
        for i, example in enumerate(examples):
            writers[i % num_shards].write(encode(example))
    finally:
        for w in writers:
            w.close()
    return paths


# The background prefetcher grew up here but serves every input pipeline
# (in-memory arrays, validation, fused multi-step windows), so it now
# lives in ``pipeline_io``; these aliases keep the long-standing import
# path (``records.prefetch_to_device``) working.
from cloud_tpu.training.pipeline_io import (  # noqa: E402,F401 — re-export
    PrefetchIterator as _PrefetchIterator,
    prefetch_to_device,
)
