"""Training runtime: sharded train state/steps, Keras-fit-parity trainer.

The reference delegated its hot loop entirely to ``model.fit()`` inside the
remote container (SURVEY.md §3.1); here the loop is owned by the framework:
a pjit-compiled train step over the planned mesh, driven by a Trainer with
an explicit callback protocol (the serializable analogue of Keras
callbacks, needed by cloud_fit — SURVEY.md §7 hard parts).
"""

from cloud_tpu.training.train import (
    TrainState,
    create_sharded_state,
    make_eval_step,
    make_multi_step,
    make_train_step,
    param_shardings,
)
from cloud_tpu.training import compile_cache, optimizers, pipeline_io
from cloud_tpu.training.pipeline_io import prefetch_to_device
from cloud_tpu.training.trainer import (
    Callback,
    EarlyStopping,
    TerminateOnNaN,
    History,
    LambdaCallback,
    ProgressLogger,
    Trainer,
)

__all__ = [
    "TrainState",
    "optimizers",
    "Trainer",
    "Callback",
    "EarlyStopping",
    "TerminateOnNaN",
    "History",
    "LambdaCallback",
    "ProgressLogger",
    "create_sharded_state",
    "make_train_step",
    "make_multi_step",
    "make_eval_step",
    "param_shardings",
    "compile_cache",
    "pipeline_io",
    "prefetch_to_device",
]
