"""Compile-ahead engine: AOT step compilation and a safe persistent cache.

XLA compilation dominates small-job submit-to-first-step latency (the
north star's second headline metric): the trainer's first dispatch pays
lower + backend-compile synchronously while the device sits idle, and a
fresh process pays it all again.  This module makes that cost an
engineered quantity instead of an accident, three ways:

* **AOT registry** — :func:`get_or_compile` keys
  ``jax.jit(step).lower(abstract_avals).compile()`` artifacts by
  (step-fn identity, abstract input avals, mesh + sharding rules,
  donation signature, steps-per-dispatch), so a second fit over the same
  shapes reuses the executable without touching jit's dispatch path.
  Every compile is spanned as ``compile/lower`` and
  ``compile/backend_compile`` (monitoring.tracing), so the report CLI
  attributes cold-start wall-clock phase by phase.
* **Background compile-ahead** — :func:`start_compile_ahead` compiles
  the fit's step executables on a worker thread *while*
  ``pipeline_io`` prefetch warms, and hands the trainer
  :class:`AotStep` wrappers that dispatch through the ready executable
  (falling back to the plain jitted function on any input mismatch —
  compile-ahead can make a fit faster, never wrong).  The machinery is
  not Trainer-specific: ``cloud_tpu.serving`` warms its whole inference
  grid through the same registry + worker at engine start — one
  slot-insert executable per prompt bucket plus the single chunk-decode
  program under the continuous scheduler, or prefill/decode executables
  per (bucket_len, batch_size) cell under the batch scheduler.
* **Safe persistent cache** — :func:`maybe_enable_persistent_cache`
  re-enables jax's on-disk compilation cache behind
  ``CLOUD_TPU_COMPILE_CACHE=<dir>``, gated on a one-time child-process
  round-trip probe (compile a trainer-shaped jitted step, drop the
  in-memory caches, recompile from disk, execute, compare).  jaxlib
  0.4.36/0.4.37 executable (de)serialization corrupts the glibc heap
  for some step executables (the reason PR 1 disabled the cache
  outright); the probe quarantines that class in a child that can die
  harmlessly, and a version blocklist refuses the known-bad jaxlibs up
  front unless ``CLOUD_TPU_COMPILE_CACHE_FORCE=1``.  Newer jaxlibs get
  warm-start across processes; ``core.deploy`` forwards the env into
  the container so deployed jobs inherit it.
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys
import threading
from typing import Any, Dict, Optional, Tuple

import numpy as np

from cloud_tpu.monitoring import metrics, tracing

logger = logging.getLogger(__name__)

#: Directory for jax's on-disk compilation cache; unset/"off" disables.
ENV_COMPILE_CACHE = "CLOUD_TPU_COMPILE_CACHE"
#: Set to 1 to bypass the known-bad jaxlib blocklist (the probe still runs).
ENV_COMPILE_CACHE_FORCE = "CLOUD_TPU_COMPILE_CACHE_FORCE"
#: Override jax's min-compile-time-to-cache threshold (seconds; default 0 —
#: the jobs this launcher targets are small, so cache everything).
ENV_COMPILE_CACHE_MIN_SECS = "CLOUD_TPU_COMPILE_CACHE_MIN_SECS"

#: jaxlib versions whose executable (de)serialization is known memory-unsafe
#: (tests/conftest.py records the observed SIGSEGV / "corrupted
#: double-linked list" aborts).  Refused without the FORCE env because the
#: corruption strikes *in-process*, after the probe child already exited
#: clean on a smaller executable.
KNOWN_BAD_JAXLIB = ("0.4.36", "0.4.37")


# --------------------------------------------------------------------------
# Abstract avals


def _canonical_dtype(dtype):
    import jax

    return jax.dtypes.canonicalize_dtype(np.dtype(dtype))


def abstract_state(state):
    """ShapeDtypeStruct pytree for a live TrainState (shardings preserved,
    so the AOT executable compiles for the exact placement jit would)."""
    import jax

    def aval(x):
        if isinstance(x, jax.Array):
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        x = np.asarray(x)
        return jax.ShapeDtypeStruct(x.shape, _canonical_dtype(x.dtype))

    return jax.tree_util.tree_map(aval, state)


def abstract_batch(batch, mesh=None, rules=None, *, stacked: bool = False,
                   batch_axis: str = "batch"):
    """ShapeDtypeStruct pytree for a batch AS THE STEP WILL SEE IT.

    Device-placed leaves keep their shardings verbatim; host leaves get
    the sharding ``train.shard_batch`` would commit them to (dim 0 on the
    data axes; ``stacked=True`` = super-batch layout with a replicated
    leading step axis).  Accepts a concrete batch or a ``batch_spec``
    pytree of anything with ``.shape``/``.dtype``.
    """
    import jax
    from jax.sharding import NamedSharding

    lead = [None, batch_axis] if stacked else [batch_axis]

    def aval(x):
        if isinstance(x, jax.Array):
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        shape = tuple(x.shape)
        dtype = _canonical_dtype(x.dtype)
        if mesh is None:
            return jax.ShapeDtypeStruct(shape, dtype)
        spec = rules.spec(*(lead + [None] * (len(shape) - len(lead))))
        return jax.ShapeDtypeStruct(
            shape, dtype, sharding=NamedSharding(mesh, spec)
        )

    return jax.tree_util.tree_map(aval, batch)


def _args_key(args) -> Tuple:
    """Hashable identity of a lowering's abstract inputs."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(args)
    return (
        str(treedef),
        tuple(
            (tuple(leaf.shape), str(leaf.dtype), str(getattr(leaf, "sharding", None)))
            for leaf in leaves
        ),
    )


def context_key(*, mesh=None, rules=None, donation: Tuple[int, ...] = (),
                steps_per_dispatch: int = 1) -> Tuple:
    """The non-aval half of a registry key: mesh layout, sharding rules,
    donation signature, and K (the fused-dispatch width)."""
    mesh_key = None
    if mesh is not None:
        mesh_key = (
            tuple(mesh.shape.items()),
            tuple(int(d.id) for d in mesh.devices.flat),
        )
    rules_key = None
    if rules is not None:
        rules_key = tuple(sorted(rules.rules.items()))
    return (mesh_key, rules_key, tuple(donation), int(steps_per_dispatch))


# --------------------------------------------------------------------------
# AOT registry

_registry: Dict[Tuple, Tuple[Any, Any]] = {}
_registry_lock = threading.Lock()

#: Registry bound: entries hold STRONG refs to the jitted fn (closure,
#: optimizer, mesh) and its compiled executable, so an unbounded registry
#: grows linearly in a long-lived process that keeps building Trainers
#: (a tuner loop).  FIFO-evict past this; jit's own dispatch cache still
#: backs an evicted fit, which just pays one lower+compile again.
REGISTRY_MAX_ENTRIES = 64


def aot_compile(jitted, *args, label: str = "step"):
    """``jitted.lower(*args).compile()`` with cold-start attribution spans.

    ``args`` may be concrete arrays, ShapeDtypeStructs, or a mix; nothing
    executes.  The two phases are spanned separately because they fail —
    and cost — differently: ``compile/lower`` is Python tracing,
    ``compile/backend_compile`` is XLA.
    """
    with tracing.span("compile/lower", fn=label):
        lowered = jitted.lower(*args)
    with tracing.span("compile/backend_compile", fn=label):
        return lowered.compile()


def get_or_compile(jitted, args, *, context: Tuple = (), label: str = "step"):
    """Registry-memoized :func:`aot_compile`.

    The key is (fn identity, context, abstract avals of ``args``); the
    entry holds a strong ref to ``jitted`` so a recycled ``id()`` can
    never alias a dead function's executables.  The registry is bounded
    at :data:`REGISTRY_MAX_ENTRIES` (FIFO eviction — an evicted fit
    falls back to jit's own cache or one recompile);
    :func:`clear_registry` drops everything.
    """
    key = (id(jitted), context, _args_key(args))
    with _registry_lock:
        entry = _registry.get(key)
    if entry is not None and entry[0] is jitted:
        metrics.counter_inc("compile/registry_hit")
        return entry[1]
    metrics.counter_inc("compile/registry_miss")
    compiled = aot_compile(jitted, *args, label=label)
    with _registry_lock:
        while len(_registry) >= REGISTRY_MAX_ENTRIES:
            _registry.pop(next(iter(_registry)))
        _registry[key] = (jitted, compiled)
    return compiled


def clear_registry() -> None:
    with _registry_lock:
        _registry.clear()


def registry_size() -> int:
    with _registry_lock:
        return len(_registry)


class AotStep:
    """Dispatch wrapper: the AOT executable when inputs match, jit otherwise.

    A compiled executable rejects mismatched input avals with a
    ``TypeError`` *before* executing (donated buffers are untouched), so
    the fallback costs nothing on the happy path — no per-dispatch shape
    walk, just one try.  The first mismatch permanently reverts this
    wrapper to the jitted function (shapes are stable within a fit; a
    mismatch means the caller moved on to different shapes, where jit's
    own cache is the right home).
    """

    __slots__ = ("jitted", "label", "_compiled")

    def __init__(self, jitted, label: str = "step"):
        self.jitted = jitted
        self.label = label
        self._compiled = None

    @property
    def compiled(self):
        return self._compiled

    def attach(self, compiled) -> None:
        self._compiled = compiled

    def __call__(self, *args):
        compiled = self._compiled
        if compiled is not None:
            try:
                return compiled(*args)
            except TypeError as exc:
                logger.warning(
                    "compile-ahead executable for %s rejected its inputs "
                    "(%s); falling back to jit dispatch", self.label, exc,
                )
                self._compiled = None
        return self.jitted(*args)


# --------------------------------------------------------------------------
# Background compile-ahead


class CompileAhead:
    """A fit's background-compile plan: AotStep wrappers + the worker.

    ``wait(label)`` blocks until that ONE job has compiled (spanned as
    ``compile/ahead_wait`` — with prefetch warming in parallel this is ~0
    by the time the first window arrives, which is the whole point); jobs
    queued after it — the eval step rides behind the train step — keep
    compiling in the background and never delay the first dispatch.
    ``wait()`` with no label joins the whole worker.  A compile failure
    is recorded in ``error`` and logged, never raised: the wrappers
    simply stay on the jit path.
    """

    def __init__(self, steps: Dict[str, AotStep]):
        self.steps = steps
        self.error: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None
        self._done = {label: threading.Event() for label in steps}

    def _launch(self, jobs) -> None:
        def worker():
            for aot_step, args, ctx in jobs:
                try:
                    if callable(args):
                        # Deferred avals (e.g. the eval job peeking the
                        # validation data's first batch): resolved HERE,
                        # off the main thread, so a slow pipeline never
                        # delays the jobs queued before it — or fit().
                        args = args()
                    if args is None:
                        continue  # thunk found nothing to compile against
                    aot_step.attach(get_or_compile(
                        aot_step.jitted, args, context=ctx,
                        label=aot_step.label,
                    ))
                except BaseException as exc:  # noqa: BLE001 — advisory only
                    self.error = exc
                    logger.warning(
                        "compile-ahead of %s failed (%s); that step will "
                        "compile on first dispatch instead",
                        aot_step.label, exc,
                    )
                finally:
                    self._done[aot_step.label].set()

        self._thread = threading.Thread(
            target=worker, daemon=True, name="cloud-tpu-compile-ahead"
        )
        self._thread.start()

    def wait(self, label: Optional[str] = None,
             timeout: Optional[float] = None) -> None:
        if label is not None:
            event = self._done.get(label)
            if event is None or event.is_set():
                return
            with tracing.span("compile/ahead_wait", fn=label):
                event.wait(timeout)
            return
        thread = self._thread
        if thread is None or not thread.is_alive():
            return
        with tracing.span("compile/ahead_wait"):
            thread.join(timeout)


def start_compile_ahead(jobs) -> CompileAhead:
    """Launch a background compile of ``jobs``.

    ``jobs`` is a list of ``(AotStep, abstract_args, context_key)``
    triples; compilation happens strictly in order on one worker thread
    (XLA compiles hold the CPU — parallel compiles would fight the
    prefetcher for cores without finishing sooner).  ``abstract_args``
    may instead be a zero-arg callable, resolved on the worker right
    before that job compiles (return None to skip the job) — for avals
    that themselves cost a blocking peek, like the eval step's
    validation batch.
    """
    steps = {job[0].label: job[0] for job in jobs}
    plan = CompileAhead(steps)
    plan._launch(jobs)
    return plan


# --------------------------------------------------------------------------
# Safe persistent cache

_persist_lock = threading.Lock()
_persist_state: Dict[str, Any] = {"checked": False, "enabled": False,
                                  "dir": None}

#: The child probe: a trainer-shaped jitted step (dict pytree, grad,
#: donation — the executable class whose (de)serialization corrupted the
#: heap on jaxlib 0.4.36/0.4.37) compiled once to POPULATE the on-disk
#: cache, then recompiled from disk after dropping the in-memory caches,
#: executed, and numerically compared.  Heap corruption anywhere in that
#: round-trip kills the child (SIGSEGV / glibc abort), which is exactly
#: the signal: only a clean exit + the OK marker enables the cache
#: in-process.  Runs on CPU (JAX_PLATFORMS pinned by the parent) so the
#: probe never contends with the training process for the accelerator —
#: the (de)serialization path under test is host-side.
_PROBE_SOURCE = """
import sys
import jax
import jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir", sys.argv[1])
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
try:
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
except Exception:
    pass


def step(state, batch):
    def loss(w):
        return ((batch["x"] @ w - batch["y"]) ** 2).mean()

    g = jax.grad(loss)(state["w"])
    return {"w": state["w"] - 0.1 * g}


jitted = jax.jit(step, donate_argnums=0)
batch = {"x": jnp.ones((8, 4)), "y": jnp.ones((8, 2))}
want = jitted({"w": jnp.zeros((4, 2))}, batch)["w"]
jax.clear_caches()  # drop in-memory caches: the next compile reads DISK
got = jitted({"w": jnp.zeros((4, 2))}, batch)["w"]
assert bool(jnp.allclose(got, jnp.asarray(want))), "round-trip changed numerics"
print("CLOUD_TPU_CACHE_PROBE_OK")
"""

_PROBE_OK_MARKER = "CLOUD_TPU_CACHE_PROBE_OK"


def _probe_marker_path(cache_dir: str) -> str:
    import jax
    import jaxlib

    return os.path.join(
        cache_dir,
        f".cloud_tpu_probe_ok-jax{jax.__version__}-jaxlib{jaxlib.__version__}",
    )


def _run_probe_child(cache_dir: str, timeout: float) -> Tuple[int, str]:
    """Run the round-trip probe in a child; returns (returncode, stdout).

    The child inherits the environment minus accelerator claims
    (JAX_PLATFORMS=cpu) so it cannot steal the TPU from the process that
    is about to train.  Any crash — the failure mode under test — is a
    nonzero returncode here, not a dead training job.
    """
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _PROBE_SOURCE, cache_dir],
            capture_output=True, text=True, timeout=timeout, env=env,
        )
    except subprocess.TimeoutExpired:
        return -1, "probe timed out"
    except OSError as exc:
        return -1, f"probe failed to launch: {exc}"
    out = (proc.stdout or "") + (proc.stderr or "")
    return proc.returncode, out


def maybe_enable_persistent_cache(
    cache_dir: Optional[str] = None,
    *,
    force: Optional[bool] = None,
    probe_timeout: float = 120.0,
) -> bool:
    """Enable jax's on-disk compilation cache iff it is provably safe here.

    Reads ``CLOUD_TPU_COMPILE_CACHE`` (or the explicit ``cache_dir``);
    unset / empty / ``off`` / ``0`` means disabled and this is a cheap
    no-op — safe to call from every ``Trainer.fit``.  The decision is
    made once per process and cached; pass a different explicit
    ``cache_dir`` to re-decide.

    Enablement requires, in order: (1) the jaxlib is not on
    :data:`KNOWN_BAD_JAXLIB` (override with
    ``CLOUD_TPU_COMPILE_CACHE_FORCE=1`` / ``force=True`` — the probe
    still runs); (2) the one-time child-process round-trip probe exits
    clean (a prior pass recorded in a per-jax-version marker file inside
    the cache dir short-circuits the child, which is what gives a SECOND
    process its warm start without paying the probe again).  Only then
    is the cache turned on in-process, with the min-compile-time
    threshold from ``CLOUD_TPU_COMPILE_CACHE_MIN_SECS`` (default 0:
    cache everything — these jobs are small and first-step latency is
    the metric).
    """
    explicit = cache_dir is not None
    if cache_dir is None:
        cache_dir = os.environ.get(ENV_COMPILE_CACHE, "")
    if not cache_dir or cache_dir.strip().lower() in ("off", "0", "false"):
        return False
    with _persist_lock:
        if _persist_state["checked"] and (
            not explicit or _persist_state["dir"] == cache_dir
        ):
            return _persist_state["enabled"]

    if force is None:
        force = os.environ.get(ENV_COMPILE_CACHE_FORCE, "").lower() in (
            "1", "true"
        )
    import jaxlib

    if jaxlib.__version__ in KNOWN_BAD_JAXLIB and not force:
        logger.warning(
            "%s=%s ignored: jaxlib %s executable (de)serialization is "
            "known memory-unsafe (set %s=1 to probe anyway)",
            ENV_COMPILE_CACHE, cache_dir, jaxlib.__version__,
            ENV_COMPILE_CACHE_FORCE,
        )
        with _persist_lock:
            _persist_state.update(checked=True, enabled=False, dir=cache_dir)
        return False

    try:
        os.makedirs(cache_dir, exist_ok=True)
    except OSError as exc:
        logger.warning("compile cache dir %s unusable: %s", cache_dir, exc)
        with _persist_lock:
            _persist_state.update(checked=True, enabled=False, dir=cache_dir)
        return False

    marker = _probe_marker_path(cache_dir)
    if not os.path.exists(marker):
        with tracing.span("compile/cache_probe"):
            rc, out = _run_probe_child(cache_dir, probe_timeout)
        if rc != 0 or _PROBE_OK_MARKER not in out:
            logger.warning(
                "persistent compile cache DISABLED: round-trip probe "
                "failed (rc=%s): %s", rc, out.strip()[-500:],
            )
            metrics.counter_inc("compile/cache_probe_failed")
            with _persist_lock:
                _persist_state.update(
                    checked=True, enabled=False, dir=cache_dir
                )
            return False
        try:
            with open(marker, "w", encoding="utf-8") as f:
                f.write(out.strip()[:200] + "\n")
        except OSError:
            pass  # marker is an optimization; next process re-probes

    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir)
    try:
        min_secs = float(os.environ.get(ENV_COMPILE_CACHE_MIN_SECS, "0"))
    except ValueError:
        min_secs = 0.0
    try:
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          min_secs)
    except Exception:  # noqa: BLE001 — knob name varies across jax versions
        pass
    try:
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:  # noqa: BLE001
        pass
    logger.info("persistent compile cache enabled at %s", cache_dir)
    metrics.counter_inc("compile/cache_enabled")
    with _persist_lock:
        _persist_state.update(checked=True, enabled=True, dir=cache_dir)
    return True


def persistent_cache_enabled() -> bool:
    with _persist_lock:
        return bool(_persist_state["enabled"])


def _reset_persistent_state_for_tests() -> None:
    """Forget the once-per-process decision AND restore jax's defaults."""
    import jax

    with _persist_lock:
        was_enabled = _persist_state["enabled"]
        _persist_state.update(checked=False, enabled=False, dir=None)
    if was_enabled:
        jax.config.update("jax_compilation_cache_dir", None)
        try:
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 1.0
            )
        except Exception:  # noqa: BLE001
            pass
        try:
            jax.config.update(
                "jax_persistent_cache_min_entry_size_bytes", 0
            )
        except Exception:  # noqa: BLE001
            pass
