"""Async checkpointing via Orbax, with chief-aware save semantics.

The reference delegated checkpointing to user code with chief-only save
paths and non-chief throwaway dirs (cloud_fit/remote.py:130-145,
testdata/save_and_load.py).  Orbax handles multi-host coordination natively
(every process participates in writing its shards), so the "throwaway dir"
dance disappears; what remains chief-only is bookkeeping like metric files.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Optional

from cloud_tpu.monitoring import metrics, tracing
from cloud_tpu.utils import faults

logger = logging.getLogger(__name__)


class CheckpointManager:
    """Thin wrapper over orbax.checkpoint.CheckpointManager.

    Keeps the framework's surface stable if orbax's API shifts, and adds
    the trainer Callback adapter.
    """

    def __init__(self, directory: str, *, max_to_keep: int = 3,
                 save_interval_steps: int = 1):
        import orbax.checkpoint as ocp

        self._directory = os.fspath(directory)
        self._manager = ocp.CheckpointManager(
            self._directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                enable_async_checkpointing=True,
            ),
        )

    @property
    def directory(self) -> str:
        return self._directory

    def save(self, step: int, state: Any) -> bool:
        import orbax.checkpoint as ocp

        # Async checkpointing: the span covers the blocking half (host
        # gather + handoff), which is exactly the cost training pays.
        with tracing.span("checkpoint/save", step=int(step)):
            # Chaos seam: a crashed/hung save surfaces here — the same
            # place a full disk or a GCS outage would.
            faults.fault_point("checkpoint.save")
            return self._manager.save(step, args=ocp.args.StandardSave(state))

    def restore(self, step: Optional[int] = None, *, template: Any = None):
        import orbax.checkpoint as ocp

        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"No checkpoints in {self._directory}")
        with tracing.span("checkpoint/restore", step=int(step)):
            faults.fault_point("checkpoint.restore")
            if template is not None:
                return self._manager.restore(
                    step, args=ocp.args.StandardRestore(template)
                )
            return self._manager.restore(step)

    def latest_step(self) -> Optional[int]:
        return self._manager.latest_step()

    def wait(self) -> None:
        self._manager.wait_until_finished()

    def close(self) -> None:
        self._manager.close()


def resume_trainer_state(trainer, manager: CheckpointManager, *,
                         only_if_ahead: bool = True) -> bool:
    """Restore the latest checkpoint into ``trainer.state``.

    The ONE shared resume recipe (used by :class:`CheckpointCallback` and
    cloud_fit's server): restores WITHOUT the rng leaf — a checkpoint
    written under the other ``stochastic`` setting has a different
    TrainState structure there, and a structure mismatch would otherwise
    fail the restore; the fresh state's key (or None) carries forward.
    The template keeps each leaf's shape/dtype/sharding, so a sharded
    state restores straight into its mesh layout.  Any restore failure
    logs and returns False (train from the fresh state) rather than
    killing the job at startup.

    ``only_if_ahead`` (the preemption-recovery default) skips a
    checkpoint not ahead of the current state.  cloud_fit passes False:
    a user-uploaded state saved at step 0 (pretrained weights for a
    fine-tune) must still replace the server's fresh init.
    """
    if trainer.state is None:
        return False
    latest = manager.latest_step()
    if latest is None:
        return False
    if only_if_ahead and latest <= int(trainer.state.step):
        return False
    current = trainer.state
    try:
        import jax

        template = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(
                x.shape, x.dtype, sharding=getattr(x, "sharding", None)
            ),
            current.replace(rng=None),
        )
        restored = manager.restore(latest, template=template)
        trainer.state = restored.replace(rng=current.rng)
        if int(current.step) == 0:
            # A resume REPLACING a step-0 init is either the intended
            # preemption recovery or a reused directory silently hijacking
            # a fresh experiment (ADVICE r4) — loud enough to notice,
            # with the opt-out spelled out.
            logger.warning(
                "resumed from checkpoint step %d in %r, REPLACING this "
                "run's fresh step-0 state; if this is a new experiment "
                "reusing an old directory, pass resume=False (or clear "
                "the directory)", latest, manager.directory,
            )
        else:
            logger.info("resumed from checkpoint step %d", latest)
        return True
    except Exception:  # noqa: BLE001 — fresh start beats a dead job
        logger.exception(
            "could not restore latest checkpoint (step %s); starting fresh",
            latest,
        )
        return False


class CheckpointCallback:
    """Trainer callback: save every N steps and at train end.

    ``resume=True`` (default) restores the latest checkpoint into
    ``trainer.state`` at train begin when one exists AND is ahead of the
    current state — the preemption-recovery contract: a recreated node
    re-runs the same script, whose fresh state is at step 0, and training
    continues from the last save instead of from scratch
    (``deploy.supervise_job`` docstring).  A fresh run with an empty
    directory is untouched, so the default is safe.  The restore template
    is the trainer's own state (same Trainer config => same TrainState
    structure).
    """

    def __init__(self, directory: str, *, every_n_steps: int = 100,
                 max_to_keep: int = 3, resume: bool = True):
        self.directory = directory
        self.every_n_steps = every_n_steps
        self.max_to_keep = max_to_keep
        self.resume = resume
        self._manager: Optional[CheckpointManager] = None

    # Lazily create the manager so the callback object stays cloudpickleable
    # before/after training (managers hold thread pools).
    def _get(self) -> CheckpointManager:
        if self._manager is None:
            self._manager = CheckpointManager(
                self.directory, max_to_keep=self.max_to_keep,
                save_interval_steps=self.every_n_steps,
            )
        return self._manager

    def __getstate__(self):
        state = dict(self.__dict__)
        state["_manager"] = None
        return state

    def _reset_manager_after_failure(self) -> None:
        """Orbax managers can wedge after a failed async save: count the
        failure, close best-effort, rebuild lazily on the next use."""
        metrics.counter_inc("checkpoint/save_failures")
        manager, self._manager = self._manager, None
        try:
            if manager is not None:
                manager.close()
        except Exception:  # noqa: BLE001 — already failing
            logger.debug("failed manager close", exc_info=True)

    def on_train_begin(self, trainer):
        if not self.resume or trainer.state is None:
            return
        resume_trainer_state(trainer, self._get())

    def on_epoch_begin(self, epoch, trainer): ...

    def on_step_end(self, step, logs, trainer):
        if step % self.every_n_steps == 0:
            try:
                self._get().save(step, trainer.state)
            except Exception:  # noqa: BLE001 — a periodic save is
                # redundancy, not the product: a transient failure
                # (full disk blip, GCS 503, injected chaos) must not
                # kill a healthy training job.  The next interval — and
                # the mandatory train-end save — retry with a fresh
                # manager; only those remaining failures are fatal.
                logger.exception(
                    "periodic checkpoint save at step %d failed; training "
                    "continues (next save at step %d)",
                    step, step + self.every_n_steps,
                )
                self._reset_manager_after_failure()

    def on_epoch_end(self, epoch, logs, trainer): ...

    def on_train_end(self, trainer):
        # The train-end save is the preemption drain's one shot at not
        # losing work: a single transient failure gets one retry with a
        # fresh manager before it is allowed to take the job down.
        try:
            manager = self._get()
            manager.save(int(trainer.state.step), trainer.state)
        except Exception:  # noqa: BLE001 — retried once, then strict
            logger.exception(
                "train-end checkpoint save failed; retrying once with a "
                "fresh manager"
            )
            self._reset_manager_after_failure()
            manager = self._get()
            manager.save(int(trainer.state.step), trainer.state)
        manager.wait()
        manager.close()
        self._manager = None
