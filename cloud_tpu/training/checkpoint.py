"""Async checkpointing via Orbax, with chief-aware save semantics and a
durable-resume layer: integrity manifests, verified walk-back restore,
and composite iterator/rng state.

The reference delegated checkpointing to user code with chief-only save
paths and non-chief throwaway dirs (cloud_fit/remote.py:130-145,
testdata/save_and_load.py).  Orbax handles multi-host coordination natively
(every process participates in writing its shards), so the "throwaway dir"
dance disappears; what remains chief-only is bookkeeping like metric files
— and this module's integrity manifests.

Durability model (docs/robustness.md "Durable resume"):

* Every completed save gets a **manifest** (``manifest.cloud-tpu.json``
  inside the step dir): per-file byte size + streamed crc32 over every
  file Orbax wrote.  The manifest is written with an atomic rename, so
  its presence IS the commit marker — a step without one was never
  proven durable.  Composite extras (iterator state) ride in a
  synchronous ``meta/`` sidecar that survives kills the manifest
  doesn't.
* Manifests are finalized when the async write is known complete: at the
  NEXT ``save()``, and at ``wait()``/``close()``.  A hard kill between a
  save and its finalize leaves that step unmanifested (restorable, but
  not verified).
* :meth:`CheckpointManager.verify` replays the manifest against disk —
  ``"verified"`` / ``"corrupt"`` / ``"unmanifested"`` — and
  :func:`resume_trainer_state` **walks back** latest→older until an
  intact step restores, quarantining corrupt/partial step dirs instead
  of throwing away all progress because the newest write died.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import threading
import time
from typing import Any, Dict, List, Optional

from cloud_tpu.monitoring import metrics, tracing
from cloud_tpu.utils import faults

logger = logging.getLogger(__name__)

#: The integrity manifest, inside each step dir.  Written via atomic
#: rename AFTER the async save completes: presence == commit marker.
MANIFEST_NAME = "manifest.cloud-tpu.json"

#: Sidecar dir (under the checkpoint root) holding per-step composite
#: extras — iterator state and friends — written SYNCHRONOUSLY at save
#: time (they must reflect the trainer's position at that step, and they
#: are tiny).  Kept outside the step dir because Orbax owns that layout
#: until the async write commits.
META_DIRNAME = "meta"

#: Where corrupt/partial step dirs are moved (never deleted in place:
#: quarantined dirs keep the forensics while getting out of the resume
#: path).  Pruned to the manager's ``max_to_keep``.
QUARANTINE_DIRNAME = "quarantine"

_VERIFIED = "verified"
_CORRUPT = "corrupt"
_UNMANIFESTED = "unmanifested"


def _is_chief() -> bool:
    try:
        from cloud_tpu.parallel import distributed

        return distributed.is_chief()
    except Exception:  # noqa: BLE001 — single-process until proven otherwise
        return True


#: Streaming-read granularity for manifest hashing: bounds peak memory
#: at one chunk regardless of how large an Orbax shard file is.
_HASH_CHUNK_BYTES = 8 * 1024 * 1024


def _file_crc32(path: str) -> "tuple":
    """(crc32, size) of a file, streamed in bounded chunks.

    zlib.crc32 (C-speed, incremental) rather than the records layer's
    one-shot crc32c: manifest files can be multi-GB Orbax shards, and
    reading them whole to hash would add an OOM-class allocation to the
    save path.  The algorithm is private to the manifest format.
    """
    import zlib

    crc = 0
    size = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(_HASH_CHUNK_BYTES)
            if not chunk:
                return crc, size
            crc = zlib.crc32(chunk, crc)
            size += len(chunk)


class CheckpointManager:
    """Thin wrapper over orbax.checkpoint.CheckpointManager.

    Keeps the framework's surface stable if orbax's API shifts, adds the
    trainer Callback adapter, and layers the durability contract on top:
    integrity manifests with a commit marker, ``verify()``, quarantine of
    damaged step dirs, and composite per-step extras (iterator state).
    """

    def __init__(self, directory: str, *, max_to_keep: int = 3,
                 save_interval_steps: int = 1):
        import orbax.checkpoint as ocp

        self._directory = os.fspath(directory)
        self._max_to_keep = max_to_keep
        self._manager = ocp.CheckpointManager(
            self._directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                enable_async_checkpointing=True,
            ),
        )
        #: Steps whose async save was started but whose manifest has not
        #: been written yet (finalized at next save / wait / close).
        self._pending_manifest: List[int] = []
        #: In-flight background manifest hashing (started at a save
        #: boundary once the previous async write is known complete, so
        #: the full-lineage read+crc overlaps training instead of
        #: stalling the step path; joined at the next boundary).
        self._finalize_thread: Optional[threading.Thread] = None

    @property
    def directory(self) -> str:
        return self._directory

    # -- local-path helpers (manifests are local-fs only for now; GCS
    # checkpoints stay unmanifested and restore through the legacy path).

    def _is_local(self) -> bool:
        return not self._directory.startswith("gs://")

    def _step_dir(self, step: int) -> str:
        return os.path.join(self._directory, str(int(step)))

    def _meta_path(self, step: int) -> str:
        return os.path.join(self._directory, META_DIRNAME, f"{int(step)}.json")

    def save(self, step: int, state: Any, *, extras: Optional[Dict] = None,
             force: bool = False) -> bool:
        """Start an async save; returns orbax's saved/skipped bool.

        ``extras`` is a small JSON-able dict saved alongside the step
        (composite checkpoint: the trainer's iterator state rides here)
        and handed back by :meth:`read_extras`.

        ``force=True`` bypasses orbax's ``save_interval_steps`` policy —
        the policy is modulo-based, so a save at an off-multiple step (a
        preemption-drain emergency save, a fused-dispatch window that
        CROSSED the interval without landing on a multiple) would
        otherwise be silently skipped.  A force at an already-saved step
        downgrades to the plain call (orbax raises StepAlreadyExists
        under force; without it the duplicate is a no-op).

        The span covers the blocking half of the async pipeline: waiting
        out the PREVIOUS save (and joining its manifest hash, which had
        the whole inter-save interval to finish in the background) plus
        the host gather/handoff of this one — exactly the cost a
        training step pays at a save boundary.
        """
        import orbax.checkpoint as ocp

        with tracing.span("checkpoint/save", step=int(step)):
            # Chaos seam: a crashed/hung save surfaces here — the same
            # place a full disk or a GCS outage would.
            faults.fault_point("checkpoint.save")
            # The previous async save is complete before orbax starts a
            # new one anyway; waiting explicitly first means the steps
            # handed to the background finalize below have known-final
            # files.
            self._manager.wait_until_finished()
            self._join_finalize()
            ready, self._pending_manifest = self._pending_manifest, []
            latest = self._manager.latest_step()
            if force and latest is not None and int(step) == int(latest):
                force = False
            try:
                saved = self._manager.save(
                    step, args=ocp.args.StandardSave(state), force=force,
                )
            except BaseException:
                # This save failing must not drop the COMPLETED earlier
                # steps' manifests with it: put them back so the next
                # save/wait/close (possibly on a rebuilt manager's
                # sibling) still commits them.
                self._pending_manifest = ready + self._pending_manifest
                raise
            if saved:
                self._write_meta(int(step), extras)
                self._pending_manifest.append(int(step))
            if ready:
                # Hash + commit the completed earlier saves on a worker:
                # a multi-GB lineage read must overlap training, not
                # extend this save's blocking half.
                self._start_finalize(ready)
            return saved

    def restore(self, step: Optional[int] = None, *, template: Any = None):
        import orbax.checkpoint as ocp

        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"No checkpoints in {self._directory}")
        with tracing.span("checkpoint/restore", step=int(step)):
            faults.fault_point("checkpoint.restore")
            if template is not None:
                return self._manager.restore(
                    step, args=ocp.args.StandardRestore(template)
                )
            return self._manager.restore(step)

    def latest_step(self) -> Optional[int]:
        return self._manager.latest_step()

    def steps(self) -> List[int]:
        """All step numbers currently on disk, ascending (re-read, so a
        quarantine or an out-of-band delete is reflected)."""
        try:
            self._manager.reload()
        except Exception:  # noqa: BLE001 — older orbax without reload()
            logger.debug("orbax manager reload failed", exc_info=True)
        return sorted(int(s) for s in self._manager.all_steps())

    def wait(self) -> None:
        self._manager.wait_until_finished()
        self._finalize_pending()

    def close(self) -> None:
        try:
            self.wait()
        except Exception:  # noqa: BLE001 — closing is best-effort
            logger.debug("wait-before-close failed", exc_info=True)
        self._manager.close()

    # -- manifest / verify / quarantine ---------------------------------

    def _write_meta(self, step: int, extras: Optional[Dict]) -> None:
        """Synchronous tiny sidecar: the composite extras must reflect
        the trainer's position AT the save call, and must survive a hard
        kill even if the manifest never commits."""
        if not extras or not self._is_local() or not _is_chief():
            return
        try:
            meta_dir = os.path.join(self._directory, META_DIRNAME)
            os.makedirs(meta_dir, exist_ok=True)
            tmp = self._meta_path(step) + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(extras, f)
            os.replace(tmp, self._meta_path(step))
        except Exception:  # noqa: BLE001 — extras are riders, not cargo
            logger.exception("could not write checkpoint extras for step %d",
                             step)

    def read_extras(self, step: int) -> Optional[Dict]:
        """The composite extras saved with ``step`` (None if absent)."""
        if not self._is_local():
            return None
        try:
            with open(self._meta_path(step), encoding="utf-8") as f:
                return json.load(f)
        except FileNotFoundError:
            return None
        except (OSError, ValueError):
            logger.warning("unreadable checkpoint extras for step %d", step,
                           exc_info=True)
            return None

    def _manifest_path(self, step: int) -> str:
        return os.path.join(self._step_dir(step), MANIFEST_NAME)

    def _join_finalize(self) -> None:
        thread, self._finalize_thread = self._finalize_thread, None
        if thread is not None:
            thread.join()

    def _start_finalize(self, steps: List[int]) -> None:
        # The meta prune must spare steps whose save is still in flight:
        # their dir is an orbax tmp name until the async write commits,
        # but their sidecar is already on disk.
        keep = frozenset(str(s) for s in steps) | frozenset(
            str(s) for s in self._pending_manifest
        )
        thread = threading.Thread(
            target=self._finalize_steps, args=(steps, keep), daemon=True,
            name="cloud-tpu-ckpt-manifest",
        )
        self._finalize_thread = thread
        thread.start()

    def _finalize_pending(self) -> None:
        """Synchronously commit every outstanding manifest (wait/close:
        the durability barrier before the process may exit)."""
        self._join_finalize()
        pending, self._pending_manifest = self._pending_manifest, []
        self._finalize_steps(pending, frozenset(str(s) for s in pending))

    def _finalize_steps(self, pending: List[int],
                        keep: frozenset = frozenset()) -> None:
        """Write manifests for saves whose async write has completed.

        Only called with steps for which ``wait_until_finished`` has
        returned, so their files are final.  A manifest that cannot be
        written leaves its step unmanifested (restorable, unverified) —
        never kills training.  Chief-only: one process hashes, the
        manifest covers the whole (shared-fs) step dir.
        """
        if not self._is_local():
            return
        if not _is_chief():
            return
        for step in pending:
            step_dir = self._step_dir(step)
            if not os.path.isdir(step_dir):
                continue  # save failed or was GC'd already
            try:
                manifest = self._build_manifest(step, step_dir)
                # Chaos seam: a commit that dies here leaves the step
                # unmanifested — exactly what a kill at this instant does.
                faults.fault_point("checkpoint.commit")
                tmp = self._manifest_path(step) + ".tmp"
                with open(tmp, "w", encoding="utf-8") as f:
                    json.dump(manifest, f)
                os.replace(tmp, self._manifest_path(step))  # commit marker
            except Exception:  # noqa: BLE001 — durability layer must not
                # take training down; the step just stays uncommitted.
                logger.exception(
                    "could not commit manifest for checkpoint step %d", step
                )
        if pending:
            self._prune_meta(keep)

    def _build_manifest(self, step: int, step_dir: str) -> Dict:
        entries: Dict[str, Dict[str, int]] = {}
        for root, _dirs, files in os.walk(step_dir):
            for name in sorted(files):
                path = os.path.join(root, name)
                rel = os.path.relpath(path, step_dir)
                if rel == MANIFEST_NAME or rel == MANIFEST_NAME + ".tmp":
                    continue
                crc, size = _file_crc32(path)
                entries[rel] = {"bytes": size, "crc32": crc}
        return {"step": int(step), "committed": True, "entries": entries}

    def verify(self, step: int) -> str:
        """Replay the manifest against disk.

        Returns ``"verified"`` (manifest present, every entry's size and
        crc32 match), ``"corrupt"`` (manifest present but unreadable, an
        entry missing, or bytes changed), or ``"unmanifested"`` (no
        manifest — a pre-durability checkpoint, a GCS dir, or a save
        whose commit a hard kill interrupted).
        """
        status = self._verify_on_disk(step)
        # Chaos seam: a corrupt-mode rule can force any verdict.
        return faults.fault_point("checkpoint.verify", status)

    def _verify_on_disk(self, step: int) -> str:
        if not self._is_local():
            return _UNMANIFESTED
        path = self._manifest_path(step)
        if not os.path.exists(path):
            return _UNMANIFESTED
        try:
            with open(path, encoding="utf-8") as f:
                manifest = json.load(f)
            entries = manifest["entries"]
        except (OSError, ValueError, KeyError):
            logger.warning("unreadable manifest for step %d", step,
                           exc_info=True)
            return _CORRUPT
        step_dir = self._step_dir(step)
        for rel, want in entries.items():
            file_path = os.path.join(step_dir, rel)
            try:
                crc, size = _file_crc32(file_path)
            except OSError:
                logger.warning("checkpoint step %d: missing entry %r",
                               step, rel)
                return _CORRUPT
            if size != want.get("bytes"):
                logger.warning(
                    "checkpoint step %d: %r is %d bytes, manifest says %s",
                    step, rel, size, want.get("bytes"),
                )
                return _CORRUPT
            if crc != want.get("crc32"):
                logger.warning("checkpoint step %d: %r fails its manifest "
                               "crc32", step, rel)
                return _CORRUPT
        return _VERIFIED

    def quarantine(self, step: int) -> bool:
        """Move a damaged step dir out of the resume path.

        The dir lands under ``quarantine/`` (kept for forensics, pruned
        to ``max_to_keep`` entries oldest-first) and the orbax manager is
        reloaded so ``latest_step`` stops pointing at it.  Removing a
        walked-past step from the lineage is load-bearing, not hygiene:
        orbax skips any ``save(step)`` not ahead of ``latest_step``, so
        a stale newer dir left in place would silently disable every
        checkpoint save of the resumed job until it passed that step.

        Chief-only in multi-host jobs (one mover on the shared
        filesystem); non-chief processes just reload, so the chief's
        move is reflected in their step listing.
        """
        step_dir = self._step_dir(step)
        if not _is_chief():
            try:
                self._manager.reload()
            except Exception:  # noqa: BLE001
                logger.debug("orbax manager reload failed", exc_info=True)
            return not os.path.isdir(step_dir)
        if not os.path.isdir(step_dir):
            return False
        qdir = os.path.join(self._directory, QUARANTINE_DIRNAME)
        try:
            os.makedirs(qdir, exist_ok=True)
            dst = os.path.join(
                qdir, f"step-{int(step)}-{int(time.time() * 1000)}"
            )
            shutil.move(step_dir, dst)
        except OSError:
            logger.exception("could not quarantine checkpoint step %d; "
                             "deleting instead", step)
            try:
                shutil.rmtree(step_dir)
            except OSError:
                logger.exception("could not delete checkpoint step %d", step)
                return False
        metrics.counter_inc("checkpoint/quarantined")
        try:
            meta = self._meta_path(step)
            if os.path.exists(meta):
                os.remove(meta)
        except OSError:
            logger.debug("meta cleanup failed for step %d", step,
                         exc_info=True)
        self._gc_quarantine(qdir)
        try:
            self._manager.reload()
        except Exception:  # noqa: BLE001 — stale cache only affects
            # latest_step hints; steps() re-reads anyway.
            logger.debug("orbax manager reload failed", exc_info=True)
        logger.warning("quarantined checkpoint step %d under %s", step, qdir)
        return True

    def _gc_quarantine(self, qdir: str) -> None:
        # Prune by QUARANTINE time, not dir mtime: shutil.move preserves
        # the step dir's original mtime, so an ancient step quarantined
        # just now would sort oldest and delete the very forensics being
        # collected.  quarantine() embeds its wall-clock (ms) in the dst
        # name; dirs without the suffix fall back to mtime (same unit).
        def _quarantined_at(entry: str) -> float:
            tail = entry.rsplit("-", 1)[-1]
            if tail.isdigit():
                return float(tail)
            return os.path.getmtime(os.path.join(qdir, entry)) * 1000.0

        try:
            entries = sorted(
                (e for e in os.listdir(qdir)
                 if os.path.isdir(os.path.join(qdir, e))),
                key=_quarantined_at,
            )
            for stale in entries[:-self._max_to_keep or None]:
                shutil.rmtree(os.path.join(qdir, stale), ignore_errors=True)
        except OSError:
            logger.debug("quarantine GC failed", exc_info=True)

    def _prune_meta(self, keep: frozenset = frozenset()) -> None:
        """Drop extras sidecars for steps no longer on disk (orbax's
        max_to_keep GC removes the step dirs; the riders go with them).
        Reads the filesystem directly — this may run on the finalize
        worker, and poking the orbax manager from a second thread while
        a save is in flight is not safe.  ``keep`` lists steps whose
        async save may not have committed its (still tmp-named) dir yet
        but whose sidecar is already written."""
        meta_dir = os.path.join(self._directory, META_DIRNAME)
        if not os.path.isdir(meta_dir):
            return
        try:
            live = {name for name in os.listdir(self._directory)
                    if name.isdigit()} | set(keep)
            for name in os.listdir(meta_dir):
                stem, ext = os.path.splitext(name)
                if ext == ".json" and stem not in live:
                    os.remove(os.path.join(meta_dir, name))
        except OSError:
            logger.debug("meta prune failed", exc_info=True)


def _state_template(state):
    import jax

    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(
            x.shape, x.dtype, sharding=getattr(x, "sharding", None)
        ),
        state,
    )


def _restore_matching_rng(manager: CheckpointManager, step: int, current):
    """Restore ``step`` into the current TrainState's structure.

    A stochastic state (``current.rng`` not None) first tries the full
    template so the saved rng chain comes back bit-exact; a checkpoint
    written without the rng leaf (the other ``stochastic`` setting, or a
    pre-durability save) falls back to the rng-less template with the
    fresh state's key carried forward — the legacy contract.
    """
    if current.rng is not None:
        try:
            restored = manager.restore(
                step, template=_state_template(current)
            )
            if restored.rng is None:
                # A checkpoint saved WITHOUT the rng leaf (deterministic
                # run, or a pre-durability save) restores leniently with
                # an empty rng: carry the fresh key forward — the legacy
                # stochastic-flip contract.
                restored = restored.replace(rng=current.rng)
            return restored
        except Exception:  # noqa: BLE001 — structure mismatch: no rng leaf
            logger.info(
                "checkpoint step %d restore with rng template failed; "
                "retrying without (the rng chain restarts from the fresh "
                "key)", step, exc_info=True,
            )
    restored = manager.restore(
        step, template=_state_template(current.replace(rng=None))
    )
    return restored.replace(rng=current.rng)


def _note_fallback(step: int, reason: str) -> None:
    metrics.counter_inc("checkpoint/fallbacks")
    now = time.perf_counter()
    tracing.record_span("checkpoint/fallback", now, now, step=int(step),
                        reason=reason)


def resume_trainer_state(trainer, manager: CheckpointManager, *,
                         only_if_ahead: bool = True,
                         apply_data_state: bool = False,
                         quarantine: bool = True) -> bool:
    """Restore the newest INTACT checkpoint into ``trainer.state``.

    The ONE shared resume recipe (used by :class:`CheckpointCallback`,
    cloud_fit's server, and the non-finite rollback path).  Candidates
    are walked latest→older:

    * a step whose manifest fails :meth:`CheckpointManager.verify` is
      quarantined and skipped (``checkpoint/fallbacks`` counter +
      ``checkpoint/fallback`` span each time);
    * an unmanifested step (pre-durability save, or a commit a hard kill
      interrupted) is restored optimistically; if the restore raises it
      is quarantined as a partial write and the walk continues;
    * a VERIFIED step whose restore still raises (template mismatch, a
      transient) is quarantined too — its bytes stay available under
      ``quarantine/`` for forensics, but it cannot stay in the lineage:
      orbax refuses to save any step not ahead of ``latest_step``, so a
      walked-past step left in place would silently turn every
      subsequent save (periodic AND the preemption-drain save) into a
      no-op until the resumed job passed it.

    Only when every candidate fails does the function log "starting
    fresh" and return False — a single corrupt newest write no longer
    throws away the intact older checkpoints sitting next to it.

    The restore template is the trainer's own state, so each leaf keeps
    its shape/dtype/sharding (a sharded state restores straight into its
    mesh layout).  A stochastic state's rng chain restores bit-exactly
    when the checkpoint carries it (see :func:`_restore_matching_rng`).

    ``only_if_ahead`` (the preemption-recovery default) skips checkpoints
    not ahead of the current state.  cloud_fit passes False: a
    user-uploaded state saved at step 0 (pretrained weights for a
    fine-tune) must still replace the server's fresh init.

    ``apply_data_state=True`` additionally hands the checkpoint's saved
    iterator state (:meth:`CheckpointManager.read_extras`) to the
    trainer (``trainer._resume_data_state``), so the next ``fit()``
    resumes the data stream exactly where the restored step left it —
    the exactly-once contract ``CheckpointCallback(resume_data=True)``
    opts into.

    ``quarantine=False`` makes the walk-back purely read-only: failed
    candidates are skipped (counted + spanned) but never moved.  For a
    directory the caller does not own — cloud_fit restoring a USER'S
    uploaded state dir, a benchmark probe — relocating someone else's
    checkpoint on a restore hiccup would be data loss, and the
    stale-newer-step save trap the default guards against (see
    :meth:`CheckpointManager.quarantine`) only exists when this same
    directory later receives saves.
    """
    if trainer.state is None:
        return False
    current = trainer.state
    current_step = int(current.step)
    try:
        candidates = [
            s for s in sorted(manager.steps(), reverse=True)
            if not (only_if_ahead and s <= current_step)
        ]
    except Exception:  # noqa: BLE001 — unreadable dir: fresh start
        logger.exception("could not list checkpoints in %r",
                         manager.directory)
        return False
    if not candidates:
        return False
    for step in candidates:
        try:
            status = manager.verify(step)
        except Exception:  # noqa: BLE001 — chaos or IO error in verify
            logger.exception("checkpoint verify raised at step %d; "
                             "skipping it", step)
            _note_fallback(step, "verify_error")
            # A walked-past step must leave the lineage like every other
            # failure mode: left in place, a stale NEWER dir would make
            # orbax silently skip every save of the resumed run (its
            # bytes survive under quarantine/ if the error was benign).
            if quarantine:
                manager.quarantine(step)
            continue
        if status == _CORRUPT:
            logger.error(
                "checkpoint step %d failed integrity verification; "
                "walking back", step,
            )
            _note_fallback(step, "corrupt")
            if quarantine:
                manager.quarantine(step)
            continue
        try:
            restored = _restore_matching_rng(manager, step, current)
        except Exception:  # noqa: BLE001 — walk back instead of dying
            logger.exception(
                "could not restore checkpoint step %d (%s); walking back",
                step, status,
            )
            _note_fallback(step, "restore_failed")
            # Even a VERIFIED step must leave the lineage once walked
            # past (see quarantine() docstring: a stale newer step would
            # make orbax skip every save of the resumed run).
            if quarantine:
                manager.quarantine(step)
            continue
        trainer.state = restored
        if apply_data_state:
            extras = manager.read_extras(step) or {}
            data_state = extras.get("data_state")
            if isinstance(data_state, dict):
                trainer._resume_data_state = dict(data_state)
        if current_step == 0 and only_if_ahead:
            # A resume REPLACING a step-0 init is either the intended
            # preemption recovery or a reused directory silently hijacking
            # a fresh experiment (ADVICE r4) — loud enough to notice,
            # with the opt-out spelled out.
            logger.warning(
                "resumed from checkpoint step %d in %r, REPLACING this "
                "run's fresh step-0 state; if this is a new experiment "
                "reusing an old directory, pass resume=False (or clear "
                "the directory)", step, manager.directory,
            )
        else:
            logger.info("resumed from checkpoint step %d (%s)", step, status)
        return True
    logger.error(
        "no intact checkpoint in %r (%d candidate(s) failed verification "
        "or restore); starting fresh", manager.directory, len(candidates),
    )
    return False


class CheckpointCallback:
    """Trainer callback: save every N steps and at train end.

    ``resume=True`` (default) restores the newest intact checkpoint into
    ``trainer.state`` at train begin when one exists AND is ahead of the
    current state — the preemption-recovery contract: a recreated node
    re-runs the same script, whose fresh state is at step 0, and training
    continues from the last save instead of from scratch
    (``deploy.supervise_job`` docstring).  A fresh run with an empty
    directory is untouched, so the default is safe.  The restore template
    is the trainer's own state (same Trainer config => same TrainState
    structure), and a corrupt newest checkpoint walks back to an older
    intact one (:func:`resume_trainer_state`).

    ``resume_data=True`` opts into the exactly-once composite resume:
    each save carries the trainer's iterator position (epoch +
    consumed-batch index, counted at the trainer boundary) and a resumed
    ``fit()`` continues the data stream — and the rng chain — bit-exactly
    from the restored step, finishing the ORIGINAL epochs budget instead
    of running ``epochs`` fresh ones.  Off by default because it changes
    what ``fit(epochs=N)`` means after a restore (absolute position, not
    N more epochs).
    """

    def __init__(self, directory: str, *, every_n_steps: int = 100,
                 max_to_keep: int = 3, resume: bool = True,
                 resume_data: bool = False):
        self.directory = directory
        self.every_n_steps = every_n_steps
        self.max_to_keep = max_to_keep
        self.resume = resume
        self.resume_data = resume_data
        self._manager: Optional[CheckpointManager] = None
        #: Last step observed by on_step_end — fused dispatch (k>1)
        #: reports only window-boundary steps, so the periodic trigger
        #: fires on interval CROSSINGS, not on exact multiples.
        self._prev_step: Optional[int] = None

    # Lazily create the manager so the callback object stays cloudpickleable
    # before/after training (managers hold thread pools).
    def _get(self) -> CheckpointManager:
        if self._manager is None:
            self._manager = CheckpointManager(
                self.directory, max_to_keep=self.max_to_keep,
                save_interval_steps=self.every_n_steps,
            )
        return self._manager

    def __getstate__(self):
        state = dict(self.__dict__)
        state["_manager"] = None
        return state

    def _reset_manager_after_failure(self) -> None:
        """Orbax managers can wedge after a failed async save: count the
        failure, close best-effort, rebuild lazily on the next use."""
        metrics.counter_inc("checkpoint/save_failures")
        manager, self._manager = self._manager, None
        try:
            if manager is not None:
                manager.close()
        except Exception:  # noqa: BLE001 — already failing
            logger.debug("failed manager close", exc_info=True)

    @staticmethod
    def _extras(trainer) -> Optional[Dict]:
        data_state = getattr(trainer, "data_state", None)
        if not isinstance(data_state, dict):
            return None
        return {"data_state": dict(data_state)}

    def on_train_begin(self, trainer):
        if self.resume_data and not self._get()._is_local():
            # The meta/ sidecar carrying iterator state is local-fs only
            # (like the manifests): on a non-local directory the composite
            # resume silently loses its data half — say so loudly instead.
            logger.warning(
                "CheckpointCallback(resume_data=True) on non-local %r: "
                "iterator state is NOT saved or restored there — a resumed "
                "fit restarts the data stream (exactly-once resume needs a "
                "local checkpoint directory)", self.directory,
            )
        if self.resume and trainer.state is not None:
            resume_trainer_state(trainer, self._get(),
                                 apply_data_state=self.resume_data)
        # Arm the interval-crossing detector AFTER a possible restore, so
        # a resumed run measures crossings from its restored step.
        self._prev_step = (
            int(trainer.state.step) if trainer.state is not None else None
        )

    def on_epoch_begin(self, epoch, trainer): ...

    def on_step_end(self, step, logs, trainer):
        # Fire when the interval was CROSSED, not only on exact
        # multiples: a fused dispatch (steps_per_dispatch=k) reports
        # steps k apart, and the modulo check alone would silently
        # degrade the save cadence to lcm(k, every_n_steps).
        prev, self._prev_step = self._prev_step, step
        every = self.every_n_steps
        on_multiple = step % every == 0
        crossed = on_multiple or (
            prev is not None and step // every > prev // every
        )
        if crossed:
            try:
                # force: an off-multiple crossing step would be skipped
                # by orbax's own modulo interval policy.
                self._get().save(step, trainer.state,
                                 extras=self._extras(trainer),
                                 force=not on_multiple)
            except Exception:  # noqa: BLE001 — a periodic save is
                # redundancy, not the product: a transient failure
                # (full disk blip, GCS 503, injected chaos) must not
                # kill a healthy training job.  The next interval — and
                # the mandatory train-end save — retry with a fresh
                # manager; only those remaining failures are fatal.
                logger.exception(
                    "periodic checkpoint save at step %d failed; training "
                    "continues (next save at step %d)",
                    step, step + self.every_n_steps,
                )
                self._reset_manager_after_failure()

    def on_epoch_end(self, epoch, logs, trainer): ...

    def rollback_state(self, trainer) -> bool:
        """Restore the newest intact checkpoint into ``trainer.state``,
        even if it is BEHIND the current step — the trainer's non-finite
        quarantine calls this to rewind a diverged run to its last
        verified state (the data stream keeps its current position: the
        batches that diverged it are not replayed)."""
        try:
            manager = self._get()
            manager.wait()  # an in-flight async save must land first
            return resume_trainer_state(
                trainer, manager, only_if_ahead=False, apply_data_state=False
            )
        except Exception:  # noqa: BLE001 — the caller terminates instead
            logger.exception("rollback restore failed")
            return False

    def on_train_end(self, trainer):
        state = getattr(trainer, "state", None)
        if state is None:
            # A fit aborted before producing state (resume crash, empty
            # dataset edge) still drains through on_train_end; dying HERE
            # would mask the original failure.
            logger.warning(
                "CheckpointCallback.on_train_end: trainer has no state "
                "(fit aborted before producing one); skipping final save"
            )
            return
        # The train-end save is the preemption drain's one shot at not
        # losing work: a single transient failure gets one retry with a
        # fresh manager before it is allowed to take the job down.
        extras = self._extras(trainer)
        try:
            manager = self._get()
            # force: the drain/final step is rarely a multiple of
            # every_n_steps, and orbax's modulo interval policy would
            # silently skip it — losing up to every_n_steps of work on
            # the one save that exists to prevent exactly that.
            manager.save(int(state.step), state, extras=extras, force=True)
        except Exception:  # noqa: BLE001 — retried once, then strict
            logger.exception(
                "train-end checkpoint save failed; retrying once with a "
                "fresh manager"
            )
            self._reset_manager_after_failure()
            manager = self._get()
            manager.save(int(state.step), state, extras=extras, force=True)
        manager.wait()
        manager.close()
        self._manager = None
