"""Async checkpointing via Orbax, with chief-aware save semantics.

The reference delegated checkpointing to user code with chief-only save
paths and non-chief throwaway dirs (cloud_fit/remote.py:130-145,
testdata/save_and_load.py).  Orbax handles multi-host coordination natively
(every process participates in writing its shards), so the "throwaway dir"
dance disappears; what remains chief-only is bookkeeping like metric files.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Optional

logger = logging.getLogger(__name__)


class CheckpointManager:
    """Thin wrapper over orbax.checkpoint.CheckpointManager.

    Keeps the framework's surface stable if orbax's API shifts, and adds
    the trainer Callback adapter.
    """

    def __init__(self, directory: str, *, max_to_keep: int = 3,
                 save_interval_steps: int = 1):
        import orbax.checkpoint as ocp

        self._directory = os.fspath(directory)
        self._manager = ocp.CheckpointManager(
            self._directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                enable_async_checkpointing=True,
            ),
        )

    def save(self, step: int, state: Any) -> bool:
        import orbax.checkpoint as ocp

        return self._manager.save(step, args=ocp.args.StandardSave(state))

    def restore(self, step: Optional[int] = None, *, template: Any = None):
        import orbax.checkpoint as ocp

        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"No checkpoints in {self._directory}")
        if template is not None:
            return self._manager.restore(
                step, args=ocp.args.StandardRestore(template)
            )
        return self._manager.restore(step)

    def latest_step(self) -> Optional[int]:
        return self._manager.latest_step()

    def wait(self) -> None:
        self._manager.wait_until_finished()

    def close(self) -> None:
        self._manager.close()


class CheckpointCallback:
    """Trainer callback: save every N steps and at train end."""

    def __init__(self, directory: str, *, every_n_steps: int = 100,
                 max_to_keep: int = 3):
        self.directory = directory
        self.every_n_steps = every_n_steps
        self.max_to_keep = max_to_keep
        self._manager: Optional[CheckpointManager] = None

    # Lazily create the manager so the callback object stays cloudpickleable
    # before/after training (managers hold thread pools).
    def _get(self) -> CheckpointManager:
        if self._manager is None:
            self._manager = CheckpointManager(
                self.directory, max_to_keep=self.max_to_keep,
                save_interval_steps=self.every_n_steps,
            )
        return self._manager

    def __getstate__(self):
        state = dict(self.__dict__)
        state["_manager"] = None
        return state

    def on_train_begin(self, trainer): ...
    def on_epoch_begin(self, epoch, trainer): ...

    def on_step_end(self, step, logs, trainer):
        if step % self.every_n_steps == 0:
            self._get().save(step, trainer.state)

    def on_epoch_end(self, epoch, logs, trainer): ...

    def on_train_end(self, trainer):
        manager = self._get()
        manager.save(int(trainer.state.step), trainer.state)
        manager.wait()
        manager.close()
        self._manager = None
