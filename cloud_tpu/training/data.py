"""Minimal input pipelines: in-memory arrays and synthetic data.

The at-scale TFRecord/GCS streaming pipeline is ``records.py`` (BASELINE
config 5: TFRecord wire framing, per-host shards, background prefetch);
this module covers the in-memory workloads the reference's golden scripts
used (keras.datasets arrays).
"""

from __future__ import annotations

from typing import Dict, Iterator, Optional

import numpy as np

from cloud_tpu.monitoring import tracing


class ArrayDataset:
    """Re-iterable batched dataset over a dict of equal-length arrays.

    ``dataset()`` yields dict batches — the zero-arg-callable contract the
    Trainer expects (fresh iterator per epoch).
    """

    def __init__(
        self,
        arrays: Dict[str, np.ndarray],
        batch_size: int,
        *,
        shuffle: bool = False,
        seed: int = 0,
        drop_remainder: bool = True,
    ):
        lengths = {k: len(v) for k, v in arrays.items()}
        if len(set(lengths.values())) != 1:
            raise ValueError(f"Unequal array lengths: {lengths}")
        self.arrays = arrays
        self.n = next(iter(lengths.values()))
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_remainder = drop_remainder
        self._rng = np.random.default_rng(seed)
        if batch_size > self.n:
            raise ValueError(f"batch_size {batch_size} > dataset size {self.n}")

    def __call__(self) -> Iterator[Dict[str, np.ndarray]]:
        with tracing.span("data/epoch_setup", shuffle=self.shuffle, n=self.n):
            order = np.arange(self.n)
            if self.shuffle:
                self._rng.shuffle(order)
        end = self.n - self.batch_size + 1 if self.drop_remainder else self.n
        for start in range(0, end, self.batch_size):
            # Span covers the gather/copy only, not the consumer's time
            # holding the generator suspended.
            with tracing.span("data/batch"):
                idx = order[start : start + self.batch_size]
                batch = {k: v[idx] for k, v in self.arrays.items()}
            yield batch

    def __len__(self) -> int:
        if self.drop_remainder:
            return self.n // self.batch_size
        return (self.n + self.batch_size - 1) // self.batch_size


def synthetic_tokens(
    *, vocab_size: int, seq_len: int, batch_size: int, num_batches: int,
    seed: int = 0,
) -> ArrayDataset:
    """Deterministic synthetic LM batches (benchmarks, smoke tests)."""
    rng = np.random.default_rng(seed)
    n = batch_size * num_batches
    tokens = rng.integers(0, vocab_size, size=(n, seq_len), dtype=np.int32)
    return ArrayDataset({"tokens": tokens}, batch_size)
