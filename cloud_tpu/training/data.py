"""Minimal input pipelines: in-memory arrays and synthetic data.

The at-scale TFRecord/GCS streaming pipeline is ``records.py`` (BASELINE
config 5: TFRecord wire framing, per-host shards, background prefetch);
this module covers the in-memory workloads the reference's golden scripts
used (keras.datasets arrays).

Both pipelines speak the **exactly-once resume contract**
(docs/robustness.md "Durable resume"): each epoch's shuffle order is a
pure function of ``(seed, epoch)``, and ``state_dict()`` /
``load_state_dict()`` let a restored trainer fast-forward the stream to
``{"epoch": E, "batches_consumed": B}`` — the position its checkpoint
recorded at the TRAINER boundary — and replay exactly the batches an
uninterrupted run would have seen from there.
"""

from __future__ import annotations

import logging
from typing import Dict, Iterator

import numpy as np

from cloud_tpu.monitoring import tracing

logger = logging.getLogger(__name__)


class ArrayDataset:
    """Re-iterable batched dataset over a dict of equal-length arrays.

    ``dataset()`` yields dict batches — the zero-arg-callable contract the
    Trainer expects (fresh iterator per epoch).

    Shuffle order is derived per epoch from ``(seed, epoch)`` (NOT from a
    persistent generator), so epoch E's order is reproducible without
    replaying epochs 0..E-1 — the property the exactly-once resume
    contract (``load_state_dict``) is built on.
    """

    def __init__(
        self,
        arrays: Dict[str, np.ndarray],
        batch_size: int,
        *,
        shuffle: bool = False,
        seed: int = 0,
        drop_remainder: bool = True,
    ):
        lengths = {k: len(v) for k, v in arrays.items()}
        if len(set(lengths.values())) != 1:
            raise ValueError(f"Unequal array lengths: {lengths}")
        self.arrays = arrays
        self.n = next(iter(lengths.values()))
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = int(seed)
        self.drop_remainder = drop_remainder
        self._epoch = 0  # epochs issued so far (next __call__ uses this)
        self._skip = 0   # one-shot batch fast-forward for the next epoch
        if batch_size > self.n:
            raise ValueError(f"batch_size {batch_size} > dataset size {self.n}")

    def state_dict(self) -> Dict[str, int]:
        """Reproducibility state (the trainer records the authoritative
        consumed-batch position; this is the dataset-side complement)."""
        return {"epoch": self._epoch, "seed": self.seed}

    def load_state_dict(self, state: Dict) -> None:
        """Fast-forward: the next iterator produces epoch
        ``state["epoch"]`` with its first ``state["batches_consumed"]``
        batches skipped; later iterators continue with epoch+1, ...  The
        positions come from a checkpoint's trainer-boundary count, so
        batches a prefetcher pulled but the trainer never consumed are
        NOT skipped.  A ``seed`` in the state is ADOPTED: epoch/batch
        positions only name the right batches under the shuffle order
        they were recorded in, so a restarted script constructed with a
        different seed must replay the checkpoint's order (loudly), not
        silently duplicate/skip data under its own."""
        self._adopt_seed(state)
        self._epoch = int(state.get("epoch", 0))
        self._skip = int(state.get("batches_consumed", 0))

    def _adopt_seed(self, state: Dict) -> None:
        saved = state.get("seed")
        if saved is not None and int(saved) != self.seed:
            logger.warning(
                "restored iterator position was recorded under shuffle "
                "seed %s but this dataset was built with seed %d; "
                "adopting the checkpoint's seed so the replayed stream "
                "is the one the position points into", saved, self.seed,
            )
            self.seed = int(saved)

    def __call__(self) -> Iterator[Dict[str, np.ndarray]]:
        # Epoch/skip are captured EAGERLY (not inside the generator), so
        # a prefetcher that creates the iterator but has not pulled yet
        # still advances the epoch counter deterministically.
        epoch = self._epoch
        self._epoch += 1
        skip, self._skip = self._skip, 0
        return self._iter_epoch(epoch, skip)

    def _iter_epoch(self, epoch: int, skip: int
                    ) -> Iterator[Dict[str, np.ndarray]]:
        with tracing.span("data/epoch_setup", shuffle=self.shuffle, n=self.n):
            order = np.arange(self.n)
            if self.shuffle:
                np.random.default_rng((self.seed, epoch)).shuffle(order)
        end = self.n - self.batch_size + 1 if self.drop_remainder else self.n
        for index, start in enumerate(range(0, end, self.batch_size)):
            if index < skip:
                continue
            # Span covers the gather/copy only, not the consumer's time
            # holding the generator suspended.
            with tracing.span("data/batch"):
                idx = order[start : start + self.batch_size]
                batch = {k: v[idx] for k, v in self.arrays.items()}
            yield batch

    def __len__(self) -> int:
        if self.drop_remainder:
            return self.n // self.batch_size
        return (self.n + self.batch_size - 1) // self.batch_size


def synthetic_tokens(
    *, vocab_size: int, seq_len: int, batch_size: int, num_batches: int,
    seed: int = 0,
) -> ArrayDataset:
    """Deterministic synthetic LM batches (benchmarks, smoke tests)."""
    rng = np.random.default_rng(seed)
    n = batch_size * num_batches
    tokens = rng.integers(0, vocab_size, size=(n, seq_len), dtype=np.int32)
    return ArrayDataset({"tokens": tokens}, batch_size)
