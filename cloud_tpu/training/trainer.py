"""Keras-fit-parity training loop with an explicit callback protocol.

The reference's UX contract is ``model.fit(...)`` running remotely with
user callbacks shipped via cloudpickle (cloud_fit client.py:173-180).  JAX
has no Keras fit, so this Trainer provides the equivalent surface:
epochs, steps, validation, History, and Callback hooks — all objects here
are cloudpickle-serializable by construction (no locks, no device arrays
held) so the cloud_fit path can ship them.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Callable, Dict, Iterable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from cloud_tpu.monitoring import tracing
from cloud_tpu.parallel.sharding import DEFAULT_RULES, ShardingRules
from cloud_tpu.training import compile_cache, pipeline_io, preemption
from cloud_tpu.training import train as train_lib
from cloud_tpu.utils import faults

logger = logging.getLogger(__name__)


class _PeekedIterator:
    """An iterator with its first item already pulled (compile-ahead peeks
    one batch to derive abstract avals, then the epoch loop must still
    consume it).  Delegates ``close`` so prefetch workers are joined."""

    _EMPTY = object()  # the peek found the source already exhausted

    def __init__(self, first, rest):
        self._first = first if first is not None else self._EMPTY
        self._rest = rest

    def __iter__(self):
        return self

    def __next__(self):
        first = self._first
        if first is self._EMPTY:
            # Never re-pull an exhausted source (a drained prefetch queue
            # has no more DONE sentinels to deliver).
            raise StopIteration
        if first is not None:
            # Hand the peeked item over WITHOUT keeping a reference: for
            # K>1 it is a whole placed super-batch — pinning it for the
            # epoch would hold K batches of device memory hostage.
            self._first = None
            return first
        return next(self._rest)

    def close(self):
        close = getattr(self._rest, "close", None)
        if close is not None:
            close()


class Callback:
    """Hook protocol (subset of Keras Callback the reference workloads use).

    ``on_step_end`` receives metrics as *device arrays* (materializing them
    with ``float()`` costs a host sync — do it sparingly); ``on_epoch_end``
    logs are already host floats.

    Cadence: with ``fit(steps_per_dispatch=K)`` and K > 1, ``on_step_end``
    fires once per fused K-step window — ``step`` is the global step at the
    window's end and ``logs`` are the window's on-device metric means.
    ``K=1`` (the default) keeps the exact per-step cadence.
    """

    def on_train_begin(self, trainer: "Trainer") -> None: ...
    def on_train_end(self, trainer: "Trainer") -> None: ...
    def on_epoch_begin(self, epoch: int, trainer: "Trainer") -> None: ...
    def on_epoch_end(self, epoch: int, logs: Dict[str, float],
                     trainer: "Trainer") -> None: ...
    def on_step_end(self, step: int, logs: Dict[str, float],
                    trainer: "Trainer") -> None: ...


class History(Callback):
    """Accumulates per-epoch metric means (Keras History analogue)."""

    def __init__(self):
        self.history: Dict[str, List[float]] = {}

    def on_epoch_end(self, epoch, logs, trainer):
        for key, value in logs.items():
            self.history.setdefault(key, []).append(float(value))


class _StepBoundaryMixin:
    """Shared cadence tracking for every-N-steps callbacks.

    With ``fit(steps_per_dispatch=K)`` the ``on_step_end`` hook only sees
    every K-th step number, so "every N steps" must mean "this window
    CROSSED a multiple of N" — a plain ``step % N`` would fire only at
    multiples of lcm(K, N).  For K=1 :meth:`_crossed` reduces to
    ``step % N == 0`` exactly.
    """

    _prev_step: Optional[int] = None

    def _seed_prev_step(self, trainer) -> None:
        state = getattr(trainer, "state", None)
        self._prev_step = int(state.step) if state is not None else None

    def _crossed(self, step: int, every_n: int) -> bool:
        prev = self._prev_step if self._prev_step is not None else step - 1
        self._prev_step = step
        return step // every_n > prev // every_n


class ProgressLogger(_StepBoundaryMixin, Callback):
    """Logs metrics every ``every_n_steps`` steps (window-aware)."""

    def __init__(self, every_n_steps: int = 50):
        self.every_n_steps = every_n_steps

    def on_train_begin(self, trainer):
        self._seed_prev_step(trainer)

    def on_step_end(self, step, logs, trainer):
        if self._crossed(step, self.every_n_steps):
            rendered = " ".join(
                f"{k}={float(v):.4f}" for k, v in sorted(logs.items())
            )
            logger.info("step %d: %s", step, rendered)


class EarlyStopping(Callback):
    """Stop training when a monitored metric stops improving.

    Keras-parity semantics (the reference shipped user EarlyStopping
    callbacks through cloud_fit's pickle path): ``monitor`` reads the
    epoch logs (use ``val_``-prefixed keys for validation metrics),
    ``patience`` counts non-improving epochs, ``restore_best_state``
    reinstates the best TrainState on stop (host copy, so it survives
    donated device buffers).

    ``restore_best_state`` snapshots per *improving* epoch: single-process
    states are gathered to host RAM (one full host copy each time, sparing
    HBM); multi-process pod-sharded states are NOT host-gatherable
    (device_get raises on non-addressable shards), so there the snapshot is
    an on-device copy — one extra state replica of HBM while training.
    Either way the restore re-commits the exact shardings it captured, so
    subsequent evaluate/checkpoint calls see an identically-placed state.
    """

    def __init__(self, monitor: str = "val_loss", *, min_delta: float = 0.0,
                 patience: int = 0, mode: str = "auto",
                 restore_best_state: bool = False):
        if mode not in ("auto", "min", "max"):
            raise ValueError(f"mode must be auto|min|max, got {mode!r}")
        self.monitor = monitor
        self.min_delta = abs(min_delta)
        self.patience = patience
        self.restore_best_state = restore_best_state
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self._sign = 1.0 if mode == "max" else -1.0
        self._best = -float("inf")
        self._wait = 0
        self._best_state = None
        # Mirror on_train_begin: a restore path that reaches on_train_end
        # without a completed on_train_begin (callback reused across fits,
        # or unpickled mid-run) must not hit AttributeError.
        self._best_shardings = None
        self.stopped_epoch: Optional[int] = None

    def on_train_begin(self, trainer):
        self._best = -float("inf")
        self._wait = 0
        self._best_state = None
        self._best_shardings = None
        self.stopped_epoch = None

    def on_epoch_end(self, epoch, logs, trainer):
        if self.monitor not in logs:
            logger.warning(
                "EarlyStopping: %r not in epoch logs %s", self.monitor,
                sorted(logs),
            )
            return
        current = self._sign * float(logs[self.monitor])
        if current > self._best + self.min_delta:
            self._best = current
            self._wait = 0
            if self.restore_best_state:
                # Snapshot the layout alongside the values: a bare
                # device_put on restore would commit everything replicated
                # on the default device, silently dropping the mesh layout
                # (and risking host/device OOM for fsdp-sharded states).
                self._best_shardings = jax.tree_util.tree_map(
                    lambda x: x.sharding, trainer.state
                )
                fully_addressable = all(
                    x.is_fully_addressable
                    for x in jax.tree_util.tree_leaves(trainer.state)
                )
                if fully_addressable:
                    self._best_state = jax.device_get(trainer.state)
                else:
                    # Pod-sharded: host gather would raise; keep a device
                    # copy (sharding rides along, survives donation).
                    self._best_state = jax.tree_util.tree_map(
                        lambda x: x.copy(), trainer.state
                    )
        else:
            self._wait += 1
            if self._wait > self.patience:
                self.stopped_epoch = epoch
                trainer.stop_training = True

    def on_train_end(self, trainer):
        if self.restore_best_state and self._best_state is not None:
            leaves = jax.tree_util.tree_leaves(self._best_state)
            if leaves and isinstance(leaves[0], jax.Array):
                trainer.state = self._best_state  # device copy, layout intact
            else:
                trainer.state = jax.device_put(
                    self._best_state, self._best_shardings
                )


class TerminateOnNaN(_StepBoundaryMixin, Callback):
    """Stop training the step a non-finite loss appears (Keras parity).

    Checks every step by default, like Keras — the cost is one host sync
    per check, which serializes host and device; long high-throughput runs
    that would rather amortize it can raise ``check_every_n_steps`` at the
    price of detecting a NaN up to that many steps late.  The stop reason
    lands in ``self.stopped_step`` and a log line, so a pod job that
    diverged fails fast and attributably instead of burning its remaining
    budget on NaNs.
    """

    def __init__(self, *, check_every_n_steps: int = 1):
        self.check_every_n_steps = max(1, check_every_n_steps)
        self.stopped_step: Optional[int] = None

    def on_train_begin(self, trainer):
        self.stopped_step = None
        self._seed_prev_step(trainer)

    def on_step_end(self, step, logs, trainer):
        if not self._crossed(step, self.check_every_n_steps):
            return
        loss = logs.get("loss")
        if loss is None:
            return
        if not np.isfinite(float(loss)):
            self.stopped_step = step
            trainer.stop_training = True
            logger.error(
                "TerminateOnNaN: non-finite loss %s at step %d; stopping",
                float(loss), step,
            )


class LambdaCallback(Callback):
    """Ad-hoc hooks, cloudpickle-friendly (reference ships these through
    cloud_fit, remote_test.py:41-53)."""

    def __init__(self, on_epoch_end: Optional[Callable] = None,
                 on_step_end: Optional[Callable] = None):
        self._on_epoch_end = on_epoch_end
        self._on_step_end = on_step_end

    def on_epoch_end(self, epoch, logs, trainer):
        if self._on_epoch_end:
            self._on_epoch_end(epoch, logs, trainer)

    def on_step_end(self, step, logs, trainer):
        if self._on_step_end:
            self._on_step_end(step, logs, trainer)


class Trainer:
    """Owns the compiled step functions and the epoch loop.

    Args:
      loss_fn: ``loss_fn(params, batch) -> (loss, metrics_dict)``.
      optimizer: optax transformation.
      init_fn: ``init_fn(rng) -> params`` (used by ``init_state``).
      mesh: parallelism mesh (None = single device).
      logical_axes: params-congruent pytree of logical axis tuples.
      rules: logical->mesh axis table.
      stochastic: thread a PRNG key through every train step —
        ``loss_fn(params, batch, rng=...)`` (dropout etc.).  Eval steps
        stay deterministic (no rng passed).  ``init_state`` derives the
        training key from its rng automatically.
      accum_steps: gradient accumulation — each train step splits its
        batch into this many micro-batches and applies ONE optimizer
        update with the mean gradient (train.make_train_step docstring).
      nonfinite_guard: build the step functions with the on-device
        non-finite quarantine (``train._build_step_fn`` docstring): a
        step whose loss/grads go NaN/Inf skips its optimizer update on
        device (params and opt_state pass through, the step counter
        still advances) and reports ``metrics["nonfinite"]``.  fit()
        counts skips (``train/nonfinite_skips``) and — with
        ``rollback_after_nonfinite`` — rolls a persistently diverged run
        back to its last verified checkpoint before stopping.
    """

    def __init__(
        self,
        loss_fn,
        optimizer,
        init_fn=None,
        *,
        mesh=None,
        logical_axes=None,
        rules: ShardingRules = DEFAULT_RULES,
        stochastic: bool = False,
        accum_steps: int = 1,
        nonfinite_guard: bool = False,
    ):
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.init_fn = init_fn
        self.mesh = mesh
        self.logical_axes = logical_axes
        self.rules = rules
        self.stochastic = stochastic
        self.accum_steps = accum_steps
        self.nonfinite_guard = nonfinite_guard
        self.state: Optional[train_lib.TrainState] = None
        self.stop_training = False
        #: True when the last fit() ended by preemption drain (the
        #: process-wide stop event, ``training.preemption``) rather than
        #: data exhaustion or a callback stop.
        self.drained = False
        #: The exactly-once data position, updated at every CONSUMPTION
        #: boundary (a batch counts as consumed only once its state
        #: update dispatched — prefetched-but-unconsumed batches are not
        #: marked done).  ``CheckpointCallback`` saves this alongside the
        #: TrainState; a restore sets ``_resume_data_state`` and the next
        #: fit() fast-forwards the dataset to match.
        self.data_state: Dict[str, int] = {"epoch": 0, "batches_consumed": 0}
        self._resume_data_state: Optional[Dict[str, int]] = None
        self._data_seed: Optional[int] = None
        self._train_step = train_lib.make_train_step(
            loss_fn, optimizer, logical_axes=logical_axes, rules=rules,
            mesh=mesh, stochastic=stochastic, accum_steps=accum_steps,
            skip_nonfinite=nonfinite_guard,
        )
        self._eval_step = train_lib.make_eval_step(loss_fn)
        # Fused K-step dispatches, built lazily per K (jit caches compile
        # per shape, so reusing the same callable across epochs/fits is
        # what keeps the multi-step path one-compile).
        self._multi_steps: Dict[int, Any] = {}

    def _drain_if_requested(self, step: int) -> bool:
        """Preemption-drain check, called at every dispatch boundary.

        When the process-wide stop event (``training.preemption`` — set
        by bootstrap's SIGTERM handler) is up, flip ``stop_training`` so
        the epoch loop exits cleanly and ``on_train_end`` fires —
        that's where ``CheckpointCallback`` saves the CURRENT step and
        waits the async write out, bounding lost work to one dispatch
        window.  Recorded once per fit as a ``preempt/drain`` span +
        counter so the robustness report shows the drain happened.
        """
        if not preemption.stop_requested():
            return False
        if not self.drained:
            self.drained = True
            from cloud_tpu.monitoring import metrics as metrics_lib

            metrics_lib.counter_inc("preempt/drains")
            now = time.perf_counter()
            tracing.record_span(
                "preempt/drain", now, now, step=step,
                reason=preemption.stop_reason() or "",
            )
            logger.warning(
                "preemption drain at step %d (%s): stopping to checkpoint",
                step, preemption.stop_reason(),
            )
        self.stop_training = True
        return True

    @staticmethod
    def _dataset_epoch(train_data, default: int) -> int:
        """The dataset-ABSOLUTE epoch its next iterator will use
        (``state_dict()['epoch']``), or ``default`` for datasets without
        resume hooks.  The saved position records absolute epochs: a
        dataset instance that was already iterated before this fit (a
        warmup fit on the same instance) has its shuffle order keyed by
        its own counter, not by this fit's epoch index — recording the
        fit-relative index would silently replay different batches after
        a restart."""
        fn = getattr(train_data, "state_dict", None)
        if fn is None:
            return default
        try:
            return int(fn().get("epoch", default))
        except Exception:  # noqa: BLE001 — positions degrade, fits don't
            logger.debug("dataset state_dict() failed", exc_info=True)
            return default

    @staticmethod
    def _dataset_seed(train_data):
        """The dataset's shuffle seed (``state_dict()['seed']``), or None
        for datasets without resume hooks.  Saved with the position: an
        epoch/batch index only names the right batches under the shuffle
        order it was recorded in, so a restarted script constructed with
        a different seed must be told (and the dataset's
        ``load_state_dict`` adopts the saved seed, loudly)."""
        fn = getattr(train_data, "state_dict", None)
        if fn is None:
            return None
        try:
            seed = fn().get("seed")
            return None if seed is None else int(seed)
        except Exception:  # noqa: BLE001 — positions degrade, fits don't
            logger.debug("dataset state_dict() failed", exc_info=True)
            return None

    def _position(self, epoch: int, consumed: int) -> Dict[str, int]:
        """A data_state dict: position plus (when known) the shuffle seed
        the position is valid under."""
        pos = {"epoch": int(epoch), "batches_consumed": int(consumed)}
        if self._data_seed is not None:
            pos["seed"] = self._data_seed
        return pos

    def _apply_data_resume(self, train_data, base_epoch: int) -> "tuple":
        """Consume a restored iterator state (set by a checkpoint resume
        with ``resume_data=True``): fast-forward the dataset and return
        ``(start_epoch, resume_skip)`` for the epoch loop.  The saved
        epoch is dataset-absolute; ``base_epoch`` (the dataset's counter
        at this fit's start — identical to the crashed run's, since the
        restarted script replayed the same pre-fit history) converts it
        back to this fit's budget position.  A dataset without
        ``load_state_dict`` logs and restarts its stream — the legacy
        behavior, never an error."""
        resume = self._resume_data_state
        self._resume_data_state = None
        if not resume:
            return 0, 0
        loader = getattr(train_data, "load_state_dict", None)
        if loader is None:
            logger.warning(
                "checkpoint carried iterator state %s but the dataset has "
                "no load_state_dict(); the data stream restarts from "
                "scratch (exactly-once resume needs a resumable dataset)",
                resume,
            )
            return 0, 0
        try:
            loader(dict(resume))
            abs_epoch = int(resume.get("epoch", 0))
            start_epoch = abs_epoch - base_epoch
            if start_epoch < 0:
                logger.warning(
                    "restored iterator state %s is behind the dataset's "
                    "current epoch %d; clamping to this fit's first epoch",
                    resume, base_epoch,
                )
                start_epoch = 0
            resume_skip = int(resume.get("batches_consumed", 0))
        except Exception:  # noqa: BLE001 — a broken fast-forward must
            # degrade to a fresh stream, not kill the recovered job.
            logger.exception(
                "could not fast-forward dataset to %s; the data stream "
                "restarts from scratch", resume,
            )
            return 0, 0
        logger.info(
            "resuming data stream at epoch %d, batch %d (exactly-once)",
            abs_epoch, resume_skip,
        )
        return start_epoch, resume_skip

    def _nonfinite_check(self, metrics, n_steps: int, step: int,
                         rollback_after: Optional[int], callbacks) -> bool:
        """Count on-device non-finite skips; roll back or stop on a
        persistent streak.  Returns True when a rollback replaced
        ``self.state`` (the caller re-reads its step counter).

        Costs one host sync per dispatch window — only when the Trainer
        was built with ``nonfinite_guard=True`` (same cost class as
        ``TerminateOnNaN``'s default every-step check).

        Also marks the window (``self._window_nonfinite``) so the epoch
        accumulator can exclude it: the guard keeps NaN out of the
        *state*, but the window's loss/grad metrics ARE NaN, and one
        poisoned window folded into the running sums would turn the
        whole epoch's logged means NaN — breaking exactly the
        monitoring (History, early-stop-on-loss) the quarantine exists
        to preserve.
        """
        self._window_nonfinite = False
        if not self.nonfinite_guard:
            return False
        flag = metrics.get("nonfinite")
        if flag is None:
            return False
        frac = float(flag)  # host sync — the guard's price
        if frac <= 0.0:
            self._nonfinite_streak = 0
            return False
        self._window_nonfinite = True
        from cloud_tpu.monitoring import metrics as metrics_lib

        skipped = max(1, int(round(frac * n_steps)))
        metrics_lib.counter_inc("train/nonfinite_skips", skipped)
        now = time.perf_counter()
        tracing.record_span("train/nonfinite_skip", now, now, step=step,
                            skipped=skipped)
        self._nonfinite_streak += 1
        logger.warning(
            "non-finite metrics at step %d: %d state update(s) skipped on "
            "device (consecutive bad windows: %d)",
            step, skipped, self._nonfinite_streak,
        )
        if not rollback_after or self._nonfinite_streak < rollback_after:
            return False
        if self._fit_rollbacks >= 1:
            logger.error(
                "non-finite streak persists after a rollback; stopping "
                "training at step %d", step,
            )
            self.stop_training = True
            return False
        provider = next(
            (cb for cb in callbacks if hasattr(cb, "rollback_state")), None
        )
        rolled = False
        if provider is not None:
            try:
                rolled = bool(provider.rollback_state(self))
            except Exception:  # noqa: BLE001 — fall through to terminate
                logger.exception("rollback to last checkpoint failed")
        if not rolled:
            logger.error(
                "%d consecutive non-finite windows and no checkpoint to "
                "roll back to; stopping training at step %d",
                self._nonfinite_streak, step,
            )
            self.stop_training = True
            return False
        self._fit_rollbacks += 1
        self._nonfinite_streak = 0
        metrics_lib.counter_inc("train/rollbacks")
        now = time.perf_counter()
        tracing.record_span("train/rollback", now, now, from_step=step,
                            to_step=int(self.state.step))
        logger.warning(
            "rolled back from step %d to verified checkpoint step %d after "
            "%d consecutive non-finite windows; continuing on fresh data",
            step, int(self.state.step), rollback_after,
        )
        return True

    def _multi_step_for(self, steps_per_dispatch: int):
        fn = self._multi_steps.get(steps_per_dispatch)
        if fn is None:
            fn = train_lib.make_multi_step(
                self.loss_fn, self.optimizer,
                steps_per_dispatch=steps_per_dispatch,
                logical_axes=self.logical_axes, rules=self.rules,
                mesh=self.mesh, stochastic=self.stochastic,
                accum_steps=self.accum_steps,
                skip_nonfinite=self.nonfinite_guard,
            )
            self._multi_steps[steps_per_dispatch] = fn
        return fn

    @staticmethod
    def _accumulate(sums: Dict[str, Any], metrics: Dict[str, Any],
                    n_steps: int) -> None:
        """Fold one step's (or one window's mean) metrics into running
        on-device f32 sums — a few scalar adds per window instead of an
        epoch-long list of pinned device buffers."""
        for key, value in metrics.items():
            contrib = value.astype(jnp.float32) if hasattr(
                value, "astype") else jnp.float32(value)
            if n_steps != 1:
                contrib = contrib * n_steps
            prev = sums.get(key)
            sums[key] = contrib if prev is None else prev + contrib

    def init_state(self, rng) -> train_lib.TrainState:
        if self.init_fn is None:
            raise ValueError("Trainer needs init_fn to create state")
        train_rng = None
        if self.stochastic:
            rng, train_rng = jax.random.split(rng)
        self.state = train_lib.create_sharded_state(
            rng, self.init_fn, self.optimizer, self.mesh,
            logical_axes=self.logical_axes, rules=self.rules,
            train_rng=train_rng,
        )
        return self.state

    def fit(
        self,
        train_data: Callable[[], Iterable],
        *,
        epochs: int = 1,
        steps_per_epoch: Optional[int] = None,
        validation_data: Optional[Callable[[], Iterable]] = None,
        callbacks: Optional[List[Callback]] = None,
        state: Optional[train_lib.TrainState] = None,
        steps_per_dispatch: int = 1,
        prefetch: int = 2,
        compile_ahead: bool = False,
        batch_spec=None,
        rollback_after_nonfinite: Optional[int] = None,
    ) -> History:
        """Run the training loop.

        ``train_data``/``validation_data`` are zero-arg callables returning a
        fresh batch iterator per epoch (re-iterable datasets).

        ``prefetch`` > 0 (default 2: double-buffering) runs host gather +
        device transfer in a background thread that many batches ahead of
        the device, for train AND validation data — pass 0 to keep the
        fully synchronous loop.  Datasets already wrapped in
        ``pipeline_io.prefetch_to_device`` are not wrapped twice.

        ``steps_per_dispatch=K`` > 1 fuses K train steps into ONE jit
        dispatch (``train.make_multi_step``): K consecutive batches are
        stacked into a super-batch and scanned on device, so per-step host
        overhead (dispatch, callback fan-out) amortizes K-fold.  The
        parameter trajectory is unchanged; the observable cadence is:
        ``on_step_end`` fires once per window with window-MEAN metrics
        (TerminateOnNaN therefore detects a NaN up to K-1 steps late).  A
        dataset tail shorter than K is zero-padded to the compiled window
        shape and dispatched through the SAME fused executable with the
        padded steps skipped on device (``sharding.pad_batch`` +
        ``make_multi_step``'s validity mask) — one compile covers the
        whole epoch, tail included, with exact metric parity.  ``K=1``
        preserves exact per-step semantics.

        ``compile_ahead=True`` compiles this fit's step executables
        (train or K-step fused, plus eval when ``validation_data`` is
        given) on a background thread WHILE the prefetcher warms, so the
        first dispatch finds a ready executable instead of paying
        lower+compile synchronously — first-step latency still lands in
        the ``run/submit_to_first_step_seconds`` gauge, now measuring
        overlap instead of a serial compile.  Abstract input avals come
        from the first prefetched batch, or from ``batch_spec`` (a pytree
        matching one HOST batch's ``.shape``/``.dtype``, e.g. numpy
        arrays or ``jax.ShapeDtypeStruct``s) when the data pipeline is
        slow to produce its first batch.  Executables are memoized in
        ``compile_cache``'s AOT registry, and a failure to compile ahead
        degrades to normal jit dispatch — never an error.

        ``rollback_after_nonfinite=K`` (requires a Trainer built with
        ``nonfinite_guard=True``) arms the divergence escape hatch: after
        K CONSECUTIVE dispatch windows whose on-device guard skipped a
        non-finite update, the trainer asks its checkpoint callback to
        roll ``state`` back to the last verified checkpoint
        (``train/rollbacks``) and continues on fresh data; a second
        K-streak — or no callback able to roll back — stops training
        (the existing terminate path).

        Exactly-once resume: when a checkpoint restore handed back a
        saved iterator state (``CheckpointCallback(resume_data=True)``),
        fit fast-forwards ``train_data`` via its ``load_state_dict`` to
        the restored epoch/batch position and continues the ORIGINAL
        epochs budget from there — together with the restored rng chain,
        the trajectory is bit-exactly the uninterrupted run's.
        """
        if steps_per_dispatch < 1:
            raise ValueError(
                f"steps_per_dispatch must be >= 1, got {steps_per_dispatch}"
            )
        if rollback_after_nonfinite is not None:
            if rollback_after_nonfinite < 1:
                raise ValueError(
                    "rollback_after_nonfinite must be >= 1, got "
                    f"{rollback_after_nonfinite}"
                )
            if not self.nonfinite_guard:
                raise ValueError(
                    "rollback_after_nonfinite needs a Trainer built with "
                    "nonfinite_guard=True (the on-device skip supplies the "
                    "signal the rollback trigger counts)"
                )
        # Env-gated persistent executable cache (CLOUD_TPU_COMPILE_CACHE):
        # a once-per-process probe + enable, a cheap no-op when unset.
        compile_cache.maybe_enable_persistent_cache()
        if state is not None:
            self.state = state
        if self.state is None:
            raise ValueError("No TrainState; call init_state() or pass state=")
        callbacks = list(callbacks or [])
        callbacks = self._with_runtime_metrics(callbacks)
        history = History()
        callbacks.append(history)
        self.stop_training = False
        self.drained = False
        self._nonfinite_streak = 0
        self._window_nonfinite = False
        self._fit_rollbacks = 0

        # on_train_begin runs BEFORE the data pipeline is wired: a
        # CheckpointCallback restore may replace self.state AND hand back
        # the checkpoint's iterator state, which must fast-forward the
        # dataset before any wrapper (or compile-ahead peek) pulls from it.
        for cb in callbacks:
            cb.on_train_begin(self)

        k = steps_per_dispatch
        # The dataset's epoch counter at fit start: saved positions are
        # recorded dataset-ABSOLUTE (base + fit-relative), so a restart
        # that replays the same pre-fit history (a warmup fit on the
        # same instance) fast-forwards to the right shuffle order.
        base_epoch = self._dataset_epoch(train_data, 0)
        start_epoch, resume_skip = self._apply_data_resume(
            train_data, base_epoch,
        )
        # Read AFTER the resume: load_state_dict may have adopted the
        # checkpoint's seed, and that adopted seed is what positions
        # saved from this fit are valid under.
        self._data_seed = self._dataset_seed(train_data)
        self.data_state = self._position(base_epoch + start_epoch,
                                         resume_skip)

        if k > 1 and pipeline_io.is_prefetched(train_data):
            raise ValueError(
                "steps_per_dispatch > 1 stacks HOST batches into a "
                "super-batch; pass the unwrapped dataset (fit prefetches "
                "whole windows itself)"
            )

        def build_source(limit):
            if k == 1:
                if prefetch > 0 and not pipeline_io.is_prefetched(train_data):
                    return pipeline_io.prefetch_to_device(
                        train_data, mesh=self.mesh, rules=self.rules,
                        size=prefetch, limit=limit,
                    )
                return train_data
            if prefetch > 0:
                return pipeline_io.prefetch_windows(
                    train_data, k, mesh=self.mesh, rules=self.rules,
                    size=prefetch, limit=limit,
                )
            return pipeline_io.iter_windows(
                train_data, k, mesh=self.mesh, rules=self.rules, limit=limit,
            )

        source = build_source(steps_per_epoch)
        # A mid-epoch resume epoch has a smaller remaining step budget:
        # its (one-shot) source must cap at what the interrupted epoch
        # has left, or the fused/prefetched pipelines would pull batches
        # the uninterrupted run never saw in that epoch.
        if resume_skip and steps_per_epoch is not None:
            first_source = build_source(max(steps_per_epoch - resume_skip, 0))
        else:
            first_source = source
        multi_step = self._multi_step_for(k) if k > 1 else None

        # Compile-ahead: spawn the background compile (against avals from
        # batch_spec or a peeked first batch) BEFORE the epoch loop, so it
        # overlaps the prefetcher warming.  The step callables are swapped
        # for AotStep wrappers that dispatch through the ready executable.
        train_step = self._train_step
        eval_step = None
        aot_plan = None
        peeked_iter = None
        # Captured BEFORE the compile-ahead peek creates the first
        # epoch's iterator (which advances the dataset's counter).
        peeked_abs_epoch = self._dataset_epoch(
            train_data, base_epoch + start_epoch,
        )
        if compile_ahead:
            aot_plan, peeked_iter = self._launch_compile_ahead(
                k, first_source, batch_spec,
                validation_data=validation_data,
                multi_step=multi_step,
            )
            if aot_plan is not None:
                if k == 1:
                    train_step = aot_plan.steps["train_step"]
                else:
                    multi_step = aot_plan.steps["multi_step"]
                eval_step = aot_plan.steps.get("eval_step")

        step = int(self.state.step)
        # The first DISPATCH of this fit() is where jit compilation happens
        # (host-side, synchronous): span it separately so compile cost is
        # attributable, and let a pending run() submit mark publish the
        # run/submit_to_first_step_seconds composite gauge.
        first_dispatch = True
        for epoch in range(start_epoch, epochs):
            if self.stop_training:
                break
            for cb in callbacks:
                cb.on_epoch_begin(epoch, self)
            # Windowed on-device accumulation: running f32 sums instead of
            # an epoch-long list of per-step device arrays, so step buffers
            # stop being pinned for the whole epoch.
            epoch_sums: Dict[str, Any] = {}
            epoch_steps = 0
            epoch_start = time.perf_counter()
            if peeked_iter is not None:
                # First epoch with compile-ahead: the avals peek already
                # started this epoch's iterator (prefetch warm underneath).
                data_iter, peeked_iter = peeked_iter, None
                abs_epoch = peeked_abs_epoch
            else:
                # Dataset-absolute epoch of the iterator about to be
                # created (read before __call__ advances the counter):
                # this is what the saved position records, so a restart
                # whose dataset was pre-advanced (warmup fit) still
                # fast-forwards to the identical shuffle order.
                abs_epoch = self._dataset_epoch(train_data, epoch)
                data_iter = iter(
                    (first_source if epoch == start_epoch else source)()
                )
            # A resumed first epoch starts mid-stream: the consumed-batch
            # counter picks up at the restored position (the dataset's
            # fast-forward already skipped those batches).
            epoch_consumed = resume_skip if epoch == start_epoch else 0
            try:
                if k == 1:
                    i = epoch_consumed
                    while steps_per_epoch is None or i < steps_per_epoch:
                        with tracing.span("step/data"):
                            # Chaos seam: an injected plan can fail/hang
                            # or corrupt the iterator pull here.
                            batch = faults.fault_point(
                                "data.next", next(data_iter, None)
                            )
                        if batch is None:
                            break
                        if first_dispatch and aot_plan is not None:
                            # Wait for the TRAIN executable only: by now
                            # its compile has been overlapping prefetch
                            # warmup (~0 wait when that paid off), and the
                            # eval compile keeps going in the background.
                            aot_plan.wait("train_step")
                        compute_span = (
                            "step/first_compile" if first_dispatch
                            else "step/compute"
                        )
                        with tracing.span(compute_span):
                            faults.fault_point("train.dispatch")
                            batch = train_lib.shard_batch(
                                batch, self.mesh, self.rules
                            )
                            with self._mesh_context():
                                self.state, metrics = train_step(
                                    self.state, batch
                                )
                        if first_dispatch:
                            first_dispatch = False
                            tracing.record_submit_to_first_step()
                        step += 1
                        i += 1
                        # Consumed = state update dispatched: prefetched
                        # batches the device never saw stay un-consumed.
                        self.data_state = self._position(abs_epoch, i)
                        if self._nonfinite_check(
                            metrics, 1, step, rollback_after_nonfinite,
                            callbacks,
                        ):
                            step = int(self.state.step)
                        # Metrics stay on device: forcing float() here would
                        # block async dispatch and serialize host and TPU
                        # every step.  Callbacks get the device arrays and
                        # pay the sync only if they materialize them.
                        # A quarantined window's NaN metrics are excluded
                        # from the epoch sums (one bad batch must not turn
                        # the whole epoch's logged means NaN).
                        if not self._window_nonfinite:
                            self._accumulate(epoch_sums, metrics, 1)
                            epoch_steps += 1
                        with tracing.span("step/callbacks"):
                            for cb in callbacks:
                                cb.on_step_end(step, metrics, self)
                        self._drain_if_requested(step)
                        if self.stop_training:
                            break
                else:
                    while True:
                        with tracing.span("step/data"):
                            item = faults.fault_point(
                                "data.next", next(data_iter, None)
                            )
                        if item is None:
                            break
                        # Every window — tail included — dispatches the ONE
                        # compiled fused executable: a short window arrives
                        # zero-padded to the full K shape with `valid`
                        # marking its real steps, and the scan skips the
                        # padded slots on device (make_multi_step).  The
                        # only remaining single-step fallback is a RAGGED
                        # window (valid None: per-batch example dims
                        # differ, so no stacking is possible).
                        n, payload, valid = item
                        if valid is None:
                            compute_span = (
                                "step/first_compile" if first_dispatch
                                else "step/compute"
                            )
                            with tracing.span(compute_span, steps=n):
                                with self._mesh_context():
                                    ragged: Dict[str, Any] = {}
                                    for batch in payload:
                                        self.state, m = self._train_step(
                                            self.state, batch
                                        )
                                        self._accumulate(ragged, m, 1)
                                    metrics = {
                                        key: value / n
                                        for key, value in ragged.items()
                                    }
                        else:
                            if first_dispatch and aot_plan is not None:
                                # Only a FUSED dispatch consumes the
                                # compiled executable; a ragged first
                                # window must not stall on it.
                                aot_plan.wait("multi_step")
                            compute_span = (
                                "step/first_compile" if first_dispatch
                                else "step/fused_compute"
                            )
                            with tracing.span(compute_span, steps=n):
                                faults.fault_point("train.dispatch")
                                with self._mesh_context():
                                    self.state, metrics = multi_step(
                                        self.state, payload, valid
                                    )
                        if first_dispatch:
                            first_dispatch = False
                            tracing.record_submit_to_first_step()
                        step += n
                        epoch_consumed += n
                        self.data_state = self._position(
                            abs_epoch, epoch_consumed,
                        )
                        if self._nonfinite_check(
                            metrics, n, step, rollback_after_nonfinite,
                            callbacks,
                        ):
                            step = int(self.state.step)
                        # A quarantined window's on-device mean is already
                        # NaN-poisoned: exclude it from the epoch sums.
                        if not self._window_nonfinite:
                            self._accumulate(epoch_sums, metrics, n)
                            epoch_steps += n
                        with tracing.span("step/callbacks"):
                            for cb in callbacks:
                                cb.on_step_end(step, metrics, self)
                        self._drain_if_requested(step)
                        if self.stop_training:
                            break
            finally:
                # An abandoned prefetch iterator (steps_per_epoch break,
                # stop_training, an exception) must join its worker thread
                # rather than leak it; plain generators close the same way.
                close = getattr(data_iter, "close", None)
                if close is not None:
                    close()
            if not self.stop_training:
                # The epoch ran to its boundary (exhaustion or the
                # steps_per_epoch budget): the resume position rolls over
                # to the next epoch's start.  An early stop (drain, NaN
                # terminate) keeps the mid-epoch position instead.
                self.data_state = self._position(abs_epoch + 1, 0)
            epoch_host = jax.device_get(epoch_sums)
            logs = {
                k_: float(np.mean(v) / max(epoch_steps, 1))
                for k_, v in epoch_host.items()
            }
            logs["epoch_seconds"] = time.perf_counter() - epoch_start
            # A drain is racing a preemption grace window: skip the
            # epoch's validation pass and get to the checkpoint save.
            if validation_data is not None and not self.drained:
                val = self.evaluate(
                    validation_data, prefetch=prefetch, step_fn=eval_step
                )
                logs.update({f"val_{k_}": v for k_, v in val.items()})
            for cb in callbacks:
                cb.on_epoch_end(epoch, logs, self)
        if peeked_iter is not None:
            # The epoch loop never ran (a resumed position past the epochs
            # budget): the compile-ahead peek's iterator still owns a
            # prefetch worker that must be joined, not leaked.
            peeked_iter.close()
        for cb in callbacks:
            cb.on_train_end(self)
        return history

    def evaluate(self, data: Callable[[], Iterable], *,
                 prefetch: int = 2, step_fn=None) -> Dict[str, float]:
        """``step_fn`` overrides the eval step callable (fit passes the
        compile-ahead :class:`compile_cache.AotStep` wrapper through)."""
        step_fn = step_fn if step_fn is not None else self._eval_step
        source = data
        if prefetch > 0 and not pipeline_io.is_prefetched(data):
            source = pipeline_io.prefetch_to_device(
                data, mesh=self.mesh, rules=self.rules, size=prefetch
            )
        sums: Dict[str, Any] = {}
        count = 0
        data_iter = iter(source())
        try:
            for batch in data_iter:
                batch = train_lib.shard_batch(batch, self.mesh, self.rules)
                with self._mesh_context():
                    metrics = step_fn(self.state, batch)
                self._accumulate(sums, metrics, 1)
                count += 1
        finally:
            close = getattr(data_iter, "close", None)
            if close is not None:
                close()
        host = jax.device_get(sums)
        return {k: float(np.mean(v) / max(count, 1)) for k, v in host.items()}

    def _launch_compile_ahead(self, k, source, batch_spec, *,
                              validation_data, multi_step):
        """Derive abstract input avals and start the background compile.

        Returns ``(plan, peeked_iter)``.  ``peeked_iter`` is non-None when
        the first batch/window of epoch 0 was pulled to derive avals — the
        epoch loop must consume it (the underlying prefetcher keeps
        warming meanwhile, which is exactly the window the compile
        overlaps).  Eval avals come from a peek at ``validation_data``'s
        own first batch — never inferred from the train batch, since the
        two may be shaped differently — deferred onto the compile worker
        (after the train-step job) so a slow validation pipeline cannot
        delay the compile that gates dispatch 1.  Any failure here
        degrades to plain jit dispatch.
        """
        import jax

        peeked = None
        try:
            state_avals = compile_cache.abstract_state(self.state)
            valid_aval = None
            if batch_spec is not None:
                if k == 1:
                    batch_avals = compile_cache.abstract_batch(
                        batch_spec, self.mesh, self.rules
                    )
                else:
                    stacked_spec = jax.tree_util.tree_map(
                        lambda x: jax.ShapeDtypeStruct(
                            (k,) + tuple(x.shape), x.dtype
                        ),
                        batch_spec,
                    )
                    batch_avals = compile_cache.abstract_batch(
                        stacked_spec, self.mesh, self.rules, stacked=True
                    )
            else:
                it = iter(source())
                first = next(it, None)
                peeked = _PeekedIterator(first, it)
                if first is None:
                    return None, peeked  # empty dataset: nothing to compile
                if k == 1:
                    batch_avals = compile_cache.abstract_batch(
                        first, self.mesh, self.rules
                    )
                else:
                    _, payload, first_valid = first
                    if first_valid is None:
                        # Ragged first window (per-batch example dims
                        # differ): no stacked avals to compile against.
                        return None, peeked
                    batch_avals = compile_cache.abstract_batch(
                        payload, self.mesh, self.rules, stacked=True
                    )
            if k > 1:
                valid_aval = jax.ShapeDtypeStruct((k,), jnp.float32)

            jobs = []
            ctx = compile_cache.context_key(
                mesh=self.mesh, rules=self.rules, donation=(0,),
                steps_per_dispatch=k,
            )
            if k == 1:
                aot = compile_cache.AotStep(self._train_step, "train_step")
                jobs.append((aot, (state_avals, batch_avals), ctx))
            else:
                aot = compile_cache.AotStep(multi_step, "multi_step")
                jobs.append(
                    (aot, (state_avals, batch_avals, valid_aval), ctx)
                )
            if validation_data is not None:
                eval_ctx = compile_cache.context_key(
                    mesh=self.mesh, rules=self.rules, donation=(),
                    steps_per_dispatch=1,
                )

                def eval_args():
                    # Runs ON THE COMPILE WORKER, after the train-step
                    # job: a slow validation pipeline's first batch must
                    # not delay the compile that gates dispatch 1.
                    val_batch = self._peek_one_batch(validation_data)
                    if val_batch is None:
                        return None
                    return (state_avals, compile_cache.abstract_batch(
                        val_batch, self.mesh, self.rules
                    ))

                jobs.append((
                    compile_cache.AotStep(self._eval_step, "eval_step"),
                    eval_args, eval_ctx,
                ))
            return compile_cache.start_compile_ahead(jobs), peeked
        except Exception:  # noqa: BLE001 — compile-ahead is advisory
            logger.warning(
                "compile-ahead setup failed; falling back to jit dispatch",
                exc_info=True,
            )
            return None, peeked

    @staticmethod
    def _peek_one_batch(dataset):
        """One batch from a fresh iterator of a re-iterable dataset (the
        fit() data contract), closing any worker it spawned."""
        it = iter(dataset())
        try:
            return next(it, None)
        finally:
            close = getattr(it, "close", None)
            if close is not None:
                close()

    def _mesh_context(self):
        import contextlib

        return self.mesh if self.mesh is not None else contextlib.nullcontext()

    @staticmethod
    def _with_runtime_metrics(callbacks: List[Callback]) -> List[Callback]:
        """Install the default metrics producer (reference parity: runtime
        metrics export with zero user code, stackdriver_exporter.cc:86-97).

        Every fit() records steps / loss / step-time / epochs into
        ``monitoring.metrics`` so the exporter always has real series to
        ship.  Opt out with ``CLOUD_TPU_RUNTIME_METRICS=0``; a user-passed
        ``MetricsCallback`` (any prefix) suppresses the default one.
        """
        import os

        if os.environ.get("CLOUD_TPU_RUNTIME_METRICS", "1") == "0":
            return callbacks
        from cloud_tpu import monitoring

        if any(
            isinstance(cb, monitoring.MetricsCallback) for cb in callbacks
        ):
            return callbacks
        return callbacks + [monitoring.MetricsCallback()]
