"""Pipelined input engine: device prefetch and multi-step windowing.

The span traces from PR 1 showed ``Trainer.fit`` paying host-side cost
every step — a synchronous ``next(data_iter)`` gather, a ``shard_batch``
placement, one jit dispatch, and the callback fan-out — so the device
idles between dispatches.  This module owns the host half of closing that
gap (TF-Replicator attributes TPU underutilization primarily to host
input + dispatch overhead, not kernel time):

* :func:`prefetch_to_device` — a background thread runs host-side decode
  and device placement up to ``size`` batches ahead of the consumer
  (double-buffering by default), so host input and device compute overlap
  instead of alternating.  Works for ANY zero-arg-callable dataset
  (``ArrayDataset``, ``RecordDataset``, plain generators); it grew up
  private to ``records.py`` and is promoted here so in-memory and
  validation pipelines get the same overlap.
* :func:`prefetch_windows` / :func:`iter_windows` — the input side of the
  fused multi-step dispatch (``train.make_multi_step``): group K
  consecutive batches, stack them into one super-batch with a leading
  step axis, and place it on device (in the background thread for the
  prefetching variant).  A short tail window (dataset exhausted
  mid-window) is zero-padded to the full window shape with a per-step
  validity mask, so the consumer reuses the compiled K-step executable
  instead of tracing a fallback mid-epoch.

The consumer-facing wait is spanned as ``step/prefetch_wait``: with the
queue warm it is ~0 (input is not the bottleneck); when it dominates the
step, the host pipeline — not the device — is the thing to fix.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from cloud_tpu.monitoring import tracing

#: Thread-name prefix for every background prefetch worker, so tests (and
#: operators reading py-spy dumps) can find — and assert the absence of —
#: leaked workers via ``threading.enumerate()``.
PREFETCH_THREAD_NAME = "cloud-tpu-prefetch"


class PrefetchIterator:
    """Drains a background thread that decodes + places batches on device.

    Abandoning the iterator mid-epoch (``steps_per_epoch`` breaks out of
    the for loop) must not leak the worker: ``close()`` — also wired to GC
    via ``__del__`` — sets a stop flag the worker checks around its bounded
    ``put``, so the thread exits and releases its open record file.
    """

    _DONE = object()

    def __init__(self, source: Iterator, place: Callable, size: int):
        self._queue: "queue.Queue" = queue.Queue(maxsize=max(1, size))
        self._stop = threading.Event()
        self._exhausted = False
        # The worker must NOT capture ``self``: the Thread object would
        # then keep the iterator alive, ``__del__`` could never fire for
        # an abandoned iterator, and the worker (blocked on its bounded
        # put) would leak forever.  It closes over only the queue, the
        # stop flag, and this one-slot error box.
        self._error_box: list = []
        out_queue, stop, error_box = self._queue, self._stop, self._error_box
        done = self._DONE

        def put(item) -> bool:
            while not stop.is_set():
                try:
                    out_queue.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def worker():
            try:
                for batch in source:
                    if not put(place(batch)):
                        return
            except BaseException as exc:  # noqa: BLE001 — re-raised below
                error_box.append(exc)
            finally:
                close = getattr(source, "close", None)
                if close is not None:
                    close()
                put(done)

        self._thread = threading.Thread(
            target=worker, daemon=True, name=PREFETCH_THREAD_NAME
        )
        self._thread.start()

    def __iter__(self):
        return self

    def __next__(self):
        # Exhausted stays exhausted: a consumer that peeked past the end
        # (compile-ahead's aval probe) and iterates again must get
        # StopIteration, not a forever-block on the empty queue.
        if self._exhausted:
            raise StopIteration
        # The get() is the consumer's actual input-wait: ~0 while the
        # worker keeps the queue warm, the full host-pipeline latency when
        # input is the bottleneck.  Spanned so the step breakdown shows
        # which regime a run is in (no-op singleton when tracing is off).
        with tracing.span("step/prefetch_wait"):
            item = self._queue.get()
        if item is self._DONE:
            self._exhausted = True
            self._thread.join()
            if self._error_box:
                raise self._error_box[0]
            raise StopIteration
        return item

    def close(self) -> None:
        self._stop.set()
        # Unblock a worker stuck on a full queue, then let it finish.
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5.0)

    def __del__(self):
        if getattr(self, "_thread", None) is not None and self._thread.is_alive():
            self.close()


def _place_batch(batch, mesh, rules, *, stacked: bool = False):
    """Device placement for one batch (or stacked super-batch) pytree."""
    if mesh is None:
        # shard_batch is a no-op without a mesh; still transfer here so
        # the overlap the prefetcher promises is real.
        import jax

        return jax.device_put(batch)
    from cloud_tpu.training import train as train_lib

    return train_lib.shard_batch(batch, mesh, rules, stacked=stacked)


def _resolve_rules(rules):
    if rules is not None:
        return rules
    from cloud_tpu.parallel.sharding import DEFAULT_RULES

    return DEFAULT_RULES


def prefetch_to_device(
    dataset: Callable[[], Iterator],
    *,
    mesh=None,
    rules=None,
    size: int = 2,
    limit: Optional[int] = None,
) -> Callable[[], Iterator]:
    """Wrap a dataset so batches are transferred ahead of consumption.

    A background thread runs host-side decode and ``shard_batch`` (device
    transfer, mesh placement) up to ``size`` batches ahead — device compute
    and host input processing overlap instead of alternating.  Returns the
    same zero-arg-callable contract, so it drops into ``Trainer.fit``
    (``shard_batch`` passes already-placed arrays through untouched).

    ``limit`` caps batches per iterator: the trainer threads
    ``steps_per_epoch`` through so the worker never decodes and transfers
    batches past the epoch budget only to have them discarded.
    """
    rules = _resolve_rules(rules)

    def place_counted(batch):
        from cloud_tpu.monitoring import metrics as _metrics

        placed = _place_batch(batch, mesh, rules)
        _metrics.counter_inc("data/host_to_device_batches")
        return placed

    def factory():
        source = iter(dataset())
        if limit is not None:
            source = _bounded(source, limit)
        return PrefetchIterator(source, place_counted, size)

    factory._cloud_tpu_prefetched = True  # Trainer: don't double-wrap
    _forward_data_state(factory, dataset)
    return factory


def _bounded(source: Iterator, limit: int) -> Iterator:
    """islice that also closes the underlying iterator when dropped.

    Checks the budget BEFORE pulling: the worker must never block in (or
    spend decode on) a next() whose result the budget already excludes.
    """
    try:
        taken = 0
        while taken < limit:
            try:
                item = next(source)
            except StopIteration:
                return
            taken += 1
            yield item
    finally:
        close = getattr(source, "close", None)
        if close is not None:
            close()


def _forward_data_state(factory, dataset) -> None:
    """Expose the wrapped dataset's exactly-once resume hooks on the
    factory, so a pre-wrapped dataset handed to ``Trainer.fit`` can still
    be fast-forwarded by a restored iterator state."""
    for name in ("state_dict", "load_state_dict"):
        hook = getattr(dataset, name, None)
        if hook is not None:
            setattr(factory, name, hook)


def is_prefetched(dataset) -> bool:
    """True for factories already wrapped by :func:`prefetch_to_device` /
    :func:`prefetch_windows` (the Trainer must not stack a second worker
    thread — and a second redundant placement — on top)."""
    return bool(getattr(dataset, "_cloud_tpu_prefetched", False))


def stack_batches(batches: Sequence[dict]):
    """Stack K host batches into one super-batch with a leading step axis.

    Leaves must be host arrays (the windowing pipelines stack BEFORE
    placement; stacking device arrays would pull them back to host).
    """
    if not batches:
        raise ValueError("stack_batches needs at least one batch")
    import jax

    return jax.tree_util.tree_map(lambda *xs: np.stack(xs), *batches)


def windowed(source: Iterator, k: int, limit: Optional[int] = None) -> Iterator[List]:
    """Group ``source`` into lists of up to ``k`` consecutive batches.

    The final window may be short (dataset exhausted mid-window).
    ``limit`` caps the TOTAL number of batches taken — the trainer threads
    ``steps_per_epoch`` through here so a fused window never overshoots
    the epoch's step budget (a stacked super-batch cannot be un-pulled).
    """
    if k < 1:
        raise ValueError(f"window size must be >= 1, got {k}")
    buf: List = []
    taken = 0
    try:
        if limit is not None and limit <= 0:
            return
        for batch in source:
            buf.append(batch)
            taken += 1
            exhausted = limit is not None and taken >= limit
            if len(buf) == k or exhausted:
                yield buf
                buf = []
            if exhausted:
                return
        if buf:
            yield buf
    finally:
        close = getattr(source, "close", None)
        if close is not None:
            close()


def _window_placer(k: int, mesh, rules, counted: bool):
    """Maps a window (list of host batches) to ``(n_steps, payload, valid)``.

    ``payload`` is normally a stacked + placed super-batch with leading
    axis exactly ``k``: a tail window shorter than k is zero-padded up to
    the compiled window shape (``sharding.pad_batch``) and ``valid``
    (float32 ``[k]``) marks the real steps — so the consumer dispatches
    the SAME fused executable for tails, with the padded slots skipped
    inside the scan (``train.make_multi_step``'s ``valid`` argument)
    instead of tracing a single-step fallback mid-epoch.

    A RAGGED window — batches whose own leading (example) dims differ,
    e.g. a ``drop_remainder=False`` dataset's short final batch — cannot
    stack: it degrades to ``(n, [placed per-step batches], None)`` and
    the consumer runs those as single-step dispatches (``valid is None``
    is the marker).  Avoid ragged finals (drop the remainder, or pad via
    ``shard_batch(pad_to=...)`` + a loss mask) to keep the one-compile
    guarantee.
    """

    def place_window(window: List) -> Tuple[int, object, object]:
        from cloud_tpu.parallel.sharding import pad_batch

        n = len(window)
        if counted:
            from cloud_tpu.monitoring import metrics as _metrics

            _metrics.counter_inc("data/host_to_device_batches", n)
        # Stackable iff every batch has the identical per-leaf shape tree
        # (np.stack's own requirement).  Comparing whole signatures — not
        # pooled leading dims — keeps batches whose DIFFERENT leaves have
        # different leading dims (or scalar leaves) on the fused path.
        def signature(batch):
            return [np.shape(leaf) for leaf in _tree_leaves(batch)]

        first_sig = signature(window[0])
        if any(signature(b) != first_sig for b in window[1:]):
            return n, [_place_batch(b, mesh, rules) for b in window], None
        stacked = stack_batches(window)
        stacked, valid = pad_batch(stacked, k)
        payload = _place_batch(stacked, mesh, rules, stacked=True)
        return n, payload, valid

    return place_window


def _tree_leaves(batch):
    import jax

    return jax.tree_util.tree_leaves(batch)


def prefetch_windows(
    dataset: Callable[[], Iterator],
    steps_per_dispatch: int,
    *,
    mesh=None,
    rules=None,
    size: int = 2,
    limit: Optional[int] = None,
) -> Callable[[], Iterator]:
    """Background-prefetched K-step windows for the fused dispatch path.

    The worker thread gathers ``steps_per_dispatch`` host batches, stacks
    them into one super-batch (leading step axis), and places it on device
    ``size`` windows ahead of the consumer — the multi-step dispatch never
    waits on host gather or H2D transfer.  Yields
    ``(n_steps, payload, valid)``; a short tail window arrives zero-padded
    to the full window shape with ``valid`` marking its real steps (see
    :func:`_window_placer`), so padding happens on the worker thread, off
    the dispatch critical path.
    """
    rules = _resolve_rules(rules)
    place = _window_placer(steps_per_dispatch, mesh, rules, counted=True)

    def factory():
        return PrefetchIterator(
            windowed(iter(dataset()), steps_per_dispatch, limit), place, size
        )

    factory._cloud_tpu_prefetched = True
    _forward_data_state(factory, dataset)
    return factory


def iter_windows(
    dataset: Callable[[], Iterator],
    steps_per_dispatch: int,
    *,
    mesh=None,
    rules=None,
    limit: Optional[int] = None,
) -> Callable[[], Iterator]:
    """Synchronous sibling of :func:`prefetch_windows` (``prefetch=0``):
    same ``(n_steps, payload, valid)`` stream, no background thread."""
    rules = _resolve_rules(rules)
    place = _window_placer(steps_per_dispatch, mesh, rules, counted=False)

    def factory():
        for window in windowed(iter(dataset()), steps_per_dispatch, limit):
            yield place(window)

    _forward_data_state(factory, dataset)
    return factory
