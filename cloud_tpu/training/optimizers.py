"""Memory-efficient optimizer state: bf16-at-rest moments, f32 compute.

Why this exists (BASELINE.md "BERT MFU ceiling"): the measured adamw cost
at BERT-base b32xs128 is ~3.1 ms/step and is HBM-BOUND — ~110 M params x
4 f32 buffers read+written (params, grads, mu, nu) ~ 3.5 GB of traffic
per step on a chip whose step is otherwise MXU work.  Storing the moments
in bfloat16 halves their share of that traffic; the UPDATE math still
runs in f32 (states are upcast for the inner transform and rounded back
down after), so the optimizer trajectory stays numerically close to the
f32 baseline.

Two surfaces:

* :func:`adamw` / :func:`adam` — drop-in presets: first moment stored
  bf16 via optax's native ``mu_dtype`` (safe: mu is a smoothed gradient,
  bf16's ~3 decimal digits are plenty), second moment KEPT f32 by
  default (nu accumulates squared gradients whose dynamic range bf16
  handles poorly near zero — rounding nu can zero the denominator).
* :func:`cast_state` — the general wrapper: bf16-at-rest for ANY optax
  transformation's floating state with f32 compute per update.  Use when
  the preset doesn't fit (custom optimizer chains); accepts a predicate
  for which leaves to cast so a nu-like leaf can stay wide.

Memory/traffic accounting for adamw on N params (bytes/step, read+write):
f32 everything = 8N (mu) + 8N (nu) + ...; ``mu_dtype=bf16`` saves 4N;
``cast_state`` over both moments saves 8N — at BERT-base's 110 M params
that is 0.44 GB and 0.88 GB per step respectively.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax


def adamw(
    learning_rate,
    *,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 1e-4,
    mu_dtype=jnp.bfloat16,
    mask: Optional[Any] = None,
) -> optax.GradientTransformation:
    """AdamW with the first moment stored in ``mu_dtype`` (default bf16).

    optax upcasts mu for the update and rounds back on store, so only the
    at-rest precision changes.  nu stays f32 (see module docstring).
    """
    return optax.adamw(
        learning_rate, b1=b1, b2=b2, eps=eps,
        weight_decay=weight_decay, mu_dtype=mu_dtype, mask=mask,
    )


def adam(
    learning_rate,
    *,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    mu_dtype=jnp.bfloat16,
) -> optax.GradientTransformation:
    """Adam with the first moment stored in ``mu_dtype`` (default bf16)."""
    return optax.adam(
        learning_rate, b1=b1, b2=b2, eps=eps, mu_dtype=mu_dtype
    )


def cast_state(
    inner: optax.GradientTransformation,
    dtype=jnp.bfloat16,
    *,
    should_cast: Optional[Callable[[jax.Array], bool]] = None,
    compute_dtype=jnp.float32,
) -> optax.GradientTransformation:
    """Store ``inner``'s floating state at ``dtype``; compute at full width.

    Every update upcasts the stored state to ``compute_dtype``, runs the
    inner transform, and rounds the new state back down — one extra
    cast pair per leaf per step (fused by XLA into the update kernels; the
    HBM win is the halved at-rest reads/writes, which dominate).

    ``should_cast(leaf) -> bool`` limits which floating leaves are cast
    (default: all of them).  It is applied symmetrically on store
    (narrow) and on load (widen), so it must judge by dtype-stable
    properties — shape/size/position — NOT by ``leaf.dtype`` (the leaf it
    sees is f32 on the way down and ``dtype`` on the way up).  A leaf the
    predicate excludes is never touched in either direction, even if the
    inner transform natively stores it at ``dtype`` (e.g. momentum over
    bf16 params): widening by dtype alone would silently promote such
    leaves and change the state structure between steps.  Integer/None
    leaves (step counters) pass through untouched.  Beware casting an
    adam-style ``nu``: squared gradients underflow bf16 near zero —
    prefer the :func:`adamw` preset (mu-only) unless measurements say
    otherwise.
    """

    def _eligible(leaf):
        return (
            isinstance(leaf, jax.Array)
            and jnp.issubdtype(leaf.dtype, jnp.floating)
            and (should_cast is None or should_cast(leaf))
        )

    def _down(leaf):
        if _eligible(leaf) and leaf.dtype != jnp.dtype(dtype):
            return leaf.astype(dtype)
        return leaf

    def _up(leaf):
        if _eligible(leaf) and leaf.dtype == jnp.dtype(dtype):
            return leaf.astype(compute_dtype)
        return leaf

    def init_fn(params):
        return jax.tree_util.tree_map(_down, inner.init(params))

    def update_fn(updates, state, params=None):
        wide = jax.tree_util.tree_map(_up, state)
        updates, new_state = inner.update(updates, wide, params)
        return updates, jax.tree_util.tree_map(_down, new_state)

    return optax.GradientTransformation(init_fn, update_fn)


def optimizer_state_bytes(opt_state) -> int:
    """Total bytes of all array leaves in an optimizer state (accounting
    helper for A/Bs and BASELINE.md entries)."""
    return sum(
        leaf.size * leaf.dtype.itemsize
        for leaf in jax.tree_util.tree_leaves(opt_state)
        if hasattr(leaf, "dtype")
    )
