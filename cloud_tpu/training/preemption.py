"""Preemption drain: turn a SIGTERM into a checkpoint, not lost work.

Cloud TPU preemptions (and most orchestrators' evictions) deliver
SIGTERM with a grace window before the hard kill.  Without a handler the
Python default tears the process down mid-step and up to
``every_n_steps`` of training is thrown away; with this module the
signal becomes a cooperative drain:

1. ``core.bootstrap`` calls :func:`install_sigterm_handler` before user
   code runs, so every deployed container gets the behavior for free.
2. The handler sets a process-wide stop event (signal-safe: no locks, no
   allocation beyond a flag and a log).
3. ``Trainer.fit`` checks :func:`stop_requested` at every dispatch
   boundary (step for K=1, window for fused K-step dispatch), breaks out
   of the epoch loop, and lets ``on_train_end`` fire — where
   ``CheckpointCallback`` saves the CURRENT step and ``wait()``\\ s the
   async write out.  Work lost is at most one dispatch window.
4. bootstrap exits with :data:`PREEMPTION_EXIT_CODE` (the conventional
   128+SIGTERM), a status ``deploy.supervise_job``'s recreate path can
   tell apart from a crash; the recreated node re-enters the same script
   and ``CheckpointCallback(resume=True)`` restores the drained save.

The event is process-global (one SIGTERM means "this process must go",
whoever is training) with an injectable clock on nothing — determinism
comes from tests calling :func:`request_stop` directly instead of
delivering real signals, though ``os.kill(os.getpid(), SIGTERM)`` works
too and is exercised in the test suite.
"""

from __future__ import annotations

import logging
import signal
import threading
from typing import Optional

logger = logging.getLogger(__name__)

#: 128 + SIGTERM(15): the exit status a drained-then-exited training
#: process reports, distinct from both success (0) and a crash (1).
PREEMPTION_EXIT_CODE = 143

_stop_event = threading.Event()
_reason: Optional[str] = None
_installed = False


def stop_requested() -> bool:
    """True once a drain was requested (SIGTERM or :func:`request_stop`)."""
    return _stop_event.is_set()


def stop_reason() -> Optional[str]:
    return _reason


def request_stop(reason: str = "explicit request") -> None:
    """Request a cooperative drain (what the SIGTERM handler calls)."""
    global _reason
    if not _stop_event.is_set():
        _reason = reason
        _stop_event.set()
        logger.warning("preemption drain requested: %s", reason)


def clear() -> None:
    """Reset the event (tests; a supervisor reusing the process)."""
    global _reason
    _reason = None
    _stop_event.clear()


def install_sigterm_handler() -> bool:
    """Install the drain handler for SIGTERM (main thread only — Python
    restricts ``signal.signal`` to it; callers elsewhere get False and
    the default kill behavior).  Idempotent; chains nothing (the
    previous handler was going to kill the process, which is exactly
    what the drain replaces).
    """
    global _installed
    if _installed:
        return True
    if threading.current_thread() is not threading.main_thread():
        logger.warning(
            "SIGTERM drain handler not installed (not on the main thread)"
        )
        return False

    def _handler(signum, frame):
        # Signal context: set the flag, count it, get out.  The actual
        # checkpoint happens on the training thread at the next window
        # boundary, with the full runtime available.
        request_stop(f"signal {signum}")
        try:
            from cloud_tpu.monitoring import metrics

            metrics.counter_inc("preempt/sigterm")
        except Exception:  # noqa: BLE001 — never raise from a handler
            pass

    try:
        signal.signal(signal.SIGTERM, _handler)
    except (ValueError, OSError):  # pragma: no cover - exotic platforms
        logger.warning("could not install SIGTERM handler", exc_info=True)
        return False
    _installed = True
    return True


def _reset_for_tests() -> None:
    """Clear the event AND restore the default SIGTERM disposition, so a
    test that delivered a real signal leaves no process-global residue
    (the CLOUD_TPU_RUNNING_REMOTELY leak of PR 1, learned once)."""
    global _installed
    clear()
    if _installed and threading.current_thread() is threading.main_thread():
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
    _installed = False
