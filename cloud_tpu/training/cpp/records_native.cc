// Native hot path for the TFRecord-compatible streaming input pipeline.
//
// The wire format (records.py: u64 length + masked crc32c, payload +
// masked crc32c) spends its decode time in crc32c — a per-byte Python
// loop upstream.  This library provides:
//   * crc32c (Castagnoli), slicing-by-8 software implementation
//   * the TFRecord mask transform
//   * a batch frame scanner: one C call parses + verifies every complete
//     frame in a buffer, returning (offset, length) pairs
//
// Mirrors the monitoring/cpp pattern: plain C ABI, ctypes-bound, built
// by Makefile, pure-Python fallback when unavailable (records.py keeps
// its table implementation).

#include <cstdint>
#include <cstring>
#include <mutex>

namespace {

uint32_t g_tables[8][256];
std::once_flag g_init_flag;

void InitTablesImpl() {
  // Castagnoli polynomial, reflected.
  const uint32_t kPoly = 0x82F63B78u;
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t crc = i;
    for (int j = 0; j < 8; ++j) {
      crc = (crc >> 1) ^ ((crc & 1) ? kPoly : 0);
    }
    g_tables[0][i] = crc;
  }
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t crc = g_tables[0][i];
    for (int t = 1; t < 8; ++t) {
      crc = (crc >> 8) ^ g_tables[0][crc & 0xFF];
      g_tables[t][i] = crc;
    }
  }
}

void InitTables() {
  // call_once: crc runs from multiple threads (the prefetch worker) and
  // ctypes releases the GIL, so a plain bool would be a data race.
  std::call_once(g_init_flag, InitTablesImpl);
}

inline uint32_t Crc32c(const uint8_t* data, uint64_t n) {
  InitTables();
  uint32_t crc = 0xFFFFFFFFu;
  // Process 8 bytes per iteration (slicing-by-8).
  while (n >= 8) {
    uint64_t word;
    std::memcpy(&word, data, 8);
    crc ^= static_cast<uint32_t>(word);
    uint32_t hi = static_cast<uint32_t>(word >> 32);
    crc = g_tables[7][crc & 0xFF] ^ g_tables[6][(crc >> 8) & 0xFF] ^
          g_tables[5][(crc >> 16) & 0xFF] ^ g_tables[4][crc >> 24] ^
          g_tables[3][hi & 0xFF] ^ g_tables[2][(hi >> 8) & 0xFF] ^
          g_tables[1][(hi >> 16) & 0xFF] ^ g_tables[0][hi >> 24];
    data += 8;
    n -= 8;
  }
  while (n--) {
    crc = (crc >> 8) ^ g_tables[0][(crc ^ *data++) & 0xFF];
  }
  return crc ^ 0xFFFFFFFFu;
}

inline uint32_t MaskedCrc32c(const uint8_t* data, uint64_t n) {
  // TensorFlow's mask (core/lib/hash/crc32c.h).
  uint32_t crc = Crc32c(data, n);
  return ((crc >> 15) | (crc << 17)) + 0xa282ead8u;
}

inline uint32_t LoadU32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

inline uint64_t LoadU64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;
}

}  // namespace

extern "C" {

uint32_t ctpu_records_crc32c(const uint8_t* data, uint64_t n) {
  return Crc32c(data, n);
}

uint32_t ctpu_records_masked_crc32c(const uint8_t* data, uint64_t n) {
  return MaskedCrc32c(data, n);
}

// Scans complete TFRecord frames in buf[0..n).  Writes payload offsets
// and lengths for up to max_records frames; returns the count parsed.
// *consumed  <- bytes of COMPLETE frames consumed (a trailing partial
//               frame is left for the caller to refill).
// *status    <- 0 ok; 1 header-crc mismatch; 2 payload-crc mismatch
//               (scan stops at the bad frame; count covers good ones).
int64_t ctpu_records_scan(const uint8_t* buf, uint64_t n, int verify,
                          uint64_t* offsets, uint64_t* lengths,
                          int64_t max_records, uint64_t* consumed,
                          int32_t* status) {
  *status = 0;
  *consumed = 0;
  int64_t count = 0;
  uint64_t pos = 0;
  while (count < max_records) {
    if (n - pos < 12) break;  // header (8) + header crc (4)
    uint64_t length = LoadU64(buf + pos);
    // Overflow-safe completeness check: a corrupt length near 2^64 must
    // not wrap 12 + length + 4 around to a small number.
    uint64_t remaining = n - pos - 12;
    if (remaining < 4 || length > remaining - 4) break;  // incomplete
    if (verify) {
      if (MaskedCrc32c(buf + pos, 8) != LoadU32(buf + pos + 8)) {
        *status = 1;
        return count;
      }
      if (MaskedCrc32c(buf + pos + 12, length) !=
          LoadU32(buf + pos + 12 + length)) {
        *status = 2;
        return count;
      }
    }
    offsets[count] = pos + 12;
    lengths[count] = length;
    ++count;
    pos += 12 + length + 4;
    *consumed = pos;
  }
  return count;
}

}  // extern "C"
