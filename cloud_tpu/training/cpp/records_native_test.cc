// Tests for the native records hot path: crc vectors + frame scanning.
#include <cassert>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

extern "C" {
uint32_t ctpu_records_crc32c(const uint8_t* data, uint64_t n);
uint32_t ctpu_records_masked_crc32c(const uint8_t* data, uint64_t n);
int64_t ctpu_records_scan(const uint8_t* buf, uint64_t n, int verify,
                          uint64_t* offsets, uint64_t* lengths,
                          int64_t max_records, uint64_t* consumed,
                          int32_t* status);
}

namespace {

void AppendU32(std::vector<uint8_t>* out, uint32_t v) {
  const uint8_t* p = reinterpret_cast<const uint8_t*>(&v);
  out->insert(out->end(), p, p + 4);
}

void AppendU64(std::vector<uint8_t>* out, uint64_t v) {
  const uint8_t* p = reinterpret_cast<const uint8_t*>(&v);
  out->insert(out->end(), p, p + 8);
}

void AppendFrame(std::vector<uint8_t>* out, const std::string& payload) {
  std::vector<uint8_t> header;
  AppendU64(&header, payload.size());
  out->insert(out->end(), header.begin(), header.end());
  AppendU32(out, ctpu_records_masked_crc32c(header.data(), header.size()));
  out->insert(out->end(), payload.begin(), payload.end());
  AppendU32(out, ctpu_records_masked_crc32c(
                     reinterpret_cast<const uint8_t*>(payload.data()),
                     payload.size()));
}

}  // namespace

int main() {
  // RFC 3720 test vector: crc32c("123456789") == 0xE3069283.
  const char* vec = "123456789";
  assert(ctpu_records_crc32c(reinterpret_cast<const uint8_t*>(vec), 9) ==
         0xE3069283u);
  // Empty input.
  assert(ctpu_records_crc32c(nullptr, 0) == 0x00000000u);
  // 32 zero bytes: crc32c == 0x8A9136AA (known vector, iSCSI).
  uint8_t zeros[32] = {0};
  assert(ctpu_records_crc32c(zeros, 32) == 0x8A9136AAu);

  // Frame round-trip: three frames, one partial tail.
  std::vector<uint8_t> buf;
  AppendFrame(&buf, "hello");
  AppendFrame(&buf, "");
  AppendFrame(&buf, std::string(1000, 'x'));
  size_t complete = buf.size();
  buf.push_back(0x07);  // garbage partial header

  uint64_t offsets[8], lengths[8], consumed;
  int32_t status;
  int64_t n = ctpu_records_scan(buf.data(), buf.size(), 1, offsets, lengths,
                                8, &consumed, &status);
  assert(status == 0);
  assert(n == 3);
  assert(consumed == complete);
  assert(lengths[0] == 5 && lengths[1] == 0 && lengths[2] == 1000);
  assert(std::memcmp(buf.data() + offsets[0], "hello", 5) == 0);

  // Corrupt the third payload: scan returns the first two, status 2.
  buf[offsets[2] + 10] ^= 0xFF;
  n = ctpu_records_scan(buf.data(), buf.size(), 1, offsets, lengths, 8,
                        &consumed, &status);
  assert(status == 2);
  assert(n == 2);

  // verify=0 skips crc checks entirely.
  n = ctpu_records_scan(buf.data(), buf.size(), 0, offsets, lengths, 8,
                        &consumed, &status);
  assert(status == 0 && n == 3);

  // max_records truncation.
  n = ctpu_records_scan(buf.data(), buf.size(), 0, offsets, lengths, 1,
                        &consumed, &status);
  assert(n == 1 && consumed == 12 + 5 + 4);

  std::printf("records_native_test: OK\n");
  return 0;
}
