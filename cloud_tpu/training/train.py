"""Sharded train state and pjit-compiled steps.

All parallelism flows through data placement: parameters are initialized
*directly into* their mesh shardings (via jit sharding propagation from
logical-axis constraints — no host-side giant arrays), batches arrive
sharded over the data axes, and XLA inserts the gradient all-reduces /
all-gathers the layout implies.  This replaces the reference's
strategy-object world (tf.distribute) with the SPMD model.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax.sharding import Mesh, NamedSharding

from cloud_tpu.parallel.sharding import DEFAULT_RULES, ShardingRules


class TrainState(struct.PyTreeNode):
    step: jnp.ndarray
    params: Any
    opt_state: Any
    #: PRNG key threading through stochastic train steps (dropout); None
    #: for deterministic training.  Each step consumes a fresh split.
    rng: Any = None


def param_shardings(
    mesh: Mesh, logical_axes, rules: ShardingRules = DEFAULT_RULES
):
    """Map a logical-axes pytree to NamedShardings."""
    return jax.tree_util.tree_map(
        lambda axes: NamedSharding(mesh, rules.spec(*axes)),
        logical_axes,
        is_leaf=lambda x: isinstance(x, tuple),
    )


def _constrain(params, logical_axes, rules, mesh):
    # Build the sharding tree from the axes tree first (axis tuples are
    # pytree containers, so they can't ride along as a second tree).
    shardings = param_shardings(mesh, logical_axes, rules)
    return jax.tree_util.tree_map(
        jax.lax.with_sharding_constraint, params, shardings
    )


def _constrain_opt_state(opt_state, params, logical_axes, rules, mesh):
    """Pin params-shaped subtrees of an optax state (mu, nu, trace...) to the
    parameter shardings; scalar leaves (step counts) stay replicated."""
    params_treedef = jax.tree_util.tree_structure(params)

    def is_params_like(subtree):
        try:
            return jax.tree_util.tree_structure(subtree) == params_treedef
        except Exception:
            return False

    return jax.tree_util.tree_map(
        lambda sub: _constrain(sub, logical_axes, rules, mesh)
        if is_params_like(sub)
        else sub,
        opt_state,
        is_leaf=is_params_like,
    )


def create_sharded_state(
    rng,
    init_fn: Callable[[Any], Any],
    optimizer: optax.GradientTransformation,
    mesh: Optional[Mesh],
    logical_axes=None,
    rules: ShardingRules = DEFAULT_RULES,
    train_rng: Any = None,
) -> TrainState:
    """Initialize a TrainState with parameters born sharded.

    ``init_fn(rng) -> params``.  With a mesh, init runs under jit so each
    device materializes only its parameter shards (crucial for models larger
    than one host's memory); optimizer state inherits the same layout by
    propagation.
    """

    def build(rng):
        params = init_fn(rng)
        if mesh is not None and logical_axes is not None:
            params = _constrain(params, logical_axes, rules, mesh)
        opt_state = optimizer.init(params)
        if mesh is not None and logical_axes is not None:
            # optax moment buffers are created via zeros_like, which carries
            # no data dependence on params — GSPMD would replicate them.
            # Constrain every params-congruent subtree to the param layout.
            opt_state = _constrain_opt_state(
                opt_state, params, logical_axes, rules, mesh
            )
        return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                          opt_state=opt_state, rng=train_rng)

    if mesh is None:
        return build(rng)
    with mesh:
        return jax.jit(build)(rng)


def make_train_step(
    loss_fn: Callable[..., Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]],
    optimizer: optax.GradientTransformation,
    *,
    logical_axes=None,
    rules: ShardingRules = DEFAULT_RULES,
    mesh: Optional[Mesh] = None,
    stochastic: bool = False,
):
    """Build ``step(state, batch) -> (state, metrics)``, jit-compiled.

    ``loss_fn(params, batch) -> (loss, metrics)``.  The returned step
    donates the input state (in-place buffer reuse on TPU — halves HBM
    traffic for the optimizer update).

    ``stochastic=True`` threads the state's PRNG key through the loss:
    ``loss_fn(params, batch, rng=...)`` gets a fresh split every step
    (dropout et al.), and the state must have been created with a
    ``train_rng`` (``create_sharded_state(..., train_rng=key)``).
    """

    def step(state: TrainState, batch) -> Tuple[TrainState, Dict]:
        next_rng = state.rng
        if stochastic:
            if state.rng is None:
                raise ValueError(
                    "stochastic=True needs a state built with train_rng"
                )
            next_rng, step_rng = jax.random.split(state.rng)
            grad_fn = jax.value_and_grad(
                partial(loss_fn, rng=step_rng), has_aux=True
            )
        else:
            grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
        (_, metrics), grads = grad_fn(state.params, batch)
        updates, new_opt_state = optimizer.update(
            grads, state.opt_state, state.params
        )
        new_params = optax.apply_updates(state.params, updates)
        if mesh is not None and logical_axes is not None:
            new_params = _constrain(new_params, logical_axes, rules, mesh)
        new_state = TrainState(
            step=state.step + 1, params=new_params,
            opt_state=new_opt_state, rng=next_rng,
        )
        metrics = dict(metrics)
        metrics["grad_norm"] = optax.global_norm(grads)
        return new_state, metrics

    return jax.jit(step, donate_argnums=0)


def make_eval_step(loss_fn: Callable[..., Tuple[jnp.ndarray, Dict]]):
    def eval_step(state: TrainState, batch) -> Dict:
        _, metrics = loss_fn(state.params, batch)
        return metrics

    return jax.jit(eval_step)


def shard_batch(batch, mesh: Optional[Mesh],
                rules: ShardingRules = DEFAULT_RULES,
                batch_axis: str = "batch"):
    """Place a batch pytree onto the mesh, sharded on dim 0.

    Single-process: ``batch`` is the global batch; a plain sharded
    device_put.  Multi-process: ``batch`` is this host's *local* slice of
    the global batch (each host loads only its own data — no host ever
    materializes the global batch), assembled into one global jax.Array via
    ``make_array_from_process_local_data``.  The reference's analogue is
    MWMS auto-sharding the per-worker dataset; at pod scale the
    all-on-every-host alternative would OOM the hosts.
    """
    if mesh is None:
        return batch

    import numpy as np

    multiprocess = jax.process_count() > 1

    def place(x):
        spec = rules.spec(*([batch_axis] + [None] * (x.ndim - 1)))
        sharding = NamedSharding(mesh, spec)
        if isinstance(x, jax.Array) and x.sharding == sharding:
            # Already placed (e.g. by records.prefetch_to_device's background
            # thread); re-placing a multiprocess array would even fail, since
            # np.asarray can't read non-addressable shards.
            return x
        if multiprocess:
            return jax.make_array_from_process_local_data(
                sharding, np.asarray(x)
            )
        return jax.device_put(x, sharding)

    return jax.tree_util.tree_map(place, batch)
