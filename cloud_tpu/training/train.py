"""Sharded train state and pjit-compiled steps.

All parallelism flows through data placement: parameters are initialized
*directly into* their mesh shardings (via jit sharding propagation from
logical-axis constraints — no host-side giant arrays), batches arrive
sharded over the data axes, and XLA inserts the gradient all-reduces /
all-gathers the layout implies.  This replaces the reference's
strategy-object world (tf.distribute) with the SPMD model.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax.sharding import Mesh, NamedSharding

from cloud_tpu.parallel.sharding import DEFAULT_RULES, ShardingRules


class TrainState(struct.PyTreeNode):
    step: jnp.ndarray
    params: Any
    opt_state: Any
    #: PRNG key threading through stochastic train steps (dropout); None
    #: for deterministic training.  Each step consumes a fresh split.
    rng: Any = None


def param_shardings(
    mesh: Mesh, logical_axes, rules: ShardingRules = DEFAULT_RULES
):
    """Map a logical-axes pytree to NamedShardings."""
    return jax.tree_util.tree_map(
        lambda axes: NamedSharding(mesh, rules.spec(*axes)),
        logical_axes,
        is_leaf=lambda x: isinstance(x, tuple),
    )


def _constrain(params, logical_axes, rules, mesh):
    # Build the sharding tree from the axes tree first (axis tuples are
    # pytree containers, so they can't ride along as a second tree).
    shardings = param_shardings(mesh, logical_axes, rules)
    return jax.tree_util.tree_map(
        jax.lax.with_sharding_constraint, params, shardings
    )


def _constrain_opt_state(opt_state, params, logical_axes, rules, mesh):
    """Pin params-shaped subtrees of an optax state (mu, nu, trace...) to the
    parameter shardings; scalar leaves (step counts) stay replicated."""
    params_treedef = jax.tree_util.tree_structure(params)

    def is_params_like(subtree):
        try:
            return jax.tree_util.tree_structure(subtree) == params_treedef
        except Exception:
            return False

    return jax.tree_util.tree_map(
        lambda sub: _constrain(sub, logical_axes, rules, mesh)
        if is_params_like(sub)
        else sub,
        opt_state,
        is_leaf=is_params_like,
    )


def create_sharded_state(
    rng,
    init_fn: Callable[[Any], Any],
    optimizer: optax.GradientTransformation,
    mesh: Optional[Mesh],
    logical_axes=None,
    rules: ShardingRules = DEFAULT_RULES,
    train_rng: Any = None,
) -> TrainState:
    """Initialize a TrainState with parameters born sharded.

    ``init_fn(rng) -> params``.  With a mesh, init runs under jit so each
    device materializes only its parameter shards (crucial for models larger
    than one host's memory); optimizer state inherits the same layout by
    propagation.
    """

    def build(rng):
        params = init_fn(rng)
        if mesh is not None and logical_axes is not None:
            params = _constrain(params, logical_axes, rules, mesh)
        opt_state = optimizer.init(params)
        if mesh is not None and logical_axes is not None:
            # optax moment buffers are created via zeros_like, which carries
            # no data dependence on params — GSPMD would replicate them.
            # Constrain every params-congruent subtree to the param layout.
            opt_state = _constrain_opt_state(
                opt_state, params, logical_axes, rules, mesh
            )
        return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                          opt_state=opt_state, rng=train_rng)

    if mesh is None:
        return build(rng)
    with mesh:
        return jax.jit(build)(rng)


def _build_step_fn(
    loss_fn: Callable[..., Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]],
    optimizer: optax.GradientTransformation,
    *,
    logical_axes=None,
    rules: ShardingRules = DEFAULT_RULES,
    mesh: Optional[Mesh] = None,
    stochastic: bool = False,
    accum_steps: int = 1,
    skip_nonfinite: bool = False,
):
    """The un-jitted ``step(state, batch) -> (state, metrics)`` body.

    Shared by :func:`make_train_step` (one step per dispatch) and
    :func:`make_multi_step` (K steps scanned inside one dispatch) so the
    two paths cannot drift numerically.

    ``skip_nonfinite=True`` wraps the optimizer update in an on-device
    ``lax.cond`` on the loss/grad-norm being finite: a step whose batch
    produced NaN/Inf leaves params, opt_state, and the carried rng-split
    pattern untouched (the step counter still advances — the batch WAS
    consumed) and reports ``metrics["nonfinite"] = 1.0``.  No host sync
    is added; the trainer's quarantine logic reads the flag off the
    returned metrics like any other.  Donation semantics are unchanged.
    """
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")

    def _grad_fn(step_rng):
        if stochastic:
            return jax.value_and_grad(
                partial(loss_fn, rng=step_rng), has_aux=True
            )
        return jax.value_and_grad(loss_fn, has_aux=True)

    def _split_rng(state):
        next_rng = state.rng
        step_rng = None
        if stochastic:
            if state.rng is None:
                raise ValueError(
                    "stochastic=True needs a state built with train_rng"
                )
            next_rng, step_rng = jax.random.split(state.rng)
        return next_rng, step_rng

    def _accumulated_grads(params, batch, step_rng):
        """Mean loss/grads/metrics over ``accum_steps`` micro-batches."""

        def to_micro(x):
            if x.shape[0] % accum_steps:
                raise ValueError(
                    f"batch dim {x.shape[0]} not divisible by "
                    f"accum_steps={accum_steps}"
                )
            return x.reshape((accum_steps, x.shape[0] // accum_steps)
                             + x.shape[1:])

        micro = jax.tree_util.tree_map(to_micro, batch)
        rngs = (
            jax.random.split(step_rng, accum_steps)
            if step_rng is not None else None
        )

        def body(acc, xs):
            if rngs is not None:
                mb, mb_rng = xs
            else:
                mb, mb_rng = xs, None
            grad_fn = _grad_fn(mb_rng)
            (_, metrics), grads = grad_fn(params, mb)
            acc_g, acc_m = acc
            acc_g = jax.tree_util.tree_map(
                lambda a, g: a + g.astype(jnp.float32), acc_g, grads
            )
            acc_m = {
                k: acc_m[k] + metrics[k].astype(jnp.float32) for k in acc_m
            }
            return (acc_g, acc_m), None

        # Accumulate in f32 regardless of param dtype (bf16 sums lose
        # precision over many micro-batches); the mean is cast back to
        # each param's dtype below so the optimizer sees the same grad
        # dtypes as the accum_steps=1 path (donated opt_state buffers
        # must keep their optimizer.init dtypes).
        zero_g = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params
        )
        # Metric structure comes from one abstract eval (no FLOPs spent);
        # its shapes seed the accumulators so non-scalar metrics
        # accumulate elementwise instead of crashing the scan carry.
        metric_shapes = jax.eval_shape(
            lambda p, b: _grad_fn(step_rng)(p, b)[0][1], params,
            jax.tree_util.tree_map(lambda x: x[0], micro),
        )
        zero_m = {
            k: jnp.zeros(v.shape, jnp.float32)
            for k, v in metric_shapes.items()
        }
        xs = (micro, rngs) if rngs is not None else micro
        (sum_g, sum_m), _ = jax.lax.scan(body, (zero_g, zero_m), xs)
        inv = 1.0 / accum_steps
        grads = jax.tree_util.tree_map(
            lambda g, p: (g * inv).astype(p.dtype), sum_g, params
        )
        metrics = {k: v * inv for k, v in sum_m.items()}
        return metrics, grads

    def step(state: TrainState, batch) -> Tuple[TrainState, Dict]:
        next_rng, step_rng = _split_rng(state)
        if accum_steps > 1:
            metrics, grads = _accumulated_grads(
                state.params, batch, step_rng
            )
        else:
            (_, metrics), grads = _grad_fn(step_rng)(state.params, batch)
        metrics = dict(metrics)
        grad_norm = optax.global_norm(grads)
        metrics["grad_norm"] = grad_norm

        def apply_update(operand):
            op_grads, op_params, op_opt_state = operand
            updates, new_opt = optimizer.update(
                op_grads, op_opt_state, op_params
            )
            new_params = optax.apply_updates(op_params, updates)
            if mesh is not None and logical_axes is not None:
                new_params = _constrain(new_params, logical_axes, rules, mesh)
            return new_params, new_opt

        if skip_nonfinite:
            finite = jnp.isfinite(grad_norm)
            loss = metrics.get("loss")
            if loss is not None:
                finite = finite & jnp.all(jnp.isfinite(loss))
            # cond, not select: the poisoned update never executes, so a
            # skipped step cannot smear NaN into params via 0*inf terms.
            new_params, new_opt_state = jax.lax.cond(
                finite, apply_update, lambda op: (op[1], op[2]),
                (grads, state.params, state.opt_state),
            )
            metrics["nonfinite"] = 1.0 - finite.astype(jnp.float32)
        else:
            new_params, new_opt_state = apply_update(
                (grads, state.params, state.opt_state)
            )
        new_state = TrainState(
            step=state.step + 1, params=new_params,
            opt_state=new_opt_state, rng=next_rng,
        )
        return new_state, metrics

    return step


def make_train_step(
    loss_fn: Callable[..., Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]],
    optimizer: optax.GradientTransformation,
    *,
    logical_axes=None,
    rules: ShardingRules = DEFAULT_RULES,
    mesh: Optional[Mesh] = None,
    stochastic: bool = False,
    accum_steps: int = 1,
    skip_nonfinite: bool = False,
):
    """Build ``step(state, batch) -> (state, metrics)``, jit-compiled.

    ``loss_fn(params, batch) -> (loss, metrics)``.  The returned step
    donates the input state (in-place buffer reuse on TPU — halves HBM
    traffic for the optimizer update).

    ``stochastic=True`` threads the state's PRNG key through the loss:
    ``loss_fn(params, batch, rng=...)`` gets a fresh split every step
    (dropout et al.), and the state must have been created with a
    ``train_rng`` (``create_sharded_state(..., train_rng=key)``).

    ``accum_steps`` > 1 accumulates gradients over that many equal
    micro-batches (batch dim 0 must divide) inside ONE optimizer update —
    peak activation memory drops to one micro-batch's while the effective
    batch stays whole.  For mean-reduced losses the accumulated gradient
    equals the full-batch gradient exactly; scalar metrics are averaged
    the same way.  The micro-batch loop is a ``lax.scan``, so the model
    compiles once regardless of ``accum_steps``.

    ``skip_nonfinite`` gates the optimizer update on finite loss/grads
    (non-finite step quarantine — see :func:`_build_step_fn`).
    """
    step = _build_step_fn(
        loss_fn, optimizer, logical_axes=logical_axes, rules=rules,
        mesh=mesh, stochastic=stochastic, accum_steps=accum_steps,
        skip_nonfinite=skip_nonfinite,
    )
    return jax.jit(step, donate_argnums=0)


def make_multi_step(
    loss_fn: Callable[..., Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]],
    optimizer: optax.GradientTransformation,
    *,
    steps_per_dispatch: int,
    logical_axes=None,
    rules: ShardingRules = DEFAULT_RULES,
    mesh: Optional[Mesh] = None,
    stochastic: bool = False,
    accum_steps: int = 1,
    skip_nonfinite: bool = False,
):
    """Fuse ``steps_per_dispatch`` train steps into ONE jit dispatch.

    ``multi_step(state, super_batch) -> (state, window_mean_metrics)``
    where ``super_batch`` stacks K consecutive batches along a new leading
    step axis (``pipeline_io.stack_batches``; place with
    ``shard_batch(..., stacked=True)``).  The K optimizer updates run as a
    ``lax.scan`` inside the compiled program, so the host pays ONE dispatch
    (and Python callback fan-out) per K steps instead of per step — the
    host-side overhead that dominates small-step workloads amortizes K-fold.

    Semantics vs K sequential :func:`make_train_step` calls: the parameter
    trajectory is identical (same step body, scanned); only the METRICS
    cadence changes — the window's per-step metrics are averaged on device
    (f32) and returned once per window, so per-step values are not
    observable from the host.  The input state is donated, and the scan
    carries it in place; per-step metrics never accumulate host-side.

    ``multi_step(state, super_batch, valid)`` additionally accepts a
    float32 ``[K]`` per-step validity mask (``sharding.pad_batch``): a
    dataset tail shorter than K is zero-padded to the compiled window
    shape and the padded slots are SKIPPED via ``lax.cond`` — no step
    body runs, the carried state (params, opt_state, rng, step counter)
    passes through untouched, and the window metrics average over valid
    steps only.  One executable therefore serves full windows and tails
    alike (valid steps execute the identical step body, so the
    trajectory matches the unpadded run exactly).  ``valid=None`` keeps
    the original two-argument contract.

    The scan traces the step body once: compile cost does not grow with K,
    and re-dispatching with the same shapes hits the jit cache (guarded by
    tests/unit/test_pipeline_engine.py).
    """
    if steps_per_dispatch < 1:
        raise ValueError(
            f"steps_per_dispatch must be >= 1, got {steps_per_dispatch}"
        )
    step = _build_step_fn(
        loss_fn, optimizer, logical_axes=logical_axes, rules=rules,
        mesh=mesh, stochastic=stochastic, accum_steps=accum_steps,
        skip_nonfinite=skip_nonfinite,
    )

    def multi_step(
        state: TrainState, super_batch, valid=None
    ) -> Tuple[TrainState, Dict]:
        leaves = jax.tree_util.tree_leaves(super_batch)
        if leaves and leaves[0].shape[0] != steps_per_dispatch:
            raise ValueError(
                f"super_batch leading axis {leaves[0].shape[0]} != "
                f"steps_per_dispatch={steps_per_dispatch}"
            )

        def run(carry, batch):
            new_state, metrics = step(carry, batch)
            return new_state, {
                k: v.astype(jnp.float32) for k, v in metrics.items()
            }

        if valid is None:
            state, stacked = jax.lax.scan(run, state, super_batch)
            # Window means in f32, on device: the host sees K steps' worth
            # of metrics as one small pytree, not K pinned buffers.
            metrics = {k: jnp.mean(v, axis=0) for k, v in stacked.items()}
            return state, metrics

        # Metric STRUCTURE from one abstract eval (no FLOPs) so the
        # skipped branch can return matching zeros.
        one_batch = jax.tree_util.tree_map(lambda x: x[0], super_batch)
        metric_shapes = jax.eval_shape(run, state, one_batch)[1]

        def body(carry, xs):
            batch, v = xs

            def skip(c):
                return c, {
                    k: jnp.zeros(s.shape, jnp.float32)
                    for k, s in metric_shapes.items()
                }

            # cond, not select: the padded slot's step body never executes
            # (no wasted FLOPs, no NaN from zero-filled inputs, no
            # params-sized select on the valid steps' fast path).
            return jax.lax.cond(
                v > 0, lambda c: run(c, batch), skip, carry
            )

        state, stacked = jax.lax.scan(body, state, (super_batch, valid))
        n_valid = jnp.maximum(jnp.sum(valid), 1.0)
        metrics = {
            k: jnp.sum(v, axis=0) / n_valid for k, v in stacked.items()
        }
        return state, metrics

    return jax.jit(multi_step, donate_argnums=0)


def make_eval_step(loss_fn: Callable[..., Tuple[jnp.ndarray, Dict]]):
    def eval_step(state: TrainState, batch) -> Dict:
        _, metrics = loss_fn(state.params, batch)
        return metrics

    return jax.jit(eval_step)


def make_hybrid_dp_train_step(
    loss_fn: Callable[..., Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]],
    optimizer: optax.GradientTransformation,
    *,
    mesh: Mesh,
    dcn_axis: str = "dp",
    ici_axis: str = "fsdp",
):
    """Data-parallel train step with EXPLICIT two-level gradient sync for
    multi-slice (DCN-split) meshes.

    The pjit step (:func:`make_train_step`) lets XLA insert the gradient
    all-reduce; on a ``dcn_sizes``-split mesh that flat all-reduce moves
    every gradient byte across the slow inter-slice links.  This step
    instead runs the grad computation inside ``shard_map`` and syncs with
    :func:`cloud_tpu.parallel.collectives.hierarchical_all_reduce_sum` —
    reduce-scatter over the in-slice ICI axis, all-reduce only the
    1/ici-sized shard over DCN, all-gather back — the bandwidth-optimal
    schedule when the outer network bottlenecks (scaling-book recipe;
    the planner's dp-over-DCN rule produces exactly these meshes).

    Params are REPLICATED (pure DP): each device computes grads on its
    batch shard (rows split over ``dcn_axis`` x ``ici_axis``), applies
    the identical synchronized update, and metrics come back globally
    averaged.  For sharded-param layouts keep the pjit step.
    """
    from jax import lax
    from jax.sharding import PartitionSpec

    from cloud_tpu.parallel import collectives

    batch_spec = PartitionSpec((dcn_axis, ici_axis))

    def inner(state: TrainState, batch) -> Tuple[TrainState, Dict]:
        n_data = lax.axis_size(dcn_axis) * lax.axis_size(ici_axis)
        (loss, metrics), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params, batch)
        grads = jax.tree_util.tree_map(
            lambda g: collectives.hierarchical_all_reduce_sum(
                g, ici_axis=ici_axis, dcn_axis=dcn_axis
            ) / n_data,
            grads,
        )
        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params
        )
        params = optax.apply_updates(state.params, updates)
        out_metrics = {
            key: lax.psum(value, (ici_axis, dcn_axis)) / n_data
            for key, value in {"loss": loss, **metrics}.items()
        }
        new_state = state.replace(
            step=state.step + 1, params=params, opt_state=opt_state
        )
        return new_state, out_metrics

    mapped = jax.shard_map(
        inner,
        mesh=mesh,
        in_specs=(PartitionSpec(), batch_spec),
        out_specs=(PartitionSpec(), PartitionSpec()),
        check_vma=False,
    )
    return jax.jit(mapped)


def shard_batch(batch, mesh: Optional[Mesh],
                rules: ShardingRules = DEFAULT_RULES,
                batch_axis: str = "batch", *, stacked: bool = False,
                pad_to: Optional[int] = None):
    """Place a batch pytree onto the mesh, sharded on dim 0.

    Single-process: ``batch`` is the global batch; a plain sharded
    device_put.  Multi-process: ``batch`` is this host's *local* slice of
    the global batch (each host loads only its own data — no host ever
    materializes the global batch), assembled into one global jax.Array via
    ``make_array_from_process_local_data``.  The reference's analogue is
    MWMS auto-sharding the per-worker dataset; at pod scale the
    all-on-every-host alternative would OOM the hosts.

    ``stacked=True`` places a multi-step super-batch (leading axis = steps
    per dispatch, ``make_multi_step``): the step axis stays replicated and
    the BATCH axis moves to dim 1.

    ``pad_to=N`` zero-pads the BATCH dimension of every (host) leaf to N
    before placement — dim 0 for a plain batch, dim 1 for a
    ``stacked=True`` super-batch — and changes the return to
    ``(batch, valid)``, with ``valid`` a float32 ``[N]`` PER-EXAMPLE mask
    of real rows.  This is the ragged-final-batch escape hatch: pad to
    the compiled batch size instead of paying a fresh compile, and gate
    the loss with the mask (e.g. fold it into ``loss_mask`` for the LM
    losses).  The windowing pipelines use sibling machinery per STEP
    (``sharding.pad_batch`` on the stacked super-batch's dim 0) so
    dataset tails reuse the fused executable.
    """
    if pad_to is not None:
        from cloud_tpu.parallel.sharding import pad_batch

        batch, valid = pad_batch(batch, pad_to, axis=1 if stacked else 0)
        return (
            shard_batch(batch, mesh, rules, batch_axis, stacked=stacked),
            valid,
        )
    if mesh is None:
        return batch

    import numpy as np

    multiprocess = jax.process_count() > 1
    lead = [None, batch_axis] if stacked else [batch_axis]

    def place(x):
        spec = rules.spec(*(lead + [None] * (x.ndim - len(lead))))
        sharding = NamedSharding(mesh, spec)
        if isinstance(x, jax.Array) and x.sharding == sharding:
            # Already placed (e.g. by records.prefetch_to_device's background
            # thread); re-placing a multiprocess array would even fail, since
            # np.asarray can't read non-addressable shards.
            return x
        if multiprocess:
            return jax.make_array_from_process_local_data(
                sharding, np.asarray(x)
            )
        return jax.device_put(x, sharding)

    return jax.tree_util.tree_map(place, batch)
