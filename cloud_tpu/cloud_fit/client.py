"""cloud_fit client: serialize in-memory training state, submit the job.

Reference analogue: ``cloud_fit/client.py`` — guards (:87-101, :159-160),
asset serialization (:138-192), default job spec (:195-224), submission
(:227-286).  The submitted container re-enters through the standard
launcher pipeline with a generated shim entry point that calls
``cloud_tpu.cloud_fit.remote.run`` — so cloud_fit rides the same
containerize/deploy path as run() instead of a bespoke job spec.
"""

from __future__ import annotations

import os
import tempfile
import textwrap
from typing import Any, Dict, List, Optional, Union

import numpy as np

from cloud_tpu.cloud_fit import serialization
from cloud_tpu.core import machine_config


def cloud_fit(
    trainer_spec: serialization.TrainerSpec,
    remote_dir: str,
    *,
    train_data: Dict[str, np.ndarray],
    validation_data: Optional[Dict[str, np.ndarray]] = None,
    callbacks: Optional[List[Any]] = None,
    chief_config: Union[str, machine_config.MachineConfig] = "auto",
    worker_count: int = 0,
    job_labels: Optional[Dict[str, str]] = None,
    docker_config=None,
    dry_run: bool = False,
    storage_client=None,
    _session=None,
    _builder=None,
    **fit_kwargs,
):
    """Serialize a TrainerSpec + data + callbacks and fit remotely.

    ``fit_kwargs`` pass through to ``Trainer.fit`` (epochs,
    steps_per_epoch, plus ``batch_size`` consumed by the remote runner).
    Returns the RunReport from the launcher pipeline.
    """
    _validate(trainer_spec, train_data, validation_data, fit_kwargs)
    serialization.serialize_assets(
        remote_dir,
        trainer_spec,
        train_data,
        validation_data=validation_data,
        callbacks=callbacks,
        fit_kwargs=fit_kwargs,
        storage_client=storage_client,
    )

    # Shim entry point: the remote container re-enters here and runs the
    # deserialized fit under the planned mesh (reference made remote.py the
    # ENTRYPOINT directly, cloud_fit.md dockerfile).
    shim_dir = tempfile.mkdtemp(prefix="cloud_fit_entry_")
    shim = os.path.join(shim_dir, "cloud_fit_entry.py")
    with open(shim, "w") as f:
        f.write(textwrap.dedent(f"""
            from cloud_tpu.cloud_fit import remote

            remote.run(remote_dir={remote_dir!r})
        """))

    from cloud_tpu.core import run as run_lib

    return run_lib.run(
        entry_point=shim,
        chief_config=chief_config,
        worker_config=chief_config if worker_count > 0 else "auto",
        worker_count=worker_count,
        job_labels=job_labels,
        docker_config=docker_config,
        parallelism_hints=trainer_spec.parallelism_hints,
        dry_run=dry_run,
        _session=_session,
        _builder=_builder,
    )


def _validate(trainer_spec, train_data, validation_data, fit_kwargs):
    if not isinstance(trainer_spec, serialization.TrainerSpec):
        raise ValueError(
            f"trainer_spec must be a TrainerSpec, got {type(trainer_spec)}"
        )
    batch_size = fit_kwargs.get("batch_size", 32)
    if batch_size <= 0:
        raise ValueError("batch_size must be positive")
    _validate_dataset("train_data", train_data, batch_size)
    if validation_data is not None:
        _validate_dataset("validation_data", validation_data, batch_size)


def _validate_dataset(name, data, batch_size):
    """Catch every remote-side ArrayDataset failure here, before a container
    is built and a TPU slice provisioned (the remote runner defaults
    batch_size to 32)."""
    if not isinstance(data, dict) or not all(
        isinstance(v, np.ndarray) for v in data.values()
    ):
        # The reference likewise rejected non-serializable dataset forms
        # (generators, client.py:159-160).
        raise ValueError(
            f"{name} must be a dict of numpy arrays (in-memory datasets "
            "are the serializable unit; for file-based data use run() with "
            "a training script)."
        )
    lengths = {k: len(v) for k, v in data.items()}
    if len(set(lengths.values())) > 1:
        raise ValueError(
            f"{name} arrays must all have the same leading dimension, "
            f"got {lengths}"
        )
    n = min(lengths.values()) if lengths else 0
    if batch_size > n:
        raise ValueError(
            f"batch_size {batch_size} exceeds the {name} size {n}; pass a "
            "smaller batch_size to cloud_fit()."
        )
