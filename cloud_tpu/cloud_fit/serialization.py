"""Asset (de)serialization for cloud_fit.

Reference analogue: ``cloud_fit/client.py:138-192`` (_serialize_assets:
tf.Modules with tf.function accessors + cloudpickled callbacks under
``remote_dir/training_assets``).  The JAX-native scheme:

- ``trainer.pkl``      cloudpickle of the TrainerSpec (loss/optimizer/init
                       closures, logical axes, rules, hints)
- ``train_data.npz``   training arrays; ``validation_data.npz`` optional
- ``callbacks.pkl``    cloudpickled callback list (the explicit protocol
                       that replaces pickling Keras callbacks)
- ``fit_kwargs.json``  epochs / steps / batch size
- ``state/``           optional Orbax checkpoint of an existing TrainState

Paths may be local or ``gs://`` (GCS handled via google.cloud.storage).
"""

from __future__ import annotations

import dataclasses
import io
import json
import os
from typing import Any, Dict, List, Optional

import cloudpickle
import numpy as np

ASSET_DIR = "training_assets"


@dataclasses.dataclass
class TrainerSpec:
    """Everything needed to rebuild a Trainer remotely."""

    loss_fn: Any
    optimizer: Any
    init_fn: Any
    logical_axes: Any = None
    rules: Any = None
    parallelism_hints: Any = None
    #: Thread a PRNG key through train steps (dropout) — Trainer's
    #: ``stochastic`` flag.
    stochastic: bool = False
    #: Gradient accumulation micro-batches per step — Trainer's
    #: ``accum_steps``.
    accum_steps: int = 1


def _is_gcs(path: str) -> bool:
    return path.startswith("gs://")


def _split_gcs(path: str):
    rest = path[len("gs://"):]
    bucket, _, name = rest.partition("/")
    return bucket, name


def _write_bytes(path: str, data: bytes, storage_client=None) -> None:
    if _is_gcs(path):
        from google.cloud import storage

        client = storage_client or storage.Client()
        bucket, name = _split_gcs(path)
        client.bucket(bucket).blob(name).upload_from_string(data)
    else:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "wb") as f:
            f.write(data)


def _read_bytes(path: str, storage_client=None) -> bytes:
    if _is_gcs(path):
        from google.cloud import storage

        client = storage_client or storage.Client()
        bucket, name = _split_gcs(path)
        return client.bucket(bucket).blob(name).download_as_bytes()
    with open(path, "rb") as f:
        return f.read()


def _join(*parts: str) -> str:
    if _is_gcs(parts[0]):
        return "/".join(p.strip("/") if i else p.rstrip("/")
                        for i, p in enumerate(parts))
    return os.path.join(*parts)


def _arrays_to_npz(arrays: Dict[str, np.ndarray]) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def _npz_to_arrays(data: bytes) -> Dict[str, np.ndarray]:
    with np.load(io.BytesIO(data)) as npz:
        return {k: npz[k] for k in npz.files}


def serialize_assets(
    remote_dir: str,
    spec: TrainerSpec,
    train_data: Dict[str, np.ndarray],
    *,
    validation_data: Optional[Dict[str, np.ndarray]] = None,
    callbacks: Optional[List[Any]] = None,
    fit_kwargs: Optional[Dict[str, Any]] = None,
    storage_client=None,
) -> str:
    """Write all training assets under remote_dir/training_assets."""
    base = _join(remote_dir, ASSET_DIR)
    _write_bytes(_join(base, "trainer.pkl"), cloudpickle.dumps(spec),
                 storage_client)
    _write_bytes(_join(base, "train_data.npz"), _arrays_to_npz(train_data),
                 storage_client)
    if validation_data is not None:
        _write_bytes(
            _join(base, "validation_data.npz"),
            _arrays_to_npz(validation_data), storage_client,
        )
    _write_bytes(
        _join(base, "callbacks.pkl"), cloudpickle.dumps(callbacks or []),
        storage_client,
    )
    _write_bytes(
        _join(base, "fit_kwargs.json"),
        json.dumps(fit_kwargs or {}).encode(), storage_client,
    )
    return base


def deserialize_assets(remote_dir: str, *, storage_client=None):
    """Load what serialize_assets wrote.  Returns (spec, train_data,
    validation_data | None, callbacks, fit_kwargs)."""
    base = _join(remote_dir, ASSET_DIR)
    spec = cloudpickle.loads(
        _read_bytes(_join(base, "trainer.pkl"), storage_client)
    )
    train_data = _npz_to_arrays(
        _read_bytes(_join(base, "train_data.npz"), storage_client)
    )
    validation_data = None
    try:
        validation_data = _npz_to_arrays(
            _read_bytes(_join(base, "validation_data.npz"), storage_client)
        )
    except Exception as e:  # local FileNotFoundError or GCS NotFound
        if type(e).__name__ not in ("FileNotFoundError", "NotFound"):
            raise
    callbacks = cloudpickle.loads(
        _read_bytes(_join(base, "callbacks.pkl"), storage_client)
    )
    fit_kwargs = json.loads(
        _read_bytes(_join(base, "fit_kwargs.json"), storage_client)
    )
    return spec, train_data, validation_data, callbacks, fit_kwargs
