"""cloud_fit: serialize an in-memory training setup and fit it remotely.

Reference analogue: ``experimental/cloud_fit/`` — client serializes model +
datasets + cloudpickled callbacks to a remote dir and submits a job whose
container deserializes and runs ``model.fit`` (client.py:45-286,
remote.py:55-169).  Here the serialized unit is a Trainer spec (loss/
optimizer/init closures via cloudpickle, arrays via npz, state via Orbax)
fitted under the planned mesh.
"""

from cloud_tpu.cloud_fit.client import cloud_fit

__all__ = ["cloud_fit"]
