"""cloud_fit server side: deserialize assets and fit under the mesh.

Reference analogue: ``cloud_fit/remote.py`` — flags CLI (:40-52), strategy
scope + asset loading + ``model.fit`` (:68-128), chief-only save with
non-chief throwaway dirs (:130-156).  Orbax replaces the throwaway-dir
dance for checkpoints (every process participates in sharded writes); the
chief-only pattern remains for the single-file outputs.
"""

from __future__ import annotations

import argparse
import logging
import os
import tempfile
from typing import Optional

from cloud_tpu.cloud_fit.serialization import _join

logger = logging.getLogger(__name__)

OUTPUT_DIR = "output"


def run(remote_dir: str, *, mesh=None, storage_client=None) -> "object":
    """Load serialized assets from ``remote_dir`` and run the fit.

    Returns the History.  Called by the generated shim entry point under
    the bootstrap runtime (mesh already installed globally), or directly
    in tests with an explicit mesh.
    """
    import jax

    from cloud_tpu.cloud_fit import serialization
    from cloud_tpu.parallel import distributed
    from cloud_tpu.parallel import mesh as mesh_lib
    from cloud_tpu.training import Trainer, data as data_lib
    from cloud_tpu.training.checkpoint import CheckpointManager

    spec, train_arrays, val_arrays, callbacks, fit_kwargs = (
        serialization.deserialize_assets(remote_dir,
                                         storage_client=storage_client)
    )
    mesh = mesh if mesh is not None else mesh_lib.get_global_mesh()

    batch_size = fit_kwargs.pop("batch_size", 32)
    train_ds = data_lib.ArrayDataset(train_arrays, batch_size, shuffle=True)
    val_ds = (
        data_lib.ArrayDataset(val_arrays, batch_size) if val_arrays else None
    )

    trainer = Trainer(
        spec.loss_fn,
        spec.optimizer,
        init_fn=spec.init_fn,
        mesh=mesh,
        logical_axes=spec.logical_axes,
        rules=spec.rules or _default_rules(),
        stochastic=spec.stochastic,
        accum_steps=spec.accum_steps,
    )
    # Init first: the fresh state doubles as the Orbax restore template
    # (checkpoint/resume — SURVEY.md §5 aux subsystems).
    trainer.init_state(jax.random.PRNGKey(0))
    _maybe_restore(trainer, _join(remote_dir, "state"))
    history = trainer.fit(
        train_ds,
        validation_data=val_ds,
        callbacks=callbacks,
        **fit_kwargs,
    )

    # Save final state.  Orbax coordinates multi-host writes itself; the
    # history/metrics file is chief-only (non-chief writes would race —
    # the concern reference remote.py:130-145 solved with throwaway dirs).
    output_dir = _join(remote_dir, OUTPUT_DIR)
    manager = CheckpointManager(_join(output_dir, "checkpoint"))
    manager.save(int(trainer.state.step), trainer.state)
    manager.wait()
    manager.close()
    if distributed.is_chief():
        _write_history(output_dir, history, storage_client)
    else:
        # Non-chief bookkeeping goes to a throwaway location (parity with
        # reference remote.py:130-145).
        with tempfile.TemporaryDirectory() as tmp:
            _write_history(tmp, history, None)
    return history


def _default_rules():
    from cloud_tpu.parallel.sharding import DEFAULT_RULES

    return DEFAULT_RULES


def _maybe_restore(trainer, state_dir: str) -> bool:
    if state_dir.startswith("gs://") or os.path.isdir(state_dir):
        try:
            # Shared resume recipe (rng-leaf-tolerant, sharding-aware,
            # failure = fresh start): training/checkpoint.py.
            from cloud_tpu.training.checkpoint import (
                CheckpointManager,
                resume_trainer_state,
            )

            # only_if_ahead=False: a user-uploaded state saved at step
            # 0 (pretrained weights) must replace the fresh init too.
            # quarantine=False: state_dir is the USER'S upload, not this
            # job's save directory — a restore hiccup must never relocate
            # their checkpoint (saves go to output/checkpoint, so the
            # stale-newer-step save trap cannot arise here).
            return resume_trainer_state(
                trainer, CheckpointManager(state_dir), only_if_ahead=False,
                quarantine=False,
            )
        except Exception:
            logger.exception("could not restore from %s; starting fresh",
                             state_dir)
    return False


def _write_history(output_dir: str, history, storage_client) -> None:
    import json

    from cloud_tpu.cloud_fit import serialization as ser

    ser._write_bytes(
        _join(output_dir, "history.json"),
        json.dumps(history.history).encode(),
        storage_client,
    )


def main(argv: Optional[list] = None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--remote-dir", required=True)
    args = parser.parse_args(argv)
    run(args.remote_dir)


if __name__ == "__main__":
    main()
