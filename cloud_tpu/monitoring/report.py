"""Phase-latency breakdown of a tracing timeline dump.

``python -m cloud_tpu.monitoring.report /path/to/timeline.json`` prints a
per-span-name table (count, total, mean, p50, max, % of wall) from a
Chrome trace-event file written by ``tracing.dump_timeline``.  The same
summarization is importable as :class:`TraceReport` for programmatic use
(bench.py ships the equivalent aggregates in its BENCH json).

When the timeline contains serving spans (``serve/*`` — the
``cloud_tpu.serving`` engine), a dedicated breakdown follows the main
table: queue wait vs prefill vs decode/chunk, each as a percentage of
total serve-span time, so "requests are slow" resolves one level deeper
— waiting for a slot (raise ``max_queue``, add capacity) vs paying
compute (shrink buckets, raise occupancy) — without leaving the CLI.
Continuous-batching timelines (``serve/chunk`` spans) additionally get
a grid-health line: chunk count, mean slot occupancy, mean active
slots, and total emitted tokens, aggregated from the per-dispatch span
attributes the scheduler stamps on every chunk — plus the slice shape
(``slice 2x1 (2 chips)``) next to occupancy when the engine is a
sharded multi-chip slice.  Prefix-cache /
chunked-prefill timelines (``serve/prefix_lookup`` /
``serve/prefill_chunk`` spans) get hit rate, hit tokens, prefill-chunk
count, and decode-stall attribution (one interleaved prefill chunk is
exactly the stall a decode chunk can see, so the max chunk duration is
the worst stall of the run).  Speculative-decoding timelines
(``serve/draft`` / ``serve/verify`` spans) get a line with the verify
dispatch count, the draft-token acceptance rate (from the
``accepted``/``proposed`` attributes the scheduler stamps per verify),
and the draft-vs-verify wall-clock split — the numbers ``spec_k`` is
tuned against, printed next to the occupancy line.

QoS timelines (``serve/request`` spans — the engine stamps one per
retired request when ``ServeConfig.qos`` is armed, carrying
``priority`` and ``ttft_s`` attributes) get a **QoS classes** section:
per-class request counts with TTFT and end-to-end latency p50/p99 —
the per-class SLO numbers the priority weights and quotas are tuned
against.  FIFO timelines carry no such spans and render no section.

Timelines carrying ``trace_id`` attributes (requests submitted while
tracing was active — the fleet mints a :class:`tracing.TraceContext`
per request and every layer stamps it) additionally get per-request
stitching: a **traced requests** line, a **TTFT decomposition** table
attributing fleet TTFT to queue / route / swap-in / prefill /
first-decode shares at p50/p99 (the distributional gate bench.py and
check_fleet.py compare instead of raw percentiles), and a ``--trace
<id>`` drill-down that prints one request's whole lifecycle — every
span under its trace id across fleet and replicas, failovers included
— in start order.

Timelines with ``fleet/*`` spans (the ``cloud_tpu.fleet`` layer) get a
**fleet** section: per-replica routed-request counts with mean
load/occupancy (from the attributes the router stamps on every
``fleet/route`` decision), failover / restart / scale-event counts, and
the occupancy spread across replicas — the imbalance number a fleet
operator tunes the router against.

Timelines touched by the fault-tolerance layer get a **robustness**
section: retry activity (``retry/*`` spans — the ``utils.retries``
policy stamps ``attempts``/``outcome`` on every retried call), shed /
deadline-exceeded serving requests (``serve/shed``), injected chaos
faults (``fault/<site>`` spans from ``utils.faults``), preemption
drains (``preempt/drain``), checkpoint restore fallbacks from the
verified walk-back (``checkpoint/fallback``), non-finite step
quarantine activity (``train/nonfinite_skip``), and divergence
rollbacks (``train/rollback``) — so a post-mortem of "what went wrong
and what absorbed it" reads off the same CLI as the latency breakdown.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional


def _percentile(sorted_values: List[float], q: float) -> float:
    if not sorted_values:
        return 0.0
    idx = min(len(sorted_values) - 1, int(q * (len(sorted_values) - 1) + 0.5))
    return sorted_values[idx]


class TraceReport:
    """Aggregates complete ("ph": "X") events from a timeline dump."""

    def __init__(self, events: List[dict]):
        self.events = [
            e for e in events
            if e.get("ph") == "X" and isinstance(e.get("dur"), (int, float))
        ]

    @classmethod
    def from_file(cls, path: str) -> "TraceReport":
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        events = doc["traceEvents"] if isinstance(doc, dict) else doc
        return cls(events)

    def wall_seconds(self) -> float:
        """End of the last span minus start of the first (timeline span)."""
        if not self.events:
            return 0.0
        start = min(e["ts"] for e in self.events)
        end = max(e["ts"] + e["dur"] for e in self.events)
        return (end - start) / 1e6

    def rows(self) -> List[Dict[str, float]]:
        """One row per span name, sorted by total time descending."""
        by_name: Dict[str, List[float]] = {}
        for event in self.events:
            by_name.setdefault(event["name"], []).append(event["dur"] / 1e6)
        wall = self.wall_seconds()
        rows = []
        for name, durations in by_name.items():
            durations.sort()
            total = sum(durations)
            rows.append({
                "name": name,
                "count": len(durations),
                "total_s": total,
                "mean_s": total / len(durations),
                "p50_s": _percentile(durations, 0.5),
                "max_s": durations[-1],
                "pct_wall": 100.0 * total / wall if wall else 0.0,
            })
        rows.sort(key=lambda r: r["total_s"], reverse=True)
        return rows

    #: The serving phases, in request order (the ``cloud_tpu.serving``
    #: engine's span names — batch-mode batch_form/decode, continuous-
    #: mode chunk); anything else under ``serve/`` rides along.
    _SERVE_ORDER = (
        "serve/queue_wait", "serve/batch_form", "serve/prefill",
        "serve/decode", "serve/chunk", "serve/draft", "serve/verify",
        "serve/host_bubble", "serve/dispatch_gap",
    )

    def continuous_summary(self) -> Optional[Dict[str, float]]:
        """Aggregate the ``serve/chunk`` spans' per-dispatch attributes
        (the continuous-batching scheduler stamps ``active``, ``slots``,
        ``tokens`` and ``occupancy`` on every chunk) into one line of
        grid health: how full the decode grid ran.  None when the
        timeline has no chunk spans (batch-mode or non-serving trace).
        """
        chunks = [
            e.get("args") or {} for e in self.events
            if e.get("name") == "serve/chunk"
        ]
        if not chunks:
            return None

        def mean_of(key):
            values = [
                a[key] for a in chunks
                if isinstance(a.get(key), (int, float))
            ]
            return sum(values) / len(values) if values else None

        tokens = [
            a["tokens"] for a in chunks
            if isinstance(a.get("tokens"), (int, float))
        ]
        # Sharded engines stamp the slice ("2x1") and its chip count on
        # every chunk span; single-chip timelines carry neither.
        slice_shape = next(
            (a["slice"] for a in chunks if a.get("slice")), None
        )
        slice_chips = next(
            (
                a["slice_chips"] for a in chunks
                if isinstance(a.get("slice_chips"), (int, float))
            ),
            None,
        )
        # Pipelined scheduling: the drain records the blocking host
        # copy it actually paid as serve/host_bubble, so bubble time /
        # chunk time is the fraction of the decode timeline the host
        # still stalls the device for (None on depth-1 timelines,
        # which record no bubble spans).
        chunk_us = sum(
            e.get("dur", 0.0) for e in self.events
            if e.get("name") in ("serve/chunk", "serve/verify")
        )
        bubble_us = sum(
            e.get("dur", 0.0) for e in self.events
            if e.get("name") == "serve/host_bubble"
        )
        bubble_fraction = (
            bubble_us / chunk_us
            if bubble_us and chunk_us else None
        )
        return {
            "chunks": len(chunks),
            "mean_occupancy": mean_of("occupancy"),
            "mean_active": mean_of("active"),
            "slots": mean_of("slots"),
            "tokens": sum(tokens) if tokens else None,
            "slice": slice_shape,
            "slice_chips": slice_chips,
            "bubble_fraction": bubble_fraction,
        }

    def prefix_summary(self) -> Optional[Dict[str, object]]:
        """Aggregate the prefix-cache / chunked-prefill spans.

        ``lookups``/``hits``/``hit_rate``/``hit_tokens`` come from
        ``serve/prefix_lookup`` span attributes (the scheduler stamps
        ``hit`` and ``hit_tokens`` per admission); ``prefill_chunks`` /
        ``prefill_chunk_seconds`` / ``max_decode_stall_seconds`` from
        the ``serve/prefill_chunk`` spans — the scheduler interleaves
        exactly one prefill chunk between decode chunks, so a single
        chunk's duration IS the decode stall a long arrival imposes,
        and the max over chunks is the worst stall of the run.

        The host-DRAM tier (ISSUE 15) shows up two ways: lookup spans
        stamp ``dram=True`` on hits that needed a swap-in, splitting
        ``hits`` into ``hbm_hits``/``dram_hits``, and the
        ``serve/prefix_swapin`` spans carry the swap-in stall the
        admission path paid to promote demoted blocks (count, total,
        and max — the worst single admission stall attributable to the
        tier).  None when the timeline has none of these spans (prefix
        caching and chunked prefill off, batch mode, or a non-serving
        trace).
        """
        lookups = 0
        hits = 0
        dram_hits = 0
        hit_tokens = 0
        chunk_durs: List[float] = []
        swapin_durs: List[float] = []
        swapin_blocks = 0
        for event in self.events:
            name = event.get("name", "")
            args = event.get("args") or {}
            if name == "serve/prefix_lookup":
                lookups += 1
                if args.get("hit"):
                    hits += 1
                    if args.get("dram"):
                        dram_hits += 1
                tokens = args.get("hit_tokens")
                if isinstance(tokens, (int, float)):
                    hit_tokens += int(tokens)
            elif name == "serve/prefill_chunk":
                chunk_durs.append(event["dur"] / 1e6)
            elif name == "serve/prefix_swapin":
                swapin_durs.append(event["dur"] / 1e6)
                blocks = args.get("blocks")
                if isinstance(blocks, (int, float)):
                    swapin_blocks += int(blocks)
        if not lookups and not chunk_durs and not swapin_durs:
            return None
        return {
            "lookups": lookups,
            "hits": hits,
            "hit_rate": hits / lookups if lookups else None,
            "hit_tokens": hit_tokens,
            "hbm_hits": hits - dram_hits,
            "dram_hits": dram_hits,
            "swapins": len(swapin_durs),
            "swapin_blocks": swapin_blocks,
            "swapin_seconds": sum(swapin_durs),
            "max_swapin_stall_seconds": (
                max(swapin_durs) if swapin_durs else None
            ),
            "prefill_chunks": len(chunk_durs),
            "prefill_chunk_seconds": sum(chunk_durs),
            "max_decode_stall_seconds": (
                max(chunk_durs) if chunk_durs else None
            ),
        }

    def spec_summary(self) -> Optional[Dict[str, object]]:
        """Aggregate the speculative-decoding spans.

        ``serve/verify`` spans carry ``tokens``/``accepted``/``proposed``
        attributes (the scheduler stamps them per verify dispatch), so
        the acceptance rate is committed-draft tokens over proposed
        ones; ``draft_seconds`` sums the ``serve/draft`` +
        ``serve/draft_prefill`` spans and ``verify_seconds`` the verify
        spans — the draft/verify wall-clock split the spec_k knob is
        tuned against.  None when the timeline has no speculative spans
        (draft off, batch mode, or a non-serving trace).
        """
        verify_durs: List[float] = []
        draft_durs: List[float] = []
        counts = {"tokens": 0, "accepted": 0, "proposed": 0}
        for event in self.events:
            name = event.get("name", "")
            if name == "serve/verify":
                verify_durs.append(event["dur"] / 1e6)
                args = event.get("args") or {}
                for key in counts:
                    value = args.get(key)
                    if isinstance(value, (int, float)):
                        counts[key] += int(value)
            elif name in ("serve/draft", "serve/draft_prefill"):
                draft_durs.append(event["dur"] / 1e6)
        if not verify_durs and not draft_durs:
            return None
        return {
            "verify_dispatches": len(verify_durs),
            "tokens": counts["tokens"],
            "accepted": counts["accepted"],
            "proposed": counts["proposed"],
            "acceptance_rate": (
                counts["accepted"] / counts["proposed"]
                if counts["proposed"] else None
            ),
            "draft_seconds": sum(draft_durs),
            "verify_seconds": sum(verify_durs),
        }

    def serving_rows(self, rows: Optional[List[Dict[str, float]]] = None
                     ) -> List[Dict[str, float]]:
        """The ``serve/*`` spans as a queue-wait vs prefill vs decode
        breakdown: same aggregates as :meth:`rows`, but ``pct_serve`` is
        each phase's share of total serve-span time (the phases are
        sequential per request, so shares read as "where a request's
        latency went") and rows come in request order, not sorted by
        cost.  Empty when the timeline has no serving spans.  Pass
        precomputed :meth:`rows` output to skip re-aggregating a large
        timeline.
        """
        if rows is None:
            rows = self.rows()
        rows = [dict(r) for r in rows if r["name"].startswith("serve/")]
        total = sum(r["total_s"] for r in rows)
        order = {name: i for i, name in enumerate(self._SERVE_ORDER)}
        rows.sort(key=lambda r: (order.get(r["name"], len(order)),
                                 r["name"]))
        for row in rows:
            row["pct_serve"] = 100.0 * row["total_s"] / total if total else 0.0
        return rows

    def robustness_summary(self) -> Optional[Dict[str, object]]:
        """Aggregate the fault-tolerance spans into one post-mortem dict.

        ``retries``: per-``retry/<name>`` — calls that needed retrying,
        total attempts, and give-ups (from the ``attempts``/``outcome``
        attributes the policy stamps; first-try successes record no
        span, so these are exactly the interesting calls).
        ``shed``: deadline-exceeded serving requests (``serve/shed``).
        ``faults``: injected chaos faults per site (``fault/<site>``).
        ``drains``: preemption drains (``preempt/drain``).
        ``restore_fallbacks``: checkpoints skipped by the verified
        walk-back restore (``checkpoint/fallback`` — corrupt, partial,
        or unrestorable steps the resume stepped past).
        ``nonfinite``: the non-finite step quarantine —
        ``{"windows": N, "steps": M}`` from ``train/nonfinite_skip``
        spans (N bad dispatch windows, M skipped state updates).
        ``rollbacks``: divergence rollbacks to the last verified
        checkpoint (``train/rollback``).  None when the timeline shows
        no robustness activity at all.
        """
        retries: Dict[str, Dict[str, int]] = {}
        faults: Dict[str, int] = {}
        shed = 0
        drains = 0
        restore_fallbacks = 0
        nonfinite_windows = 0
        nonfinite_steps = 0
        rollbacks = 0
        for event in self.events:
            name = event.get("name", "")
            args = event.get("args") or {}
            if name.startswith("retry/"):
                row = retries.setdefault(
                    name[len("retry/"):],
                    {"calls": 0, "attempts": 0, "gave_up": 0},
                )
                row["calls"] += 1
                attempts = args.get("attempts")
                if isinstance(attempts, (int, float)):
                    row["attempts"] += int(attempts)
                if args.get("outcome") == "gave_up":
                    row["gave_up"] += 1
            elif name == "serve/shed":
                shed += 1
            elif name.startswith("fault/"):
                faults[name[len("fault/"):]] = (
                    faults.get(name[len("fault/"):], 0) + 1
                )
            elif name == "preempt/drain":
                drains += 1
            elif name == "checkpoint/fallback":
                restore_fallbacks += 1
            elif name == "train/nonfinite_skip":
                nonfinite_windows += 1
                skipped = args.get("skipped")
                nonfinite_steps += (
                    int(skipped) if isinstance(skipped, (int, float)) else 1
                )
            elif name == "train/rollback":
                rollbacks += 1
        if (not retries and not faults and not shed and not drains
                and not restore_fallbacks and not nonfinite_windows
                and not rollbacks):
            return None
        return {
            "retries": retries, "shed": shed, "faults": faults,
            "drains": drains, "restore_fallbacks": restore_fallbacks,
            "nonfinite": {"windows": nonfinite_windows,
                          "steps": nonfinite_steps},
            "rollbacks": rollbacks,
        }

    def qos_summary(self) -> Optional[Dict[str, object]]:
        """Aggregate the per-request QoS spans into a per-class SLO
        table.

        ``serve/request`` spans exist only on QoS-armed engines (one
        per retired request, duration = end-to-end latency, ``ttft_s``
        attribute = submit -> first token); grouping by the
        ``priority`` attribute yields per-class request counts and
        TTFT / latency p50/p99 — the numbers class weights, SLO
        targets, and quotas are tuned against.  None when the timeline
        has no QoS spans (FIFO engine, or a non-serving trace).
        """
        by_class: Dict[str, Dict[str, List[float]]] = {}
        for event in self.events:
            if event.get("name") != "serve/request":
                continue
            args = event.get("args") or {}
            priority = args.get("priority")
            if priority is None:
                # Traced FIFO requests also emit a terminal
                # serve/request span (it anchors the per-request
                # lifecycle) but carry no priority — they belong to
                # request_summary(), not to a phantom QoS class.
                continue
            name = str(priority)
            row = by_class.setdefault(
                name, {"ttft": [], "latency": []}
            )
            row["latency"].append(event["dur"] / 1e6)
            ttft = args.get("ttft_s")
            if isinstance(ttft, (int, float)):
                row["ttft"].append(float(ttft))
        if not by_class:
            return None
        classes = {}
        for name, row in by_class.items():
            ttft = sorted(row["ttft"])
            latency = sorted(row["latency"])
            classes[name] = {
                "requests": len(latency),
                "ttft_p50_s": _percentile(ttft, 0.5) if ttft else None,
                "ttft_p99_s": _percentile(ttft, 0.99) if ttft else None,
                "latency_p50_s": _percentile(latency, 0.5),
                "latency_p99_s": _percentile(latency, 0.99),
            }
        return {"classes": classes}

    def fleet_summary(self) -> Optional[Dict[str, object]]:
        """Aggregate the serving-fleet spans into one operations dict.

        ``replicas``: per-replica-id — requests routed there (one
        ``fleet/route`` span each) plus mean load and mean occupancy
        from the attributes the router stamps per decision.
        ``occupancy_spread``: max - min of the per-replica mean
        occupancies (an unbalanced fleet wastes exactly this much of
        its best replica's amortization) — None until two replicas
        report occupancy.  Plus counts of ``fleet/failover``,
        ``fleet/restart``, ``fleet/shed``, and ``fleet/scale`` events
        by direction.  None when the timeline has no fleet spans.
        """
        replicas: Dict[object, Dict[str, float]] = {}
        failovers = 0
        restarts = 0
        shed = 0
        scale = {"up": 0, "down": 0}
        seen = False
        for event in self.events:
            name = event.get("name", "")
            if not name.startswith("fleet/"):
                continue
            seen = True
            args = event.get("args") or {}
            if name == "fleet/route":
                row = replicas.setdefault(args.get("replica"), {
                    "requests": 0, "load_sum": 0.0, "load_n": 0,
                    "occ_sum": 0.0, "occ_n": 0,
                })
                row["requests"] += 1
                if isinstance(args.get("load"), (int, float)):
                    row["load_sum"] += args["load"]
                    row["load_n"] += 1
                if isinstance(args.get("occupancy"), (int, float)):
                    row["occ_sum"] += args["occupancy"]
                    row["occ_n"] += 1
            elif name == "fleet/failover":
                failovers += 1
            elif name == "fleet/restart":
                restarts += 1
            elif name == "fleet/shed":
                shed += 1
            elif name == "fleet/scale":
                direction = args.get("direction")
                if direction in scale:
                    scale[direction] += 1
        if not seen:
            return None
        per_replica = {}
        occupancies = []
        for rid, row in replicas.items():
            mean_occ = (
                row["occ_sum"] / row["occ_n"] if row["occ_n"] else None
            )
            if mean_occ is not None:
                occupancies.append(mean_occ)
            per_replica[rid] = {
                "requests": int(row["requests"]),
                "mean_load": (
                    row["load_sum"] / row["load_n"] if row["load_n"]
                    else None
                ),
                "mean_occupancy": mean_occ,
            }
        spread = (
            max(occupancies) - min(occupancies)
            if len(occupancies) >= 2 else None
        )
        return {
            "replicas": per_replica,
            "failovers": failovers,
            "restarts": restarts,
            "shed": shed,
            "scale": scale,
            "occupancy_spread": spread,
        }

    # -- per-request trace stitching ------------------------------------

    #: Prefill-phase span names charged to the "prefill" TTFT component
    #: (batch prefill, chunked prefill, and the finalize insert).
    _PREFILL_SPANS = (
        "serve/prefill", "serve/prefill_chunk", "serve/prefill_finalize",
    )

    def trace_spans(self, trace_id: str) -> List[dict]:
        """Every span stitched under ``trace_id``, in start order.

        A span belongs to a trace either directly (its ``trace_id``
        attribute — fleet/route, serve/request, serve/queue_wait, ...)
        or through the ``traces`` slot map the continuous scheduler
        stamps on shared dispatches (serve/chunk, serve/verify serve
        many slots at once; the map says which requests rode along).
        """
        wanted = str(trace_id)
        spans = []
        for event in self.events:
            args = event.get("args") or {}
            tid = args.get("trace_id")
            if tid is not None and str(tid) == wanted:
                spans.append(event)
                continue
            traces = args.get("traces")
            if isinstance(traces, dict) and any(
                    str(t) == wanted for t in traces.values()):
                spans.append(event)
        spans.sort(key=lambda e: e["ts"])
        return spans

    def _spans_by_trace(self) -> Dict[str, List[dict]]:
        by_trace: Dict[str, List[dict]] = {}
        for event in self.events:
            args = event.get("args") or {}
            tid = args.get("trace_id")
            if tid is not None:
                by_trace.setdefault(str(tid), []).append(event)
            traces = args.get("traces")
            if isinstance(traces, dict):
                for tid in {str(t) for t in traces.values()}:
                    by_trace.setdefault(tid, []).append(event)
        return by_trace

    def request_summary(self) -> Optional[Dict[str, dict]]:
        """Per-request lifecycle, stitched by ``trace_id``.

        One row per traced request (fleet or engine submissions made
        with tracing active), with the milestone gaps of its life as
        durations in seconds:

        * ``queue_s`` — fleet-queue wait before the first routing
          attempt (the attempt-1 ``fleet/route`` span's ``queue_s``
          attribute; None on engine-only timelines).
        * ``route_s`` / ``routes`` — total routing time and attempt
          count; ``failovers`` counts ``fleet/failover`` re-admissions.
        * ``engine_queue_s`` — admission waits inside the engine(s).
        * ``swapin_s`` — host-DRAM prefix swap-in stall paid at
          admission.
        * ``prefill_s`` — prefill compute (batch, chunked, finalize).
        * ``ttft_s`` / ``latency_s`` / ``tokens`` — from the terminal
          ``serve/request`` span (engine-clock TTFT, end-to-end
          latency, emitted tokens); ``fleet_ttft_s`` adds the fleet
          queue + routing time on top of the engine TTFT.
        * ``chunks`` — shared decode dispatches the request rode
          (via the slot map); ``spec_accepted`` — draft tokens the
          verify dispatches it participated in committed (batch-level:
          a shared verify credits every rider).
        * ``handoff_s`` / ``handoffs`` — disaggregated-serving KV
          transport time (``serve/kv_handoff`` export/import dispatches
          plus the fleet's ``fleet/handoff`` stash) and the number of
          prefill->decode handoffs; ``prefill_leg_s`` — the prefill
          leg's full service time (its non-final ``serve/request``
          terminals).  All zero on colocated timelines.
        * ``shed`` — the request hit a shed span; ``complete`` — a
          terminal ``serve/request`` span exists.

        Rows degrade gracefully when the ring buffer evicted early
        spans: missing milestones are None (or 0 for counters), and
        ``complete`` only needs the terminal span.  None when the
        timeline carries no trace ids at all.
        """
        by_trace = self._spans_by_trace()
        if not by_trace:
            return None
        requests: Dict[str, dict] = {}
        for tid, spans in sorted(by_trace.items()):
            routes = [e for e in spans if e["name"] == "fleet/route"]
            terminals = [
                e for e in spans if e["name"] == "serve/request"
            ]
            queue_s = next(
                (
                    (e.get("args") or {}).get("queue_s")
                    for e in routes
                    if isinstance((e.get("args") or {}).get("queue_s"),
                                  (int, float))
                ),
                None,
            )

            def total_of(*names):
                return sum(
                    e["dur"] / 1e6 for e in spans if e["name"] in names
                )

            spec_accepted = 0
            for event in spans:
                if event["name"] != "serve/verify":
                    continue
                accepted = (event.get("args") or {}).get("accepted")
                if isinstance(accepted, (int, float)):
                    spec_accepted += int(accepted)
            row = {
                "spans": len(spans),
                "routes": len(routes),
                "failovers": sum(
                    1 for e in spans if e["name"] == "fleet/failover"
                ),
                "queue_s": queue_s,
                "route_s": total_of("fleet/route"),
                "engine_queue_s": total_of("serve/queue_wait"),
                "swapin_s": total_of("serve/prefix_swapin"),
                "prefill_s": total_of(*self._PREFILL_SPANS),
                "chunks": sum(
                    1 for e in spans if e["name"] == "serve/chunk"
                ),
                "spec_accepted": spec_accepted,
                "handoff_s": total_of("serve/kv_handoff",
                                      "fleet/handoff"),
                "handoffs": sum(
                    1 for e in spans if e["name"] == "fleet/handoff"
                ),
                "prefill_leg_s": 0.0,
                "shed": any(
                    e["name"] in ("serve/shed", "fleet/shed")
                    for e in spans
                ),
                "ttft_s": None,
                "fleet_ttft_s": None,
                "latency_s": None,
                "tokens": None,
                "complete": bool(terminals),
            }
            if terminals:
                # Re-admitted requests keep one trace identity; the
                # engine that actually finished them retired them last.
                terminal = max(terminals, key=lambda e: e["ts"])
                args = terminal.get("args") or {}
                row["latency_s"] = terminal["dur"] / 1e6
                if row["handoffs"]:
                    # Disaggregated request: the earlier terminals are
                    # its prefill leg(s) — service time the decode
                    # leg's own TTFT never saw.  Colocated rows (no
                    # handoff spans) keep this at exactly 0.0 even
                    # across failover re-runs, whose earlier terminals
                    # are retries, not legs.
                    row["prefill_leg_s"] = sum(
                        e["dur"] / 1e6 for e in terminals
                        if e is not terminal
                    )
                ttft = args.get("ttft_s")
                if isinstance(ttft, (int, float)):
                    row["ttft_s"] = float(ttft)
                    row["fleet_ttft_s"] = (
                        float(ttft) + (queue_s or 0.0) + row["route_s"]
                        + row["prefill_leg_s"]
                    )
                tokens = args.get("tokens")
                if isinstance(tokens, (int, float)):
                    row["tokens"] = int(tokens)
            requests[tid] = row
        return requests

    #: The TTFT components, in lifecycle order (render + bench key
    #: order; first_decode is the remainder after the attributable
    #: phases).
    TTFT_COMPONENTS = (
        "queue", "route", "swapin", "prefill", "handoff", "first_decode",
    )

    def ttft_decomposition(
            self, summary: Optional[Dict[str, dict]] = None,
    ) -> Optional[Dict[str, object]]:
        """Fleet-level TTFT attribution across all stitched requests.

        For every traced request with a terminal span, fleet TTFT is
        ``queue_s + route_s + engine ttft_s`` and decomposes into:

        * ``queue`` — fleet-queue wait plus engine admission waits,
        * ``route`` — routing decisions (all attempts),
        * ``swapin`` — host-DRAM prefix swap-in stalls,
        * ``prefill`` — prefill compute,
        * ``handoff`` — disaggregated KV transport (export/import
          dispatches plus the host-pool stash; 0 on colocated
          timelines, whose totals are unchanged),
        * ``first_decode`` — the remainder (scheduler slack + the first
          decode step), clamped at zero.

        Disaggregated requests count their prefill leg's service time
        (``prefill_leg_s``) inside the total: fleet TTFT is the time
        the CALLER waited for the first decode-leg token, wherever the
        work ran.

        Returns per-component **shares** of fleet TTFT at p50/p99
        across requests, plus the fleet-TTFT percentiles themselves —
        the distributional gate the chaos harness and the QPS sweep
        check instead of raw percentiles (a regression that moves time
        *between* phases at equal TTFT still shows here).  None when no
        request decomposes (tracing off, or all terminals evicted).
        Pass a precomputed :meth:`request_summary` to skip restitching.
        """
        if summary is None:
            summary = self.request_summary()
        if not summary:
            return None
        shares: Dict[str, List[float]] = {
            name: [] for name in self.TTFT_COMPONENTS
        }
        totals: List[float] = []
        for row in summary.values():
            if row["ttft_s"] is None:
                continue
            queue = (row["queue_s"] or 0.0) + row["engine_queue_s"]
            route = row["route_s"]
            total = (
                (row["queue_s"] or 0.0) + route
                + row.get("prefill_leg_s", 0.0) + row["ttft_s"]
            )
            if total <= 0:
                continue
            components = {
                "queue": queue,
                "route": route,
                "swapin": row["swapin_s"],
                "prefill": row["prefill_s"],
                "handoff": row.get("handoff_s", 0.0),
            }
            components["first_decode"] = max(
                total - sum(components.values()), 0.0
            )
            totals.append(total)
            for name, value in components.items():
                shares[name].append(value / total)
        if not totals:
            return None
        totals.sort()
        return {
            "requests": len(totals),
            "ttft_p50_s": _percentile(totals, 0.5),
            "ttft_p99_s": _percentile(totals, 0.99),
            "shares": {
                name: {
                    "p50": _percentile(sorted(values), 0.5),
                    "p99": _percentile(sorted(values), 0.99),
                }
                for name, values in shares.items()
            },
        }

    def render_trace(self, trace_id: str) -> Optional[str]:
        """One request's stitched lifecycle as text (the ``--trace``
        drill-down): every span in start order with offset, duration
        and attributes, then the request's summary row.  None when the
        timeline holds no span for the id."""
        spans = self.trace_spans(trace_id)
        if not spans:
            return None
        t0 = spans[0]["ts"]
        lines = [f"trace {trace_id}: {len(spans)} span(s)"]
        for event in spans:
            args = dict(event.get("args") or {})
            for noise in ("trace_id", "traces", "span_id", "parent_id"):
                args.pop(noise, None)
            attrs = " ".join(
                f"{k}={v}" for k, v in sorted(args.items())
            )
            offset = _fmt_s((event["ts"] - t0) / 1e6)
            lines.append(
                f"  +{offset:>8}  {event['name']:<24}"
                f"  {_fmt_s(event['dur'] / 1e6):>8}"
                + (f"  {attrs}" if attrs else "")
            )
        row = (self.request_summary() or {}).get(str(trace_id))
        if row:
            parts = [
                f"routes {row['routes']}",
                f"failovers {row['failovers']}",
            ]
            if row["ttft_s"] is not None:
                parts.append(f"engine ttft {_fmt_s(row['ttft_s'])}")
            if row["fleet_ttft_s"] is not None:
                parts.append(
                    f"fleet ttft {_fmt_s(row['fleet_ttft_s'])}"
                )
            if row["latency_s"] is not None:
                parts.append(f"latency {_fmt_s(row['latency_s'])}")
            if row["tokens"] is not None:
                parts.append(f"{row['tokens']} tokens")
            if row["spec_accepted"]:
                parts.append(
                    f"{row['spec_accepted']} spec-accepted tokens"
                )
            if row["shed"]:
                parts.append("SHED")
            if not row["complete"]:
                parts.append("incomplete (no terminal span)")
            lines.append("  " + " · ".join(parts))
        return "\n".join(lines)

    @staticmethod
    def _render_table(rows, header) -> List[str]:
        table = [header] + rows
        widths = [max(len(row[i]) for row in table) for i in range(len(header))]
        lines = []
        for i, row in enumerate(table):
            lines.append("  ".join(
                cell.ljust(w) if j == 0 else cell.rjust(w)
                for j, (cell, w) in enumerate(zip(row, widths))
            ))
            if i == 0:
                lines.append("  ".join("-" * w for w in widths))
        return lines

    def render(self) -> str:
        rows = self.rows()
        header = ("span", "count", "total", "mean", "p50", "max", "% wall")
        lines = self._render_table([
            (
                r["name"],
                str(r["count"]),
                _fmt_s(r["total_s"]),
                _fmt_s(r["mean_s"]),
                _fmt_s(r["p50_s"]),
                _fmt_s(r["max_s"]),
                f"{r['pct_wall']:.1f}",
            )
            for r in rows
        ], header)
        serve_rows = self.serving_rows(rows)
        if serve_rows:
            lines.append("")
            lines.append("serving breakdown (per-request phases, % of "
                         "serve time):")
            lines.extend(self._render_table([
                (
                    r["name"],
                    str(r["count"]),
                    _fmt_s(r["total_s"]),
                    _fmt_s(r["mean_s"]),
                    _fmt_s(r["p50_s"]),
                    _fmt_s(r["max_s"]),
                    f"{r['pct_serve']:.1f}",
                )
                for r in serve_rows
            ], ("phase", "count", "total", "mean", "p50", "max",
                "% serve")))
        robustness = self.robustness_summary()
        if robustness:
            lines.append("")
            lines.append("robustness (retries, shedding, faults, drains):")
            for name, row in sorted(robustness["retries"].items()):
                detail = (
                    f"  retry/{name}: {row['calls']} retried call(s), "
                    f"{row['attempts']} attempts"
                )
                if row["gave_up"]:
                    detail += f", {row['gave_up']} gave up"
                lines.append(detail)
            if robustness["shed"]:
                lines.append(
                    f"  shed requests (deadline exceeded): "
                    f"{robustness['shed']}"
                )
            for site, count in sorted(robustness["faults"].items()):
                lines.append(f"  injected fault {site}: x{count}")
            if robustness["drains"]:
                lines.append(
                    f"  preemption drains: {robustness['drains']}"
                )
            if robustness["restore_fallbacks"]:
                lines.append(
                    f"  checkpoint restore fallbacks (walk-back): "
                    f"{robustness['restore_fallbacks']}"
                )
            nonfinite = robustness["nonfinite"]
            if nonfinite["windows"]:
                lines.append(
                    f"  non-finite updates skipped: {nonfinite['steps']} "
                    f"step(s) over {nonfinite['windows']} window(s)"
                )
            if robustness["rollbacks"]:
                lines.append(
                    f"  divergence rollbacks to verified checkpoint: "
                    f"{robustness['rollbacks']}"
                )
        fleet = self.fleet_summary()
        if fleet:
            lines.append("")
            lines.append("fleet (routing, supervision, scaling):")
            for rid in sorted(fleet["replicas"], key=str):
                row = fleet["replicas"][rid]
                detail = f"  replica {rid}: {row['requests']} request(s)"
                if row["mean_load"] is not None:
                    detail += f", mean load {row['mean_load']:.2f}"
                if row["mean_occupancy"] is not None:
                    detail += f", mean occupancy {row['mean_occupancy']:.1%}"
                lines.append(detail)
            events_line = (
                f"  failovers: {fleet['failovers']} · restarts: "
                f"{fleet['restarts']} · scale up x{fleet['scale']['up']} / "
                f"down x{fleet['scale']['down']}"
            )
            if fleet["shed"]:
                events_line += f" · shed {fleet['shed']}"
            lines.append(events_line)
            if fleet["occupancy_spread"] is not None:
                lines.append(
                    f"  occupancy spread across replicas: "
                    f"{fleet['occupancy_spread']:.1%}"
                )
        qos = self.qos_summary()
        if qos:
            lines.append("")
            lines.append("QoS classes (per-class TTFT / latency):")
            for name in sorted(qos["classes"]):
                row = qos["classes"][name]
                detail = f"  {name}: {row['requests']} request(s)"
                if row["ttft_p50_s"] is not None:
                    detail += (
                        f", ttft p50 {_fmt_s(row['ttft_p50_s'])} / "
                        f"p99 {_fmt_s(row['ttft_p99_s'])}"
                    )
                detail += (
                    f", latency p50 {_fmt_s(row['latency_p50_s'])} / "
                    f"p99 {_fmt_s(row['latency_p99_s'])}"
                )
                lines.append(detail)
        summary = self.request_summary()
        if summary:
            complete = sum(1 for r in summary.values() if r["complete"])
            failed_over = sum(
                1 for r in summary.values() if r["failovers"]
            )
            shed_traces = sum(1 for r in summary.values() if r["shed"])
            line = (
                f"traced requests: {len(summary)} · {complete} complete"
            )
            if failed_over:
                line += f" · {failed_over} failed over"
            if shed_traces:
                line += f" · {shed_traces} shed"
            lines.append("")
            lines.append(line)
        decomposition = self.ttft_decomposition(summary)
        if decomposition:
            lines.append("")
            lines.append(
                f"TTFT decomposition ({decomposition['requests']} traced "
                "request(s), share of fleet TTFT):"
            )
            lines.extend(self._render_table([
                (
                    name,
                    f"{decomposition['shares'][name]['p50'] * 100:.1f}",
                    f"{decomposition['shares'][name]['p99'] * 100:.1f}",
                )
                for name in self.TTFT_COMPONENTS
            ], ("component", "% p50", "% p99")))
            lines.append(
                f"  fleet ttft p50 {_fmt_s(decomposition['ttft_p50_s'])}"
                f" / p99 {_fmt_s(decomposition['ttft_p99_s'])}"
            )
        continuous = self.continuous_summary()
        if continuous:
            parts = [f"{continuous['chunks']} chunks"]
            if continuous["mean_occupancy"] is not None:
                parts.append(
                    f"mean occupancy {continuous['mean_occupancy']:.1%}"
                )
            if continuous.get("slice"):
                slice_part = f"slice {continuous['slice']}"
                if continuous.get("slice_chips"):
                    slice_part += (
                        f" ({continuous['slice_chips']:.0f} chips)"
                    )
                parts.append(slice_part)
            if continuous["mean_active"] is not None:
                active = f"mean active {continuous['mean_active']:.1f}"
                if continuous["slots"]:
                    active += f"/{continuous['slots']:.0f} slots"
                parts.append(active)
            if continuous["tokens"] is not None:
                parts.append(f"{continuous['tokens']:.0f} tokens")
            if continuous.get("bubble_fraction") is not None:
                parts.append(
                    f"host bubble {continuous['bubble_fraction']:.1%}"
                )
            lines.append("")
            lines.append("continuous batching: " + " · ".join(parts))
        spec = self.spec_summary()
        if spec:
            parts = [f"{spec['verify_dispatches']} verify dispatches"]
            if spec["acceptance_rate"] is not None:
                parts.append(
                    f"accept rate {spec['acceptance_rate']:.1%}"
                )
            if spec["tokens"]:
                parts.append(f"{spec['tokens']} tokens committed")
            parts.append(
                f"draft {_fmt_s(spec['draft_seconds'])} / verify "
                f"{_fmt_s(spec['verify_seconds'])}"
            )
            lines.append("")
            lines.append("speculative decoding: " + " · ".join(parts))
        prefix = self.prefix_summary()
        if prefix:
            parts = []
            if prefix["lookups"]:
                parts.append(
                    f"{prefix['lookups']} lookups · "
                    f"{prefix['hit_rate']:.1%} hit rate · "
                    f"{prefix['hit_tokens']} hit tokens"
                )
            lines.append("")
            lines.append(
                "prefix cache: " + (" · ".join(parts) if parts else "off")
            )
            if prefix["dram_hits"] or prefix["swapins"]:
                tier_parts = [
                    f"{prefix['hbm_hits']} hbm hits",
                    f"{prefix['dram_hits']} dram swap-in hits",
                ]
                if prefix["swapins"]:
                    tier_parts.append(
                        f"{prefix['swapins']} swap-ins "
                        f"({prefix['swapin_blocks']} blocks, "
                        f"{_fmt_s(prefix['swapin_seconds'])} total)"
                    )
                    tier_parts.append(
                        "max swap-in stall "
                        f"{_fmt_s(prefix['max_swapin_stall_seconds'])}"
                    )
                lines.append("prefix tiers: " + " · ".join(tier_parts))
            if prefix["prefill_chunks"]:
                lines.append(
                    f"chunked prefill: {prefix['prefill_chunks']} chunks · "
                    f"{_fmt_s(prefix['prefill_chunk_seconds'])} total · "
                    "max decode stall "
                    f"{_fmt_s(prefix['max_decode_stall_seconds'])}"
                )
        lines.append("")
        lines.append(
            f"{len(self.events)} spans over {_fmt_s(self.wall_seconds())} "
            "of timeline"
        )
        return "\n".join(lines)


def _fmt_s(seconds: float) -> str:
    if seconds >= 1.0:
        return f"{seconds:.2f}s"
    if seconds >= 1e-3:
        return f"{seconds * 1e3:.1f}ms"
    return f"{seconds * 1e6:.0f}us"


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m cloud_tpu.monitoring.report",
        description="Summarize a tracing.dump_timeline() Chrome-trace file.",
    )
    parser.add_argument("timeline", help="path to timeline.json")
    parser.add_argument(
        "--trace", metavar="ID", default=None,
        help="render one traced request's stitched lifecycle (every "
             "span carrying this trace_id, plus the shared dispatches "
             "it rode) instead of the timeline summary",
    )
    args = parser.parse_args(argv)
    try:
        report = TraceReport.from_file(args.timeline)
    except (OSError, ValueError, KeyError) as exc:
        print(f"could not read {args.timeline!r}: {exc}", file=sys.stderr)
        return 2
    if not report.events:
        print("no spans in timeline (was tracing enabled?)")
        return 0
    if args.trace is not None:
        rendered = report.render_trace(args.trace)
        if rendered is None:
            print(
                f"trace {args.trace!r} not found in timeline "
                "(was tracing enabled on the fleet?)",
                file=sys.stderr,
            )
            return 2
        print(rendered)
        return 0
    print(report.render())
    return 0


if __name__ == "__main__":
    sys.exit(main())
