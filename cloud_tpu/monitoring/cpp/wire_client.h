// Native Cloud Monitoring wire client: snapshot JSON -> CreateTimeSeries
// REST bodies + HTTP transport.
//
// Reference analogue: stackdriver_client.{h,cc} — conversion of the
// runtime's metric snapshot into Cloud Monitoring v3 structures
// (histogram->Distribution :69-98, point by value type :100-124,
// custom.googleapis.com metric prefix :126-136, descriptor creation deduped
// per name :138-183) and the transport that ships them
// (CreateTimeSeries :207-226).  Differences are deliberate TPU-era choices:
// REST+JSON instead of gRPC+protos (no googleapis proto toolchain in the
// training image), libcurl resolved via dlopen at runtime (no -dev
// package needed), and OAuth bearer tokens from the TPU-VM metadata
// server instead of grpc::GoogleDefaultCredentials.
//
// Testability mirrors the reference's injectable stub
// (stackdriver_client.h:41-47): the transport is a function pointer a test
// (C++ or Python/ctypes) swaps for a capture stub; conversion is a pure
// string->string function asserted against goldens.

#ifndef CLOUD_TPU_MONITORING_WIRE_CLIENT_H_
#define CLOUD_TPU_MONITORING_WIRE_CLIENT_H_

#include <mutex>
#include <set>
#include <string>
#include <utility>
#include <vector>

namespace cloud_tpu {

// Transport: POST `body` to `url` with `auth_header` (full "Authorization:
// Bearer ..." line, may be empty).  Returns HTTP status (or -1).
using TransportFn = int (*)(const char* url, const char* body,
                            const char* auth_header);

class WireClient {
 public:
  static WireClient& Global();

  // Pure conversion (no I/O): registry snapshot JSON -> the CreateTimeSeries
  // request body {"timeSeries": [...]}.  Empty string when the snapshot has
  // no series.  `start_time`/`end_time` are RFC3339 timestamps (CUMULATIVE
  // intervals start at process start, like the Python exporter).
  std::string TimeSeriesBody(const std::string& snapshot_json,
                             const std::string& start_time,
                             const std::string& end_time);

  // JSON array of descriptor bodies for names not yet successfully
  // described.  PURE (no state change): ExportSnapshot marks a name
  // described only after its POST succeeds, so transient failures retry
  // on the next interval (the Python fallback adds to _described after
  // posting the same way).
  std::string NewDescriptorBodies(const std::string& snapshot_json);

  // Full export: descriptors (deduped) then time series (chunks of 200).
  // Returns 0 on success, else the first failing HTTP status / -1.
  int ExportSnapshot(const std::string& snapshot_json);

  void SetTransport(TransportFn transport);  // test seam
  void SetProject(const std::string& project);
  void ResetForTest();

  // True when a usable transport exists (libcurl resolved or injected).
  bool TransportAvailable();

 private:
  std::string Project();
  std::string AuthHeader();
  // (name, body) for every snapshot metric not yet marked described.
  std::vector<std::pair<std::string, std::string>> PendingDescriptors(
      const std::string& snapshot_json);

  std::mutex mu_;
  int last_logged_status_ = 0;  // rate-limits failure logging
  std::string project_;
  std::set<std::string> described_;
  TransportFn transport_ = nullptr;
  // OAuth token cache (metadata-server fetches are rate-limited).
  std::string cached_token_;
  long token_expiry_unix_ = 0;
};

}  // namespace cloud_tpu

extern "C" {
// 1 when HTTP transport is usable (libcurl dlopen'd or a stub injected).
int ctpu_wire_available();
void ctpu_wire_set_project(const char* project);
void ctpu_wire_set_transport(cloud_tpu::TransportFn transport);
void ctpu_wire_reset();
// Conversion-only surfaces (golden tests); caller frees with ctpu_free.
char* ctpu_wire_time_series_body(const char* snapshot_json,
                                 const char* start_time,
                                 const char* end_time);
char* ctpu_wire_new_descriptor_bodies(const char* snapshot_json);
// Full export of one snapshot; 0 on success.
int ctpu_wire_export_snapshot(const char* snapshot_json);
// Route the periodic Exporter's sink through this wire client (the pure
// C++ path: timer thread -> snapshot -> convert -> POST, no Python).
void ctpu_exporter_use_wire_client();
}

#endif  // CLOUD_TPU_MONITORING_WIRE_CLIENT_H_
