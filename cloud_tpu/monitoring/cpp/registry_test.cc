// Native-side test (assert-based; the reference used gtest/gmock with a
// mock gRPC stub, stackdriver_client_test.cc — here the sink callback is
// the injectable seam).
#include <cassert>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include "exporter.h"
#include "metrics_registry.h"

namespace {

std::vector<std::string> g_sink_payloads;

void TestSink(const char* json) { g_sink_payloads.emplace_back(json); }

void TestCountersAndGauges() {
  ctpu_registry_reset();
  ctpu_counter_inc("steps", 1);
  ctpu_counter_inc("steps", 2);
  ctpu_gauge_set("lr", 0.5);
  char* json = ctpu_metrics_snapshot_json();
  std::string s(json);
  ctpu_free(json);
  assert(s.find("\"steps\":3") != std::string::npos);
  assert(s.find("\"lr\":0.5") != std::string::npos);
}

void TestDistributionWelford() {
  ctpu_registry_reset();
  // values 2, 4, 6 -> count 3, mean 4, ssd = 8
  ctpu_distribution_record("latency", 2.0);
  ctpu_distribution_record("latency", 4.0);
  ctpu_distribution_record("latency", 6.0);
  char* json = ctpu_metrics_snapshot_json();
  std::string s(json);
  ctpu_free(json);
  assert(s.find("\"count\":3") != std::string::npos);
  assert(s.find("\"mean\":4") != std::string::npos);
  assert(s.find("\"sum_squared_deviation\":8") != std::string::npos);
  // buckets: 2 -> [2,4) idx 2; 4 -> [4,8) idx 3; 6 -> idx 3
  assert(s.find("\"buckets\":[0,0,1,2,") != std::string::npos);
}

void TestConcurrentIncrements() {
  ctpu_registry_reset();
  std::vector<std::thread> threads;
  for (int t = 0; t < 8; ++t) {
    threads.emplace_back([] {
      for (int i = 0; i < 10000; ++i) ctpu_counter_inc("concurrent", 1);
    });
  }
  for (auto& th : threads) th.join();
  char* json = ctpu_metrics_snapshot_json();
  std::string s(json);
  ctpu_free(json);
  assert(s.find("\"concurrent\":80000") != std::string::npos);
}

void TestExportOnceThroughSink() {
  ctpu_registry_reset();
  g_sink_payloads.clear();
  ctpu_counter_inc("exported", 7);
  ctpu_exporter_set_sink(TestSink);
  ctpu_exporter_export_once();
  assert(g_sink_payloads.size() == 1);
  assert(g_sink_payloads[0].find("\"exported\":7") != std::string::npos);
  ctpu_exporter_set_sink(nullptr);
}

void TestEscaping() {
  ctpu_registry_reset();
  ctpu_counter_inc("weird\"name\\x", 1);
  char* json = ctpu_metrics_snapshot_json();
  std::string s(json);
  ctpu_free(json);
  assert(s.find("weird\\\"name\\\\x") != std::string::npos);
}

}  // namespace

int main() {
  TestCountersAndGauges();
  TestDistributionWelford();
  TestConcurrentIncrements();
  TestExportOnceThroughSink();
  TestEscaping();
  std::printf("registry_test: all tests passed\n");
  return 0;
}
