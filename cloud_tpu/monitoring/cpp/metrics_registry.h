// Native metrics registry: counters, gauges, distributions.
//
// Reference analogue: the TF CollectionRegistry the reference's C++
// exporter collected from (stackdriver_exporter.cc:86-89).  This framework
// owns its own registry (SURVEY.md §7 hard parts: "the new framework needs
// its own metrics registry with a C++ collection point").
//
// The C API (extern "C") is consumed from Python via ctypes; all
// registry operations are thread-safe and lock-cheap (one mutex per
// registry; hot-path increments are a map lookup + add).

#ifndef CLOUD_TPU_MONITORING_METRICS_REGISTRY_H_
#define CLOUD_TPU_MONITORING_METRICS_REGISTRY_H_

#include <cstdint>
#include <map>
#include <mutex>
#include <string>
#include <vector>

namespace cloud_tpu {

// JSON string escaping shared by the registry (names INTO snapshots) and
// the wire client (names OUT into request bodies) — one implementation so
// the two sides can never disagree on an escape.
std::string JsonEscapeString(const std::string& s);

// Exponential histogram buckets: [0, 1), [1, 2), [2, 4), ... 2^k.
constexpr int kNumBuckets = 24;

struct Distribution {
  int64_t count = 0;
  double mean = 0.0;
  double sum_squared_deviation = 0.0;
  int64_t buckets[kNumBuckets] = {0};

  void Record(double value);
};

class MetricsRegistry {
 public:
  static MetricsRegistry& Global();

  void CounterInc(const std::string& name, int64_t delta);
  void GaugeSet(const std::string& name, double value);
  void DistributionRecord(const std::string& name, double value);

  // Serializes every metric to JSON:
  // {"counters": {name: int}, "gauges": {name: float},
  //  "distributions": {name: {count, mean, sum_squared_deviation,
  //                           buckets: [...]}}}
  std::string SnapshotJson();

  // Same, restricted to names for which filter() returns true.
  std::string SnapshotJsonFiltered(bool (*filter)(const std::string&, void*),
                                   void* arg);

  void Reset();

 private:
  std::mutex mu_;
  std::map<std::string, int64_t> counters_;
  std::map<std::string, double> gauges_;
  std::map<std::string, Distribution> distributions_;
};

}  // namespace cloud_tpu

extern "C" {
void ctpu_counter_inc(const char* name, int64_t delta);
void ctpu_gauge_set(const char* name, double value);
void ctpu_distribution_record(const char* name, double value);
// Returns a malloc'd JSON string; free with ctpu_free.
char* ctpu_metrics_snapshot_json();
void ctpu_free(char* ptr);
void ctpu_registry_reset();
}

#endif  // CLOUD_TPU_MONITORING_METRICS_REGISTRY_H_
