#include "exporter.h"

#include <cctype>
#include <chrono>
#include <cstdlib>
#include <sstream>

#include "metrics_registry.h"

namespace cloud_tpu {

namespace {

std::string GetEnv(const char* name) {
  const char* value = std::getenv(name);
  return value ? std::string(value) : std::string();
}

}  // namespace

ExporterConfig::ExporterConfig() { ReadFromEnv(); }

void ExporterConfig::Reload() {
  std::lock_guard<std::mutex> lock(mu_);
  ReadFromEnv();
}

void ExporterConfig::ReadFromEnv() {
  std::string enabled = GetEnv("CLOUD_TPU_MONITORING_ENABLED");
  for (auto& c : enabled) c = static_cast<char>(std::tolower(c));
  // Case-insensitive, matching the Python-side gate exactly.
  enabled_ = (enabled == "1" || enabled == "true");
  const std::string interval = GetEnv("CLOUD_TPU_MONITORING_INTERVAL");
  interval_seconds_ = 10;  // reference period: stackdriver_exporter.cc:28
  if (!interval.empty()) {
    const int parsed = std::atoi(interval.c_str());
    if (parsed > 0) interval_seconds_ = parsed;
  }
  // Comma-separated allowlist (stackdriver_config.cc:26-32); empty =>
  // export every metric (this framework's registry only holds framework
  // metrics, unlike TF's global registry which needed a default allowlist).
  allowlist_.clear();
  std::stringstream ss(GetEnv("CLOUD_TPU_MONITORING_ALLOWLIST"));
  std::string item;
  while (std::getline(ss, item, ',')) {
    if (!item.empty()) allowlist_.insert(item);
  }
}

ExporterConfig& ExporterConfig::Global() {
  static ExporterConfig* config = new ExporterConfig();
  return *config;
}

bool ExporterConfig::Enabled() const {
  std::lock_guard<std::mutex> lock(mu_);
  return enabled_;
}

int ExporterConfig::IntervalSeconds() const {
  std::lock_guard<std::mutex> lock(mu_);
  return interval_seconds_;
}

bool ExporterConfig::Allowed(const std::string& name) const {
  std::lock_guard<std::mutex> lock(mu_);
  if (allowlist_.empty()) return true;
  return allowlist_.count(name) > 0;
}

Exporter& Exporter::Global() {
  static Exporter* exporter = new Exporter();
  return *exporter;
}

void Exporter::SetSink(SinkFn sink) {
  std::lock_guard<std::mutex> lock(mu_);
  sink_ = sink;
}

bool Exporter::Start() {
  std::lock_guard<std::mutex> lock(mu_);
  if (!ExporterConfig::Global().Enabled()) return false;
  if (running_.load()) return false;  // idempotent (exporter.h:35-46 parity)
  running_.store(true);
  thread_ = std::thread(&Exporter::Loop, this);
  return true;
}

void Exporter::Stop() {
  {
    std::lock_guard<std::mutex> lock(mu_);
    if (!running_.load()) return;
    running_.store(false);
  }
  cv_.notify_all();
  if (thread_.joinable()) thread_.join();
}

namespace {
bool AllowedFilter(const std::string& name, void*) {
  return ExporterConfig::Global().Allowed(name);
}
}  // namespace

std::string Exporter::FilteredSnapshot() {
  return MetricsRegistry::Global().SnapshotJsonFiltered(AllowedFilter,
                                                        nullptr);
}

void Exporter::ExportOnce() {
  SinkFn sink;
  {
    std::lock_guard<std::mutex> lock(mu_);
    sink = sink_;
  }
  if (sink == nullptr) return;
  const std::string json = FilteredSnapshot();
  sink(json.c_str());
}

void Exporter::Loop() {
  std::unique_lock<std::mutex> lock(mu_);
  while (running_.load()) {
    const auto interval =
        std::chrono::seconds(ExporterConfig::Global().IntervalSeconds());
    cv_.wait_for(lock, interval, [this] { return !running_.load(); });
    if (!running_.load()) break;
    SinkFn sink = sink_;
    lock.unlock();
    if (sink != nullptr) {
      const std::string json = FilteredSnapshot();
      sink(json.c_str());
    }
    lock.lock();
  }
}

}  // namespace cloud_tpu

extern "C" {

void ctpu_exporter_set_sink(cloud_tpu::SinkFn sink) {
  cloud_tpu::Exporter::Global().SetSink(sink);
}

int ctpu_exporter_start() {
  return cloud_tpu::Exporter::Global().Start() ? 1 : 0;
}

void ctpu_exporter_stop() { cloud_tpu::Exporter::Global().Stop(); }

void ctpu_exporter_config_reload() {
  cloud_tpu::ExporterConfig::Global().Reload();
}

void ctpu_exporter_export_once() {
  cloud_tpu::Exporter::Global().ExportOnce();
}

}  // extern "C"
