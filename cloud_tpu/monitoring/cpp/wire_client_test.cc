// Wire-client test (assert-based, like registry_test.cc).  The reference
// tested stackdriver_client.cc by injecting MockMetricServiceStub through
// a test-only constructor and asserting the exact protos
// (stackdriver_client_test.cc); here the injectable seam is the transport
// function pointer and the assertions are on the exact JSON bodies.
#include <cassert>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include "exporter.h"
#include "metrics_registry.h"
#include "wire_client.h"

namespace {

struct Request {
  std::string url;
  std::string body;
  std::string auth;
};

std::vector<Request> g_requests;

int CaptureTransport(const char* url, const char* body,
                     const char* auth_header) {
  g_requests.push_back({url, body, auth_header ? auth_header : ""});
  return 200;
}

constexpr char kSnapshot[] =
    "{\"counters\":{\"steps\":3},\"gauges\":{\"lr\":0.5},"
    "\"distributions\":{\"latency\":{\"count\":3,\"mean\":4,"
    "\"sum_squared_deviation\":8,\"buckets\":[0,0,1,2]}}}";

void TestTimeSeriesGolden() {
  char* body = ctpu_wire_time_series_body(
      kSnapshot, "2026-01-01T00:00:00Z", "2026-01-01T00:00:10Z");
  std::string s(body);
  ctpu_free(body);
  // Counter -> CUMULATIVE int64 with start time.
  assert(s.find("{\"metric\":{\"type\":\"custom.googleapis.com/cloud_tpu/"
                "steps\"},\"resource\":{\"type\":\"global\",\"labels\":{}},"
                "\"metricKind\":\"CUMULATIVE\",\"points\":[{\"interval\":{"
                "\"startTime\":\"2026-01-01T00:00:00Z\",\"endTime\":"
                "\"2026-01-01T00:00:10Z\"},\"value\":{\"int64Value\":\"3\"}}"
                "]}") != std::string::npos);
  // Gauge -> GAUGE double, no start time.
  assert(s.find("\"metricKind\":\"GAUGE\",\"points\":[{\"interval\":{"
                "\"endTime\":\"2026-01-01T00:00:10Z\"},\"value\":{"
                "\"doubleValue\":0.5}}]}") != std::string::npos);
  // Distribution -> the reference's histogram mapping
  // (stackdriver_client.cc:69-98): count/mean/ssd + exponential buckets.
  assert(s.find("\"distributionValue\":{\"count\":\"3\",\"mean\":4,"
                "\"sumOfSquaredDeviation\":8,\"bucketOptions\":{"
                "\"exponentialBuckets\":{\"numFiniteBuckets\":2,"
                "\"growthFactor\":2,\"scale\":1}},\"bucketCounts\":"
                "[\"0\",\"0\",\"1\",\"2\"]}}") != std::string::npos);
}

void TestEmptySnapshotProducesNoBody() {
  char* body = ctpu_wire_time_series_body("{\"counters\":{}}", "a", "b");
  assert(std::strlen(body) == 0);
  ctpu_free(body);
}

void TestDescriptorBodiesArePureAndComplete() {
  ctpu_wire_reset();
  char* first = ctpu_wire_new_descriptor_bodies(kSnapshot);
  std::string s1(first);
  ctpu_free(first);
  assert(s1.find("\"type\":\"custom.googleapis.com/cloud_tpu/steps\","
                 "\"metricKind\":\"CUMULATIVE\",\"valueType\":\"INT64\"") !=
         std::string::npos);
  assert(s1.find("\"valueType\":\"DOUBLE\"") != std::string::npos);
  assert(s1.find("\"valueType\":\"DISTRIBUTION\"") != std::string::npos);
  // Pure view: names become "described" only after a successful POST
  // (TestExportThroughStubTransport covers the dedup), so a second call
  // before any export still lists everything.
  char* second = ctpu_wire_new_descriptor_bodies(kSnapshot);
  assert(s1 == second);
  ctpu_free(second);
}

int FailingTransport(const char*, const char*, const char*) { return 503; }

void TestDescriptorRetryAfterTransportFailure() {
  // A transiently failing transport must NOT burn the descriptor dedup:
  // the names retry on the next export (reference parity: _described is
  // appended only after the POST in the Python fallback too).
  ctpu_wire_reset();
  ctpu_wire_set_project("test-proj");
  ctpu_wire_set_transport(FailingTransport);
  assert(ctpu_wire_export_snapshot(kSnapshot) == 503);
  g_requests.clear();
  ctpu_wire_set_transport(CaptureTransport);
  assert(ctpu_wire_export_snapshot(kSnapshot) == 0);
  int descriptor_posts = 0;
  for (const Request& request : g_requests) {
    if (request.url.find("/metricDescriptors") != std::string::npos) {
      ++descriptor_posts;
    }
  }
  assert(descriptor_posts == 3);  // steps, lr, latency — all retried
}

void TestMetricNameEscaping() {
  ctpu_wire_reset();
  // The registry escapes names into its snapshot; the wire client must
  // re-escape on the way out or the request body is invalid JSON.
  char* body = ctpu_wire_time_series_body(
      "{\"counters\":{\"weird\\\"name\":1}}", "a", "b");
  std::string s(body);
  ctpu_free(body);
  assert(s.find("cloud_tpu/weird\\\"name") != std::string::npos);
}

void TestDoubleRoundTrip() {
  ctpu_wire_reset();
  // %g would truncate to 1.23457e+06; full precision must survive.
  char* body = ctpu_wire_time_series_body(
      "{\"gauges\":{\"examples\":1234567}}", "a", "b");
  std::string s(body);
  ctpu_free(body);
  assert(s.find("\"doubleValue\":1234567") != std::string::npos);
}

void TestSeriesChunkedAt200() {
  ctpu_wire_reset();
  g_requests.clear();
  ctpu_wire_set_project("test-proj");
  ctpu_wire_set_transport(CaptureTransport);
  std::string snapshot = "{\"counters\":{";
  for (int i = 0; i < 250; ++i) {
    if (i != 0) snapshot += ",";
    snapshot += "\"m" + std::to_string(i) + "\":1";
  }
  snapshot += "}}";
  assert(ctpu_wire_export_snapshot(snapshot.c_str()) == 0);
  int series_posts = 0;
  for (const Request& request : g_requests) {
    if (request.url.find("/timeSeries") != std::string::npos) ++series_posts;
  }
  assert(series_posts == 2);  // 200 + 50 (API cap per CreateTimeSeries)
}

void TestEscapedNameRoundTrip() {
  ctpu_wire_reset();
  // A name with a tab: the registry writes \t into the snapshot; the wire
  // client must parse it back and re-emit the SAME escape (shared
  // JsonEscapeString), not a corrupted literal.
  char* body = ctpu_wire_time_series_body(
      "{\"counters\":{\"a\\tb\":1}}", "s", "e");
  std::string s(body);
  ctpu_free(body);
  assert(s.find("cloud_tpu/a\\tb") != std::string::npos);
}

void TestExportThroughStubTransport() {
  ctpu_wire_reset();
  g_requests.clear();
  ctpu_wire_set_project("test-proj");
  ctpu_wire_set_transport(CaptureTransport);
  const int rc = ctpu_wire_export_snapshot(kSnapshot);
  assert(rc == 0);
  // 3 descriptor posts + 1 timeSeries post.
  assert(g_requests.size() == 4);
  for (int i = 0; i < 3; ++i) {
    assert(g_requests[i].url ==
           "https://monitoring.googleapis.com/v3/projects/test-proj/"
           "metricDescriptors");
  }
  assert(g_requests[3].url ==
         "https://monitoring.googleapis.com/v3/projects/test-proj/"
         "timeSeries");
  assert(g_requests[3].body.find("\"timeSeries\":[") != std::string::npos);
  // Injected stub => no real auth header attached.
  assert(g_requests[3].auth.empty());

  // Second export: descriptors deduped, only the timeSeries post remains.
  g_requests.clear();
  assert(ctpu_wire_export_snapshot(kSnapshot) == 0);
  assert(g_requests.size() == 1);
  assert(g_requests[0].url.find("/timeSeries") != std::string::npos);
}

void TestMissingProjectFails() {
  ctpu_wire_reset();
  ctpu_wire_set_transport(CaptureTransport);
  // No project configured and (in this test env) no env var.
  unsetenv("CLOUD_TPU_MONITORING_PROJECT_ID");
  assert(ctpu_wire_export_snapshot(kSnapshot) == -2);
}

void TestPeriodicExporterRidesWireClient() {
  // The pure-C++ path: registry -> Exporter::ExportOnce -> wire client ->
  // transport, no host-language hop anywhere.
  ctpu_registry_reset();
  ctpu_wire_reset();
  g_requests.clear();
  ctpu_wire_set_project("test-proj");
  ctpu_wire_set_transport(CaptureTransport);
  ctpu_counter_inc("native_steps", 5);
  ctpu_exporter_use_wire_client();
  ctpu_exporter_export_once();
  assert(!g_requests.empty());
  const std::string& body = g_requests.back().body;
  assert(body.find("native_steps") != std::string::npos);
  assert(body.find("\"int64Value\":\"5\"") != std::string::npos);
  ctpu_exporter_set_sink(nullptr);
}

}  // namespace

int main() {
  TestTimeSeriesGolden();
  TestEmptySnapshotProducesNoBody();
  TestDescriptorBodiesArePureAndComplete();
  TestDescriptorRetryAfterTransportFailure();
  TestMetricNameEscaping();
  TestEscapedNameRoundTrip();
  TestSeriesChunkedAt200();
  TestDoubleRoundTrip();
  TestExportThroughStubTransport();
  TestMissingProjectFails();
  TestPeriodicExporterRidesWireClient();
  std::printf("wire_client_test: all tests passed\n");
  return 0;
}
