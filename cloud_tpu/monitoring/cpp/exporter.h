// Periodic metrics exporter with an injectable sink.
//
// Reference analogue: stackdriver_exporter.{h,cc} — a 10s-period thread
// (:28) collecting from the registry (:86-89), filtering against an
// env-configured allowlist (stackdriver_config.cc:26-45), env-gated
// enablement (:31-36), idempotent start under a mutex
// (stackdriver_exporter.h:35-46).  The gRPC transport is replaced by a
// sink callback (registered from Python via ctypes) that receives the
// filtered snapshot JSON — transport lives host-side where auth already
// is, the collection point stays native.
//
// Env contract:
//   CLOUD_TPU_MONITORING_ENABLED    "1"/"true" to allow StartExporter
//   CLOUD_TPU_MONITORING_INTERVAL   seconds between exports (default 10)
//   CLOUD_TPU_MONITORING_ALLOWLIST  comma-separated metric names
//                                   (default: framework metrics, see .cc)

#ifndef CLOUD_TPU_MONITORING_EXPORTER_H_
#define CLOUD_TPU_MONITORING_EXPORTER_H_

#include <atomic>
#include <condition_variable>
#include <mutex>
#include <set>
#include <string>
#include <thread>

namespace cloud_tpu {

using SinkFn = void (*)(const char* json);

class ExporterConfig {
 public:
  static ExporterConfig& Global();
  bool Enabled() const;
  int IntervalSeconds() const;
  // True if the metric is exported (allowlist semantics of
  // stackdriver_config.cc:34-45).
  bool Allowed(const std::string& name) const;
  // Re-read the env vars.  The singleton caches them at first use, which
  // may predate the host process deciding to enable monitoring (e.g. a
  // snapshot is taken before StartExporter); Start() reloads first.
  void Reload();

 private:
  ExporterConfig();
  void ReadFromEnv();
  mutable std::mutex mu_;
  bool enabled_;
  int interval_seconds_;
  std::set<std::string> allowlist_;
};

class Exporter {
 public:
  static Exporter& Global();

  void SetSink(SinkFn sink);
  // Idempotent; returns false when disabled by env or already running.
  bool Start();
  void Stop();
  // One collection+filter+sink cycle (exposed for tests/manual flush).
  void ExportOnce();

 private:
  void Loop();
  std::string FilteredSnapshot();

  std::mutex mu_;
  std::condition_variable cv_;
  std::thread thread_;
  std::atomic<bool> running_{false};
  SinkFn sink_ = nullptr;
};

}  // namespace cloud_tpu

extern "C" {
void ctpu_exporter_set_sink(cloud_tpu::SinkFn sink);
int ctpu_exporter_start();
void ctpu_exporter_stop();
void ctpu_exporter_export_once();
void ctpu_exporter_config_reload();
}

#endif  // CLOUD_TPU_MONITORING_EXPORTER_H_
