#include "metrics_registry.h"

#include <cmath>
#include <cstdlib>
#include <cstring>
#include <limits>
#include <sstream>

namespace cloud_tpu {

namespace {

int BucketIndex(double value) {
  // Non-finite guard: log2(nan/inf) would yield an out-of-range index.
  if (value == std::numeric_limits<double>::infinity()) return kNumBuckets - 1;
  if (!std::isfinite(value) || value < 1.0) return 0;
  int idx = 1 + static_cast<int>(std::floor(std::log2(value)));
  if (idx >= kNumBuckets) idx = kNumBuckets - 1;
  return idx;
}

void AppendDouble(std::ostringstream& os, double v) {
  if (std::isfinite(v)) {
    os << v;
  } else {
    os << "0";  // JSON has no inf/nan; clamp
  }
}

}  // namespace

// Shared with the wire client (metrics_registry.h): names are escaped the
// same way INTO snapshots and OUT into request bodies.
std::string JsonEscapeString(const std::string& s) {
  std::string out;
  out.reserve(s.size());
  for (char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\t': out += "\\t"; break;
      default:
        if (static_cast<unsigned char>(c) < 0x20) {
          char buf[8];
          std::snprintf(buf, sizeof(buf), "\\u%04x", c);
          out += buf;
        } else {
          out += c;
        }
    }
  }
  return out;
}

void Distribution::Record(double value) {
  ++count;
  const double delta = value - mean;
  mean += delta / static_cast<double>(count);
  sum_squared_deviation += delta * (value - mean);  // Welford
  ++buckets[BucketIndex(value)];
}

MetricsRegistry& MetricsRegistry::Global() {
  static MetricsRegistry* registry = new MetricsRegistry();
  return *registry;
}

void MetricsRegistry::CounterInc(const std::string& name, int64_t delta) {
  std::lock_guard<std::mutex> lock(mu_);
  counters_[name] += delta;
}

void MetricsRegistry::GaugeSet(const std::string& name, double value) {
  std::lock_guard<std::mutex> lock(mu_);
  gauges_[name] = value;
}

void MetricsRegistry::DistributionRecord(const std::string& name,
                                         double value) {
  std::lock_guard<std::mutex> lock(mu_);
  distributions_[name].Record(value);
}

namespace {
bool AllowAll(const std::string&, void*) { return true; }
}  // namespace

std::string MetricsRegistry::SnapshotJson() {
  return SnapshotJsonFiltered(AllowAll, nullptr);
}

std::string MetricsRegistry::SnapshotJsonFiltered(
    bool (*filter)(const std::string&, void*), void* arg) {
  std::lock_guard<std::mutex> lock(mu_);
  std::ostringstream os;
  os.precision(17);
  os << "{\"counters\":{";
  bool first = true;
  for (const auto& [name, value] : counters_) {
    if (!filter(name, arg)) continue;
    if (!first) os << ",";
    first = false;
    os << "\"" << JsonEscapeString(name) << "\":" << value;
  }
  os << "},\"gauges\":{";
  first = true;
  for (const auto& [name, value] : gauges_) {
    if (!filter(name, arg)) continue;
    if (!first) os << ",";
    first = false;
    os << "\"" << JsonEscapeString(name) << "\":";
    AppendDouble(os, value);
  }
  os << "},\"distributions\":{";
  first = true;
  for (const auto& [name, dist] : distributions_) {
    if (!filter(name, arg)) continue;
    if (!first) os << ",";
    first = false;
    os << "\"" << JsonEscapeString(name) << "\":{\"count\":" << dist.count
       << ",\"mean\":";
    AppendDouble(os, dist.mean);
    os << ",\"sum_squared_deviation\":";
    AppendDouble(os, dist.sum_squared_deviation);
    os << ",\"buckets\":[";
    for (int i = 0; i < kNumBuckets; ++i) {
      if (i) os << ",";
      os << dist.buckets[i];
    }
    os << "]}";
  }
  os << "}}";
  return os.str();
}

void MetricsRegistry::Reset() {
  std::lock_guard<std::mutex> lock(mu_);
  counters_.clear();
  gauges_.clear();
  distributions_.clear();
}

}  // namespace cloud_tpu

extern "C" {

void ctpu_counter_inc(const char* name, int64_t delta) {
  cloud_tpu::MetricsRegistry::Global().CounterInc(name, delta);
}

void ctpu_gauge_set(const char* name, double value) {
  cloud_tpu::MetricsRegistry::Global().GaugeSet(name, value);
}

void ctpu_distribution_record(const char* name, double value) {
  cloud_tpu::MetricsRegistry::Global().DistributionRecord(name, value);
}

char* ctpu_metrics_snapshot_json() {
  const std::string json =
      cloud_tpu::MetricsRegistry::Global().SnapshotJson();
  char* out = static_cast<char*>(std::malloc(json.size() + 1));
  std::memcpy(out, json.c_str(), json.size() + 1);
  return out;
}

void ctpu_free(char* ptr) { std::free(ptr); }

void ctpu_registry_reset() { cloud_tpu::MetricsRegistry::Global().Reset(); }

}  // extern "C"
