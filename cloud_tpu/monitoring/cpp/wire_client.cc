#include "wire_client.h"

#include <dlfcn.h>
#include <signal.h>
#include <sys/wait.h>
#include <unistd.h>

#include <algorithm>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <map>
#include <sstream>
#include <vector>

#include "exporter.h"
#include "metrics_registry.h"

namespace cloud_tpu {
namespace {

std::string GetEnv(const char* name) {
  const char* value = std::getenv(name);
  return value ? std::string(value) : std::string();
}

std::string Rfc3339Now() {
  char buf[32];
  std::time_t now = std::time(nullptr);
  std::tm tm_utc;
  gmtime_r(&now, &tm_utc);
  std::strftime(buf, sizeof(buf), "%Y-%m-%dT%H:%M:%SZ", &tm_utc);
  return std::string(buf);
}

// ---------------------------------------------------------------------------
// Minimal JSON reader for the registry snapshot schema (flat objects of
// numbers, one nested object per distribution, one numeric array).
// String escapes mirror JsonEscapeString (the registry produces every
// string this parser reads).
// ---------------------------------------------------------------------------

struct JsonValue {
  enum Kind { kNull, kNumber, kString, kObject, kArray } kind = kNull;
  double number = 0.0;
  std::string text;
  std::vector<std::pair<std::string, JsonValue>> members;  // object
  std::vector<JsonValue> items;                            // array
};

class JsonParser {
 public:
  explicit JsonParser(const std::string& text) : s_(text) {}

  bool Parse(JsonValue* out) { return Value(out) && (Skip(), pos_ == s_.size()); }

 private:
  void Skip() {
    while (pos_ < s_.size() && std::isspace(static_cast<unsigned char>(s_[pos_])))
      ++pos_;
  }

  bool Value(JsonValue* out) {
    Skip();
    if (pos_ >= s_.size()) return false;
    const char c = s_[pos_];
    if (c == '{') return Object(out);
    if (c == '[') return Array(out);
    if (c == '"') return String(out);
    return Number(out);
  }

  bool Object(JsonValue* out) {
    out->kind = JsonValue::kObject;
    ++pos_;  // '{'
    Skip();
    if (pos_ < s_.size() && s_[pos_] == '}') { ++pos_; return true; }
    while (pos_ < s_.size()) {
      JsonValue key;
      Skip();
      if (!String(&key)) return false;
      Skip();
      if (pos_ >= s_.size() || s_[pos_] != ':') return false;
      ++pos_;
      JsonValue value;
      if (!Value(&value)) return false;
      out->members.emplace_back(key.text, std::move(value));
      Skip();
      if (pos_ < s_.size() && s_[pos_] == ',') { ++pos_; continue; }
      if (pos_ < s_.size() && s_[pos_] == '}') { ++pos_; return true; }
      return false;
    }
    return false;
  }

  bool Array(JsonValue* out) {
    out->kind = JsonValue::kArray;
    ++pos_;  // '['
    Skip();
    if (pos_ < s_.size() && s_[pos_] == ']') { ++pos_; return true; }
    while (pos_ < s_.size()) {
      JsonValue item;
      if (!Value(&item)) return false;
      out->items.push_back(std::move(item));
      Skip();
      if (pos_ < s_.size() && s_[pos_] == ',') { ++pos_; continue; }
      if (pos_ < s_.size() && s_[pos_] == ']') { ++pos_; return true; }
      return false;
    }
    return false;
  }

  bool String(JsonValue* out) {
    if (pos_ >= s_.size() || s_[pos_] != '"') return false;
    out->kind = JsonValue::kString;
    ++pos_;
    while (pos_ < s_.size() && s_[pos_] != '"') {
      char c = s_[pos_];
      if (c == '\\' && pos_ + 1 < s_.size()) {
        // Decode the escapes JsonEscapeString emits (the registry is the
        // producer of every string this parser reads).
        ++pos_;
        switch (s_[pos_]) {
          case 'n': c = '\n'; break;
          case 't': c = '\t'; break;
          case 'r': c = '\r'; break;
          case 'b': c = '\b'; break;
          case 'f': c = '\f'; break;
          case 'u': {
            if (pos_ + 4 < s_.size()) {
              const std::string hex = s_.substr(pos_ + 1, 4);
              c = static_cast<char>(std::strtol(hex.c_str(), nullptr, 16));
              pos_ += 4;
            }
            break;
          }
          default: c = s_[pos_];  // \" \\ \/ and anything else: literal
        }
      }
      out->text.push_back(c);
      ++pos_;
    }
    if (pos_ >= s_.size()) return false;
    ++pos_;  // closing quote
    return true;
  }

  bool Number(JsonValue* out) {
    const size_t start = pos_;
    while (pos_ < s_.size() &&
           (std::isdigit(static_cast<unsigned char>(s_[pos_])) ||
            s_[pos_] == '-' || s_[pos_] == '+' || s_[pos_] == '.' ||
            s_[pos_] == 'e' || s_[pos_] == 'E')) {
      ++pos_;
    }
    if (pos_ == start) return false;
    out->kind = JsonValue::kNumber;
    out->number = std::atof(s_.substr(start, pos_ - start).c_str());
    return true;
  }

  const std::string& s_;
  size_t pos_ = 0;
};

const JsonValue* Find(const JsonValue& obj, const std::string& key) {
  if (obj.kind != JsonValue::kObject) return nullptr;
  for (const auto& member : obj.members) {
    if (member.first == key) return &member.second;
  }
  return nullptr;
}

std::string FormatDouble(double value) {
  char buf[40];
  // %.17g round-trips every double (plain %g keeps only 6 significant
  // digits — a gauge like 1234567 would silently export as 1.23457e+06).
  std::snprintf(buf, sizeof(buf), "%.17g", value);
  return std::string(buf);
}

// Names are re-escaped on the way out with the SAME escaper the registry
// used on the way in (JsonEscapeString, metrics_registry.h) — a quote or
// control char in a metric name round-trips instead of corrupting the
// request body.

constexpr char kMetricPrefix[] = "custom.googleapis.com/cloud_tpu";
constexpr double kBucketGrowth = 2.0;  // registry buckets are 2^(k-1)

std::string OneSeries(const std::string& name, const char* kind,
                      const std::string& value_json,
                      const std::string& start_time,
                      const std::string& end_time) {
  std::ostringstream out;
  out << "{\"metric\":{\"type\":\"" << kMetricPrefix << "/"
      << JsonEscapeString(name) << "\"},"
      << "\"resource\":{\"type\":\"global\",\"labels\":{}},"
      << "\"metricKind\":\"" << kind << "\",\"points\":[{\"interval\":{";
  if (std::string(kind) == "CUMULATIVE") {
    out << "\"startTime\":\"" << start_time << "\",";
  }
  out << "\"endTime\":\"" << end_time << "\"},\"value\":" << value_json
      << "}]}";
  return out.str();
}

// The API caps CreateTimeSeries at 200 series per call (the Python
// fallback chunks the same way).
constexpr size_t kMaxSeriesPerPost = 200;

std::string JoinSeriesChunk(const std::vector<std::string>& series,
                            size_t begin, size_t end) {
  std::ostringstream out;
  out << "{\"timeSeries\":[";
  for (size_t i = begin; i < end; ++i) {
    if (i != begin) out << ",";
    out << series[i];
  }
  out << "]}";
  return out.str();
}

std::vector<std::string> SeriesList(const std::string& snapshot_json,
                                    const std::string& start_time,
                                    const std::string& end_time) {
  std::vector<std::string> series;
  JsonValue snapshot;
  if (!JsonParser(snapshot_json).Parse(&snapshot)) return series;
  if (const JsonValue* counters = Find(snapshot, "counters")) {
    for (const auto& entry : counters->members) {
      series.push_back(OneSeries(
          entry.first, "CUMULATIVE",
          "{\"int64Value\":\"" +
              std::to_string(static_cast<long long>(entry.second.number)) +
              "\"}",
          start_time, end_time));
    }
  }
  if (const JsonValue* gauges = Find(snapshot, "gauges")) {
    for (const auto& entry : gauges->members) {
      series.push_back(OneSeries(
          entry.first, "GAUGE",
          "{\"doubleValue\":" + FormatDouble(entry.second.number) + "}",
          start_time, end_time));
    }
  }
  if (const JsonValue* dists = Find(snapshot, "distributions")) {
    for (const auto& entry : dists->members) {
      const JsonValue& dist = entry.second;
      const JsonValue* buckets = Find(dist, "buckets");
      const JsonValue* count = Find(dist, "count");
      const JsonValue* mean = Find(dist, "mean");
      const JsonValue* ssd = Find(dist, "sum_squared_deviation");
      if (!buckets || !count || !mean || !ssd) continue;
      std::ostringstream value;
      value << "{\"distributionValue\":{\"count\":\""
            << static_cast<long long>(count->number)
            << "\",\"mean\":" << FormatDouble(mean->number)
            << ",\"sumOfSquaredDeviation\":" << FormatDouble(ssd->number)
            << ",\"bucketOptions\":{\"exponentialBuckets\":{"
            << "\"numFiniteBuckets\":"
            << static_cast<int>(buckets->items.size()) - 2
            << ",\"growthFactor\":" << FormatDouble(kBucketGrowth)
            << ",\"scale\":1}},\"bucketCounts\":[";
      for (size_t i = 0; i < buckets->items.size(); ++i) {
        if (i != 0) value << ",";
        value << "\"" << static_cast<long long>(buckets->items[i].number)
              << "\"";
      }
      value << "]}}";
      series.push_back(OneSeries(entry.first, "CUMULATIVE", value.str(),
                                 start_time, end_time));
    }
  }
  return series;
}

// ---------------------------------------------------------------------------
// libcurl via dlopen (no -dev headers needed; CURLOPT values are stable ABI)
// ---------------------------------------------------------------------------

constexpr int kCurloptUrl = 10002;
constexpr int kCurloptPostfields = 10015;
constexpr int kCurloptHttpheader = 10023;
constexpr int kCurloptWritedata = 10001;
constexpr int kCurloptWritefunction = 20011;
constexpr int kCurloptTimeout = 13;
constexpr int kCurloptHttpget = 80;
constexpr int kCurloptNosignal = 99;
constexpr int kCurlinfoResponseCode = 0x200000 + 2;

struct CurlApi {
  void* (*easy_init)() = nullptr;
  int (*easy_setopt)(void*, int, ...) = nullptr;
  int (*easy_perform)(void*) = nullptr;
  void (*easy_cleanup)(void*) = nullptr;
  int (*easy_getinfo)(void*, int, ...) = nullptr;
  void* (*slist_append)(void*, const char*) = nullptr;
  void (*slist_free_all)(void*) = nullptr;
  int (*global_init)(long) = nullptr;
  bool ok = false;
};

void* DlopenCurl() {
  for (const char* name :
       {"libcurl.so.4", "libcurl-gnutls.so.4", "libcurl.so"}) {
    // RTLD_LOCAL, never GLOBAL: every entry point is resolved through
    // dlsym, and promoting libcurl's dependency chain (OpenSSL) into
    // the global namespace collides with other SSL runtimes already in
    // the process.
    void* lib = dlopen(name, RTLD_NOW | RTLD_LOCAL);
    if (lib != nullptr) return lib;
  }
  return nullptr;
}

// Loading libcurl pulls in an SSL runtime whose initialization can
// corrupt the heap when the host process already carries a conflicting
// one (observed: grpc's boringssl alongside OpenSSL-linked libcurl —
// SIGSEGV / "corrupted double-linked list" abort, killing the whole
// process).  Monitoring must never take the job down, so sacrifice a
// forked child to find out: the child replicates this process's exact
// library state, performs the dangerous dlopen + curl_global_init, and
// reports back via its exit status.  Crash or hang in the child ⇒ the
// wire client declares itself unavailable and the exporter falls back
// to the Python transport.
bool CurlLoadsSafely() {
  pid_t pid = fork();
  if (pid < 0) return true;  // cannot probe; keep the old direct path
  if (pid == 0) {
    // The host (a Python process) may have its own SIGALRM disposition;
    // the inherited handler would swallow the alarm instead of killing
    // the wedged child, so restore the default first.
    signal(SIGALRM, SIG_DFL);
    alarm(10);  // a wedged child must not wedge the parent's waitpid
    void* lib = DlopenCurl();
    if (lib == nullptr) _exit(1);
    auto global_init =
        reinterpret_cast<int (*)(long)>(dlsym(lib, "curl_global_init"));
    if (global_init != nullptr) global_init(3L /* CURL_GLOBAL_ALL */);
    _exit(0);
  }
  // Timed reap: the child's alarm is backup, not the only bound — the
  // parent must never block in waitpid on a child that cannot die.
  int status = 0;
  for (int waited_ms = 0; waited_ms < 12000; waited_ms += 50) {
    pid_t reaped = waitpid(pid, &status, WNOHANG);
    if (reaped == pid) {
      return WIFEXITED(status) && WEXITSTATUS(status) == 0;
    }
    if (reaped < 0) return true;  // cannot observe; keep the direct path
    usleep(50 * 1000);
  }
  kill(pid, SIGKILL);
  waitpid(pid, &status, 0);
  return false;  // hung probe: the load is not safe here
}

CurlApi& Curl() {
  static CurlApi* api = [] {
    auto* a = new CurlApi();
    if (!CurlLoadsSafely()) return a;
    void* lib = DlopenCurl();
    if (lib == nullptr) return a;
    a->easy_init = reinterpret_cast<void* (*)()>(dlsym(lib, "curl_easy_init"));
    a->easy_setopt = reinterpret_cast<int (*)(void*, int, ...)>(
        dlsym(lib, "curl_easy_setopt"));
    a->easy_perform =
        reinterpret_cast<int (*)(void*)>(dlsym(lib, "curl_easy_perform"));
    a->easy_cleanup =
        reinterpret_cast<void (*)(void*)>(dlsym(lib, "curl_easy_cleanup"));
    a->easy_getinfo = reinterpret_cast<int (*)(void*, int, ...)>(
        dlsym(lib, "curl_easy_getinfo"));
    a->slist_append = reinterpret_cast<void* (*)(void*, const char*)>(
        dlsym(lib, "curl_slist_append"));
    a->slist_free_all =
        reinterpret_cast<void (*)(void*)>(dlsym(lib, "curl_slist_free_all"));
    a->global_init =
        reinterpret_cast<int (*)(long)>(dlsym(lib, "curl_global_init"));
    a->ok = a->easy_init && a->easy_setopt && a->easy_perform &&
            a->easy_cleanup && a->easy_getinfo && a->slist_append &&
            a->slist_free_all;
    // Explicit one-time global init inside this static initializer (so it
    // runs exactly once, before any thread uses easy handles): relying on
    // easy_init's lazy implicit init is not thread-safe on older libcurl.
    if (a->ok && a->global_init != nullptr) {
      a->global_init(3L /* CURL_GLOBAL_ALL */);
    }
    return a;
  }();
  return *api;
}

size_t CollectBody(char* data, size_t size, size_t nmemb, void* userdata) {
  static_cast<std::string*>(userdata)->append(data, size * nmemb);
  return size * nmemb;
}

// Perform an HTTP request; returns status code or -1.  `post_body` nullptr
// means GET.  `response` may be nullptr.
int CurlRequest(const char* url, const char* post_body,
                const std::vector<std::string>& headers,
                std::string* response) {
  CurlApi& api = Curl();
  if (!api.ok) return -1;
  void* handle = api.easy_init();
  if (handle == nullptr) return -1;
  void* header_list = nullptr;
  for (const auto& header : headers) {
    header_list = api.slist_append(header_list, header.c_str());
  }
  api.easy_setopt(handle, kCurloptUrl, url);
  api.easy_setopt(handle, kCurloptTimeout, 30L);
  // Mandatory in multithreaded hosts: without NOSIGNAL libcurl's timeout
  // path uses SIGALRM + longjmp, which can abort the training process.
  api.easy_setopt(handle, kCurloptNosignal, 1L);
  if (header_list != nullptr) {
    api.easy_setopt(handle, kCurloptHttpheader, header_list);
  }
  if (post_body != nullptr) {
    api.easy_setopt(handle, kCurloptPostfields, post_body);
  } else {
    api.easy_setopt(handle, kCurloptHttpget, 1L);
  }
  std::string body;
  api.easy_setopt(handle, kCurloptWritefunction, CollectBody);
  api.easy_setopt(handle, kCurloptWritedata, &body);
  const int rc = api.easy_perform(handle);
  long status = -1;
  if (rc == 0) api.easy_getinfo(handle, kCurlinfoResponseCode, &status);
  if (header_list != nullptr) api.slist_free_all(header_list);
  api.easy_cleanup(handle);
  if (response != nullptr) *response = body;
  return rc == 0 ? static_cast<int>(status) : -1;
}

int CurlTransport(const char* url, const char* body, const char* auth_header) {
  std::vector<std::string> headers = {"Content-Type: application/json"};
  if (auth_header != nullptr && auth_header[0] != '\0') {
    headers.push_back(auth_header);
  }
  return CurlRequest(url, body, headers, nullptr);
}

constexpr char kMonitoringApi[] = "https://monitoring.googleapis.com/v3";
constexpr char kMetadataTokenUrl[] =
    "http://metadata.google.internal/computeMetadata/v1/instance/"
    "service-accounts/default/token";

// Process start = CUMULATIVE interval start (Python exporter parity).
const std::string& ProcessStartTime() {
  static const std::string* start = new std::string(Rfc3339Now());
  return *start;
}

}  // namespace

WireClient& WireClient::Global() {
  static WireClient* client = new WireClient();
  return *client;
}

std::string WireClient::TimeSeriesBody(const std::string& snapshot_json,
                                       const std::string& start_time,
                                       const std::string& end_time) {
  const std::vector<std::string> series =
      SeriesList(snapshot_json, start_time, end_time);
  if (series.empty()) return "";
  return JoinSeriesChunk(series, 0, series.size());
}

std::vector<std::pair<std::string, std::string>>
WireClient::PendingDescriptors(const std::string& snapshot_json) {
  std::vector<std::pair<std::string, std::string>> out;
  JsonValue snapshot;
  if (!JsonParser(snapshot_json).Parse(&snapshot)) return out;
  struct Group {
    const char* key;
    const char* kind;
    const char* value_type;
  };
  static constexpr Group kGroups[] = {
      {"counters", "CUMULATIVE", "INT64"},
      {"gauges", "GAUGE", "DOUBLE"},
      {"distributions", "CUMULATIVE", "DISTRIBUTION"},
  };
  std::lock_guard<std::mutex> lock(mu_);
  for (const Group& group : kGroups) {
    const JsonValue* members = Find(snapshot, group.key);
    if (members == nullptr) continue;
    for (const auto& entry : members->members) {
      if (described_.count(entry.first) != 0) continue;
      std::ostringstream body;
      body << "{\"type\":\"" << kMetricPrefix << "/"
           << JsonEscapeString(entry.first) << "\",\"metricKind\":\"" << group.kind
           << "\",\"valueType\":\"" << group.value_type
           << "\",\"description\":\"cloud_tpu framework metric "
           << JsonEscapeString(entry.first) << "\"}";
      out.emplace_back(entry.first, body.str());
    }
  }
  return out;
}

std::string WireClient::NewDescriptorBodies(const std::string& snapshot_json) {
  std::ostringstream out;
  out << "[";
  bool first = true;
  for (const auto& pending : PendingDescriptors(snapshot_json)) {
    if (!first) out << ",";
    first = false;
    out << pending.second;
  }
  out << "]";
  return out.str();
}

int WireClient::ExportSnapshot(const std::string& snapshot_json) {
  const std::string project = Project();
  if (project.empty()) return -2;
  TransportFn transport;
  {
    std::lock_guard<std::mutex> lock(mu_);
    transport = transport_;
  }
  if (transport == nullptr) {
    if (!Curl().ok) return -3;
    transport = CurlTransport;
  }
  const std::string auth = AuthHeader();

  // Descriptors: once per metric name (reference :105-126) — but marked
  // described only after a successful POST, so a not-yet-ready network or
  // token retries next interval instead of never creating the descriptor.
  const std::string descriptor_url =
      std::string(kMonitoringApi) + "/projects/" + project +
      "/metricDescriptors";
  for (const auto& pending : PendingDescriptors(snapshot_json)) {
    const int status = transport(descriptor_url.c_str(),
                                 pending.second.c_str(), auth.c_str());
    if (status >= 200 && status < 300) {
      std::lock_guard<std::mutex> lock(mu_);
      described_.insert(pending.first);
    }
  }

  const std::vector<std::string> series =
      SeriesList(snapshot_json, ProcessStartTime(), Rfc3339Now());
  if (series.empty()) return 0;
  const std::string url = std::string(kMonitoringApi) + "/projects/" +
                          project + "/timeSeries";
  int rc = 0;
  for (size_t begin = 0; begin < series.size(); begin += kMaxSeriesPerPost) {
    const size_t end =
        std::min(series.size(), begin + kMaxSeriesPerPost);
    const std::string body = JoinSeriesChunk(series, begin, end);
    const int status = transport(url.c_str(), body.c_str(), auth.c_str());
    if (!(status >= 200 && status < 300) && rc == 0) rc = status;
  }
  // Failure visibility without log spam: one stderr line per status
  // change (the Python fallback logs every failure via logging).
  {
    std::lock_guard<std::mutex> lock(mu_);
    if (rc != last_logged_status_) {
      if (rc != 0) {
        std::fprintf(stderr,
                     "cloud_tpu monitoring: native export failed "
                     "(http status %d)\n",
                     rc);
      } else if (last_logged_status_ != 0) {
        std::fprintf(stderr, "cloud_tpu monitoring: native export recovered\n");
      }
      last_logged_status_ = rc;
    }
  }
  return rc;
}

void WireClient::SetTransport(TransportFn transport) {
  std::lock_guard<std::mutex> lock(mu_);
  transport_ = transport;
}

void WireClient::SetProject(const std::string& project) {
  std::lock_guard<std::mutex> lock(mu_);
  project_ = project;
}

void WireClient::ResetForTest() {
  std::lock_guard<std::mutex> lock(mu_);
  described_.clear();
  transport_ = nullptr;
  project_.clear();
  cached_token_.clear();
  token_expiry_unix_ = 0;
  last_logged_status_ = 0;
}

bool WireClient::TransportAvailable() {
  {
    std::lock_guard<std::mutex> lock(mu_);
    if (transport_ != nullptr) return true;
  }
  return Curl().ok;
}

std::string WireClient::Project() {
  {
    std::lock_guard<std::mutex> lock(mu_);
    if (!project_.empty()) return project_;
  }
  // Same env contract as the Python exporter (reference keyed the singleton
  // off TF_MONITORING_STACKDRIVER_PROJECT_ID, stackdriver_client.cc:38-43).
  return GetEnv("CLOUD_TPU_MONITORING_PROJECT_ID");
}

std::string WireClient::AuthHeader() {
  const std::string env_token = GetEnv("CLOUD_TPU_MONITORING_TOKEN");
  if (!env_token.empty()) return "Authorization: Bearer " + env_token;
  {
    std::lock_guard<std::mutex> lock(mu_);
    if (transport_ != nullptr) return "";  // injected stub: no real auth
    if (!cached_token_.empty() &&
        std::time(nullptr) < token_expiry_unix_ - 60) {
      return "Authorization: Bearer " + cached_token_;
    }
  }
  if (!Curl().ok) return "";
  // TPU-VM/GCE path: the instance metadata server mints access tokens for
  // the node's service account (what the startup script runs under).
  std::string response;
  const int status = CurlRequest(kMetadataTokenUrl, nullptr,
                                 {"Metadata-Flavor: Google"}, &response);
  if (status != 200) return "";
  JsonValue token_json;
  if (!JsonParser(response).Parse(&token_json)) return "";
  const JsonValue* token = Find(token_json, "access_token");
  const JsonValue* expires = Find(token_json, "expires_in");
  if (token == nullptr || token->kind != JsonValue::kString) return "";
  std::lock_guard<std::mutex> lock(mu_);
  cached_token_ = token->text;
  token_expiry_unix_ =
      std::time(nullptr) +
      (expires != nullptr ? static_cast<long>(expires->number) : 300);
  return "Authorization: Bearer " + cached_token_;
}

}  // namespace cloud_tpu

extern "C" {

int ctpu_wire_available() {
  return cloud_tpu::WireClient::Global().TransportAvailable() ? 1 : 0;
}

void ctpu_wire_set_project(const char* project) {
  cloud_tpu::WireClient::Global().SetProject(project ? project : "");
}

void ctpu_wire_set_transport(cloud_tpu::TransportFn transport) {
  cloud_tpu::WireClient::Global().SetTransport(transport);
}

void ctpu_wire_reset() { cloud_tpu::WireClient::Global().ResetForTest(); }

static char* DupString(const std::string& value) {
  char* out = static_cast<char*>(std::malloc(value.size() + 1));
  std::memcpy(out, value.c_str(), value.size() + 1);
  return out;
}

char* ctpu_wire_time_series_body(const char* snapshot_json,
                                 const char* start_time,
                                 const char* end_time) {
  return DupString(cloud_tpu::WireClient::Global().TimeSeriesBody(
      snapshot_json ? snapshot_json : "", start_time ? start_time : "",
      end_time ? end_time : ""));
}

char* ctpu_wire_new_descriptor_bodies(const char* snapshot_json) {
  return DupString(cloud_tpu::WireClient::Global().NewDescriptorBodies(
      snapshot_json ? snapshot_json : ""));
}

int ctpu_wire_export_snapshot(const char* snapshot_json) {
  return cloud_tpu::WireClient::Global().ExportSnapshot(
      snapshot_json ? snapshot_json : "");
}

namespace {
void WireSink(const char* snapshot_json) {
  cloud_tpu::WireClient::Global().ExportSnapshot(snapshot_json);
}
}  // namespace

void ctpu_exporter_use_wire_client() {
  cloud_tpu::Exporter::Global().SetSink(&WireSink);
}

}  // extern "C"
