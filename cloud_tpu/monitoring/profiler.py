"""Tracing / profiling subsystem: first-class ``jax.profiler`` capture.

The reference has no profiler of its own — its nearest artifact is a
TensorBoard callback shipped through cloud_fit serialization
(cloud_fit/tests/unit/remote_test.py:72) and README-promised "hosted
TensorBoard" monitoring.  SURVEY.md §5 calls for the TPU-native
equivalent to be first-class: ``jax.profiler`` trace capture viewable in
XProf/Perfetto/TensorBoard, a profiler *server* for on-demand remote
capture from a running pod, op-level trace annotations, and device-memory
snapshots.

Three entry styles, mirroring how the reference exposes monitoring:

* explicit API — ``trace(logdir)`` context manager, ``start_server()``;
* env-gated auto-start — ``maybe_start_server_from_env()`` called by the
  container bootstrap, gated on ``CLOUD_TPU_PROFILER_PORT`` the same way
  the metrics exporter gates on ``CLOUD_TPU_MONITORING_ENABLED``
  (reference: TF_MONITORING_STACKDRIVER_EXPORTER_ENABLED,
  stackdriver_exporter.cc:31-36);
* Trainer callback — ``ProfilerCallback`` captures a window of training
  steps (the "trace steps 10-20 of epoch 0" TensorBoard idiom) with
  per-step ``StepTraceAnnotation`` markers so XProf can cut the trace by
  step.
"""

from __future__ import annotations

import contextlib
import logging
import os
from typing import Optional

import jax

logger = logging.getLogger(__name__)

#: Setting this env var in the job spec turns the profiler server on in
#: every remote host process (deploy.py forwards job env to the
#: bootstrap).  Value = port to listen on.
ENV_PROFILER_PORT = "CLOUD_TPU_PROFILER_PORT"

#: Where ProfilerCallback / trace() write when no logdir is given.
ENV_PROFILER_LOGDIR = "CLOUD_TPU_PROFILER_LOGDIR"

_DEFAULT_LOGDIR = "/tmp/cloud_tpu_profile"

_server = None


def default_logdir() -> str:
    return os.environ.get(ENV_PROFILER_LOGDIR, _DEFAULT_LOGDIR)


def start_server(port: int = 9012):
    """Start the profiler server for on-demand capture.

    A running server lets ``jax.profiler.trace_server`` clients / XProf
    "capture profile" pull a trace from a live pod without restarting the
    job — the TPU-native replacement for the reference's "hosted
    TensorBoard" monitoring promise (README "What happens when you call
    run?").  Idempotent per process.
    """
    global _server
    if _server is None:
        _server = jax.profiler.start_server(port)
        logger.info("profiler server listening on :%d", port)
    return _server


def stop_server() -> None:
    global _server
    if _server is not None:
        jax.profiler.stop_server()
        _server = None


def maybe_start_server_from_env() -> bool:
    """Env-gated auto-start; called by ``core.bootstrap`` on every host."""
    port = os.environ.get(ENV_PROFILER_PORT)
    if not port:
        return False
    try:
        start_server(int(port))
    except Exception:  # pragma: no cover - double-start in odd harnesses
        logger.exception("profiler server failed to start")
        return False
    return True


@contextlib.contextmanager
def trace(logdir: Optional[str] = None, *, perfetto_link: bool = False):
    """Capture a trace of the enclosed block to ``logdir``.

    The output is a TensorBoard-ready ``plugins/profile/...`` directory
    (open with XProf or ``tensorboard --logdir``).  ``gs://`` logdirs are
    supported by the underlying writer, so traces can land next to the
    job's checkpoints.
    """
    from cloud_tpu.monitoring import tracing

    logdir = logdir or default_logdir()
    with jax.profiler.trace(logdir, create_perfetto_link=perfetto_link):
        # Host-side tracing spans opened inside the block mirror
        # themselves as TraceAnnotations onto the device timeline.
        tracing.xprof_trace_started()
        try:
            yield logdir
        finally:
            tracing.xprof_trace_stopped()


def start_trace(logdir: Optional[str] = None) -> str:
    from cloud_tpu.monitoring import tracing

    logdir = logdir or default_logdir()
    jax.profiler.start_trace(logdir)
    tracing.xprof_trace_started()
    return logdir


def stop_trace() -> None:
    from cloud_tpu.monitoring import tracing

    jax.profiler.stop_trace()
    tracing.xprof_trace_stopped()


def annotate(name: str, **kwargs):
    """Named span visible on the XProf timeline (TraceAnnotation)."""
    return jax.profiler.TraceAnnotation(name, **kwargs)


def annotate_function(fn=None, *, name: Optional[str] = None):
    """Decorator form of :func:`annotate`."""
    if fn is None:
        import functools

        def deco(f):
            return annotate_function(f, name=name)

        return deco
    return jax.profiler.annotate_function(fn, name=name)


def save_device_memory_profile(path: Optional[str] = None) -> str:
    """Dump a pprof-format device-memory snapshot (HBM attribution).

    Works on CPU and standard TPU-VM runtimes.  PJRT C-API plugins that
    don't implement ``PJRT_Executable_SizeOfGeneratedCodeInBytes`` fatally
    abort inside the runtime when live executables exist (runtime CHECK,
    not a Python exception) — on such backends prefer :func:`trace`, whose
    capture includes a memory-viewer plane.
    """
    path = path or os.path.join(default_logdir(), "memory.prof")
    if "://" not in path:
        # Only local paths need (or tolerate) makedirs; for gs:// the
        # underlying writer owns path creation — a naive makedirs would
        # create a bogus local "gs:/..." directory tree.
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    jax.profiler.save_device_memory_profile(path)
    return path


class ProfilerCallback:
    """Trainer callback: trace steps ``[start_step, start_step+num_steps)``.

    Equivalent UX to Keras TensorBoard(profile_batch=(a, b)) — the
    mechanism the reference ships via cloud_fit's pickled-callback path.
    Captures once per fit() run; each traced step is wrapped in a
    ``StepTraceAnnotation`` so XProf's step-time view segments correctly.
    """

    def __init__(self, logdir: Optional[str] = None, *, start_step: int = 2,
                 num_steps: int = 3):
        if num_steps < 1:
            raise ValueError("num_steps must be >= 1")
        self.logdir = logdir or default_logdir()
        self.start_step = start_step
        self.num_steps = num_steps
        self._tracing = False
        self._done = False
        self._step_span = None

    # Callback protocol (training.trainer.Callback) -------------------
    def on_train_begin(self, trainer) -> None:
        self._done = False

    def on_step_end(self, step: int, logs, trainer) -> None:
        if self._step_span is not None:
            self._step_span.__exit__(None, None, None)
            self._step_span = None
        if self._tracing and step >= self.start_step + self.num_steps - 1:
            # Block on the last traced step's result so device activity is
            # inside the capture window before stop_trace().
            jax.block_until_ready(next(iter(logs.values()), None))
            stop_trace()
            self._tracing = False
            self._done = True
            logger.info("profiler: wrote trace to %s", self.logdir)
        elif (not self._done and not self._tracing
              and step >= self.start_step - 1):
            start_trace(self.logdir)
            self._tracing = True
        if self._tracing:
            self._step_span = jax.profiler.StepTraceAnnotation(
                "train", step_num=step + 1
            )
            self._step_span.__enter__()

    def on_train_end(self, trainer) -> None:
        if self._step_span is not None:
            self._step_span.__exit__(None, None, None)
            self._step_span = None
        if self._tracing:  # fit() ended before the window closed
            stop_trace()
            self._tracing = False
            self._done = True

    def on_epoch_begin(self, epoch: int, trainer) -> None: ...
    def on_epoch_end(self, epoch: int, logs, trainer) -> None: ...
