"""Native metrics + Cloud Monitoring export.

Reference analogue: ``src/cpp/monitoring/`` (SURVEY.md §2.5) — a C++
collection registry, an env-gated periodic exporter, an allowlist config,
and a transport client; here the registry/exporter/allowlist are C++
(``cpp/``, ctypes-bound with a pure-Python fallback) and the authenticated
transport is the shared REST session.

Also provides the Trainer integration: ``MetricsCallback`` records
steps/sec and loss into the registry so the exporter ships real training
telemetry.
"""

from cloud_tpu.monitoring.metrics import (
    backend,
    counter_inc,
    distribution_record,
    gauge_set,
    reset,
    snapshot,
)
from cloud_tpu.monitoring.exporter import (
    CloudMonitoringExporter,
    start_exporter,
    stop_exporter,
)
from cloud_tpu.monitoring import profiler

import time as _time


class MetricsCallback:
    """Trainer callback feeding the native registry each step/epoch."""

    def __init__(self, prefix: str = "train"):
        self.prefix = prefix
        self._last_step_time = None

    def on_train_begin(self, trainer):
        self._last_step_time = _time.perf_counter()

    def on_train_end(self, trainer): ...
    def on_epoch_begin(self, epoch, trainer): ...

    def on_step_end(self, step, logs, trainer):
        now = _time.perf_counter()
        if self._last_step_time is not None:
            distribution_record(
                f"{self.prefix}/step_seconds", now - self._last_step_time
            )
        self._last_step_time = now
        counter_inc(f"{self.prefix}/steps")

    def on_epoch_end(self, epoch, logs, trainer):
        for key, value in logs.items():
            gauge_set(f"{self.prefix}/{key}", float(value))


__all__ = [
    "CloudMonitoringExporter",
    "MetricsCallback",
    "backend",
    "counter_inc",
    "distribution_record",
    "gauge_set",
    "profiler",
    "reset",
    "snapshot",
    "start_exporter",
    "stop_exporter",
]
