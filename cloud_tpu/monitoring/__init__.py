"""Native metrics + Cloud Monitoring export.

Reference analogue: ``src/cpp/monitoring/`` (SURVEY.md §2.5) — a C++
collection registry, an env-gated periodic exporter, an allowlist config,
and a transport client; here the registry/exporter/allowlist are C++
(``cpp/``, ctypes-bound with a pure-Python fallback) and the authenticated
transport is the shared REST session.

Also provides the Trainer integration: ``MetricsCallback`` records
steps/sec and loss into the registry so the exporter ships real training
telemetry.
"""

from cloud_tpu.monitoring.metrics import (
    backend,
    counter_inc,
    distribution_record,
    gauge_set,
    reset,
    snapshot,
)
from cloud_tpu.monitoring.exporter import (
    CloudMonitoringExporter,
    start_exporter,
    stop_exporter,
)
from cloud_tpu.monitoring import tracing

import time as _time


def __getattr__(name):
    # Lazy: profiler imports jax at module level; spelling it eagerly here
    # would put jax on the import path of every tracing/metrics consumer
    # (training.data, core.run).  ``monitoring.profiler`` still resolves.
    # importlib, not ``from ... import``: the from-import form asks the
    # package for the attribute first, which re-enters this __getattr__
    # and recurses until the interpreter gives up.
    if name == "profiler":
        import importlib

        return importlib.import_module("cloud_tpu.monitoring.profiler")
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


class MetricsCallback:
    """Trainer callback feeding the native registry each step/epoch.

    ``Trainer.fit`` installs one automatically (reference parity: the
    stackdriver exporter shipped TF runtime metrics with zero user code,
    ``stackdriver_exporter.cc:86-97``) — every training run produces
    ``train/steps``, ``train/step_time_ms``, ``train/steps_per_sec``,
    ``train/loss``, and ``train/epochs`` for the exporter to ship.

    Hot-path contract: never force a device sync.  The loss gauge is
    read with a ONE-STEP LAG — by the time step N ends, step N-1's
    metrics are materialized on device, so ``float()`` on them returns
    without stalling the async dispatch pipeline.  Step time is host
    wall-clock between step dispatches; steps/sec is a windowed gauge
    (updated every ``window`` steps).
    """

    def __init__(self, prefix: str = "train", *, window: int = 20):
        from cloud_tpu.monitoring.metrics import WindowedRate

        self.prefix = prefix
        self._rate = WindowedRate(f"{prefix}/steps_per_sec", window)
        self._last_step_time = None
        self._last_step_number = None
        self._lagged_logs = None

    def _record_lagged_loss(self):
        logs = self._lagged_logs
        self._lagged_logs = None
        if not logs or "loss" not in logs:
            return
        try:
            gauge_set(f"{self.prefix}/loss", float(logs["loss"]))
        except (TypeError, ValueError):
            pass

    def on_train_begin(self, trainer):
        now = _time.perf_counter()
        self._last_step_time = now
        self._rate.restart(now)
        self._lagged_logs = None
        # Seed the step-delta base so the FIRST fused window counts all
        # its steps (resumed fits start above zero).
        self._last_step_number = None
        state = getattr(trainer, "state", None)
        if state is not None:
            try:
                self._last_step_number = int(state.step)
            except (TypeError, ValueError):
                pass
        counter_inc(f"{self.prefix}/runs")

    def on_train_end(self, trainer):
        # The final step's loss never got its lagged read; it is
        # materialized by now (the epoch loop device_get'd the metrics).
        self._record_lagged_loss()

    def on_epoch_begin(self, epoch, trainer):
        # Restart both timers: inter-epoch work (validation, epoch-end
        # callbacks, device_get of epoch metrics) must count neither as
        # step time nor as steps/sec window time.
        now = _time.perf_counter()
        self._last_step_time = now
        self._rate.restart(now)

    def on_step_end(self, step, logs, trainer):
        now = _time.perf_counter()
        # With fit(steps_per_dispatch=K) this hook fires once per fused
        # K-step window; the step-number delta recovers K so train/steps
        # and steps_per_sec stay per-STEP series, and step_time_ms stays
        # per-step (window wall-clock / K).
        n = 1
        if self._last_step_number is not None:
            n = max(1, step - self._last_step_number)
        self._last_step_number = step
        if self._last_step_time is not None:
            distribution_record(
                f"{self.prefix}/step_time_ms",
                (now - self._last_step_time) * 1e3 / n,
            )
        self._last_step_time = now
        counter_inc(f"{self.prefix}/steps", n)
        self._record_lagged_loss()
        self._lagged_logs = logs
        self._rate.add(now, n)

    def on_epoch_end(self, epoch, logs, trainer):
        # Publish the partial window with the LAST step's timestamp, so
        # short epochs still produce a rate and validation time is
        # excluded from it.
        if self._last_step_time is not None:
            self._rate.flush(self._last_step_time)
        counter_inc(f"{self.prefix}/epochs")
        for key, value in logs.items():
            if key == "loss":
                # train/loss is the per-step lagged gauge; writing the
                # epoch MEAN into the same series would make it
                # alternate between two different quantities.
                continue
            try:
                gauge_set(f"{self.prefix}/{key}", float(value))
            except (TypeError, ValueError):
                continue


__all__ = [
    "CloudMonitoringExporter",
    "MetricsCallback",
    "backend",
    "counter_inc",
    "distribution_record",
    "gauge_set",
    # "profiler" deliberately absent: a star-import must not defeat the
    # lazy __getattr__ and drag jax onto every consumer's import path.
    "reset",
    "snapshot",
    "start_exporter",
    "stop_exporter",
    "tracing",
]
