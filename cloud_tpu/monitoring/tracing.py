"""Host-side span tracing across the launch-and-train pipeline.

The metrics registry answers "how often / how large"; this module answers
"where did the wall-clock go".  A span is a named host-side phase
(``run/validate``, ``step/compute``, ``checkpoint/save`` ...) opened as a
context manager or decorator.  Every finished span is recorded three ways:

* as a ``span/<name>`` distribution in the metrics registry
  (``monitoring.metrics``), so the exporter ships phase latencies like any
  other series;
* into an in-process timeline ring buffer, exportable as Chrome
  trace-event JSON via :func:`dump_timeline` (open in ``chrome://tracing``
  / Perfetto) and summarizable with ``python -m cloud_tpu.monitoring.report``;
* when a ``jax.profiler`` trace is active (``monitoring.profiler`` keeps
  the flag), mirrored as a ``TraceAnnotation`` so host phases line up with
  device activity on the XProf timeline.

Disabled is the default and costs ~nothing: without an active collector
:func:`span` returns a shared no-op context manager — one function call,
no allocation, no clock read (< 1 µs; asserted in tests/unit/test_tracing.py)
— so permanent instrumentation in hot paths (per-step phases, collectives)
is safe.  Enable with :func:`enable` / the :func:`collecting` context
manager, or the ``CLOUD_TPU_TRACE=1`` env gate (same idiom as
``CLOUD_TPU_MONITORING_ENABLED``).

The north-star composite metric lives here too: :func:`mark_submit` is
called by ``core.run.run()`` when a job is submitted, and the trainer's
first completed step calls :func:`record_submit_to_first_step`, which
publishes the ``run/submit_to_first_step_seconds`` gauge.  Across machines
the submit timestamp rides the job env (``CLOUD_TPU_SUBMIT_TS``, stamped
into the deploy startup script) so the in-container first step measures
true submit-to-first-step latency.
"""

from __future__ import annotations

import dataclasses
import functools
import itertools
import json
import os
import threading
import time
from typing import Any, Dict, Iterable, List, Optional

from cloud_tpu.monitoring import metrics

#: Wall-clock unix seconds of job submission, stamped into the deployed
#: container's env by ``core.deploy.startup_script`` so the remote first
#: step can compute true submit-to-first-step latency.
ENV_SUBMIT_TS = "CLOUD_TPU_SUBMIT_TS"

#: Set to 1/true to enable the collector at import time (containers,
#: benchmark children — anywhere nobody calls :func:`enable` by hand).
ENV_TRACE = "CLOUD_TPU_TRACE"

#: Gauge published once per process when a pending submit mark exists.
SUBMIT_TO_FIRST_STEP_GAUGE = "run/submit_to_first_step_seconds"

_DEFAULT_CAPACITY = 100_000


class _NoopSpan:
    """Shared do-nothing span: what :func:`span` returns while disabled."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False

    def set_attribute(self, key: str, value: Any) -> None:
        return None


_NOOP = _NoopSpan()

_tls = threading.local()


def _stack() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


# --- trace context (fleet-wide request identity) ---------------------------

#: Process-unique trace-id suffix source.  ``itertools.count`` because its
#: ``next`` is atomic in CPython — same reliance as the stdlib's own id
#: allocators — so minting needs no lock on the submit hot path.
_trace_ids = itertools.count(1)


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """Propagatable identity of ONE request across fleet hops.

    Minted once at the fleet (or engine) ingress while tracing is
    enabled, then carried — not re-minted — through routing, failover
    re-admission, and the replica's scheduler, so every span a request
    touches stamps the same ``trace_id`` and ``report.py`` can stitch
    the full lifecycle back together.  ``parent_id`` optionally links to
    an enclosing span (0 = root).  Frozen: a context is an identity, and
    failover must re-submit the SAME identity.
    """

    trace_id: str
    parent_id: int = 0


def new_trace_context(parent_id: int = 0) -> Optional[TraceContext]:
    """Mint a fresh :class:`TraceContext`, or None while tracing is off.

    The None return IS the default-off contract: callers store it in
    their request record unconditionally and the field rides inert —
    no ids are allocated, no span gains attributes, and disabled-mode
    span sets stay byte-identical.
    """
    if _collector is None:
        return None
    return TraceContext(
        trace_id=f"{os.getpid():x}-{next(_trace_ids):x}",
        parent_id=parent_id,
    )


# --- timeline lanes (multi-replica pid rows in one process) ----------------

#: Lane ids start far above any plausible OS pid so a lane row can never
#: collide with (and silently absorb) the process's own default lane.
_LANE_BASE = 1 << 24

_lane_lock = threading.Lock()
_lane_labels: Dict[int, str] = {}
_next_lane = _LANE_BASE


def register_lane(label: str) -> int:
    """Allocate a timeline lane: a synthetic Chrome-trace ``pid`` row.

    All fleet replicas live in ONE process and share the process-global
    collector, so without lanes every span lands on the same ``pid`` and
    Perfetto renders the fleet as a single process.  A lane gives each
    replica its own labelled row; threads adopt it via
    :func:`set_thread_lane`.  Cheap and always available (a dict entry)
    so replica startup never branches on whether tracing is enabled.
    """
    global _next_lane
    with _lane_lock:
        lane = _next_lane
        _next_lane += 1
        _lane_labels[lane] = str(label)
        return lane


def lane_label(lane: int) -> Optional[str]:
    with _lane_lock:
        return _lane_labels.get(lane)


def set_thread_lane(lane: Optional[int]) -> None:
    """Stamp spans finished on THIS thread with ``pid=lane`` (None resets
    to the real ``os.getpid()``).  Thread-local, so one replica's
    scheduler adopting its lane never relabels another's."""
    _tls.lane = lane


def current_thread_lane() -> Optional[int]:
    return getattr(_tls, "lane", None)


def _event_pid() -> int:
    lane = getattr(_tls, "lane", None)
    return lane if lane is not None else os.getpid()


class TimelineCollector:
    """Bounded in-process buffer of finished spans + running aggregates.

    The ring buffer bounds memory on long runs (oldest events drop); the
    per-name aggregates are incremental and never dropped, so
    :func:`aggregates` stays exact even after eviction.
    """

    def __init__(self, capacity: int = _DEFAULT_CAPACITY):
        self.capacity = max(1, int(capacity))
        self._lock = threading.Lock()
        self._events: List[dict] = []
        self._evicted = 0
        self._aggregates: Dict[str, dict] = {}
        self._next_id = 1
        # Chrome-trace ts is microseconds on an arbitrary epoch; anchor it
        # so dumped timelines start near zero and stay monotonic.
        self.epoch = time.perf_counter()

    def next_span_id(self) -> int:
        with self._lock:
            span_id = self._next_id
            self._next_id += 1
            return span_id

    def add(self, event: dict, duration_s: float) -> None:
        with self._lock:
            self._events.append(event)
            if len(self._events) > self.capacity:
                drop = len(self._events) - self.capacity
                del self._events[:drop]
                self._evicted += drop
            agg = self._aggregates.setdefault(
                event["name"],
                {"count": 0, "total_seconds": 0.0, "max_seconds": 0.0},
            )
            agg["count"] += 1
            agg["total_seconds"] += duration_s
            if duration_s > agg["max_seconds"]:
                agg["max_seconds"] = duration_s

    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def aggregates(self) -> Dict[str, dict]:
        with self._lock:
            return {
                name: {
                    **agg,
                    "mean_seconds": agg["total_seconds"] / agg["count"],
                }
                for name, agg in self._aggregates.items()
            }

    @property
    def evicted(self) -> int:
        return self._evicted

    def snapshot(self) -> dict:
        """One consistent cut for merge/export: epoch + events + evicted.

        ``epoch`` rides along because merged timelines (fleet + replicas,
        eventually one collector per host) must normalize each source's
        monotonic clock onto a common origin — see
        :func:`merge_timelines`.
        """
        with self._lock:
            return {
                "epoch": self.epoch,
                "events": list(self._events),
                "evicted": self._evicted,
            }


_collector: Optional[TimelineCollector] = None
_collector_lock = threading.Lock()

_submit_perf: Optional[float] = None
_submit_consumed = False

# Incremented/decremented by monitoring.profiler around jax.profiler
# traces; nonzero => spans mirror themselves as TraceAnnotations.
_xprof_depth = 0


class Span:
    """A live span: times itself, records on exit.  Not reentrant."""

    __slots__ = (
        "name", "attributes", "span_id", "parent_id",
        "_collector", "_start", "_annotation",
    )

    def __init__(self, name: str, collector: TimelineCollector,
                 attributes: Optional[Dict[str, Any]]):
        self.name = name
        self.attributes = attributes
        self._collector = collector
        self.span_id = collector.next_span_id()
        self.parent_id = 0
        self._start = 0.0
        self._annotation = None

    def set_attribute(self, key: str, value: Any) -> None:
        if self.attributes is None:
            self.attributes = {}
        self.attributes[key] = value

    def __enter__(self):
        stack = _stack()
        if stack:
            self.parent_id = stack[-1].span_id
        stack.append(self)
        if _xprof_depth:
            try:
                import jax

                self._annotation = jax.profiler.TraceAnnotation(self.name)
                self._annotation.__enter__()
            except Exception:  # noqa: BLE001 — tracing never kills the job
                self._annotation = None
        self._start = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        end = time.perf_counter()
        if self._annotation is not None:
            try:
                self._annotation.__exit__(exc_type, exc, tb)
            except Exception:  # noqa: BLE001 — tracing never kills the job
                pass
            self._annotation = None
        stack = _stack()
        if stack and stack[-1] is self:
            stack.pop()
        else:  # pragma: no cover - misnested exit (generator finalization)
            try:
                stack.remove(self)
            except ValueError:
                pass
        duration = end - self._start
        collector = self._collector
        args = {"span_id": self.span_id, "parent_id": self.parent_id}
        if self.attributes:
            args.update(self.attributes)
        if exc_type is not None:
            args["error"] = exc_type.__name__
        collector.add(
            {
                "name": self.name,
                "ph": "X",
                "ts": (self._start - collector.epoch) * 1e6,
                "dur": duration * 1e6,
                "pid": _event_pid(),
                "tid": threading.get_ident(),
                "args": args,
            },
            duration,
        )
        metrics.distribution_record(f"span/{self.name}", duration)
        return False


# --- lifecycle -----------------------------------------------------------


def enabled() -> bool:
    """Cheap predicate for call sites that compute span attributes."""
    return _collector is not None


def enable(capacity: int = _DEFAULT_CAPACITY) -> TimelineCollector:
    """Install the process-wide collector (idempotent)."""
    global _collector
    with _collector_lock:
        if _collector is None:
            _collector = TimelineCollector(capacity)
        return _collector


def disable() -> None:
    global _collector
    with _collector_lock:
        _collector = None


def active() -> Optional[TimelineCollector]:
    return _collector


class collecting:
    """Context manager: enable tracing for a block, restore after.

    Returns the collector, so ``with tracing.collecting() as c:`` gives
    direct access to ``c.events()`` / ``c.aggregates()``.
    """

    def __init__(self, capacity: int = _DEFAULT_CAPACITY):
        self.capacity = capacity
        self._previous: Optional[TimelineCollector] = None

    def __enter__(self) -> TimelineCollector:
        global _collector
        with _collector_lock:
            self._previous = _collector
            _collector = TimelineCollector(self.capacity)
            return _collector

    def __exit__(self, exc_type, exc, tb):
        global _collector
        with _collector_lock:
            _collector = self._previous
        return False


def maybe_enable_from_env() -> bool:
    """Env-gated enable, same contract as the exporter/profiler gates."""
    if os.environ.get(ENV_TRACE, "").lower() in ("1", "true"):
        enable()
        return True
    return False


# --- the span API --------------------------------------------------------


def span(name: str, **attributes: Any):
    """Open a span: ``with tracing.span("step/compute"): ...``.

    No-op (shared singleton, < 1 µs) when no collector is active.
    Attributes land in the Chrome-trace ``args`` (payload bytes, step
    numbers, trial ids ...).
    """
    collector = _collector
    if collector is None:
        return _NOOP
    return Span(name, collector, attributes or None)


def record_span(name: str, start: float, end: float,
                **attributes: Any) -> None:
    """Record an already-measured interval as a finished span.

    For phases whose start and end live on different threads — a serving
    request's queue wait begins at ``submit()`` on the caller's thread
    and ends when the scheduler folds it into a batch — where a context
    manager cannot wrap the interval, and for phases known only in
    retrospect: the pipelined serving scheduler measures each chunk's
    dispatch→drain interval (``serve/chunk``/``serve/verify``), the
    blocking host copy actually paid at drain (``serve/host_bubble``),
    and the gap between consecutive dispatches (``serve/dispatch_gap``)
    this way, since at ``pipeline_depth=2`` no live context manager can
    bracket work that completes one scheduler pass later.
    ``start``/``end`` are ``time.perf_counter()`` readings; the span
    lands in the timeline, aggregates, and the ``span/<name>`` metrics
    distribution exactly like a context-manager span (no parent
    nesting, since no thread "owns" it).  No-op while tracing is
    disabled, same as :func:`span`.
    """
    collector = _collector
    if collector is None:
        return
    duration = max(0.0, end - start)
    args: Dict[str, Any] = {"span_id": collector.next_span_id(),
                            "parent_id": 0}
    args.update(attributes)
    collector.add(
        {
            "name": name,
            "ph": "X",
            "ts": (start - collector.epoch) * 1e6,
            "dur": duration * 1e6,
            "pid": _event_pid(),
            "tid": threading.get_ident(),
            "args": args,
        },
        duration,
    )
    metrics.distribution_record(f"span/{name}", duration)


def traced(fn=None, *, name: Optional[str] = None):
    """Decorator form: ``@tracing.traced`` or ``@tracing.traced(name=...)``.

    The span is named after the function (``module.qualname``) unless
    ``name`` is given.  Disabled-mode overhead is one extra call frame.
    """
    if fn is None:
        return functools.partial(traced, name=name)
    span_name = name or f"{fn.__module__.split('.')[-1]}.{fn.__qualname__}"

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        if _collector is None:
            return fn(*args, **kwargs)
        with span(span_name):
            return fn(*args, **kwargs)

    return wrapper


def current_span() -> Optional[Span]:
    stack = _stack()
    return stack[-1] if stack else None


# --- timeline export -----------------------------------------------------


def timeline_events() -> List[dict]:
    collector = _collector
    return collector.events() if collector is not None else []


def aggregates() -> Dict[str, dict]:
    """Per-name ``{count, total_seconds, mean_seconds, max_seconds}``."""
    collector = _collector
    return collector.aggregates() if collector is not None else {}


def dump_timeline(path: str) -> str:
    """Write the collected spans as Chrome trace-event JSON.

    Open the file in ``chrome://tracing`` or https://ui.perfetto.dev, or
    summarize with ``python -m cloud_tpu.monitoring.report <path>``.
    """
    collector = _collector
    events = collector.events() if collector is not None else []
    meta = [
        {
            "ph": "M",
            "pid": pid,
            "tid": 0,
            "name": "process_name",
            "args": {"name": label},
        }
        for pid, label in sorted(
            (pid, lane_label(pid))
            for pid in {e["pid"] for e in events}
        )
        if label is not None
    ]
    meta += [
        {
            "ph": "M",
            "pid": pid,
            "tid": tid,
            "name": "thread_name",
            "args": {"name": _thread_name(tid)},
        }
        for pid, tid in sorted({(e["pid"], e["tid"]) for e in events})
    ]
    doc = {"traceEvents": meta + events, "displayTimeUnit": "ms"}
    if collector is not None and collector.evicted:
        doc["otherData"] = {"evicted_events": collector.evicted}
    return _write_timeline(doc, path)


def _write_timeline(doc: dict, path: str) -> str:
    directory = os.path.dirname(os.path.abspath(path))
    os.makedirs(directory, exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    return path


def merge_timelines(sources: Iterable[dict], path: str) -> str:
    """Merge per-source span snapshots into ONE Chrome-trace JSON.

    Each source is ``{"label", "epoch", "events", "evicted"?, "pid"?}``
    — the shape :meth:`TimelineCollector.snapshot` returns plus a lane
    label (``pid`` defaults to the source's position, so sources from
    different processes that reused the same OS pid still get distinct
    rows).  Every source becomes a ``process_name``-labelled ``pid``
    lane, and each event's ``ts`` is shifted by the source's monotonic
    epoch offset against the EARLIEST source epoch, so spans from
    collectors born at different times line up on one wall: the
    normalization ``Fleet.dump_timeline`` relies on to show a request
    bouncing between replicas in a single Perfetto view.
    """
    sources = list(sources)
    epochs = [float(s["epoch"]) for s in sources]
    base = min(epochs) if epochs else 0.0
    merged: List[dict] = []
    meta: List[dict] = []
    evicted = 0
    for index, source in enumerate(sources):
        pid = int(source.get("pid", index))
        offset_us = (float(source["epoch"]) - base) * 1e6
        meta.append({
            "ph": "M",
            "pid": pid,
            "tid": 0,
            "name": "process_name",
            "args": {"name": str(source["label"])},
        })
        tids = set()
        for event in source["events"]:
            event = dict(event)
            event["pid"] = pid
            ts = event.get("ts")
            if isinstance(ts, (int, float)):
                event["ts"] = ts + offset_us
            if isinstance(event.get("tid"), int):
                tids.add(event["tid"])
            merged.append(event)
        meta += [
            {
                "ph": "M",
                "pid": pid,
                "tid": tid,
                "name": "thread_name",
                "args": {"name": _thread_name(tid)},
            }
            for tid in sorted(tids)
        ]
        evicted += int(source.get("evicted") or 0)
    doc = {"traceEvents": meta + merged, "displayTimeUnit": "ms"}
    if evicted:
        doc["otherData"] = {"evicted_events": evicted}
    return _write_timeline(doc, path)


def _thread_name(tid: int) -> str:
    for thread in threading.enumerate():
        if thread.ident == tid:
            return thread.name
    return f"thread-{tid}"


# --- submit-to-first-step ------------------------------------------------


def mark_submit() -> None:
    """Record "a job was submitted now" (called by ``core.run.run()``).

    Arms :func:`record_submit_to_first_step`; a later mark re-arms (a new
    ``run()`` in the same process supersedes the old pending mark).
    """
    global _submit_perf, _submit_consumed
    _submit_perf = time.perf_counter()
    _submit_consumed = False


def record_submit_to_first_step() -> Optional[float]:
    """Publish ``run/submit_to_first_step_seconds`` once per submit mark.

    Called by the trainer after the first completed train step.  The
    elapsed time comes from (in priority order):

    1. ``CLOUD_TPU_SUBMIT_TS`` — wall-clock submit stamp threaded through
       the job env by deploy, the true cross-machine latency;
    2. the in-process :func:`mark_submit` monotonic mark (local runs,
       dry-run smoke tests).

    Returns the recorded seconds, or None when nothing is pending.
    """
    global _submit_consumed
    if _submit_consumed:
        return None
    elapsed: Optional[float] = None
    env_ts = os.environ.get(ENV_SUBMIT_TS)
    if env_ts:
        try:
            elapsed = max(0.0, time.time() - float(env_ts))
        except ValueError:
            elapsed = None
    if elapsed is None and _submit_perf is not None:
        elapsed = time.perf_counter() - _submit_perf
    if elapsed is None:
        return None
    _submit_consumed = True
    metrics.gauge_set(SUBMIT_TO_FIRST_STEP_GAUGE, elapsed)
    collector = _collector
    if collector is not None:
        collector.add(
            {
                "name": SUBMIT_TO_FIRST_STEP_GAUGE,
                "ph": "X",
                "ts": (time.perf_counter() - collector.epoch) * 1e6
                - elapsed * 1e6,
                "dur": elapsed * 1e6,
                "pid": os.getpid(),
                "tid": threading.get_ident(),
                "args": {"span_id": collector.next_span_id(), "parent_id": 0},
            },
            elapsed,
        )
    return elapsed


def clear_submit() -> None:
    """Disarm a pending submit mark.

    Called by ``run()`` when it raises before submitting: a failed run
    must not leave a mark for a later, unrelated ``fit()`` in the same
    process to consume as its submit-to-first-step origin.
    """
    global _submit_perf, _submit_consumed
    _submit_perf = None
    _submit_consumed = False


def _reset_submit_state_for_tests() -> None:
    clear_submit()


# --- xprof mirroring (driven by monitoring.profiler) ---------------------


def xprof_trace_started() -> None:
    global _xprof_depth
    _xprof_depth += 1


def xprof_trace_stopped() -> None:
    global _xprof_depth
    _xprof_depth = max(0, _xprof_depth - 1)


maybe_enable_from_env()
