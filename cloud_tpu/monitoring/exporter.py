"""Cloud Monitoring exporter wiring + the Python FALLBACK transport.

The primary wire client is native C++ (``cpp/wire_client.cc``, the
equivalent of the reference's ``stackdriver_client.cc``): when the shared
library is built and libcurl resolves, the whole periodic path — timer
thread, snapshot, snapshot->TimeSeries conversion, HTTP POST, OAuth token
from the TPU-VM metadata server — runs in C++ with no Python hop
(SURVEY.md §2.5: "C++ TPU-native equivalents, not Python stand-ins").

This module keeps (a) the start/stop lifecycle and env gates, and (b) a
pure-Python ``CloudMonitoringExporter`` used only when the native path is
unavailable (no .so / no libcurl / an injected test session forces the
Python transport).  Reference mapping for the fallback:
``stackdriver_client.cc`` histogram->Distribution :69-98, point by type
:100-124, ``custom.googleapis.com`` metric prefix :126-136, descriptor
dedup :105-126, project from env :38-43.
"""

from __future__ import annotations

import ctypes
import json
import logging
import os
import threading
import time
from typing import Optional, Set

from cloud_tpu.monitoring import metrics as metrics_lib
from cloud_tpu.utils import api_client

logger = logging.getLogger(__name__)

_MONITORING_API = "https://monitoring.googleapis.com/v3"
METRIC_PREFIX = "custom.googleapis.com/cloud_tpu"
ENV_PROJECT = "CLOUD_TPU_MONITORING_PROJECT_ID"

#: Exponential bucket bounds matching the native registry: 2^(k-1).
_BUCKET_GROWTH = 2.0


def _now_rfc3339() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


class CloudMonitoringExporter:
    """Converts registry snapshots to CreateTimeSeries requests."""

    def __init__(self, project: Optional[str] = None,
                 session: Optional[api_client.GcpApiSession] = None):
        self.project = project or os.environ.get(ENV_PROJECT)
        if not self.project:
            raise ValueError(
                f"Set {ENV_PROJECT} (reference used "
                "TF_MONITORING_STACKDRIVER_PROJECT_ID the same way)."
            )
        self._session = session or api_client.default_session()
        self._described: Set[str] = set()  # descriptor dedup (:105-126)

    # --- conversion (pure; golden-tested) ---

    def time_series(self, snapshot: dict) -> list:
        end_time = _now_rfc3339()
        series = []
        for name, value in snapshot.get("counters", {}).items():
            series.append(self._one_series(
                name, "CUMULATIVE", {"int64Value": str(value)}, end_time
            ))
        for name, value in snapshot.get("gauges", {}).items():
            series.append(self._one_series(
                name, "GAUGE", {"doubleValue": value}, end_time
            ))
        for name, dist in snapshot.get("distributions", {}).items():
            buckets = dist["buckets"]
            series.append(self._one_series(
                name,
                "CUMULATIVE",
                {
                    "distributionValue": {
                        "count": str(dist["count"]),
                        "mean": dist["mean"],
                        "sumOfSquaredDeviation": dist["sum_squared_deviation"],
                        "bucketOptions": {
                            "exponentialBuckets": {
                                "numFiniteBuckets": len(buckets) - 2,
                                "growthFactor": _BUCKET_GROWTH,
                                "scale": 1.0,
                            }
                        },
                        "bucketCounts": [str(c) for c in buckets],
                    }
                },
                end_time,
            ))
        return series

    def _one_series(self, name, kind, value, end_time):
        interval = {"endTime": end_time}
        if kind == "CUMULATIVE":
            interval["startTime"] = _START_TIME
        return {
            "metric": {"type": f"{METRIC_PREFIX}/{name}"},
            "resource": {"type": "global", "labels": {}},
            "metricKind": kind,
            "points": [{"interval": interval, "value": value}],
        }

    # --- transport ---

    def export(self, snapshot: dict) -> None:
        series = self.time_series(snapshot)
        if not series:
            return
        self._ensure_descriptors(snapshot)
        url = f"{_MONITORING_API}/projects/{self.project}/timeSeries"
        # The API caps 200 series per call.
        for start in range(0, len(series), 200):
            self._session.post(
                url, body={"timeSeries": series[start:start + 200]}
            )

    def _ensure_descriptors(self, snapshot: dict) -> None:
        kinds = (
            [(n, "CUMULATIVE", "INT64") for n in snapshot.get("counters", {})]
            + [(n, "GAUGE", "DOUBLE") for n in snapshot.get("gauges", {})]
            + [
                (n, "CUMULATIVE", "DISTRIBUTION")
                for n in snapshot.get("distributions", {})
            ]
        )
        url = f"{_MONITORING_API}/projects/{self.project}/metricDescriptors"
        for name, kind, value_type in kinds:
            if name in self._described:
                continue
            self._session.post(url, body={
                "type": f"{METRIC_PREFIX}/{name}",
                "metricKind": kind,
                "valueType": value_type,
                "description": f"cloud_tpu framework metric {name}",
            })
            self._described.add(name)


_START_TIME = _now_rfc3339()  # process start = CUMULATIVE interval start

_sink_keepalive = None  # the ctypes callback must outlive the C thread
_python_thread: Optional[threading.Thread] = None
_python_stop = threading.Event()
_final_flush = None  # set by start_exporter; drains the last interval
_started = False  # idempotency guard covering both backends


def _env_allowlist() -> Set[str]:
    """Same contract as the native exporter (CLOUD_TPU_MONITORING_ALLOWLIST,
    ref stackdriver_config.cc:26-32); empty => export everything."""
    return {
        name
        for name in os.environ.get(
            "CLOUD_TPU_MONITORING_ALLOWLIST", ""
        ).split(",")
        if name
    }


def _filtered_snapshot(allowlist: Set[str]) -> dict:
    snap = metrics_lib.snapshot()
    if not allowlist:
        return snap
    return {
        group: {k: v for k, v in values.items() if k in allowlist}
        for group, values in snap.items()
    }


def start_exporter(project: Optional[str] = None, session=None) -> bool:
    """Start periodic export (env-gated, like REGISTER_TF_METRICS_EXPORTER +
    TF_MONITORING_STACKDRIVER_EXPORTER_ENABLED, stackdriver_exporter.cc:31-36).

    Returns True if the exporter started.  Uses the native timer thread when
    the C++ library is live, else a Python thread.
    """
    global _sink_keepalive, _python_thread, _final_flush, _started
    if os.environ.get("CLOUD_TPU_MONITORING_ENABLED", "").lower() not in (
        "1", "true",
    ):
        return False
    if _started:
        # Idempotent, matching Exporter::Start — and crucially *before*
        # constructing a second exporter, which would rebind the sink and
        # final flush onto a fresh descriptor-dedup set mid-run.
        return True

    # Preferred: the all-native wire path (no Python in the loop).  An
    # injected session is a test/transport override and forces the Python
    # exporter; CLOUD_TPU_MONITORING_WIRE=python opts out explicitly.
    if (
        metrics_lib.backend() == "native"
        and session is None
        and os.environ.get("CLOUD_TPU_MONITORING_WIRE", "native") != "python"
    ):
        lib = metrics_lib._get_registry()._lib  # type: ignore[union-attr]
        if (
            hasattr(lib, "ctpu_wire_available")
            and lib.ctpu_wire_available()
            and (project or os.environ.get(ENV_PROJECT))
        ):
            lib.ctpu_wire_set_project.argtypes = [ctypes.c_char_p]
            lib.ctpu_wire_export_snapshot.argtypes = [ctypes.c_char_p]
            if project:
                lib.ctpu_wire_set_project(project.encode())
            lib.ctpu_exporter_use_wire_client()
            lib.ctpu_exporter_config_reload()
            _started = bool(lib.ctpu_exporter_start())
            if _started:
                def native_flush() -> None:
                    rc = lib.ctpu_wire_export_snapshot(
                        json.dumps(
                            _filtered_snapshot(_env_allowlist())
                        ).encode()
                    )
                    if rc != 0:
                        logger.warning(
                            "native final metrics flush failed (status %d)",
                            rc,
                        )

                _final_flush = native_flush
                logger.info("monitoring: native C++ wire client active")
            else:
                _final_flush = None
            return _started

    exporter = CloudMonitoringExporter(project=project, session=session)

    def sink_json(payload: str) -> None:
        try:
            exporter.export(json.loads(payload))
        except Exception:
            logger.exception("metrics export failed")

    def final_flush() -> None:
        sink_json(json.dumps(_filtered_snapshot(_env_allowlist())))

    if metrics_lib.backend() == "native":
        lib = metrics_lib._get_registry()._lib  # type: ignore[union-attr]
        SINK = ctypes.CFUNCTYPE(None, ctypes.c_char_p)

        def c_sink(raw):
            sink_json(raw.decode())

        _sink_keepalive = SINK(c_sink)
        lib.ctpu_exporter_set_sink.argtypes = [SINK]
        lib.ctpu_exporter_set_sink(_sink_keepalive)
        # The C++ config singleton caches env at first touch, which may
        # predate this call (any snapshot constructs it); re-read so the
        # enable gate above and the native gate agree.
        lib.ctpu_exporter_config_reload()
        _started = bool(lib.ctpu_exporter_start())
        # Arm the final flush only for a live exporter: a failed start must
        # not leave stop_exporter() posting a snapshot through an exporter
        # that never ran.
        _final_flush = final_flush if _started else None
        return _started

    interval = int(os.environ.get("CLOUD_TPU_MONITORING_INTERVAL", "10"))
    allowlist = _env_allowlist()
    _python_stop.clear()

    def loop():
        while not _python_stop.wait(interval):
            sink_json(json.dumps(_filtered_snapshot(allowlist)))

    _python_thread = threading.Thread(target=loop, daemon=True)
    _python_thread.start()
    _started = True
    _final_flush = final_flush
    return True


def stop_exporter() -> None:
    """Stop the periodic thread and drain the final partial interval."""
    global _python_thread, _final_flush, _started
    if metrics_lib.backend() == "native":
        lib = metrics_lib._get_registry()._lib  # type: ignore[union-attr]
        lib.ctpu_exporter_stop()  # joins the C thread (exporter.cc:74-81)
    _python_stop.set()
    joined = True
    if _python_thread is not None:
        _python_thread.join(timeout=5)
        joined = not _python_thread.is_alive()
        _python_thread = None
    if _final_flush is not None:
        if joined:
            # Safe: no loop thread shares the session/exporter anymore.
            _final_flush()
        else:
            logger.warning(
                "export loop still mid-request; skipping final flush"
            )
        _final_flush = None
    _started = False
