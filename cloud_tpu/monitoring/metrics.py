"""Python surface of the native metrics registry (ctypes).

The C++ registry (``cpp/metrics_registry.cc``) is the collection point —
counters/gauges/distributions recorded from any thread, snapshotted as
JSON.  When the shared library hasn't been built, a pure-Python registry
with the identical surface takes over (capability degrades gracefully;
``backend()`` reports which is live).

Builds on demand: first use attempts ``make`` once (g++ is baked into TPU
VM images; build cost ~1s, cached as a .so next to the sources).
"""

from __future__ import annotations

import ctypes
import json
import logging
import os
import threading
from typing import Dict, Optional

logger = logging.getLogger(__name__)

_CPP_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "cpp")
_LIB_PATH = os.path.join(_CPP_DIR, "libcloud_tpu_monitoring.so")

_NUM_BUCKETS = 24


class _PurePythonRegistry:
    """Fallback with the same semantics as the native registry."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._gauges: Dict[str, float] = {}
        self._dists: Dict[str, dict] = {}

    def counter_inc(self, name, delta=1):
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + int(delta)

    def gauge_set(self, name, value):
        with self._lock:
            self._gauges[name] = float(value)

    def distribution_record(self, name, value):
        import math

        with self._lock:
            d = self._dists.setdefault(
                name,
                {
                    "count": 0,
                    "mean": 0.0,
                    "sum_squared_deviation": 0.0,
                    "buckets": [0] * _NUM_BUCKETS,
                },
            )
            d["count"] += 1
            delta = value - d["mean"]
            d["mean"] += delta / d["count"]
            d["sum_squared_deviation"] += delta * (value - d["mean"])
            if value == math.inf:
                idx = _NUM_BUCKETS - 1
            elif not math.isfinite(value) or value < 1.0:
                idx = 0
            else:
                idx = min(1 + int(math.floor(math.log2(value))), _NUM_BUCKETS - 1)
            d["buckets"][idx] += 1

    def snapshot(self):
        import math

        # JSON has no inf/nan; clamp like the native registry's AppendDouble
        # so a diverged metric can't poison every downstream export POST.
        def fin(v):
            return v if math.isfinite(v) else 0.0

        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": {k: fin(v) for k, v in self._gauges.items()},
                "distributions": {
                    k: {
                        **v,
                        "mean": fin(v["mean"]),
                        "sum_squared_deviation": fin(v["sum_squared_deviation"]),
                        "buckets": list(v["buckets"]),
                    }
                    for k, v in self._dists.items()
                },
            }

    def reset(self):
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._dists.clear()


class _NativeRegistry:
    def __init__(self, lib: ctypes.CDLL):
        self._lib = lib
        lib.ctpu_counter_inc.argtypes = [ctypes.c_char_p, ctypes.c_int64]
        lib.ctpu_gauge_set.argtypes = [ctypes.c_char_p, ctypes.c_double]
        lib.ctpu_distribution_record.argtypes = [
            ctypes.c_char_p, ctypes.c_double,
        ]
        lib.ctpu_metrics_snapshot_json.restype = ctypes.c_void_p
        lib.ctpu_free.argtypes = [ctypes.c_void_p]

    def counter_inc(self, name, delta=1):
        self._lib.ctpu_counter_inc(name.encode(), int(delta))

    def gauge_set(self, name, value):
        self._lib.ctpu_gauge_set(name.encode(), float(value))

    def distribution_record(self, name, value):
        self._lib.ctpu_distribution_record(name.encode(), float(value))

    def snapshot(self):
        ptr = self._lib.ctpu_metrics_snapshot_json()
        try:
            return json.loads(ctypes.string_at(ptr).decode())
        finally:
            self._lib.ctpu_free(ptr)

    def reset(self):
        self._lib.ctpu_registry_reset()


_registry = None
_registry_lock = threading.Lock()


def _build_native() -> Optional[ctypes.CDLL]:
    from cloud_tpu.utils.native import load_native_lib

    return load_native_lib(_CPP_DIR, "libcloud_tpu_monitoring.so",
                           what="native metrics registry")


def _get_registry():
    global _registry
    with _registry_lock:
        if _registry is None:
            lib = _build_native()
            _registry = (
                _NativeRegistry(lib) if lib is not None else _PurePythonRegistry()
            )
        return _registry


def backend() -> str:
    return (
        "native" if isinstance(_get_registry(), _NativeRegistry) else "python"
    )


# --- module-level API ---

class WindowedRate:
    """Events/sec gauge over a sliding window of ``window`` events.

    Shared by every throughput producer (trainer steps/sec, records
    pipeline examples/sec): accumulate counts via :meth:`add`, and the
    gauge updates each time a window fills; :meth:`flush` publishes a
    partial window (short runs, end of stream) and restarts timing —
    call it at natural boundaries (epoch end, stream end) so dead time
    between them is never counted as event time.
    """

    def __init__(self, name: str, window: int):
        self.name = name
        self.window = max(1, int(window))
        self._count = 0
        self._start: Optional[float] = None

    def restart(self, now: float) -> None:
        """Drop the current window and start timing from ``now``."""
        self._count = 0
        self._start = now

    def add(self, now: float, n: int = 1) -> None:
        if self._start is None:
            self._start = now
            return
        self._count += n
        if self._count >= self.window:
            self.flush(now)

    def flush(self, now: float) -> None:
        if self._count and self._start is not None and now > self._start:
            gauge_set(self.name, self._count / (now - self._start))
        self.restart(now)


def counter_inc(name: str, delta: int = 1) -> None:
    _get_registry().counter_inc(name, delta)


def gauge_set(name: str, value: float) -> None:
    _get_registry().gauge_set(name, value)


def distribution_record(name: str, value: float) -> None:
    _get_registry().distribution_record(name, value)


def snapshot() -> dict:
    return _get_registry().snapshot()


def reset() -> None:
    _get_registry().reset()
