"""Paged decode-attention as a Pallas TPU kernel, with a jnp reference.

The serving hot path (``models.generation``'s slot-grid programs) reads
KV through ``_cache_attention`` over a padded ``[num_slots, max_len]``
slot grid, and a prefix-cache hit first COPIES pool blocks into the slot
row (``copy_prefix_program``) before a single token decodes.  This
module removes both costs: attention gathers KV **in place** through a
per-slot block table — page ``p`` of a row reads either the slot row
itself (table entry ``-1``) or a prefix-pool block (table entry ``>= 0``,
an index into the ``init_prefix_pool`` layout ``[num_blocks,
block_tokens, H, hd]`` per layer) — and pages past each row's valid
length are skipped outright, so decode stops re-reading padded dead
slots and a prefix hit stops dispatching the copy program.

The kernel is the house flash-attention shape transposed to serving:
the grid walks ``(row, page)`` with the block table and per-row lengths
scalar-prefetched (``pltpu.PrefetchScalarGridSpec`` — the table drives
the page BlockSpec index maps, which is what makes the gather a DMA
schedule rather than a gather op), online-softmax accumulators in VMEM
scratch, and the kv_quant int8 dequant fused in-VMEM (scales fold into
scores/weights exactly like ``_cache_attention``'s post-scale algebra —
no full-width page ever materializes).

Three entry points match the serving dispatch shapes:

- :func:`paged_decode_attention` — the single-token decode step
  (``decode_chunk_program``'s inner attention, ``T_q == 1``);
- :func:`paged_chunk_attention` — the chunk-causal prefill shape
  (``prefill_chunk_program``: query ``t`` sits at cache position
  ``cur_len - 1 + t``);
- :func:`paged_verify_attention` — the speculative verify window
  (``verify_chunk_program``; same mask as the chunk shape).

Dispatch follows the house playbook: ``use_pallas=None`` auto-dispatch
takes the kernel on real TPU at ``S >= CLOUD_TPU_PAGED_MIN_LEN``
(measure with ``scripts/decode_crossover.py`` and keep docs/KERNELS.md's
table honest), ``CLOUD_TPU_PAGED_FORCE_INTERPRET=1`` (or the house-wide
``CLOUD_TPU_FLASH_FORCE_INTERPRET=1``) runs the kernel code path through
the Pallas interpreter (the CI rig; the dedicated knob exists because the
flash interpret path is jax-0.4.37-blocked — arming it house-wide would
drag prefill's flash_attention into its known-red ``vma`` failure while
this kernel's interpret path is fine), and everything
else — off-TPU, ineligible shapes, ``CLOUD_TPU_PAGED_KERNEL=0`` — takes
:func:`_reference`, a pure-jnp block-table gather whose math mirrors
``_cache_attention`` term for term (same einsum order, same finite mask,
same post-scale quant algebra), so the fallback is bit-identical to the
copy-based XLA path given identical pool bytes.  jax 0.4.37 lacks
``SdyShardingRule``; the ``partitioned=True`` route degrades to the
unwrapped kernel there (one warning) instead of going red.
"""

from __future__ import annotations

import functools
import math
import os
from typing import Optional

import jax
import jax.numpy as jnp

from cloud_tpu.ops import dispatch as dispatch_lib

NEG_INF = -1e30  # finite: fully-masked rows softmax to zeros, not NaN

#: Auto-dispatch (``use_pallas=None``) takes the kernel only when the slot
#: row length S reaches this.  Default mirrors the flash kernel's measured
#: shape of crossover (short rows fit XLA's fused path cache-friendly;
#: the kernel pays at long context where the dead-page skip and the
#: no-copy hit path dominate) — measure on the real rig with
#: scripts/decode_crossover.py and pin the table in docs/KERNELS.md.
MIN_SEQ_LEN_FOR_KERNEL = int(os.environ.get("CLOUD_TPU_PAGED_MIN_LEN", 1024))

#: Operational kill switch (the bench flips the GroupNorm twin when a
#: hardware gate diverges; same contract here).
def _kernel_enabled() -> bool:
    return os.environ.get("CLOUD_TPU_PAGED_KERNEL", "1") != "0"


def _force_interpret() -> bool:
    """CI interpret contract: the house-wide flash knob OR the dedicated
    paged knob.  The dedicated one lets CPU rigs arm THIS kernel's
    interpreter while flash_attention (whose interpret path is known-red
    on jax 0.4.37: ShapeDtypeStruct(vma=...)) keeps its jnp reference."""
    return (
        dispatch_lib.force_interpret()
        or os.environ.get("CLOUD_TPU_PAGED_FORCE_INTERPRET", "") == "1"
    )


#: Page size used when no prefix pool rides along (pure slot paging): the
#: lane-width default; fitted down to the row length when shorter.
DEFAULT_PAGE_TOKENS = 128

#: Diagnostic counter: bumped every time the Pallas kernel is actually
#: traced — serving retrace guards and the unit suite assert it advances
#: to prove the kernel path (not the jnp reference) ran.
KERNEL_TRACE_COUNT = 0


# ---------------------------------------------------------------------------
# Reference implementation (ground truth + non-TPU fallback)
# ---------------------------------------------------------------------------


def _gather_paged(slot_leaf, pool_leaf, block_table):
    """Materialize the virtual KV a block table describes: position ``j``
    of row ``b`` reads ``pool_leaf[table[b, j // bt], j % bt]`` when that
    table entry is ``>= 0``, else ``slot_leaf[b, j]``.  Positions beyond
    the table's page coverage always read the slot row.  Pure jnp — the
    reference path's (and only the reference path's) full-width gather.
    """
    b, s = slot_leaf.shape[:2]
    if pool_leaf is None or block_table is None:
        return slot_leaf
    bt = pool_leaf.shape[1]
    n_pages = block_table.shape[1]
    j = jnp.arange(s)
    page = j // bt  # [S]
    in_pages = page < n_pages
    blk = jnp.where(
        in_pages[None, :],
        jnp.take(block_table, jnp.minimum(page, n_pages - 1), axis=1),
        jnp.int32(-1),
    )  # [B, S]
    gathered = pool_leaf[jnp.maximum(blk, 0), (j % bt)[None, :]]  # [B,S,...]
    sel = (blk >= 0).reshape(b, s, *([1] * (slot_leaf.ndim - 2)))
    return jnp.where(sel, gathered, slot_leaf)


def _reference(q, cache_l, cur_len, pool_l, block_table):
    """``_cache_attention``'s exact math over the block-table gather:
    chunk-causal mask (key ``j`` valid for query ``t`` iff ``j <
    cur_len + t`` — with ``T_q == 1`` this IS the plain decode mask),
    f32 softmax, finite mask value, post-scale int8 algebra."""
    k_cache = _gather_paged(
        cache_l["k"], None if pool_l is None else pool_l["k"], block_table
    )
    v_cache = _gather_paged(
        cache_l["v"], None if pool_l is None else pool_l["v"], block_table
    )
    s = k_cache.shape[1]
    scale = 1.0 / math.sqrt(q.shape[-1])

    def fold(scores_like, kv_scale):
        # [B, S, H, 1] -> [B, H, 1, S] broadcast over the query dim.
        return scores_like * jnp.transpose(kv_scale, (0, 2, 3, 1))

    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(jnp.float32),
        k_cache.astype(jnp.float32),
    ) * scale
    if "k_scale" in cache_l:
        k_sc = _gather_paged(
            cache_l["k_scale"],
            None if pool_l is None else pool_l["k_scale"], block_table,
        )
        scores = fold(scores, k_sc)
    valid = jnp.arange(s)[None, None, :] < (
        cur_len[:, None, None] + jnp.arange(q.shape[1])[None, :, None]
    )
    scores = jnp.where(valid[:, None, :, :], scores, NEG_INF)
    weights = jax.nn.softmax(scores, axis=-1)
    if "v_scale" in cache_l:
        v_sc = _gather_paged(
            cache_l["v_scale"],
            None if pool_l is None else pool_l["v_scale"], block_table,
        )
        weights = fold(weights, v_sc)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd", weights, v_cache.astype(jnp.float32)
    )
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas kernel
# ---------------------------------------------------------------------------


def _paged_kernel(*refs, bt, tq, h, hd, s_total, scale, quantized,
                  has_pool):
    """One (row, page) grid cell: select the page's KV source (slot row
    vs pool block), dequant in-VMEM, fold the page into the online
    softmax.  Scalar-prefetch refs lead: the block table and per-row
    lengths."""
    refs = list(refs)
    table_ref, len_ref = refs[0], refs[1]
    pos = 2
    q_ref = refs[pos]; pos += 1
    sk_ref, sv_ref = refs[pos], refs[pos + 1]; pos += 2
    sks_ref = svs_ref = None
    if quantized:
        sks_ref, svs_ref = refs[pos], refs[pos + 1]; pos += 2
    pk_ref = pv_ref = pks_ref = pvs_ref = None
    if has_pool:
        pk_ref, pv_ref = refs[pos], refs[pos + 1]; pos += 2
        if quantized:
            pks_ref, pvs_ref = refs[pos], refs[pos + 1]; pos += 2
    o_ref = refs[pos]; pos += 1
    m_scr, l_scr, acc_scr = refs[pos], refs[pos + 1], refs[pos + 2]

    b, p = pl.program_id(0), pl.program_id(1)
    n_pages = pl.num_programs(1)

    @pl.when(p == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # Dead-page skip: keys of page p start at p*bt; the largest index any
    # query can see is cur_len + tq - 2 (key j valid iff j < cur_len + t,
    # t < tq).  Pages past that contribute nothing — no compute (and the
    # index maps pin their DMA to the last live page, so no fetch either).
    limit = len_ref[b] + (tq - 1)
    run = p * bt < limit

    @pl.when(run)
    def _compute():
        def pick(slot_ref, pool_ref):
            page = slot_ref[0].astype(jnp.float32)
            if pool_ref is None:
                return page
            use_pool = table_ref[b, p] >= 0
            return jnp.where(use_pool, pool_ref[0].astype(jnp.float32),
                             page)

        # Zero columns past the true row length: the last page may be a
        # padded partial block whose out-of-bounds lanes hold garbage
        # (NaN under the interpreter) — 0 * garbage would still poison
        # the pv matmul through masked-but-summed lanes.
        col = jax.lax.broadcasted_iota(jnp.int32, (bt, 1, 1), 0)
        in_range = (p * bt + col) < s_total
        k_page = jnp.where(in_range, pick(sk_ref, pk_ref), 0.0)
        v_page = jnp.where(in_range, pick(sv_ref, pv_ref), 0.0)

        q = q_ref[0].astype(jnp.float32)  # [tq, h, hd]
        s = jax.lax.dot_general(
            q.transpose(1, 0, 2), k_page.transpose(1, 0, 2),
            (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale  # [h, tq, bt]
        if quantized:
            k_sc = pick(sks_ref, pks_ref)  # [bt, h, 1]
            s = s * k_sc.transpose(1, 2, 0)  # [h, 1, bt]

        jglob = p * bt + jax.lax.broadcasted_iota(jnp.int32, (tq, bt), 1)
        tq_idx = jax.lax.broadcasted_iota(jnp.int32, (tq, bt), 0)
        valid = (jglob < len_ref[b] + tq_idx) & (jglob < s_total)
        s = jnp.where(valid[None], s, NEG_INF)

        s2 = s.reshape(h * tq, bt)
        m_prev = m_scr[:, :1]
        l_prev = l_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s2, axis=-1, keepdims=True))
        pmat = jnp.exp(s2 - m_new)  # [h*tq, bt]
        correction = jnp.exp(m_prev - m_new)
        l_new = l_prev * correction + jnp.sum(pmat, axis=-1, keepdims=True)
        p3 = pmat.reshape(h, tq, bt)
        if quantized:
            v_sc = jnp.where(in_range, pick(svs_ref, pvs_ref), 0.0)
            p3 = p3 * v_sc.transpose(1, 2, 0)
        pv = jax.lax.dot_general(
            p3, v_page.transpose(1, 0, 2), (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )  # [h, tq, hd]
        acc_scr[...] = acc_scr[...] * correction + pv.reshape(h * tq, hd)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(p == n_pages - 1)
    def _finalize():
        l = l_scr[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        out = (acc_scr[...] / safe_l).reshape(h, tq, hd)
        o_ref[0] = out.transpose(1, 0, 2).astype(o_ref.dtype)


# Imported lazily-but-module-level like flash_attention: pallas is part
# of jax proper; the TPU sub-module only at kernel-build time.
from jax.experimental import pallas as pl  # noqa: E402


def _compiler_params():
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams", None
    )
    if cls is None:  # pragma: no cover — very old pallas
        return None
    return cls(dimension_semantics=("parallel", "arbitrary"))


def _paged_pallas(q, cache_l, cur_len, pool_l, block_table, bt, *,
                  interpret):
    """q [B,Tq,H,hd]; slot leaves [B,S,H,hd]; pool leaves [NB,bt,H,hd];
    block_table [B, ceil(S/bt)] int32 (-1 = slot page); cur_len [B]."""
    global KERNEL_TRACE_COUNT
    KERNEL_TRACE_COUNT += 1
    from jax.experimental.pallas import tpu as pltpu

    b, tq, h, hd = q.shape
    s_total = cache_l["k"].shape[1]
    n_pages = -(-s_total // bt)
    quantized = "k_scale" in cache_l
    has_pool = pool_l is not None
    scale = 1.0 / math.sqrt(hd)

    if block_table is None:
        block_table = jnp.full((b, n_pages), -1, jnp.int32)
    else:
        block_table = block_table.astype(jnp.int32)
        width = block_table.shape[1]
        if width < n_pages:
            block_table = jnp.pad(
                block_table, ((0, 0), (0, n_pages - width)),
                constant_values=-1,
            )
        elif width > n_pages:
            block_table = block_table[:, :n_pages]
    cur_len = cur_len.astype(jnp.int32)

    def last_live(ln, b_):
        # Largest page any query of row b_ can read (>= 0 so the map is
        # always a legal index); dead pages pin here -> their DMA is a
        # repeat fetch the pipeline skips.
        limit = ln[b_] + (tq - 1)
        return jnp.maximum((limit - 1) // bt, 0)

    def q_map(b_, p_, tbl, ln):
        return (b_, 0, 0, 0)

    def slot_map(b_, p_, tbl, ln):
        return (b_, jnp.minimum(p_, last_live(ln, b_)), 0, 0)

    def pool_map(b_, p_, tbl, ln):
        pc = jnp.minimum(p_, last_live(ln, b_))
        return (jnp.maximum(tbl[b_, pc], 0), 0, 0, 0)

    kv_spec = pl.BlockSpec((1, bt, h, hd), slot_map)
    sc_spec = pl.BlockSpec((1, bt, h, 1), slot_map)
    pkv_spec = pl.BlockSpec((1, bt, h, hd), pool_map)
    psc_spec = pl.BlockSpec((1, bt, h, 1), pool_map)

    in_specs = [pl.BlockSpec((1, tq, h, hd), q_map), kv_spec, kv_spec]
    operands = [q, cache_l["k"], cache_l["v"]]
    if quantized:
        in_specs += [sc_spec, sc_spec]
        operands += [cache_l["k_scale"], cache_l["v_scale"]]
    if has_pool:
        in_specs += [pkv_spec, pkv_spec]
        operands += [pool_l["k"], pool_l["v"]]
        if quantized:
            in_specs += [psc_spec, psc_spec]
            operands += [pool_l["k_scale"], pool_l["v_scale"]]

    kernel = functools.partial(
        _paged_kernel, bt=bt, tq=tq, h=h, hd=hd, s_total=s_total,
        scale=scale, quantized=quantized, has_pool=has_pool,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, n_pages),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, tq, h, hd), q_map),
        scratch_shapes=[
            pltpu.VMEM((h * tq, 128), jnp.float32),
            pltpu.VMEM((h * tq, 128), jnp.float32),
            pltpu.VMEM((h * tq, hd), jnp.float32),
        ],
    )
    kwargs = {}
    params = _compiler_params()
    if params is not None:
        kwargs["compiler_params"] = params
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
        **kwargs,
    )(block_table, cur_len, *operands)


# ---------------------------------------------------------------------------
# Partitioner-visible route (custom_partitioning; heads-shardable)
# ---------------------------------------------------------------------------

_partition_fallback_warned = False


@functools.lru_cache(maxsize=None)
def _partitioned_call(bt, quantized, has_pool, interpret):
    """The kernel wrapped for the partitioner: batch/heads shardable,
    pages/positions/depth replicated — the TP(xSP) slot grid is sharded
    over heads, and paged attention is per-head independent, so the rule
    lets each shard run the kernel on its own head slice.  jax builds
    without ``SdyShardingRule`` (0.4.37) fall back to the unwrapped
    kernel with a one-time warning (the partitioner then replicates it —
    correct, just not sharded)."""

    def impl(block_table, cur_len, q, *leaves):
        cache_l, pool_l = _unflatten(leaves, quantized, has_pool)
        return _paged_pallas(q, cache_l, cur_len, pool_l, block_table,
                             bt, interpret=interpret)

    try:
        from jax.experimental.custom_partitioning import (  # noqa: PLC0415
            SdyShardingRule,
            custom_partitioning,
        )
    except ImportError:
        SdyShardingRule = None
        custom_partitioning = None
    if custom_partitioning is None or SdyShardingRule is None:
        global _partition_fallback_warned
        if not _partition_fallback_warned:
            _partition_fallback_warned = True
            import logging

            logging.getLogger(__name__).warning(
                "paged attention: this jax lacks SdyShardingRule; the "
                "partitioned route runs the unwrapped kernel (replicated "
                "by the partitioner) instead."
            )
        return impl

    fn = custom_partitioning(impl)
    infer, part = dispatch_lib.passthrough_callbacks(impl, 1,
                                                     result_like=2)

    slot = ("b", "s", "h", "d")
    pool = ("n", "p1", "h", "d")
    slot_sc = ("b", "s", "h", "one")
    pool_sc = ("n", "p1", "h", "one")
    kv = (slot, slot) + ((slot_sc, slot_sc) if quantized else ())
    pp = ()
    if has_pool:
        pp = (pool, pool) + ((pool_sc, pool_sc) if quantized else ())
    fn.def_partition(
        infer_sharding_from_operands=infer,
        partition=part,
        sharding_rule=SdyShardingRule(
            operand_mappings=(("b", "p"), ("b",), ("b", "t", "h", "d"))
            + kv + pp,
            result_mappings=(("b", "t", "h", "d"),),
            need_replication_factors=("p", "t", "s", "d", "n", "p1",
                                      "one"),
        ),
    )
    return fn


def _flatten(cache_l, pool_l, quantized, has_pool):
    leaves = [cache_l["k"], cache_l["v"]]
    if quantized:
        leaves += [cache_l["k_scale"], cache_l["v_scale"]]
    if has_pool:
        leaves += [pool_l["k"], pool_l["v"]]
        if quantized:
            leaves += [pool_l["k_scale"], pool_l["v_scale"]]
    return leaves


def _unflatten(leaves, quantized, has_pool):
    leaves = list(leaves)
    cache_l = {"k": leaves.pop(0), "v": leaves.pop(0)}
    if quantized:
        cache_l["k_scale"] = leaves.pop(0)
        cache_l["v_scale"] = leaves.pop(0)
    pool_l = None
    if has_pool:
        pool_l = {"k": leaves.pop(0), "v": leaves.pop(0)}
        if quantized:
            pool_l["k_scale"] = leaves.pop(0)
            pool_l["v_scale"] = leaves.pop(0)
    return cache_l, pool_l


# ---------------------------------------------------------------------------
# Dispatch + public entry points
# ---------------------------------------------------------------------------


def _fit_page(s: int, bt: Optional[int]) -> Optional[int]:
    """Resolve the page size: the pool's block_tokens when a pool rides
    along (pages must align to pool blocks), else the largest multiple
    of 8 at or below ``min(DEFAULT_PAGE_TOKENS, S)``."""
    if bt is not None:
        return bt
    fitted = min(DEFAULT_PAGE_TOKENS, s)
    fitted -= fitted % 8
    return fitted if fitted >= 8 else None


def _kernel_eligible(q, cache_l, bt) -> bool:
    return (
        q.ndim == 4
        and cache_l["k"].ndim == 4
        and bt is not None
        and q.shape[-1] <= 256  # head_dim beyond this overflows VMEM
        and q.shape[0] == cache_l["k"].shape[0]
    )


def would_use_kernel(q, cache_l, *, page_tokens: Optional[int] = None
                     ) -> bool:
    """The ``use_pallas=None`` auto-dispatch predicate, exposed so the
    serving engine and tests share one spelling."""
    bt = _fit_page(cache_l["k"].shape[1], page_tokens)
    return (
        jax.default_backend() == "tpu"
        and _kernel_enabled()
        and _kernel_eligible(q, cache_l, bt)
        and cache_l["k"].shape[1] >= MIN_SEQ_LEN_FOR_KERNEL
    )


def _paged(q, cache_l, cur_len, *, pool_l, block_table, use_pallas,
           interpret, partitioned):
    quantized = "k_scale" in cache_l
    has_pool = pool_l is not None
    bt = _fit_page(
        cache_l["k"].shape[1],
        None if pool_l is None else pool_l["k"].shape[1],
    )
    if not interpret and _force_interpret():
        interpret = True
    eligible = _kernel_eligible(q, cache_l, bt) and _kernel_enabled()
    if use_pallas is None:
        use_pallas = would_use_kernel(
            q, cache_l,
            page_tokens=None if pool_l is None else pool_l["k"].shape[1],
        ) or (interpret and eligible)
    if use_pallas and not eligible:
        use_pallas = False
    if use_pallas and jax.default_backend() != "tpu":
        interpret = True
    if not use_pallas:
        return _reference(q, cache_l, cur_len, pool_l, block_table)
    if block_table is None:
        block_table = jnp.full(
            (q.shape[0], -(-cache_l["k"].shape[1] // bt)), -1, jnp.int32
        )
    if partitioned:
        fn = _partitioned_call(bt, quantized, has_pool, interpret)
        leaves = _flatten(cache_l, pool_l, quantized, has_pool)
        return fn(block_table.astype(jnp.int32),
                  cur_len.astype(jnp.int32), q, *leaves)
    return _paged_pallas(q, cache_l, cur_len, pool_l, block_table, bt,
                         interpret=interpret)


def paged_decode_attention(
    q: jnp.ndarray,
    cache_l,
    cur_len: jnp.ndarray,
    *,
    pool_l=None,
    block_table: Optional[jnp.ndarray] = None,
    use_pallas: Optional[bool] = None,
    interpret: bool = False,
    partitioned: bool = False,
) -> jnp.ndarray:
    """Single-token decode attention ([B, 1, H, hd] queries) over a
    block-table view of slot rows + pool blocks.

    Drop-in for ``_cache_attention(q, cache_l, cur_len)``: key ``j`` of
    row ``b`` is valid iff ``j < cur_len[b]`` (callers pass ``pos + 1``
    exactly as they do to ``_cache_attention``).  ``block_table``
    [B, n_pages] int32 maps page ``p`` (positions ``[p*bt, (p+1)*bt)``)
    to a ``pool_l`` block when ``>= 0``, to the slot row when ``-1``;
    ``block_table=None`` (or ``pool_l=None``) reads slot rows only —
    the cold-insert shape.
    """
    return _paged(q, cache_l, cur_len, pool_l=pool_l,
                  block_table=block_table, use_pallas=use_pallas,
                  interpret=interpret, partitioned=partitioned)


def paged_chunk_attention(
    q: jnp.ndarray,
    cache_l,
    cur_len: jnp.ndarray,
    *,
    pool_l=None,
    block_table: Optional[jnp.ndarray] = None,
    use_pallas: Optional[bool] = None,
    interpret: bool = False,
    partitioned: bool = False,
) -> jnp.ndarray:
    """Chunk-causal paged attention — the ``prefill_chunk_program``
    shape.  Queries are CONSECUTIVE cache positions starting at
    ``cur_len - 1``: key ``j`` is valid for query ``t`` iff
    ``j < cur_len + t`` (``_cache_attention(..., chunk_causal=True)``'s
    exact mask).  With ``T_q == 1`` this degenerates to
    :func:`paged_decode_attention` — one kernel serves both."""
    return _paged(q, cache_l, cur_len, pool_l=pool_l,
                  block_table=block_table, use_pallas=use_pallas,
                  interpret=interpret, partitioned=partitioned)


def paged_verify_attention(
    q: jnp.ndarray,
    cache_l,
    cur_len: jnp.ndarray,
    *,
    pool_l=None,
    block_table: Optional[jnp.ndarray] = None,
    use_pallas: Optional[bool] = None,
    interpret: bool = False,
    partitioned: bool = False,
) -> jnp.ndarray:
    """Speculative verify-window paged attention — the
    ``verify_chunk_program`` shape ([num_slots, spec_k, H, hd] queries,
    per-slot window starts).  Mask-wise identical to
    :func:`paged_chunk_attention` (the window IS a chunk at ``pos``);
    a separate entry point so the serving dispatch sites and the
    crossover bench name the shape they measure."""
    return _paged(q, cache_l, cur_len, pool_l=pool_l,
                  block_table=block_table, use_pallas=use_pallas,
                  interpret=interpret, partitioned=partitioned)
