"""Fused linear + softmax cross-entropy: the LM-head loss without the
[N, V] materialization.

Why: CloudLM's stock loss path computes ``logits = x @ W`` ([B, T, V]
f32) and then ``log_softmax`` — under ``value_and_grad`` XLA keeps both
as residuals, ~2 * B*T*V*4 bytes.  At B8 x T2048 x V32000 that is
~4 GiB of HBM for ONE layer of the program, and the softmax+gather
epilogue is pure HBM traffic (BASELINE.md's BERT ablation measured the
vocab term at 1.4 ms/step at only V=30k classification scale).

This op computes per-token ``nll = logsumexp_V(x @ W) - (x @ W)[target]``
by scanning the vocab in chunks with an online (running max / scaled
sum) logsumexp — the same numerics trick as flash attention's softmax —
and a ``custom_vjp`` whose backward RE-computes each chunk's logits
(one extra [N, C] matmul per chunk) instead of keeping any [N, V]
residual.  Peak extra memory is O(N * chunk_size); FLOPs go up ~1.33x
on the head (recompute) in exchange — on an HBM-bound epilogue that is
the right trade for the MXU.

No reference counterpart (the reference owns no kernels or losses —
SURVEY.md §5); the technique is the public "fused/chunked linear
cross-entropy" pattern used by large-vocab LM trainers.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

#: Default vocab chunk: 8k columns x f32 keeps the live chunk tensor at
#: N x 32 KiB — far below the [N, V] it replaces, big enough to feed the
#: MXU efficient [*, D] x [D, C] tiles.
DEFAULT_CHUNK = 8192


def _prep_table(table, layout: str):
    """Normalize to [V, D] (rows = classes)."""
    if layout == "vd":
        return table
    if layout == "dv":
        return table.T
    raise ValueError(f"table layout must be 'vd' or 'dv', got {layout!r}")


def _chunked(table_vd, chunk: int):
    """[V, D] -> (padded [n_chunks, chunk, D], n_chunks, V)."""
    v = table_vd.shape[0]
    n_chunks = -(-v // chunk)
    pad = n_chunks * chunk - v
    if pad:
        table_vd = jnp.pad(table_vd, ((0, pad), (0, 0)))
    return table_vd.reshape(n_chunks, chunk, table_vd.shape[-1]), n_chunks, v


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _fused_nll(x, table, targets, layout, chunk):
    nll, _ = _fused_fwd(x, table, targets, layout, chunk)
    return nll


def _fused_fwd(x, table, targets, layout, chunk):
    x32 = x.astype(jnp.float32)
    chunks, n_chunks, v = _chunked(
        _prep_table(table, layout).astype(jnp.float32), chunk
    )
    n = x32.shape[0]

    def body(carry, inp):
        m, s, tgt = carry
        idx, w_c = inp  # w_c: [C, D]
        logits = x32 @ w_c.T  # [N, C] — the only [N, C] live at a time
        cols = idx * chunk + jnp.arange(chunk)  # global class ids
        logits = jnp.where(cols[None, :] < v, logits, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=-1
        )
        # Accumulate the target logit when it falls in this chunk.
        hit = (targets >= idx * chunk) & (targets < (idx + 1) * chunk)
        local = jnp.clip(targets - idx * chunk, 0, chunk - 1)
        picked = jnp.take_along_axis(logits, local[:, None], axis=-1)[:, 0]
        tgt = jnp.where(hit, picked, tgt)
        return (m_new, s, tgt), None

    init = (
        jnp.full((n,), -jnp.inf, jnp.float32),
        jnp.zeros((n,), jnp.float32),
        jnp.zeros((n,), jnp.float32),
    )
    (m, s, tgt), _ = lax.scan(body, init, (jnp.arange(n_chunks), chunks))
    lse = m + jnp.log(s)
    return lse - tgt, (x, table, targets, lse)


def _fused_bwd(layout, chunk, res, g):
    x, table, targets, lse = res
    x32 = x.astype(jnp.float32)
    chunks, n_chunks, v = _chunked(
        _prep_table(table, layout).astype(jnp.float32), chunk
    )
    g32 = g.astype(jnp.float32)

    def body(dx, inp):
        idx, w_c = inp
        logits = x32 @ w_c.T  # recompute — no [N, V] residual exists
        cols = idx * chunk + jnp.arange(chunk)
        p = jnp.where(
            cols[None, :] < v, jnp.exp(logits - lse[:, None]), 0.0
        )
        onehot = (targets[:, None] == cols[None, :]).astype(jnp.float32)
        gp = (p - onehot) * g32[:, None]  # [N, C]
        dx = dx + gp @ w_c  # [N, D]
        dw_c = gp.T @ x32  # [C, D]
        return dx, dw_c

    dx, dws = lax.scan(
        body, jnp.zeros(x32.shape, jnp.float32),
        (jnp.arange(n_chunks), chunks),
    )
    dtable_vd = dws.reshape(n_chunks * chunk, -1)[:v]
    dtable = dtable_vd if layout == "vd" else dtable_vd.T
    return (
        dx.astype(x.dtype),
        dtable.astype(table.dtype),
        None,
    )


_fused_nll.defvjp(_fused_fwd, _fused_bwd)


def fused_linear_cross_entropy(
    x: jnp.ndarray,
    table: jnp.ndarray,
    targets: jnp.ndarray,
    *,
    table_layout: str = "vd",
    chunk_size: int = DEFAULT_CHUNK,
    weights: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Mean cross-entropy of ``softmax(x @ W)`` against ``targets``
    without materializing the [..., V] logits.

    Args:
      x: activations [..., D] (any leading shape; flattened internally).
      table: class matrix — [V, D] (``table_layout="vd"``, the tied
        token-embedding layout: logits = x @ table^T) or [D, V]
        (``"dv"``, a dense head kernel).
      targets: int class ids, shape = x's leading shape.
      chunk_size: vocab columns per scan step (memory/efficiency knob).
      weights: optional per-position weights, broadcastable to targets'
        shape; the result is sum(nll * w) / max(sum(w), 1) — the same
        normalization as the stock loss path.

    Returns the scalar mean loss.  Compute is f32 regardless of input
    dtypes (matching ``lm_logits``' f32 head).
    """
    import math

    lead = targets.shape
    n = math.prod(lead)
    nll = _fused_nll(
        x.reshape(n, x.shape[-1]),
        table,
        targets.reshape(n),
        table_layout,
        int(chunk_size),
    ).reshape(lead)
    if weights is None:
        return jnp.mean(nll)
    w = jnp.broadcast_to(weights.astype(jnp.float32), lead)
    return jnp.sum(nll * w) / jnp.clip(jnp.sum(w), 1.0)
