"""Flash attention as a Pallas TPU kernel, with a jnp reference fallback.

Forward: online-softmax over K/V blocks — the grid's innermost dimension
walks key blocks while VMEM scratch carries the running (max, sum, output)
accumulators, so attention scores never materialize in HBM (memory
O(block_q x block_k) instead of O(T^2)).  Backward: custom VJP with the
standard recompute scheme — one kernel accumulates dQ over key blocks, one
accumulates dK/dV over query blocks, both reusing the forward's saved
logsumexp so no O(T^2) residuals are stored.

Layout contract matches ``layers.causal_attention``: [B, T, H, D] in, same
out.  Kernels run over [B, H, T, D] internally (last two dims tile onto
the (8,128) VMEM lanes; D and the block sizes should be multiples of 128
for full MXU tiles — head_dim 64 works, at half-lane occupancy).

Dispatch: real TPU + tile-divisible shapes -> kernels; anything else (CPU
tests, ragged shapes, explicit masks) -> ``_reference`` (pure jnp, XLA).
The causal mask is applied in *global* positions so the kernels compose
with ring attention's per-block fold later.

No reference counterpart (SURVEY.md §5: the reference owns no kernels);
this is TPU-native capability the rebuild adds.
"""

from __future__ import annotations

import functools
import math
import os
from typing import Optional

import jax
import jax.numpy as jnp

from cloud_tpu.ops import dispatch as dispatch_lib
import numpy as np
from jax.experimental import pallas as pl

NEG_INF = -1e30  # finite: fully-masked rows softmax to zeros, not NaN

DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 512

#: Auto-dispatch (``use_pallas=None``) takes the kernel only at T >= this.
#: Measured on TPU v5e (scripts/attn_crossover.py, value+grad, steady
#: state): XLA's fused attention is ~1.15-1.25x faster at T in [256, 512]
#: (the whole O(T^2) score tensor still fits cache-friendly tiles there),
#: while the kernel wins 1.38x at 1024, 1.45x at 2048, 1.61x at 4096 — and
#: is O(T) in memory where XLA materializes the [B,H,T,T] scores.  Callers
#: that need the kernel below the threshold (masked long-tail, tests) pass
#: ``use_pallas=True`` explicitly.
MIN_SEQ_LEN_FOR_KERNEL = int(os.environ.get("CLOUD_TPU_FLASH_MIN_SEQ", 1024))

#: ...unless the would-be [B, H, Tq, Tk] f32 score tensor is this large
#: (bytes), in which case the kernel is taken regardless of T.  Speed is
#: not the issue below the T threshold — memory is: under ``value_and_grad``
#: XLA saves the softmax scores as residuals PER LAYER (a 12-layer BERT
#: scan at B=32, T=512 allocates 4.5 GiB f32 + 2.25 GiB bf16 of score
#: residuals and OOMs a 16 GiB v5e chip), while the kernel's residual is
#: the O(T) logsumexp.  128 MiB per call keeps a 12-layer stack under
#: ~1.5 GiB of attention residuals.
SCORE_BYTES_FOR_KERNEL = int(
    os.environ.get("CLOUD_TPU_FLASH_SCORE_BYTES", 128 * 1024**2)
)

#: Diagnostic counter: bumped every time a Pallas kernel call is actually
#: traced (fwd or bwd).  The multichip dryrun asserts it advances to prove
#: the kernel path — not the jnp reference — ran inside the pipeline
#: region (VERDICT r2 weak #5's done-criterion).
KERNEL_TRACE_COUNT = 0


# ---------------------------------------------------------------------------
# Reference implementation (ground truth + non-TPU fallback)
# ---------------------------------------------------------------------------


def _reference(q, k, v, *, causal, mask):
    return _reference_with_lse(q, k, v, causal=causal, mask=mask)[0]


def _reference_with_lse(q, k, v, *, causal, mask):
    """Reference path that also returns the log-sum-exp [B, H, T_q] —
    the quantity ring attention needs to merge per-block partials."""
    dim = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    s = s / math.sqrt(dim)
    t_q, t_k = q.shape[1], k.shape[1]
    if causal:
        causal_mask = jnp.tril(jnp.ones((t_q, t_k), bool), k=t_k - t_q)
        s = jnp.where(causal_mask, s, NEG_INF)
    if mask is not None:
        s = jnp.where(mask[:, None, None, :], s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    safe_l = jnp.where(l == 0.0, 1.0, l)
    w = (p / safe_l).astype(q.dtype)
    out = jnp.einsum("bhqk,bkhd->bqhd", w, v)
    lse = (m + jnp.log(safe_l))[..., 0]  # [B, H, T_q]
    return out, lse


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------


def _fwd_kernel(*refs, scale, causal, block_q, block_k, use_mask):
    if use_mask:
        (q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref,
         m_scr, l_scr, acc_scr) = refs
    else:
        (q_ref, k_ref, v_ref, o_ref, lse_ref,
         m_scr, l_scr, acc_scr) = refs
        mask_ref = None
    qi, ki = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # Blocks strictly above the causal diagonal contribute nothing: skip
    # the matmuls entirely (the grid still visits them; compute does not).
    run = (
        (ki * block_k <= qi * block_q + block_q - 1) if causal else True
    )

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0]  # [block_q, D]
        k = k_ref[0, 0]  # [block_k, D]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [block_q, block_k]

        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0
            )
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1
            )
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        if mask_ref is not None:
            # Key-side padding mask [block_k] (nonzero = valid token),
            # broadcast over query rows — matches the reference path's
            # mask[:, None, None, :] semantics.
            s = jnp.where(mask_ref[0][None, :] != 0, s, NEG_INF)

        m_prev = m_scr[:, :1]  # [block_q, 1] (value replicated over lanes)
        l_prev = l_scr[:, :1]
        m_blk = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_blk)
        p = jnp.exp(s - m_new)  # [block_q, block_k] f32
        correction = jnp.exp(m_prev - m_new)  # [block_q, 1]
        l_new = l_prev * correction + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_q, D]
        acc_scr[...] = acc_scr[...] * correction + pv
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_scr[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scr[...] / safe_l).astype(o_ref.dtype)
        # lse carried as [block_q, 1] (trailing singleton keeps the block
        # tile legal: Mosaic requires the last dim equal to the array's).
        lse_ref[0, 0] = m_scr[:, :1] + jnp.log(safe_l)


def _check_divisible(t, block_q, block_k):
    if t % block_q or t % block_k:
        # The grid would silently skip the tail rows otherwise.
        raise ValueError(
            f"flash attention kernel needs T divisible by the block sizes; "
            f"got T={t}, block_q={block_q}, block_k={block_k}"
        )
    if block_q % 8 or block_k % 8:
        # Catches e.g. T=100 clamped to block=100: divisible, but Mosaic
        # would fail the (8,128) sublane tile with a cryptic error.
        raise ValueError(
            f"flash attention blocks must be multiples of 8 (sublane tile); "
            f"got block_q={block_q}, block_k={block_k}"
        )


def _carry_vma(*operands):
    """The varying-manual-axes set the kernel outputs must declare when the
    call is traced inside a ``check_vma=True`` shard_map (e.g. the pipeline
    body): outputs vary over every axis any operand varies over.  Outside a
    manual region every vma is empty, so this is a no-op there."""
    vma = frozenset()
    for x in operands:
        if x is None:
            continue
        aval = jax.typeof(x)
        vma = vma | getattr(aval, "vma", frozenset())
    return vma


def _fwd_pallas(q, k, v, mask, *, causal, block_q, block_k, interpret):
    """q,k,v: [B, H, T, D]; mask: [B, T] i32 or None ->
    (out [B, H, T, D], lse [B, H, T, 1])."""
    global KERNEL_TRACE_COUNT
    KERNEL_TRACE_COUNT += 1
    b, h, t, d = q.shape
    _check_divisible(t, block_q, block_k)
    nq, nk = t // block_q, t // block_k
    scale = 1.0 / math.sqrt(d)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, use_mask=mask is not None,
    )
    qspec = pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, qi, ki: (b_, h_, qi, 0))
    kspec = pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, qi, ki: (b_, h_, ki, 0))
    in_specs = [qspec, kspec, kspec]
    operands = [q, k, v]
    if mask is not None:
        in_specs.append(
            pl.BlockSpec((1, block_k), lambda b_, h_, qi, ki: (b_, ki))
        )
        operands.append(mask)
    out, lse = pl.pallas_call(
        kernel,
        grid=(b, h, nq, nk),
        in_specs=in_specs,
        out_specs=[
            qspec,
            pl.BlockSpec(
                (1, 1, block_q, 1), lambda b_, h_, qi, ki: (b_, h_, qi, 0)
            ),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype,
                                 vma=_carry_vma(q, k, v, mask)),
            jax.ShapeDtypeStruct((b, h, t, 1), jnp.float32,
                                 vma=_carry_vma(q, k, v, mask)),
        ],
        scratch_shapes=[
            _vmem((block_q, 128), jnp.float32),
            _vmem((block_q, 128), jnp.float32),
            _vmem((block_q, d), jnp.float32),
        ],
        compiler_params=_compiler_params(),
        interpret=interpret,
    )(*operands)
    return out, lse


def _vmem(shape, dtype):
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.VMEM(shape, dtype)


def _compiler_params():
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
    )


# ---------------------------------------------------------------------------
# Backward kernels (recompute scheme)
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(*refs, scale, causal, block_q, block_k, use_mask,
                   use_glse):
    refs = list(refs)
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref = refs[:6]
    pos = 6
    glse_ref = refs[pos] if use_glse else None
    pos += 1 if use_glse else 0
    mask_ref = refs[pos] if use_mask else None
    pos += 1 if use_mask else 0
    dq_ref, dq_scr = refs[pos], refs[pos + 1]
    qi, ki = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    run = (
        (ki * block_k <= qi * block_q + block_q - 1) if causal else True
    )

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]  # [block_q, 1]
        delta = delta_ref[0, 0]  # [block_q, 1]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        if mask_ref is not None:
            s = jnp.where(mask_ref[0][None, :] != 0, s, NEG_INF)
        p = jnp.exp(s - lse)  # [block_q, block_k]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_q, block_k]
        # lse cotangent: d(lse_i)/d(s_ij) = p_ij, so ds += p * g_lse.
        row_term = delta - (glse_ref[0, 0] if glse_ref is not None else 0.0)
        ds = p * (dp - row_term)  # [block_q, block_k] f32
        dq_scr[...] += scale * jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0, 0] = dq_scr[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(*refs, scale, causal, block_q, block_k, use_mask,
                    use_glse):
    refs = list(refs)
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref = refs[:6]
    pos = 6
    glse_ref = refs[pos] if use_glse else None
    pos += 1 if use_glse else 0
    mask_ref = refs[pos] if use_mask else None
    pos += 1 if use_mask else 0
    dk_ref, dv_ref, dk_scr, dv_scr = refs[pos:pos + 4]
    ki, qi = pl.program_id(2), pl.program_id(3)
    nq = pl.num_programs(3)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    # Query blocks entirely above the diagonal see none of this key block.
    run = (
        (qi * block_q + block_q - 1 >= ki * block_k) if causal else True
    )

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]  # [block_q, 1]
        delta = delta_ref[0, 0]  # [block_q, 1]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [block_q, block_k]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        if mask_ref is not None:
            # This grid walks key blocks in dim 2: the mask block is the
            # one covering this kernel's key rows (index i, not j).
            s = jnp.where(mask_ref[0][None, :] != 0, s, NEG_INF)
        p = jnp.exp(s - lse)  # [block_q, block_k]
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_k, D]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_q, block_k]
        row_term = delta - (glse_ref[0, 0] if glse_ref is not None else 0.0)
        ds = p * (dp - row_term)
        dk_scr[...] += scale * jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_k, D]

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0, 0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[...].astype(dv_ref.dtype)


def _bwd_pallas(q, k, v, mask, do, out, lse, *, causal, block_q, block_k,
                interpret, g_lse=None):
    """``g_lse`` is the [B, H, T, 1] cotangent of the forward's lse output
    (None for the out-only entry point); it adds ``p * g_lse`` to ds in
    both kernels."""
    global KERNEL_TRACE_COUNT
    KERNEL_TRACE_COUNT += 1
    b, h, t, d = q.shape
    _check_divisible(t, block_q, block_k)
    nq, nk = t // block_q, t // block_k
    scale = 1.0 / math.sqrt(d)
    use_mask = mask is not None
    use_glse = g_lse is not None
    # delta_i = rowsum(dO_i * O_i): elementwise, XLA fuses it; no kernel.
    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1,
        keepdims=True,
    )  # [B, H, T, 1], matching lse's layout

    qspec = pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, i, j: (b_, h_, i, 0))
    kspec_i = pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, i, j: (b_, h_, j, 0))
    rowspec = pl.BlockSpec(
        (1, 1, block_q, 1), lambda b_, h_, i, j: (b_, h_, i, 0)
    )

    dq_in_specs = [qspec, kspec_i, kspec_i, qspec, rowspec, rowspec]
    dq_operands = [q, k, v, do, lse, delta]
    if use_glse:
        dq_in_specs.append(rowspec)
        dq_operands.append(g_lse)
    if use_mask:
        dq_in_specs.append(
            pl.BlockSpec((1, block_k), lambda b_, h_, i, j: (b_, j))
        )
        dq_operands.append(mask)
    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, use_mask=use_mask,
            use_glse=use_glse,
        ),
        grid=(b, h, nq, nk),
        in_specs=dq_in_specs,
        out_specs=[qspec],
        out_shape=[jax.ShapeDtypeStruct(
            q.shape, q.dtype, vma=_carry_vma(*dq_operands))],
        scratch_shapes=[_vmem((block_q, d), jnp.float32)],
        compiler_params=_compiler_params(),
        interpret=interpret,
    )(*dq_operands)[0]

    # dK/dV: grid walks key blocks in the parallel dims, query blocks in the
    # arbitrary (accumulating) dim.
    kspec_o = pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, i, j: (b_, h_, i, 0))
    qspec_j = pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, i, j: (b_, h_, j, 0))
    rowspec_j = pl.BlockSpec(
        (1, 1, block_q, 1), lambda b_, h_, i, j: (b_, h_, j, 0)
    )
    dkv_in_specs = [qspec_j, kspec_o, kspec_o, qspec_j, rowspec_j, rowspec_j]
    dkv_operands = [q, k, v, do, lse, delta]
    if use_glse:
        dkv_in_specs.append(rowspec_j)
        dkv_operands.append(g_lse)
    if use_mask:
        dkv_in_specs.append(
            pl.BlockSpec((1, block_k), lambda b_, h_, i, j: (b_, i))
        )
        dkv_operands.append(mask)
    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, use_mask=use_mask,
            use_glse=use_glse,
        ),
        grid=(b, h, nk, nq),
        in_specs=dkv_in_specs,
        out_specs=[kspec_o, kspec_o],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype,
                                 vma=_carry_vma(*dkv_operands)),
            jax.ShapeDtypeStruct(v.shape, v.dtype,
                                 vma=_carry_vma(*dkv_operands)),
        ],
        scratch_shapes=[
            _vmem((block_k, d), jnp.float32),
            _vmem((block_k, d), jnp.float32),
        ],
        compiler_params=_compiler_params(),
        interpret=interpret,
    )(*dkv_operands)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp plumbing + public dispatch
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash(q, k, v, mask, causal, block_q, block_k, interpret):
    out, _ = _fwd_pallas(
        q, k, v, mask, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret,
    )
    return out


def _flash_fwd(q, k, v, mask, causal, block_q, block_k, interpret):
    out, lse = _fwd_pallas(
        q, k, v, mask, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret,
    )
    return out, (q, k, v, mask, out, lse)


def _flash_bwd(causal, block_q, block_k, interpret, residuals, g):
    q, k, v, mask, out, lse = residuals
    dq, dk, dv = _bwd_pallas(
        q, k, v, mask, g, out, lse, causal=causal, block_q=block_q,
        block_k=block_k, interpret=interpret,
    )
    # The i32 mask's cotangent is float0 (integer operands carry no grad).
    dmask = (
        None if mask is None
        else np.zeros(mask.shape, jax.dtypes.float0)
    )
    return dq, dk, dv, dmask


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash_lse(q, k, v, mask, causal, block_q, block_k, interpret):
    """Kernel forward returning (out, lse [B,H,T,1]) — the building block
    for ring attention's per-block folds.  The VJP handles BOTH outputs'
    cotangents: g_lse enters ds as ``p * g_lse`` (dlse/ds = softmax)."""
    return _fwd_pallas(
        q, k, v, mask, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret,
    )


def _flash_lse_fwd(q, k, v, mask, causal, block_q, block_k, interpret):
    out, lse = _fwd_pallas(
        q, k, v, mask, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret,
    )
    return (out, lse), (q, k, v, mask, out, lse)


def _flash_lse_bwd(causal, block_q, block_k, interpret, residuals, g):
    q, k, v, mask, out, lse = residuals
    g_out, g_lse = g
    dq, dk, dv = _bwd_pallas(
        q, k, v, mask, g_out, out, lse, causal=causal, block_q=block_q,
        block_k=block_k, interpret=interpret, g_lse=g_lse,
    )
    dmask = (
        None if mask is None
        else np.zeros(mask.shape, jax.dtypes.float0)
    )
    return dq, dk, dv, dmask


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


# ---------------------------------------------------------------------------
# Partitioner-visible kernels (custom_partitioning)
# ---------------------------------------------------------------------------
#
# ``pallas_call`` lowers to a custom call GSPMD cannot partition: in an
# auto-sharded context an unwrapped kernel would replicate every operand,
# and a nested shard_map inside the pipeline's partial-manual region fails
# sdy verification ("manual axis after free axis" — models/layers.py).
# ``custom_partitioning`` is the third route: declare a Shardy sharding
# rule (batch/heads shardable, sequence/depth need-replication) and hand
# the partitioner a per-shard lowering.  This is what lets the flash
# kernel run INSIDE pipeline stages (VERDICT r2 weak #5).


@functools.lru_cache(maxsize=None)
def _cp_fwd_call(causal, block_q, block_k, interpret, use_mask):
    """Forward kernel wrapped for the partitioner ([B,H,T,D] layout)."""
    from jax.experimental.custom_partitioning import (
        SdyShardingRule,
        custom_partitioning,
    )

    def impl(*args):
        q, k, v = args[:3]
        mask = args[3] if use_mask else None
        return _fwd_pallas(q, k, v, mask, causal=causal, block_q=block_q,
                           block_k=block_k, interpret=interpret)

    fn = custom_partitioning(impl)

    # t/d are need-replication factors, so q's sharding tiles only (b, h)
    # — and lse [B,H,T,1] (rank 4, same leading dims) therefore shards
    # identically to out; both reuse q's sharding.
    infer, part = dispatch_lib.passthrough_callbacks(impl, 2)

    bhtd = ("b", "h", "t", "d")
    fn.def_partition(
        infer_sharding_from_operands=infer,
        partition=part,
        sharding_rule=SdyShardingRule(
            operand_mappings=((bhtd,) * 3
                              + ((("b", "t"),) if use_mask else ())),
            result_mappings=(bhtd, ("b", "h", "t2", "d2")),
            need_replication_factors=("t", "d", "t2", "d2"),
        ),
    )
    return fn


@functools.lru_cache(maxsize=None)
def _cp_bwd_call(causal, block_q, block_k, interpret, use_mask):
    """Backward kernels wrapped for the partitioner: (q, k, v, do, out,
    lse[, mask]) -> (dq, dk, dv)."""
    from jax.experimental.custom_partitioning import (
        SdyShardingRule,
        custom_partitioning,
    )

    def impl(*args):
        q, k, v, do, out, lse = args[:6]
        mask = args[6] if use_mask else None
        return _bwd_pallas(q, k, v, mask, do, out, lse, causal=causal,
                           block_q=block_q, block_k=block_k,
                           interpret=interpret)

    fn = custom_partitioning(impl)

    # dq/dk/dv all shard like q ([B,H,T,D], t/d replicated by the rule).
    infer, part = dispatch_lib.passthrough_callbacks(impl, 3)

    bhtd = ("b", "h", "t", "d")
    fn.def_partition(
        infer_sharding_from_operands=infer,
        partition=part,
        sharding_rule=SdyShardingRule(
            operand_mappings=((bhtd,) * 5 + (("b", "h", "t2", "d2"),)
                              + ((("b", "t"),) if use_mask else ())),
            result_mappings=(bhtd,) * 3,
            need_replication_factors=("t", "d", "t2", "d2"),
        ),
    )
    return fn


@functools.lru_cache(maxsize=None)
def _flash_partitioned(causal, block_q, block_k, interpret, use_mask):
    """custom_vjp around the partitioner-visible kernels.  The vjp sits
    OUTSIDE custom_partitioning (which has no autodiff rules): the forward
    cp call appears in the primal HLO, the backward cp call in the
    cotangent HLO, and each is partitioned independently."""
    fwd_call = _cp_fwd_call(causal, block_q, block_k, interpret, use_mask)
    bwd_call = _cp_bwd_call(causal, block_q, block_k, interpret, use_mask)

    @jax.custom_vjp
    def f(*args):  # (q, k, v[, mask_i32])
        out, _ = fwd_call(*args)
        return out

    def f_fwd(*args):
        out, lse = fwd_call(*args)
        return out, args + (out, lse)

    def f_bwd(res, g):
        args, out, lse = res[:-2], res[-2], res[-1]
        q, k, v = args[:3]
        grads = bwd_call(q, k, v, g, out, lse, *args[3:])
        if use_mask:
            return tuple(grads) + (
                np.zeros(args[3].shape, jax.dtypes.float0),
            )
        return tuple(grads)

    f.defvjp(f_fwd, f_bwd)
    return f


def _score_bytes(q, k) -> int:
    """Size of the would-be [B, H, Tq, Tk] f32 score tensor."""
    return (
        q.shape[0] * q.shape[2] * q.shape[1] * k.shape[1] * 4
        if q.ndim == 4 else 0
    )


def _kernel_worthwhile(q, k) -> bool:
    """The size half of the auto-dispatch predicate: is this shape big
    enough that the kernel (not XLA's fused path) is the right call?
    Shared by would_use_kernel and the partitioned-fallback warning so
    the two can't drift."""
    return (
        q.shape[1] >= MIN_SEQ_LEN_FOR_KERNEL
        or _score_bytes(q, k) >= SCORE_BYTES_FOR_KERNEL
    )


_partitioned_fallback_warned = False


def _warn_partitioned_fallback(q, k, mask):
    """One-time warning when a ``partitioned=True`` caller (the pipeline
    region / mesh-auto path, which EXPECTS the O(T) kernel) falls back to
    the O(T^2) reference at a size where that hurts — ineligible shapes
    (unalignable T, head_dim > 256, mask shape mismatch) reach here with
    no other signal."""
    global _partitioned_fallback_warned
    if _partitioned_fallback_warned:
        return
    if not _kernel_worthwhile(q, k):
        return  # below both thresholds XLA's fused path is the right call
    if jax.default_backend() != "tpu" and not dispatch_lib.force_interpret():
        return  # off-TPU the reference is the only option — not a fallback
    _partitioned_fallback_warned = True
    import logging

    logging.getLogger(__name__).warning(
        "partitioned attention dispatch at q shape %s fell back to the "
        "O(T^2) jnp reference (shape not kernel-eligible: unalignable T, "
        "head_dim > 256, or mask shape mismatch). Expect per-layer score "
        "residual memory; pad T to an 8-aligned size to restore the "
        "flash kernel.",
        tuple(q.shape),
    )


def _dispatch(q, k, v, *, causal, mask, block_q, block_k, use_pallas,
              interpret, with_lse, partitioned=False):
    """Shared fit/dispatch/transpose wrapper for both public entry points
    (kept in ONE place so mask/fit rules can't drift between them)."""
    explicit_opt_out = use_pallas is False
    if not interpret and dispatch_lib.force_interpret():
        interpret = True
    fitted_q = _fit_block(q.shape[1], block_q)
    fitted_k = _fit_block(k.shape[1], block_k)
    mask_ok = mask is None or (
        mask.ndim == 2
        and mask.shape[0] == q.shape[0]
        and mask.shape[1] == k.shape[1]
    )
    if use_pallas is None:
        use_pallas = would_use_kernel(q, k, mask, block_q=block_q,
                                      block_k=block_k)
    if interpret and _kernel_eligible(q, k, fitted_q, fitted_k):
        # Force the interpreter ONLY where the kernels apply — shapes the
        # kernels can't express (rectangular q/k, oversize head_dim,
        # unalignable T) must still fall through to the reference.
        use_pallas = True
    if not use_pallas or not mask_ok:
        # Warn only when AUTO dispatch fell back — an explicit
        # use_pallas=False caller opted out deliberately.
        if partitioned and not explicit_opt_out:
            _warn_partitioned_fallback(q, k, mask)
        if with_lse:
            return _reference_with_lse(q, k, v, causal=causal, mask=mask)
        return _reference(q, k, v, causal=causal, mask=mask)
    # Requested blocks are upper bounds: run with the largest aligned
    # divisor of T at or below them.  No aligned divisor (forced kernel
    # path only) falls through to the clamp and _check_divisible's error.
    block_q = fitted_q if fitted_q is not None else min(block_q, q.shape[1])
    block_k = fitted_k if fitted_k is not None else min(block_k, k.shape[1])
    # [B, T, H, D] -> [B, H, T, D] for (T, D)-tiled kernels.
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    mask_i32 = None if mask is None else mask.astype(jnp.int32)
    if partitioned:
        if with_lse:
            raise NotImplementedError(
                "partitioned dispatch covers the out-only entry point "
                "(ring attention wraps its own full-manual shard_map)"
            )
        f = _flash_partitioned(
            causal, block_q, block_k, interpret, mask is not None
        )
        args = (qt, kt, vt) + (() if mask is None else (mask_i32,))
        return f(*args).transpose(0, 2, 1, 3)
    if with_lse:
        out, lse = _flash_lse(
            qt, kt, vt, mask_i32, causal, block_q, block_k, interpret
        )
        return out.transpose(0, 2, 1, 3), lse[..., 0]
    out = _flash(qt, kt, vt, mask_i32, causal, block_q, block_k, interpret)
    return out.transpose(0, 2, 1, 3)


def flash_attention_with_lse(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = True,
    mask: Optional[jnp.ndarray] = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    use_pallas: Optional[bool] = None,
    interpret: bool = False,
):
    """Like :func:`flash_attention` but also returns lse [B, H, T_q] —
    fully differentiable in both outputs (ring attention merges per-block
    partials through the lse, so its gradient must flow).
    """
    return _dispatch(
        q, k, v, causal=causal, mask=mask, block_q=block_q, block_k=block_k,
        use_pallas=use_pallas, interpret=interpret, with_lse=True,
    )


def _fit_block(t: int, block: int) -> Optional[int]:
    """Largest multiple-of-8 block <= ``block`` that divides ``t``.

    T=768 with the default block_k=512 fits at 384 (not a clamp — 512
    doesn't divide 768); T=100 has no 8-aligned divisor and returns None
    (the (8,128) sublane tile would break)."""
    for candidate in range(min(block, t) - min(block, t) % 8, 7, -8):
        if t % candidate == 0:
            return candidate
    return None


def _kernel_eligible(q, k, block_q, block_k) -> bool:
    """Called with blocks already fitted to T: both must have resolved to
    8-aligned divisors of their sequence length."""
    return (
        q.ndim == 4
        and q.shape == k.shape
        and block_q is not None
        and block_k is not None
        and q.shape[-1] <= 256  # head_dim beyond this overflows VMEM blocks
    )


def would_use_kernel(
    q,
    k,
    mask: Optional[jnp.ndarray] = None,
    *,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
) -> bool:
    """The full ``use_pallas=None`` auto-dispatch predicate, exposed so
    callers (tests, capacity planners) never duplicate it and drift."""
    import jax as _jax

    fitted_q = _fit_block(q.shape[1], block_q)
    fitted_k = _fit_block(k.shape[1], block_k)
    mask_ok = mask is None or (
        mask.ndim == 2
        and mask.shape[0] == q.shape[0]
        and mask.shape[1] == k.shape[1]
    )
    return (
        _jax.default_backend() == "tpu"
        and mask_ok
        and _kernel_worthwhile(q, k)
        and _kernel_eligible(q, k, fitted_q, fitted_k)
    )


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = True,
    mask: Optional[jnp.ndarray] = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    use_pallas: Optional[bool] = None,
    interpret: bool = False,
    partitioned: bool = False,
) -> jnp.ndarray:
    """Attention over [B, T, H, D] tensors, differentiable.

    ``use_pallas=None`` auto-dispatches: kernels on TPU when shapes tile,
    reference jnp otherwise.  ``mask`` is a [B, T_k] valid-token padding
    mask (bool/int; nonzero = attend) applied key-side inside the kernels —
    fully-masked query rows produce uniform garbage (finite NEG_INF
    semantics), which the caller's loss mask must drop, matching the
    reference path.  ``interpret=True`` runs the kernels in the Pallas
    interpreter (CPU tests of kernel logic).

    ``partitioned=True`` emits the kernels through ``custom_partitioning``
    so the GSPMD/shardy partitioner places them itself (batch/heads
    shardable, sequence replicated) instead of the caller wrapping a
    shard_map.  Required inside partial-manual regions (the pipeline
    body); valid in any auto-sharded context.
    """
    return _dispatch(
        q, k, v, causal=causal, mask=mask, block_q=block_q, block_k=block_k,
        use_pallas=use_pallas, interpret=interpret, with_lse=False,
        partitioned=partitioned,
    )
