"""Shared dispatch plumbing for the Pallas kernel modules.

Two rules every kernel module needs identically:

- :func:`force_interpret` — the ``CLOUD_TPU_FLASH_FORCE_INTERPRET=1`` env
  contract (CPU rigs — the unit suite, the driver's virtual-mesh dryrun —
  set it to exercise real kernel code paths through the Pallas interpreter
  instead of silently taking jnp references).  One implementation so the
  contract cannot drift between ops.
- :func:`passthrough_callbacks` — the custom_partitioning callback pair
  for kernels whose Shardy rule already forces every non-batch factor to
  replicate: operand shardings are reused verbatim (inside a
  partial-manual region they arrive as opaque GSPMDShardings with no
  ``.spec`` — do NOT rebuild PartitionSpecs from them), and every result
  reuses operand 0's sharding (valid because the rule leaves only
  batch-like dims sharded, and result ranks/leading dims match by
  construction — each caller documents why).
"""

from __future__ import annotations

import os


def force_interpret() -> bool:
    return os.environ.get("CLOUD_TPU_FLASH_FORCE_INTERPRET", "") == "1"


def passthrough_callbacks(impl, n_results: int, result_like: int = 0):
    """(infer_sharding_from_operands, partition) for a rule-replicated
    kernel: results [0..n_results) all shard like operand
    ``result_like`` (default 0 — kernels whose first operand is the
    output-shaped one; paged attention passes the query's index, since
    its scalar-prefetch operands lead); the local lowering is ``impl``
    itself."""

    def infer(mesh, arg_shapes, result_shape):
        return (arg_shapes[result_like].sharding,) * n_results

    def part(mesh, arg_shapes, result_shape):
        arg_shardings = tuple(s.sharding for s in arg_shapes)
        return (mesh, impl, (arg_shardings[result_like],) * n_results,
                arg_shardings)

    return infer, part
