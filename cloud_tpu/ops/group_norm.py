"""Fused GroupNorm Pallas kernels (NHWC, per-sample grid).

Why a kernel: at CIFAR scale the ResNet50 step is VPU/HBM-bound and
GroupNorm is its largest non-conv cost (BASELINE.md "ResNet ceiling").
XLA's lowering reads the activation twice (reduce, then normalize); the
kernel computes group statistics and writes the normalized+affine output
in ONE pass over VMEM-resident data — one HBM read + one write per
sample.  The backward pass is a second kernel producing dx plus
per-sample dscale/dbias partials (summed outside — a [B, C] reduction).

Group reductions avoid the TPU-hostile [H, W, G, C/g] reshape (C/g lands
in the lane dimension at width 2-64): the activation stays [HW, C] with
channels in lanes, per-channel sums reduce over sublanes, and a [C, G]
one-hot matmul folds channels into groups (MXU-friendly).

Numerics match models/resnet.py's shifted-moments implementation: sums
are computed around a per-channel pivot (the first spatial row) so the
E[x^2]-E[x]^2 combination stays O(var) even when |mean| >> std, and the
group variance is assembled from per-channel shifted sums exactly
(grouped shifted-data algebra, not an approximation).

Reference parity note: the reference framework has no kernels at all —
this is TPU-native capability (SURVEY.md SS5 "perf baselines are
established by this rebuild").
"""

from __future__ import annotations

import functools

from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from cloud_tpu.ops import dispatch as dispatch_lib

#: Diagnostic counter (see flash_attention.KERNEL_TRACE_COUNT): bumped per
#: kernel trace so tests can assert the fused path — not the jnp
#: reference — actually ran.
KERNEL_TRACE_COUNT = 0


def _reference(x, scale, bias, num_groups, eps=1e-5, relu=False,
               residual=None):
    """Ground truth (and non-TPU fallback) — mirrors models/resnet.py."""
    b, h, w, c = x.shape
    g = min(num_groups, c)
    x32 = x.astype(jnp.float32).reshape(b, h, w, g, c // g)
    pivot = jax.lax.stop_gradient(x32[:, :1, :1, :, :1])
    xc = x32 - pivot
    m1c = jnp.mean(xc, axis=(1, 2, 4), keepdims=True)
    m2c = jnp.mean(xc * xc, axis=(1, 2, 4), keepdims=True)
    var = jnp.maximum(m2c - m1c * m1c, 0.0)
    y = (xc - m1c) * jax.lax.rsqrt(var + eps)
    y = y.reshape(b, h, w, c) * scale + bias
    if residual is not None:
        y = y + residual.astype(jnp.float32)
    if relu:
        y = jnp.maximum(y, 0.0)
    return y.astype(x.dtype)


def _onehot(c: int, g: int) -> jnp.ndarray:
    """[C, G] channel->group fold matrix.  Built from iota (traced ops,
    not a baked array constant): custom_partitioning traces its impl with
    an empty const list, so a materialized jnp constant would trip its
    ``assert not consts``."""
    cg = c // g
    ch_group = jax.lax.broadcasted_iota(jnp.int32, (c, g), 0) // cg
    group = jax.lax.broadcasted_iota(jnp.int32, (c, g), 1)
    return (ch_group == group).astype(jnp.float32)


def _fwd_math(x2, scale_row, bias_row, oh, oht, hw, cg, eps):
    """Shared forward math: [HW, C] -> (pre-activation y2, mean_g, rstd_g)."""
    n = float(hw * cg)
    pivot = x2[0:1, :]  # [1, C] per-channel shift
    xc = x2 - pivot
    s1 = jnp.sum(xc, axis=0, keepdims=True)        # [1, C]
    s2 = jnp.sum(xc * xc, axis=0, keepdims=True)   # [1, C]

    sum_g = (s1 + hw * pivot) @ oh                 # [1, G] true sums
    mean_g = sum_g / n
    mean_c = mean_g @ oht                           # [1, C]
    d = mean_c - pivot                              # [1, C]
    # sum_(hw,c in g) (x - m)^2 = s2 - 2 d s1 + hw d^2, folded per group.
    var_g = (s2 - 2.0 * d * s1 + hw * d * d) @ oh / n
    rstd_g = jax.lax.rsqrt(jnp.maximum(var_g, 0.0) + eps)
    rstd_c = rstd_g @ oht                           # [1, C]
    y2 = (x2 - mean_c) * rstd_c * scale_row + bias_row
    return y2, mean_g, rstd_g


def _fwd_kernel(x_ref, scale_ref, bias_ref, oh_ref, oht_ref, y_ref,
                mean_ref, rstd_ref, *, eps, hw, cg, relu):
    x = x_ref[0].astype(jnp.float32)
    h, w, c = x.shape
    y, mean_g, rstd_g = _fwd_math(
        x.reshape(hw, c), scale_ref[...], bias_ref[...],
        oh_ref[...], oht_ref[...], hw, cg, eps,
    )
    if relu:
        # Fused epilogue: the separate XLA relu would cost one more HBM
        # read+write of the whole activation on a bandwidth-bound model.
        y = jnp.maximum(y, 0.0)
    y_ref[0] = y.reshape(h, w, c).astype(y_ref.dtype)
    mean_ref[0] = mean_g[0]
    rstd_ref[0] = rstd_g[0]


def _fwd_kernel_res(x_ref, scale_ref, bias_ref, res_ref, oh_ref, oht_ref,
                    y_ref, mean_ref, rstd_ref, *, eps, hw, cg, relu):
    """Forward with a fused residual add: y = [relu](gn(x) + residual) —
    the bottleneck tail's add+relu never round-trips HBM separately."""
    x = x_ref[0].astype(jnp.float32)
    h, w, c = x.shape
    y, mean_g, rstd_g = _fwd_math(
        x.reshape(hw, c), scale_ref[...], bias_ref[...],
        oh_ref[...], oht_ref[...], hw, cg, eps,
    )
    y = y + res_ref[0].astype(jnp.float32).reshape(hw, c)
    if relu:
        y = jnp.maximum(y, 0.0)
    y_ref[0] = y.reshape(h, w, c).astype(y_ref.dtype)
    mean_ref[0] = mean_g[0]
    rstd_ref[0] = rstd_g[0]


def _bwd_core(x2, dy2, mean_row, rstd_row, scale_row, oh, oht, n):
    """GN backward for an already-gated cotangent: (dx2, ds, db)."""
    mean_c = mean_row @ oht                         # [1, C]
    rstd_c = rstd_row @ oht                         # [1, C]
    xhat = (x2 - mean_c) * rstd_c
    dxh = dy2 * scale_row

    a_c = (jnp.sum(dxh, axis=0, keepdims=True) @ oh) @ oht         # [1, C]
    b_c = (jnp.sum(dxh * xhat, axis=0, keepdims=True) @ oh) @ oht   # [1, C]
    dx = rstd_c * (dxh - (a_c + xhat * b_c) / n)
    ds = jnp.sum(dy2 * xhat, axis=0)                # [C] per-sample partial
    db = jnp.sum(dy2, axis=0)                       # [C]
    return dx, ds, db, xhat


def _bwd_kernel(x_ref, dy_ref, mean_ref, rstd_ref, scale_ref, bias_ref,
                oh_ref, oht_ref, dx_ref, ds_ref, db_ref, *, hw, cg, relu):
    x = x_ref[0].astype(jnp.float32)
    dy = dy_ref[0].astype(jnp.float32)
    h, w, c = x.shape
    x2 = x.reshape(hw, c)
    dy2 = dy.reshape(hw, c)
    oh = oh_ref[...]
    oht = oht_ref[...]
    n = float(hw * cg)

    if relu:
        # Recompute the pre-activation sign from the saved stats: the
        # relu gate zeroes the cotangent where the fused forward clamped.
        mean_c = mean_ref[...] @ oht
        rstd_c = rstd_ref[...] @ oht
        pre = (x2 - mean_c) * rstd_c * scale_ref[...] + bias_ref[...]
        dy2 = jnp.where(pre > 0.0, dy2, 0.0)
    dx, ds, db, _ = _bwd_core(
        x2, dy2, mean_ref[...], rstd_ref[...], scale_ref[...], oh, oht, n
    )
    dx_ref[0] = dx.reshape(h, w, c).astype(dx_ref.dtype)
    ds_ref[0] = ds
    db_ref[0] = db


def _bwd_kernel_res(x_ref, dy_ref, mean_ref, rstd_ref, scale_ref, bias_ref,
                    res_ref, oh_ref, oht_ref, dx_ref, ds_ref, db_ref,
                    dres_ref, *, hw, cg, relu):
    """Backward of y = [relu](gn(x) + residual): the gate (recomputed
    from stats + the residual) applies to BOTH branches; the residual's
    cotangent is exactly the gated dy."""
    x = x_ref[0].astype(jnp.float32)
    dy = dy_ref[0].astype(jnp.float32)
    h, w, c = x.shape
    x2 = x.reshape(hw, c)
    dy2 = dy.reshape(hw, c)
    oh = oh_ref[...]
    oht = oht_ref[...]
    n = float(hw * cg)

    if relu:
        mean_c = mean_ref[...] @ oht
        rstd_c = rstd_ref[...] @ oht
        pre = (
            (x2 - mean_c) * rstd_c * scale_ref[...] + bias_ref[...]
            + res_ref[0].astype(jnp.float32).reshape(hw, c)
        )
        dy2 = jnp.where(pre > 0.0, dy2, 0.0)
    dres_ref[0] = dy2.reshape(h, w, c).astype(dres_ref.dtype)
    dx, ds, db, _ = _bwd_core(
        x2, dy2, mean_ref[...], rstd_ref[...], scale_ref[...], oh, oht, n
    )
    dx_ref[0] = dx.reshape(h, w, c).astype(dx_ref.dtype)
    ds_ref[0] = ds
    db_ref[0] = db


def _block_specs(b, h, w, c, g):
    x_spec = pl.BlockSpec((1, h, w, c), lambda i: (i, 0, 0, 0))
    vec_spec = pl.BlockSpec((1, c), lambda i: (0, 0))
    oh_spec = pl.BlockSpec((c, g), lambda i: (0, 0))
    oht_spec = pl.BlockSpec((g, c), lambda i: (0, 0))
    stat_spec = pl.BlockSpec((1, g), lambda i: (i, 0))
    return x_spec, vec_spec, oh_spec, oht_spec, stat_spec


def _fwd_pallas(x, scale, bias, num_groups, eps, interpret, relu=False):
    global KERNEL_TRACE_COUNT
    KERNEL_TRACE_COUNT += 1
    b, h, w, c = x.shape
    g = min(num_groups, c)
    hw, cg = h * w, c // g
    oh = _onehot(c, g)
    x_spec, vec_spec, oh_spec, oht_spec, stat_spec = _block_specs(b, h, w, c, g)
    y, mean, rstd = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps, hw=hw, cg=cg, relu=relu),
        grid=(b,),
        in_specs=[x_spec, vec_spec, vec_spec, oh_spec, oht_spec],
        out_specs=[x_spec, stat_spec, stat_spec],
        out_shape=[
            jax.ShapeDtypeStruct(x.shape, x.dtype),
            jax.ShapeDtypeStruct((b, g), jnp.float32),
            jax.ShapeDtypeStruct((b, g), jnp.float32),
        ],
        interpret=interpret,
    )(x, scale.reshape(1, c), bias.reshape(1, c), oh, oh.T)
    return y, mean, rstd


def _bwd_pallas(x, dy, mean, rstd, scale, bias, num_groups, interpret,
                relu=False):
    global KERNEL_TRACE_COUNT
    KERNEL_TRACE_COUNT += 1
    b, h, w, c = x.shape
    g = min(num_groups, c)
    hw, cg = h * w, c // g
    oh = _onehot(c, g)
    x_spec, vec_spec, oh_spec, oht_spec, stat_spec = _block_specs(b, h, w, c, g)
    partial_spec = pl.BlockSpec((1, c), lambda i: (i, 0))
    dx, ds, db = pl.pallas_call(
        functools.partial(_bwd_kernel, hw=hw, cg=cg, relu=relu),
        grid=(b,),
        in_specs=[x_spec, x_spec, stat_spec, stat_spec, vec_spec, vec_spec,
                  oh_spec, oht_spec],
        out_specs=[x_spec, partial_spec, partial_spec],
        out_shape=[
            jax.ShapeDtypeStruct(x.shape, x.dtype),
            jax.ShapeDtypeStruct((b, c), jnp.float32),
            jax.ShapeDtypeStruct((b, c), jnp.float32),
        ],
        interpret=interpret,
    )(x, dy, mean, rstd, scale.reshape(1, c), bias.reshape(1, c), oh, oh.T)
    return dx, ds, db


def _fwd_pallas_res(x, scale, bias, residual, num_groups, eps, interpret,
                    relu):
    global KERNEL_TRACE_COUNT
    KERNEL_TRACE_COUNT += 1
    b, h, w, c = x.shape
    g = min(num_groups, c)
    hw, cg = h * w, c // g
    oh = _onehot(c, g)
    x_spec, vec_spec, oh_spec, oht_spec, stat_spec = _block_specs(b, h, w, c, g)
    y, mean, rstd = pl.pallas_call(
        functools.partial(_fwd_kernel_res, eps=eps, hw=hw, cg=cg, relu=relu),
        grid=(b,),
        in_specs=[x_spec, vec_spec, vec_spec, x_spec, oh_spec, oht_spec],
        out_specs=[x_spec, stat_spec, stat_spec],
        out_shape=[
            jax.ShapeDtypeStruct(x.shape, x.dtype),
            jax.ShapeDtypeStruct((b, g), jnp.float32),
            jax.ShapeDtypeStruct((b, g), jnp.float32),
        ],
        interpret=interpret,
    )(x, scale.reshape(1, c), bias.reshape(1, c), residual, oh, oh.T)
    return y, mean, rstd


def _bwd_pallas_res(x, dy, mean, rstd, scale, bias, residual, num_groups,
                    interpret, relu):
    global KERNEL_TRACE_COUNT
    KERNEL_TRACE_COUNT += 1
    b, h, w, c = x.shape
    g = min(num_groups, c)
    hw, cg = h * w, c // g
    oh = _onehot(c, g)
    x_spec, vec_spec, oh_spec, oht_spec, stat_spec = _block_specs(b, h, w, c, g)
    partial_spec = pl.BlockSpec((1, c), lambda i: (i, 0))
    dx, ds, db, dres = pl.pallas_call(
        functools.partial(_bwd_kernel_res, hw=hw, cg=cg, relu=relu),
        grid=(b,),
        in_specs=[x_spec, x_spec, stat_spec, stat_spec, vec_spec, vec_spec,
                  x_spec, oh_spec, oht_spec],
        out_specs=[x_spec, partial_spec, partial_spec, x_spec],
        out_shape=[
            jax.ShapeDtypeStruct(x.shape, x.dtype),
            jax.ShapeDtypeStruct((b, c), jnp.float32),
            jax.ShapeDtypeStruct((b, c), jnp.float32),
            jax.ShapeDtypeStruct(residual.shape, residual.dtype),
        ],
        interpret=interpret,
    )(x, dy, mean, rstd, scale.reshape(1, c), bias.reshape(1, c), residual,
      oh, oh.T)
    return dx, ds, db, dres


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _gn(x, scale, bias, num_groups, eps, interpret, relu=False):
    y, _, _ = _fwd_pallas(x, scale, bias, num_groups, eps, interpret,
                          relu=relu)
    return y


def _gn_fwd(x, scale, bias, num_groups, eps, interpret, relu=False):
    y, mean, rstd = _fwd_pallas(x, scale, bias, num_groups, eps, interpret,
                                relu=relu)
    return y, (x, mean, rstd, scale, bias)


def _gn_bwd(num_groups, eps, interpret, relu, residuals, dy):
    x, mean, rstd, scale, bias = residuals
    dx, ds, db = _bwd_pallas(
        x, dy, mean, rstd, scale, bias, num_groups, interpret, relu=relu
    )
    return dx, jnp.sum(ds, axis=0), jnp.sum(db, axis=0)


_gn.defvjp(_gn_fwd, _gn_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _gn_res(x, scale, bias, residual, num_groups, eps, interpret, relu):
    y, _, _ = _fwd_pallas_res(x, scale, bias, residual, num_groups, eps,
                              interpret, relu)
    return y


def _gn_res_fwd(x, scale, bias, residual, num_groups, eps, interpret, relu):
    y, mean, rstd = _fwd_pallas_res(x, scale, bias, residual, num_groups,
                                    eps, interpret, relu)
    # Without relu the backward never reads the residual (dres == dy
    # exactly); keep only a zero-size dtype token so the full tensor
    # neither lives in residuals nor streams through the bwd kernel.
    saved_res = residual if relu else residual[:0]
    return y, (x, mean, rstd, scale, bias, saved_res)


def _gn_res_bwd(num_groups, eps, interpret, relu, residuals, dy):
    x, mean, rstd, scale, bias, saved_res = residuals
    if relu:
        dx, ds, db, dres = _bwd_pallas_res(
            x, dy, mean, rstd, scale, bias, saved_res, num_groups,
            interpret, relu,
        )
    else:
        dx, ds, db = _bwd_pallas(
            x, dy, mean, rstd, scale, bias, num_groups, interpret,
            relu=False,
        )
        dres = dy.astype(saved_res.dtype)
    return dx, jnp.sum(ds, axis=0), jnp.sum(db, axis=0), dres


_gn_res.defvjp(_gn_res_fwd, _gn_res_bwd)


# ---------------------------------------------------------------------------
# Partitioner-visible route (custom_partitioning), mirroring
# ops/flash_attention.py: under a mesh an unwrapped pallas_call would be
# replicated by GSPMD; the Shardy rule (batch shardable, everything else
# need-replication) lets the partitioner run the kernel per batch shard.
# Group statistics are returned rank-4 ([B, G, 1, 1]) so every result can
# reuse x's sharding verbatim — the callbacks then work on the opaque
# GSPMDShardings a partial-manual region hands them.
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _cp_fwd_call(num_groups, eps, interpret, relu=False):
    from jax.experimental.custom_partitioning import (
        SdyShardingRule,
        custom_partitioning,
    )

    def impl(x, scale, bias):
        y, mean, rstd = _fwd_pallas(x, scale, bias, num_groups, eps,
                                    interpret, relu=relu)
        return y, mean[..., None, None], rstd[..., None, None]

    fn = custom_partitioning(impl)

    # Stats come back rank-4 [B, G, 1, 1] precisely so all three results
    # can reuse x's sharding (only b is shardable under the rule).
    infer, part = dispatch_lib.passthrough_callbacks(impl, 3)

    bhwc = ("b", "h", "w", "c")
    fn.def_partition(
        infer_sharding_from_operands=infer,
        partition=part,
        sharding_rule=SdyShardingRule(
            operand_mappings=(bhwc, ("c",), ("c",)),
            result_mappings=(bhwc, ("b", "g", "o1", "o2"),
                             ("b", "g2", "o3", "o4")),
            need_replication_factors=(
                "h", "w", "c", "g", "o1", "o2", "g2", "o3", "o4"
            ),
        ),
    )
    return fn


@functools.lru_cache(maxsize=None)
def _cp_bwd_call(num_groups, interpret, relu=False):
    from jax.experimental.custom_partitioning import (
        SdyShardingRule,
        custom_partitioning,
    )

    def impl(x, dy, mean4, rstd4, scale, bias):
        dx, ds, db = _bwd_pallas(
            x, dy, mean4[..., 0, 0], rstd4[..., 0, 0], scale, bias,
            num_groups, interpret, relu=relu,
        )
        return dx, ds[:, None, None, :], db[:, None, None, :]

    fn = custom_partitioning(impl)

    # dx and the [B, 1, 1, C] dscale/dbias partials all reuse x's sharding.
    infer, part = dispatch_lib.passthrough_callbacks(impl, 3)

    bhwc = ("b", "h", "w", "c")
    fn.def_partition(
        infer_sharding_from_operands=infer,
        partition=part,
        sharding_rule=SdyShardingRule(
            operand_mappings=(bhwc, bhwc, ("b", "g", "o1", "o2"),
                              ("b", "g2", "o3", "o4"), ("c",), ("c",)),
            result_mappings=(bhwc, ("b", "o5", "o6", "c"),
                             ("b", "o7", "o8", "c")),
            need_replication_factors=(
                "h", "w", "c", "g", "o1", "o2", "g2", "o3", "o4",
                "o5", "o6", "o7", "o8",
            ),
        ),
    )
    return fn


@functools.lru_cache(maxsize=None)
def _cp_fwd_call_res(num_groups, eps, interpret, relu):
    from jax.experimental.custom_partitioning import (
        SdyShardingRule,
        custom_partitioning,
    )

    def impl(x, scale, bias, residual):
        y, mean, rstd = _fwd_pallas_res(x, scale, bias, residual,
                                        num_groups, eps, interpret, relu)
        return y, mean[..., None, None], rstd[..., None, None]

    fn = custom_partitioning(impl)
    infer, part = dispatch_lib.passthrough_callbacks(impl, 3)
    bhwc = ("b", "h", "w", "c")
    fn.def_partition(
        infer_sharding_from_operands=infer,
        partition=part,
        sharding_rule=SdyShardingRule(
            operand_mappings=(bhwc, ("c",), ("c",), bhwc),
            result_mappings=(bhwc, ("b", "g", "o1", "o2"),
                             ("b", "g2", "o3", "o4")),
            need_replication_factors=(
                "h", "w", "c", "g", "o1", "o2", "g2", "o3", "o4"
            ),
        ),
    )
    return fn


@functools.lru_cache(maxsize=None)
def _cp_bwd_call_res(num_groups, interpret, relu):
    from jax.experimental.custom_partitioning import (
        SdyShardingRule,
        custom_partitioning,
    )

    def impl(x, dy, mean4, rstd4, scale, bias, residual):
        dx, ds, db, dres = _bwd_pallas_res(
            x, dy, mean4[..., 0, 0], rstd4[..., 0, 0], scale, bias,
            residual, num_groups, interpret, relu,
        )
        return dx, ds[:, None, None, :], db[:, None, None, :], dres

    fn = custom_partitioning(impl)
    infer, part = dispatch_lib.passthrough_callbacks(impl, 4)
    bhwc = ("b", "h", "w", "c")
    fn.def_partition(
        infer_sharding_from_operands=infer,
        partition=part,
        sharding_rule=SdyShardingRule(
            operand_mappings=(bhwc, bhwc, ("b", "g", "o1", "o2"),
                              ("b", "g2", "o3", "o4"), ("c",), ("c",),
                              bhwc),
            result_mappings=(bhwc, ("b", "o5", "o6", "c"),
                             ("b", "o7", "o8", "c"), bhwc),
            need_replication_factors=(
                "h", "w", "c", "g", "o1", "o2", "g2", "o3", "o4",
                "o5", "o6", "o7", "o8",
            ),
        ),
    )
    return fn


@functools.lru_cache(maxsize=None)
def _gn_partitioned_res(num_groups, eps, interpret, relu):
    fwd_call = _cp_fwd_call_res(num_groups, eps, interpret, relu)
    bwd_call = _cp_bwd_call_res(num_groups, interpret, relu)

    plain_bwd_call = _cp_bwd_call(num_groups, interpret, relu=False)

    @jax.custom_vjp
    def f(x, scale, bias, residual):
        y, _, _ = fwd_call(x, scale, bias, residual)
        return y

    def f_fwd(x, scale, bias, residual):
        y, mean4, rstd4 = fwd_call(x, scale, bias, residual)
        saved_res = residual if relu else residual[:0]
        return y, (x, mean4, rstd4, scale, bias, saved_res)

    def f_bwd(res, dy):
        x, mean4, rstd4, scale, bias, saved_res = res
        if relu:
            dx, ds4, db4, dres = bwd_call(
                x, dy, mean4, rstd4, scale, bias, saved_res
            )
        else:
            dx, ds4, db4 = plain_bwd_call(
                x, dy, mean4, rstd4, scale, bias
            )
            dres = dy.astype(saved_res.dtype)
        return (dx, jnp.sum(ds4, axis=(0, 1, 2)),
                jnp.sum(db4, axis=(0, 1, 2)), dres)

    f.defvjp(f_fwd, f_bwd)
    return f


@functools.lru_cache(maxsize=None)
def _gn_partitioned(num_groups, eps, interpret, relu=False):
    fwd_call = _cp_fwd_call(num_groups, eps, interpret, relu)
    bwd_call = _cp_bwd_call(num_groups, interpret, relu)

    @jax.custom_vjp
    def f(x, scale, bias):
        y, _, _ = fwd_call(x, scale, bias)
        return y

    def f_fwd(x, scale, bias):
        y, mean4, rstd4 = fwd_call(x, scale, bias)
        return y, (x, mean4, rstd4, scale, bias)

    def f_bwd(res, dy):
        x, mean4, rstd4, scale, bias = res
        dx, ds4, db4 = bwd_call(x, dy, mean4, rstd4, scale, bias)
        # Cross-batch reduction OUTSIDE the cp boundary: GSPMD turns the
        # sharded [B, 1, 1, C] sum into the right collective itself.
        return dx, jnp.sum(ds4, axis=(0, 1, 2)), jnp.sum(db4, axis=(0, 1, 2))

    f.defvjp(f_fwd, f_bwd)
    return f


def kernel_eligible(x, num_groups, has_residual: bool = False) -> bool:
    """Shapes the kernel handles: 4-D NHWC, groups divide channels, the
    [HW, C] view sublane-aligned, and a per-sample block that fits VMEM
    (f32 activation + working copies, conservatively 4 MiB; halved when
    a fused residual doubles the resident blocks)."""
    if x.ndim != 4:
        return False
    b, h, w, c = x.shape
    g = min(num_groups, c)
    if c % g:
        return False
    if (h * w) % 8:
        return False
    budget = (2 if has_residual else 4) * 1024 * 1024
    return h * w * c * 4 <= budget


def group_norm(
    x: jnp.ndarray,
    scale: jnp.ndarray,
    bias: jnp.ndarray,
    *,
    num_groups: int = 32,
    eps: float = 1e-5,
    use_pallas: Optional[bool] = None,
    interpret: bool = False,
    partitioned: Optional[bool] = None,
    activation: Optional[str] = None,
    residual: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """GroupNorm over NHWC with affine params [C]; differentiable.

    ``use_pallas=None`` auto-dispatches to the fused kernel on TPU when
    :func:`kernel_eligible`; elsewhere (or on odd shapes) the jnp
    reference runs — identical algorithm, so dispatch never changes
    numerics beyond kernel-vs-fusion float ordering.

    ``partitioned=None`` routes through custom_partitioning whenever the
    framework's global mesh is installed (an unwrapped pallas_call would
    be replicated by GSPMD there); ``False``/``True`` force the direct /
    partitioner-visible path.

    ``activation="relu"`` fuses the ReLU epilogue into the kernel (the
    separate XLA relu costs one extra HBM read+write of the whole
    activation per call — material on the bandwidth-bound ResNet path);
    the backward gates the cotangent by the recomputed pre-activation
    sign, so gradients equal relu(group_norm(x)) exactly.

    ``residual`` (same shape as x) fuses a residual add BEFORE the
    activation — ``[relu](group_norm(x) + residual)`` — the ResNet
    bottleneck tail, whose separate add+relu otherwise re-reads both
    tensors from HBM.  Fully differentiable in the residual too.
    """
    import os

    if activation not in (None, "relu"):
        raise ValueError(
            f"activation must be None or 'relu', got {activation!r}"
        )
    relu = activation == "relu"
    if residual is not None and residual.shape != x.shape:
        raise ValueError(
            f"residual shape {residual.shape} != x shape {x.shape}"
        )
    if os.environ.get("CLOUD_TPU_GN_KERNEL", "") == "0":
        # Operational kill switch (the bench flips it when the hardware
        # gate fails, so a kernel regression degrades to the jnp path
        # instead of sinking the measurement).  Checked before every other
        # dispatch rule — including force-interpret — so it always wins.
        return _reference(x, scale, bias, num_groups, eps, relu=relu,
                          residual=residual)
    if not interpret and dispatch_lib.force_interpret():
        interpret = True
    has_res = residual is not None
    if use_pallas is None:
        use_pallas = (
            jax.default_backend() == "tpu"
            and kernel_eligible(x, num_groups)
        )
    if interpret and kernel_eligible(x, num_groups):
        use_pallas = True
    if not use_pallas or not kernel_eligible(x, num_groups):
        return _reference(x, scale, bias, num_groups, eps, relu=relu,
                          residual=residual)
    if has_res and not kernel_eligible(x, num_groups, True):
        # The block + residual pair exceeds the VMEM budget: drop ONLY
        # the fusion (kernel GN + XLA add/relu — the pre-fusion
        # schedule), never the whole kernel.
        y = group_norm(
            x, scale, bias, num_groups=num_groups, eps=eps,
            use_pallas=True, interpret=interpret, partitioned=partitioned,
        )
        y = y.astype(jnp.float32) + residual.astype(jnp.float32)
        if relu:
            y = jnp.maximum(y, 0.0)
        return y.astype(x.dtype)
    if partitioned is None:
        from cloud_tpu.parallel import mesh as mesh_lib

        partitioned = mesh_lib.get_global_mesh() is not None
    scale32 = scale.astype(jnp.float32)
    bias32 = bias.astype(jnp.float32)
    g = min(num_groups, x.shape[-1])
    if residual is not None:
        if partitioned:
            return _gn_partitioned_res(g, eps, interpret, relu)(
                x, scale32, bias32, residual
            )
        return _gn_res(x, scale32, bias32, residual, num_groups, eps,
                       interpret, relu)
    if partitioned:
        return _gn_partitioned(g, eps, interpret, relu)(x, scale32, bias32)
    return _gn(x, scale32, bias32, num_groups, eps, interpret, relu)
