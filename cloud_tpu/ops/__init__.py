"""Pallas TPU kernels for the hot ops, with reference fallbacks.

Kernels live here, not in models/: a model expresses *what* to compute
with logical-axis sharding; ops/ owns *how* the inner loop maps onto
MXU/VMEM (pallas_guide.md).  Every op has a pure-jnp reference
implementation used off-TPU (and as the ground truth in tests); dispatch
is automatic.
"""

from cloud_tpu.utils import jax_compat as _jax_compat  # noqa: F401  (shims)
from cloud_tpu.ops.flash_attention import flash_attention
from cloud_tpu.ops.fused_cross_entropy import fused_linear_cross_entropy
from cloud_tpu.ops.group_norm import group_norm
from cloud_tpu.ops.paged_attention import (
    paged_chunk_attention,
    paged_decode_attention,
    paged_verify_attention,
)

__all__ = [
    "flash_attention",
    "fused_linear_cross_entropy",
    "group_norm",
    "paged_chunk_attention",
    "paged_decode_attention",
    "paged_verify_attention",
]
