"""In-process TPU serving: continuous-batched inference on the
generation path.

The training side got its occupancy engineering in PRs 2-3 (prefetch,
fused dispatch, compile-ahead); this package is the inference
counterpart — a request queue + scheduler that drives
``models.generation``'s slot-grid programs (insert + chunk decode) at
steady-state occupancy, retiring and refilling decode slots between
chunks, while individual callers see a simple future-per-request API.
The PR 4 batch-synchronous scheduler survives as
``ServeConfig(scheduler="batch")``, the baseline the continuous path is
measured against.  See ``docs/serving.md`` and
:mod:`cloud_tpu.serving.engine`.
"""

from cloud_tpu.serving.engine import (
    DeadlineExceededError,
    DispatchTimeoutError,
    DraftConfig,
    EngineClosedError,
    QueueFullError,
    ServeConfig,
    ServeResult,
    ServingEngine,
    SERVE_DISPATCH_THREAD_NAME,
    SERVE_SCHEDULER_THREAD_NAME,
)
from cloud_tpu.serving.prefix_cache import (
    AFFINITY_PREFIX_TOKENS,
    PrefixCacheManager,
    PrefixHit,
    affinity_key,
)
from cloud_tpu.serving.qos import (
    BrownoutShedError,
    PriorityClass,
    QosConfig,
    QosScheduler,
    QuotaExceededError,
    TenantQuota,
    TokenBucket,
    TokenStream,
)

__all__ = [
    "AFFINITY_PREFIX_TOKENS",
    "affinity_key",
    "BrownoutShedError",
    "DeadlineExceededError",
    "DispatchTimeoutError",
    "DraftConfig",
    "EngineClosedError",
    "PrefixCacheManager",
    "PrefixHit",
    "PriorityClass",
    "QosConfig",
    "QosScheduler",
    "QueueFullError",
    "QuotaExceededError",
    "ServeConfig",
    "ServeResult",
    "ServingEngine",
    "TenantQuota",
    "TokenBucket",
    "TokenStream",
    "SERVE_DISPATCH_THREAD_NAME",
    "SERVE_SCHEDULER_THREAD_NAME",
]
