"""In-process TPU serving: dynamic-batched inference on the generation path.

The training side got its occupancy engineering in PRs 2-3 (prefetch,
fused dispatch, compile-ahead); this package is the inference
counterpart — a request queue + scheduler that drives
``models.generation``'s prefill/decode programs at high batch occupancy
while individual callers see a simple future-per-request API.  See
``docs/serving.md`` and :mod:`cloud_tpu.serving.engine`.
"""

from cloud_tpu.serving.engine import (
    EngineClosedError,
    QueueFullError,
    ServeConfig,
    ServeResult,
    ServingEngine,
    SERVE_SCHEDULER_THREAD_NAME,
)

__all__ = [
    "EngineClosedError",
    "QueueFullError",
    "ServeConfig",
    "ServeResult",
    "ServingEngine",
    "SERVE_SCHEDULER_THREAD_NAME",
]
