"""Multi-tenant QoS for the serving stack: priority classes, tenant
quotas, SLO-aware slot admission, and per-token streaming.

Everything the engine and fleet serve without this module is FIFO with
one deadline knob — a batch tenant flooding ``Fleet.submit()`` starves
interactive traffic, and futures only complete at end-of-generation, so
TTFT is measured but never *delivered*.  This module is the pure-policy
half of the fix; the scheduler hooks live in ``serving.engine`` and
``fleet.fleet`` (the TF-Replicator lesson — arxiv 1902.00465 — is that
a policy layer like this belongs ABOVE the compiled data path: nothing
here touches a compiled program, and with every knob off the serving
stack is byte-identical to the FIFO path):

* **Priority classes** (:class:`PriorityClass`) — each named class
  (default ``interactive`` / ``standard`` / ``batch``) carries a
  fairness ``weight`` and a TTFT SLO target ``slo_s``.  Admission to
  decode slots is ordered by ``(SLO slack, weighted fairness debt)``:
  earliest-slack first while SLOs still have slack (interactive's tight
  SLO wins the queue), and weighted fair queuing once slack is
  exhausted under saturation (batch's weight share keeps it from
  starving forever — :class:`QosScheduler`).
* **Tenant quotas** (:class:`TenantQuota` / :class:`TokenBucket`) —
  per-tenant token buckets charged ``prompt + decode-budget`` tokens at
  submit; an empty bucket raises :class:`QuotaExceededError` (typed,
  immediate, never queued) so one tenant's flood is bounded BEFORE it
  costs anyone else queue position.
* **Brownout shedding** — when the waiting set exceeds
  ``brownout_queue_depth``, the excess is shed from the LOWEST-weight
  class first, newest first within a class, with
  :class:`BrownoutShedError` — the class-aware generalization of the
  deadline shed (batch sheds before interactive; an interactive
  request is only ever shed once no lower class remains).
* **Per-token streaming** (:class:`TokenStream`) — ``submit(...,
  stream=True)`` returns a stream fed from the host-side emission path
  as chunks commit; iterating yields token ids the moment they exist,
  and the stream's ``result()`` is the same final
  :class:`~cloud_tpu.serving.ServeResult` the plain future resolves
  with.  Streamed tokens are pinned byte-identical to the non-streamed
  row (they are literally the same host mirror), and feeds are
  idempotent by token index, so a fleet failover's deterministic
  re-run resumes a stream without duplicates.

See docs/serving.md "Multi-tenant QoS & streaming" and docs/fleet.md.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from concurrent.futures import Future, InvalidStateError
from typing import Dict, Iterator, List, Mapping, Optional

#: The default class ladder (highest service priority first).  The shed
#: order is the reverse of the WEIGHT order, not this tuple's — a custom
#: class map defines its own ladder through the weights.
DEFAULT_PRIORITIES = ("interactive", "standard", "batch")


class QuotaExceededError(RuntimeError):
    """Typed rejection at submit: the tenant's token bucket cannot cover
    this request's cost right now — retry after the bucket refills, or
    raise the tenant's quota.  Never queued, never routed."""


class BrownoutShedError(RuntimeError):
    """The request was shed under brownout: the waiting set exceeded
    ``QosConfig.brownout_queue_depth`` and this request's class was the
    lowest-weight one still queued.  Permanent by routing
    classification — re-submitting into the same overload amplifies
    it."""


@dataclasses.dataclass(frozen=True)
class PriorityClass:
    """One service class: fairness weight + TTFT SLO target.

    ``weight`` is the weighted-fair-queuing share under saturation
    (a weight-4 class gets 4x a weight-1 class's token share once every
    SLO is blown) AND the shed ladder (lowest weight sheds first).
    ``slo_s`` is the time-to-first-token target; admission slack is
    measured against it, so a tighter SLO wins the queue while slack
    remains.
    """

    weight: float = 1.0
    slo_s: float = 1.0

    def __post_init__(self):
        if self.weight <= 0:
            raise ValueError(f"weight must be > 0, got {self.weight}")
        if self.slo_s <= 0:
            raise ValueError(f"slo_s must be > 0, got {self.slo_s}")


@dataclasses.dataclass(frozen=True)
class TenantQuota:
    """Token-bucket quota for one tenant: sustained ``tokens_per_s``
    refill with a ``burst_tokens`` ceiling.  A request costs its prompt
    length plus its decode budget (the tokens it may make the fleet
    produce), charged at submit."""

    tokens_per_s: float
    burst_tokens: float

    def __post_init__(self):
        if self.tokens_per_s <= 0:
            raise ValueError(
                f"tokens_per_s must be > 0, got {self.tokens_per_s}"
            )
        if self.burst_tokens < 1:
            raise ValueError(
                f"burst_tokens must be >= 1, got {self.burst_tokens}"
            )


def _default_classes() -> Dict[str, PriorityClass]:
    return {
        "interactive": PriorityClass(weight=8.0, slo_s=0.25),
        "standard": PriorityClass(weight=4.0, slo_s=2.0),
        "batch": PriorityClass(weight=1.0, slo_s=30.0),
    }


@dataclasses.dataclass(frozen=True)
class QosConfig:
    """The QoS policy knobs (shared by ``ServeConfig.qos`` and
    ``FleetConfig.qos``; both default ``None`` — FIFO, byte-identical
    to the pre-QoS path).

    ``classes`` maps class name -> :class:`PriorityClass`;
    ``default_priority`` is assigned to requests submitted without one.
    ``quotas`` maps tenant name -> :class:`TenantQuota` (a tenant not
    listed gets ``default_quota``, or no quota when that is ``None`` —
    quotas bind only where they are configured).
    ``brownout_queue_depth`` arms class-aware shedding of the waiting
    set (``None``: never shed for depth; deadlines still shed).
    """

    classes: Mapping[str, PriorityClass] = dataclasses.field(
        default_factory=_default_classes
    )
    default_priority: str = "standard"
    quotas: Mapping[str, TenantQuota] = dataclasses.field(
        default_factory=dict
    )
    default_quota: Optional[TenantQuota] = None
    brownout_queue_depth: Optional[int] = None
    #: Decode-token cost charged (quota AND fairness debt) for a
    #: request that omits ``max_new_tokens``.  The fleet surface cannot
    #: see the engine-side budget such a request resolves to, and an
    #: omitted budget must not read as free — a tenant could otherwise
    #: consume full decode capacity while its bucket only drains by
    #: prompt lengths.  Set it near your engines' ``max_new_tokens``.
    unbudgeted_decode_cost: int = 256

    def __post_init__(self):
        classes = dict(self.classes)
        object.__setattr__(self, "classes", classes)
        if not classes:
            raise ValueError("QosConfig.classes must name at least one "
                             "priority class")
        for name, pc in classes.items():
            if not isinstance(pc, PriorityClass):
                raise ValueError(
                    f"classes[{name!r}] must be a PriorityClass, "
                    f"got {type(pc).__name__}"
                )
        if self.default_priority not in classes:
            raise ValueError(
                f"default_priority {self.default_priority!r} is not a "
                f"configured class (have {sorted(classes)})"
            )
        quotas = dict(self.quotas)
        object.__setattr__(self, "quotas", quotas)
        for tenant, quota in quotas.items():
            if not isinstance(quota, TenantQuota):
                raise ValueError(
                    f"quotas[{tenant!r}] must be a TenantQuota, "
                    f"got {type(quota).__name__}"
                )
        if (self.brownout_queue_depth is not None
                and self.brownout_queue_depth < 1):
            raise ValueError(
                f"brownout_queue_depth must be >= 1 or None, got "
                f"{self.brownout_queue_depth}"
            )
        if self.unbudgeted_decode_cost < 0:
            raise ValueError(
                f"unbudgeted_decode_cost must be >= 0, got "
                f"{self.unbudgeted_decode_cost}"
            )

    def request_cost(self, prompt_len: int,
                     max_new_tokens: Optional[int]) -> int:
        """One request's token cost — prompt plus decode budget — as
        charged against quotas and the fairness debt.  ONE definition
        for both schedulers (engine and fleet), so the WFQ shares and
        the buckets can never disagree on what a request costs."""
        budget = (
            int(max_new_tokens) if max_new_tokens is not None
            else self.unbudgeted_decode_cost
        )
        return int(prompt_len) + budget

    def resolve_priority(self, priority: Optional[str]) -> str:
        """Validate a submitted priority against the class map (typed
        error naming the valid classes), defaulting unset ones."""
        if priority is None:
            return self.default_priority
        if priority not in self.classes:
            raise ValueError(
                f"unknown priority {priority!r}: configured classes are "
                f"{sorted(self.classes)}"
            )
        return priority

    def shed_order(self) -> List[str]:
        """Class names in shed precedence: lowest weight first (ties to
        the later name, so the default ladder sheds batch -> standard ->
        interactive)."""
        return sorted(self.classes, key=lambda c: (
            self.classes[c].weight, c
        ))


def brownout_victims(requests, excess: int,
                     config: QosConfig) -> List[object]:
    """Select which waiting requests a brownout sheds: lowest-weight
    class first, NEWEST arrival first within a class (the requests
    that waited longest keep their place), up to ``excess`` victims.

    ONE definition of the shed order for both schedulers — the engine
    and the fleet each own their queue mechanics (removal, typed
    failure, counters) but must never drift on the policy itself.
    ``requests`` is any iterable of objects with ``.priority`` and
    ``.submitted``.
    """
    if excess <= 0:
        return []
    victims: List[object] = []
    by_class: Dict[str, List[object]] = {}
    for request in requests:
        by_class.setdefault(request.priority, []).append(request)
    for name in config.shed_order():
        if len(victims) >= excess:
            break
        pool = sorted(
            by_class.get(name, ()), key=lambda r: -r.submitted
        )
        victims.extend(pool[:excess - len(victims)])
    return victims


def validate_priority(priority: Optional[str]) -> Optional[str]:
    """Validation for a priority tag submitted WITHOUT a QoS config:
    type-checked only.  The FIFO path records the tag but never
    reorders on it, and it must accept ANY class name — a QoS fleet
    with custom classes legitimately forwards them to replicas whose
    own QoS is off (rejecting there would typed-fail every request of
    a perfectly valid deployment).  Class-NAME validation happens at
    whichever surface has a :class:`QosConfig` armed —
    :meth:`QosConfig.resolve_priority`."""
    if priority is not None and not isinstance(priority, str):
        raise ValueError(
            f"priority must be a class name (str) or None, got "
            f"{type(priority).__name__}"
        )
    return priority


class TokenBucket:
    """Thread-safe token bucket (one per tenant).

    ``try_acquire(n)`` refills by elapsed time x rate (capped at the
    burst ceiling), then takes ``n`` tokens or takes nothing — quota
    charging is all-or-nothing so a partially-charged rejected request
    cannot exist.  ``clock`` is injectable for tests.
    """

    def __init__(self, quota: TenantQuota, clock=time.monotonic):
        self.quota = quota
        self._clock = clock
        self._tokens = float(quota.burst_tokens)
        self._last = clock()
        self._lock = threading.Lock()

    def _refill_locked(self) -> None:
        now = self._clock()
        elapsed = max(now - self._last, 0.0)
        self._last = now
        self._tokens = min(
            self._tokens + elapsed * self.quota.tokens_per_s,
            float(self.quota.burst_tokens),
        )

    def try_acquire(self, tokens: float) -> bool:
        with self._lock:
            self._refill_locked()
            if tokens > self._tokens:
                return False
            self._tokens -= tokens
            return True

    def credit(self, tokens: float) -> None:
        """Refund tokens (capped at the burst ceiling): a request whose
        charge succeeded but which was then REJECTED before entering
        the queue (admission full, fleet closing) received no service —
        burning its tokens would quota-block the tenant for work the
        fleet refused to do."""
        with self._lock:
            self._refill_locked()
            self._tokens = min(
                self._tokens + tokens, float(self.quota.burst_tokens)
            )

    def available(self) -> float:
        with self._lock:
            self._refill_locked()
            return self._tokens


class QosScheduler:
    """The admission-order policy: pick the waiting request minimizing
    ``(max(SLO slack, 0), weighted fairness debt, arrival)``.

    *Slack* is ``submitted + slo_s - now``: while any request still has
    positive slack, the earliest-expiring SLO is served first (EDF —
    interactive's tight SLO wins the queue under light load).  Once
    slack is exhausted (clamped to 0 — the saturated regime where every
    SLO is blown), the *fairness debt* decides: each class accrues
    virtual service ``tokens / weight`` as its requests are admitted,
    and the class with the least virtual service goes first — weighted
    fair queuing, so a flood cannot starve anyone and weights set the
    shares.  Arrival time is the final tiebreak (FIFO within a class).

    Pure policy: no locks (callers hold their queue lock), no clock of
    its own.  One instance per scheduler (engine or fleet); the debt
    state is the only mutation, via :meth:`charge`.
    """

    def __init__(self, config: QosConfig):
        self.config = config
        self._vservice: Dict[str, float] = {
            name: 0.0 for name in config.classes
        }
        #: Virtual time: the max-ever of min-vservice-over-backlogged
        #: classes.  A class that returns from idleness is lifted to
        #: it (the WFQ start-tag clamp) so it cannot hoard an idle
        #: period as credit and monopolize admission afterwards; a
        #: continuously-backlogged lagging class DEFINES the min, so
        #: the lift never erases debt it is legitimately owed.
        self._vtime = 0.0

    def key(self, priority: str, submitted: float, now: float):
        """The admission sort key for one waiting request (smaller =
        admitted sooner)."""
        pc = self.config.classes[priority]
        slack = submitted + pc.slo_s - now
        return (max(slack, 0.0), self._vservice[priority], submitted)

    def select(self, requests, now: float):
        """The waiting request to admit next — argmin of :meth:`key`
        over ``requests`` (objects with ``.priority``/``.submitted``),
        or None when empty.  ONE selection definition for both
        schedulers (the engine's slot admission and the fleet's queue
        pop own only their removal mechanics), and the place the
        idle-credit clamp runs: classes present in this waiting set
        are lifted to the virtual time before their keys compare."""
        requests = list(requests)
        present = {r.priority for r in requests}
        if present:
            floor = min(self._vservice[name] for name in present)
            if floor > self._vtime:
                self._vtime = floor
            for name in present:
                if self._vservice[name] < self._vtime:
                    self._vservice[name] = self._vtime
        best = None
        best_key = None
        for request in requests:
            key = self.key(request.priority, request.submitted, now)
            if best_key is None or key < best_key:
                best, best_key = request, key
        return best

    def charge(self, priority: str, tokens: int) -> None:
        """Accrue one admitted request's virtual service to its class
        (``tokens`` = prompt + decode budget — the work the admission
        bought)."""
        pc = self.config.classes[priority]
        self._vservice[priority] += tokens / pc.weight

    def virtual_service(self) -> Dict[str, float]:
        return dict(self._vservice)


class TokenStream:
    """Per-token delivery for one request: a thread-safe token list fed
    by the scheduler as emissions commit, plus the final result future.

    Iterating yields token ids as they arrive and returns at
    end-of-generation (raising the request's failure, if any, after the
    tokens already delivered).  ``feed`` is idempotent by token index —
    re-feeding an already-delivered index is a no-op — which is what
    makes a fleet failover's deterministic greedy re-run resume the
    stream instead of duplicating it.  ``result()`` blocks for the same
    final result the non-streamed future resolves with; the streamed
    tokens are a prefix-consistent view of exactly that row.
    """

    def __init__(self):
        self._cond = threading.Condition()
        self._tokens: List[int] = []
        self._done = False
        self._exc: Optional[BaseException] = None
        #: Resolves with the final ServeResult (or the typed failure) —
        #: the same object the non-streamed submit future carries.
        self.future: Future = Future()
        #: Fleet-wide trace id when the submit carried a TraceContext
        #: (engine/fleet stamp it at admission); None otherwise.  Lets a
        #: streaming consumer correlate its tokens with the request's
        #: spans in a merged timeline without waiting for the final
        #: ServeResult.
        self.trace_id: Optional[str] = None

    # -- producer side (scheduler / fleet threads) -------------------------

    def feed(self, index: int, token: int) -> None:
        """Deliver the token at emission ``index`` (idempotent: indexes
        at or below what was already delivered are dropped; a gap —
        impossible from the in-order emission path — is dropped too
        rather than delivering out of order)."""
        with self._cond:
            if self._done or index != len(self._tokens):
                return
            self._tokens.append(int(token))
            self._cond.notify_all()

    def _complete_from_future(self, fut: Future) -> None:
        """Done-callback for the request's future: back-fill any tokens
        the incremental path did not deliver (the batch scheduler
        materializes them all at once), then close the stream with the
        same result/exception."""
        try:
            exc = fut.exception()
        except BaseException as cancelled:  # noqa: BLE001 - cancelled
            exc = cancelled
        if exc is None:
            result = fut.result()
            tokens = getattr(result, "tokens", None)
            count = getattr(result, "num_generated", None)
            if tokens is not None and count is not None:
                for i in range(int(count)):
                    self.feed(i, int(tokens[i]))
            with self._cond:
                self._done = True
                self._cond.notify_all()
            try:
                self.future.set_result(result)
            except InvalidStateError:  # pragma: no cover - double close
                pass
            return
        with self._cond:
            self._exc = exc
            self._done = True
            self._cond.notify_all()
        try:
            self.future.set_exception(exc)
        except InvalidStateError:  # pragma: no cover - double close
            pass

    # -- consumer side -----------------------------------------------------

    def __iter__(self) -> Iterator[int]:
        i = 0
        while True:
            with self._cond:
                while i >= len(self._tokens) and not self._done:
                    self._cond.wait()
                if i < len(self._tokens):
                    token = self._tokens[i]
                else:
                    if self._exc is not None:
                        raise self._exc
                    return
            yield token
            i += 1

    def result(self, timeout: Optional[float] = None):
        """The final :class:`~cloud_tpu.serving.ServeResult` (or the
        request's typed failure) — same contract as the plain future."""
        return self.future.result(timeout)

    def tokens_so_far(self) -> List[int]:
        with self._cond:
            return list(self._tokens)

    def done(self) -> bool:
        with self._cond:
            return self._done
