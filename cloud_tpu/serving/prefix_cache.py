"""Host-side bookkeeping for the shared-prefix KV block pool.

The device half is ``generation.init_prefix_pool`` — ``num_blocks`` KV
rows of ``block_tokens`` positions each, plus the copy/save programs
that move blocks between the pool and the slot grid.  This module owns
WHICH token prefix each block holds: a token-trie (radix tree at block
granularity) where every node is one block, keyed by that block's
token tuple, child nodes extending the prefix by one block.  A prompt's
longest cached prefix is a root-down walk (:meth:`PrefixCacheManager.
match`); the blocks it returns are the pool rows to copy.

Lifecycle is reference-counted: a slot that copies blocks in (a hit) or
saves new blocks out (a miss becoming tomorrow's hit) holds a reference
on each until the slot retires, so a block shared by two in-flight
requests survives either one finishing.  Eviction is LRU over
*unreferenced leaves* — a parent can never leave before its children
(the trie walk would dangle), and a referenced block never leaves at
all.  When every block is pinned, :meth:`insert` simply caches less:
the prefix cache is an accelerator, never a correctness dependency.

``match`` does NOT pin.  The scheduler pins with :meth:`acquire`, which
re-validates that every matched node is still live — a block evicted
between lookup and insert (allocation pressure from a neighboring
request in the same scheduling pass) fails the acquire, and the engine
falls back to a cold prefill instead of copying a reused block's bytes
(the no-stale-KV contract, pinned in tests/unit/test_serving_prefix.py).

Everything here is plain host Python on the scheduler thread; a small
lock guards the counters that ``health()``/``stats()`` read from other
threads.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Optional, Sequence, Tuple

#: Scatter sentinel for "do not write this block": out of any real pool's
#: range, so ``generation.save_prefix_program``'s drop-mode scatter skips
#: it.  (Reads clamp rather than drop, so the COPY side pads with real
#: hit ids instead — see ``ServingEngine._copy_prefix``.)
SKIP_BLOCK = 2 ** 30


@dataclasses.dataclass(eq=False)  # identity semantics: nodes are unique,
class _Node:                      # and the evictable set hashes them
    """One cached block: ``key`` is this block's token tuple (the full
    prefix is the root-down concatenation), ``block`` its pool row."""

    key: Tuple[int, ...]
    block: int
    parent: Optional["_Node"]
    children: Dict[Tuple[int, ...], "_Node"] = dataclasses.field(
        default_factory=dict
    )
    refs: int = 0
    last_used: int = 0
    #: Flipped False on eviction: a PrefixHit holding this node fails
    #: ``acquire`` instead of copying a reused block's bytes.
    live: bool = True


@dataclasses.dataclass(frozen=True)
class PrefixHit:
    """A ``match`` result: the trie nodes of the longest cached prefix
    (root-down order) and how many prompt tokens they cover.  Holds no
    references until :meth:`PrefixCacheManager.acquire`."""

    nodes: Tuple[_Node, ...]
    tokens: int

    @property
    def blocks(self) -> List[int]:
        return [node.block for node in self.nodes]

    def __bool__(self) -> bool:
        return bool(self.nodes)


class PrefixCacheManager:
    """Radix bookkeeping over a ``num_blocks``-row device pool."""

    def __init__(self, num_blocks: int, block_tokens: int):
        if num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
        if block_tokens < 1:
            raise ValueError(
                f"block_tokens must be >= 1, got {block_tokens}"
            )
        self.num_blocks = num_blocks
        self.block_tokens = block_tokens
        self._root = _Node(key=(), block=-1, parent=None)
        self._free: List[int] = list(range(num_blocks))[::-1]
        #: Eviction candidates — nodes that WERE (refs == 0, childless)
        #: at their last transition.  Maintained incrementally so an
        #: allocation under pool pressure scans candidates, not the
        #: whole trie (entries are re-validated at eviction time, so a
        #: stale member is skipped, never wrongly evicted).
        self._evictable: set = set()
        self._clock = 0
        self._lock = threading.Lock()
        self._stats = {
            "lookups": 0, "hits": 0, "misses": 0, "hit_tokens": 0,
            "acquire_failures": 0, "evictions": 0, "saved_blocks": 0,
        }

    # -- introspection -----------------------------------------------------

    @property
    def blocks_in_use(self) -> int:
        return self.num_blocks - len(self._free)

    def stats(self) -> dict:
        with self._lock:
            snap = dict(self._stats)
        snap["blocks_in_use"] = self.blocks_in_use
        return snap

    def _count(self, **deltas) -> None:
        with self._lock:
            for key, delta in deltas.items():
                self._stats[key] += delta

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    # -- lookup / pin ------------------------------------------------------

    def _walk(self, tokens: Sequence[int], max_tokens: int) -> PrefixHit:
        node = self._root
        nodes: List[_Node] = []
        offset = 0
        while offset + self.block_tokens <= max_tokens:
            key = tuple(
                int(t) for t in tokens[offset:offset + self.block_tokens]
            )
            child = node.children.get(key)
            if child is None:
                break
            nodes.append(child)
            node = child
            offset += self.block_tokens
        return PrefixHit(nodes=tuple(nodes), tokens=offset)

    def match(self, tokens: Sequence[int]) -> PrefixHit:
        """Longest cached prefix of ``tokens``, in whole blocks, capped
        at ``len(tokens) - 1`` tokens — at least the prompt's last token
        always prefills, so even a fully cached prompt produces the
        logits its first sampled token needs.  Counts a lookup (and the
        miss, when nothing matched); a HIT is only counted by a
        successful :meth:`acquire` — a match whose blocks evict before
        the pin lands serves cold, and the stats must say so (the same
        verdict the engine's own counters reach)."""
        hit = self._walk(tokens, max(len(tokens) - 1, 0))
        self._count(lookups=1, misses=0 if hit.nodes else 1)
        return hit

    def acquire(self, hit: PrefixHit) -> bool:
        """Pin a match's blocks (ref +1 each, LRU bumped).  Returns
        False — pinning NOTHING, counting a miss — if any node was
        evicted since the match: the caller must fall back to a cold
        prefill."""
        if not hit.nodes:
            return False
        if not all(node.live for node in hit.nodes):
            self._count(misses=1, acquire_failures=1)
            return False
        now = self._tick()
        for node in hit.nodes:
            node.refs += 1
            node.last_used = now
            self._evictable.discard(node)
        self._count(hits=1, hit_tokens=hit.tokens)
        return True

    def release(self, nodes: Sequence[_Node]) -> None:
        """Drop one reference per node (a retiring slot's held blocks).
        Evicted-while-held nodes still count down safely."""
        for node in nodes:
            if node.refs > 0:
                node.refs -= 1
            if node.live and node.refs == 0 and not node.children:
                self._evictable.add(node)

    # -- insert / evict ----------------------------------------------------

    def insert(self, tokens: Sequence[int],
               already: PrefixHit,
               ) -> Tuple[List[_Node], List[_Node], int]:
        """Extend the trie with the full blocks of ``tokens`` beyond the
        ``already``-cached prefix (the hit the caller copied in, or an
        empty one).  Allocates pool rows — evicting LRU unreferenced
        leaves as needed — and returns ``(held, created, evicted)``:
        ``held`` is every walked node beyond the prefix (one reference
        taken on each — the caller's slot releases them at retire;
        in-flight siblings may have cached some of them since the
        caller's match), ``created`` the subset whose pool rows are NEW
        and must be written by ``save_prefix_program`` (existing blocks
        are never rewritten — in-flight readers may share them), and
        ``evicted`` how many LRU blocks THIS insert reclaimed.  Stops
        early, caching less, when the pool is fully pinned.  The last
        ``len(tokens) % block_tokens`` tokens never cache (partial
        blocks are not addressable), and like :meth:`match` the
        cacheable span is capped at ``len(tokens) - 1``."""
        max_tokens = max(len(tokens) - 1, 0)
        node = self._root if not already.nodes else already.nodes[-1]
        offset = already.tokens
        now = self._tick()
        held: List[_Node] = []
        created: List[_Node] = []
        evicted = 0
        while offset + self.block_tokens <= max_tokens:
            key = tuple(
                int(t) for t in tokens[offset:offset + self.block_tokens]
            )
            child = node.children.get(key)
            if child is None:
                block, from_eviction = self._allocate()
                if block is None:
                    break
                evicted += 1 if from_eviction else 0
                child = _Node(key=key, block=block, parent=node)
                node.children[key] = child
                self._evictable.discard(node)  # no longer a leaf
                created.append(child)
                self._count(saved_blocks=1)
            child.refs += 1
            child.last_used = now
            self._evictable.discard(child)
            held.append(child)
            node = child
            offset += self.block_tokens
        return held, created, evicted

    def _allocate(self) -> Tuple[Optional[int], bool]:
        """A free pool row, or an evicted one: ``(block | None,
        came_from_eviction)``."""
        if self._free:
            return self._free.pop(), False
        block = self._evict_one()
        return block, block is not None

    def _evict_one(self) -> Optional[int]:
        """Reclaim the LRU unreferenced LEAF block; None if every block
        is referenced (or an interior parent of one).  Scans the
        incrementally-maintained candidate set — not the trie — and
        re-validates each member (stale entries are dropped), so the
        scheduler-thread cost of an allocation under pool pressure is
        bounded by the evictable population."""
        victim: Optional[_Node] = None
        stale = []
        for node in self._evictable:
            if not node.live or node.refs > 0 or node.children:
                stale.append(node)
                continue
            if victim is None or node.last_used < victim.last_used:
                victim = node
        for node in stale:
            self._evictable.discard(node)
        if victim is None:
            return None
        self._evict_node(victim)
        return victim.block

    def _evict_node(self, victim: _Node) -> None:
        victim.live = False
        self._evictable.discard(victim)
        parent = victim.parent
        parent.children.pop(victim.key, None)
        if (parent is not self._root and parent.live
                and parent.refs == 0 and not parent.children):
            self._evictable.add(parent)  # now an evictable leaf itself
        self._count(evictions=1)

    def evict_prefix(self, tokens: Sequence[int]) -> int:
        """Force-evict every cached block along ``tokens``'s prefix that
        is unreferenced and childless, deepest first (a test/ops hook —
        the eviction-between-lookup-and-insert seam).  Returns the
        number of blocks evicted."""
        hit = self._walk(tokens, len(tokens))
        evicted = 0
        for node in reversed(hit.nodes):
            if node.refs > 0 or node.children:
                break
            self._evict_node(node)
            self._free.append(node.block)
            evicted += 1
        return evicted
