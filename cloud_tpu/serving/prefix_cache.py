"""Host-side bookkeeping for the shared-prefix KV block pool.

The device half is ``generation.init_prefix_pool`` — ``num_blocks`` KV
rows of ``block_tokens`` positions each, plus the copy/save programs
that move blocks between the pool and the slot grid.  This module owns
WHICH token prefix each block holds: a token-trie (radix tree at block
granularity) where every node is one block, keyed by that block's
token tuple, child nodes extending the prefix by one block.  A prompt's
longest cached prefix is a root-down walk (:meth:`PrefixCacheManager.
match`); the blocks it returns are the pool rows to copy.

Lifecycle is reference-counted: a slot that copies blocks in (a hit) or
saves new blocks out (a miss becoming tomorrow's hit) holds a reference
on each until the slot retires, so a block shared by two in-flight
requests survives either one finishing.  Eviction is LRU over
*unreferenced leaves* — a parent can never leave before its children
(the trie walk would dangle), and a referenced block never leaves at
all.  When every block is pinned, :meth:`insert` simply caches less:
the prefix cache is an accelerator, never a correctness dependency.

**Host-DRAM second tier** (``dram_blocks > 0``): a block evicted from
the HBM pool does not vanish — its bytes are *demoted* to a bounded
host-side pool (numpy pytrees captured through an engine-installed
``demote_fn``, outside jit) and the node stays in the trie, flagged
``tier == "dram"``.  A later match that walks through demoted nodes is
still a hit; :meth:`acquire_swapin` *promotes* those nodes back —
allocating fresh HBM rows (which may itself demote colder blocks) and
returning the host payloads for the engine to upload asynchronously —
and a promotion that cannot allocate rows (the pool fully pinned:
the swap-in lost the race) fails the acquire exactly like the PR 9
evicted-between-match-and-acquire window, so the engine falls back to
a cold prefill and greedy outputs stay token-identical in every tier
state.  A *pinned* block (refs > 0) never demotes and never leaves
DRAM; when the DRAM pool overflows, its LRU unreferenced leaf is
evicted for real (the miss-after-demote-evict state).  With
``dram_blocks == 0`` (the default) none of this machinery exists and
behavior is byte-identical to the single-tier manager.

``match`` does NOT pin.  The scheduler pins with :meth:`acquire` (or
:meth:`acquire_swapin` when the DRAM tier is armed), which re-validates
that every matched node is still live — a block evicted between lookup
and insert (allocation pressure from a neighboring request in the same
scheduling pass) fails the acquire, and the engine falls back to a
cold prefill instead of copying a reused block's bytes (the
no-stale-KV contract, pinned in tests/unit/test_serving_prefix.py).

Everything here is plain host Python on the scheduler thread; a small
lock guards the counters that ``health()``/``stats()`` read from other
threads.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

#: Scatter sentinel for "do not write this block": out of any real pool's
#: range, so ``generation.save_prefix_program``'s drop-mode scatter skips
#: it.  (Reads clamp rather than drop, so the COPY side pads with real
#: hit ids instead — see ``ServingEngine._copy_prefix``.)
SKIP_BLOCK = 2 ** 30

#: Leading tokens hashed into a request's router affinity key AND into
#: the per-replica cached-prefix summary (:meth:`PrefixCacheManager.
#: hot_prefixes`) the cost-model router scores against.  One spelling,
#: defined at the serving layer so the engine's summary and the fleet's
#: request key can never drift: sized to cover typical shared
#: system-prompt heads without making every long unique prompt its own
#: key.
AFFINITY_PREFIX_TOKENS = 32


def affinity_key(tokens: Sequence[int]) -> int:
    """The router-facing key of a token sequence's leading prefix —
    used by the fleet for each request and by the engine's
    ``hot_prefixes`` summary, so a summary lookup with a request's key
    estimates how many of ITS prefix tokens the replica caches."""
    return hash(tuple(int(t) for t in tokens[:AFFINITY_PREFIX_TOKENS]))


@dataclasses.dataclass(eq=False)  # identity semantics: nodes are unique,
class _Node:                      # and the evictable set hashes them
    """One cached block: ``key`` is this block's token tuple (the full
    prefix is the root-down concatenation), ``block`` its pool row."""

    key: Tuple[int, ...]
    block: int
    parent: Optional["_Node"]
    children: Dict[Tuple[int, ...], "_Node"] = dataclasses.field(
        default_factory=dict
    )
    refs: int = 0
    last_used: int = 0
    #: Flipped False on (full) eviction: a PrefixHit holding this node
    #: fails ``acquire`` instead of copying a reused block's bytes.
    live: bool = True
    #: Which pool holds the block's bytes: ``"hbm"`` (``block`` is a
    #: live device pool row) or ``"dram"`` (``payload`` is the host
    #: copy; ``block`` is meaningless until a promotion re-rows it).
    tier: str = "hbm"
    #: Host-side bytes while demoted (whatever ``demote_fn`` returned —
    #: the engine uses a per-leaf numpy pytree mirroring the pool row).
    payload: object = None


@dataclasses.dataclass(frozen=True)
class PrefixHit:
    """A ``match`` result: the trie nodes of the longest cached prefix
    (root-down order) and how many prompt tokens they cover.  Holds no
    references until :meth:`PrefixCacheManager.acquire`."""

    nodes: Tuple[_Node, ...]
    tokens: int

    @property
    def blocks(self) -> List[int]:
        return [node.block for node in self.nodes]

    def __bool__(self) -> bool:
        return bool(self.nodes)


class PrefixCacheManager:
    """Radix bookkeeping over a ``num_blocks``-row device pool, with an
    optional ``dram_blocks``-slot host tier (module docstring).

    ``demote_fn`` captures an HBM block's bytes host-side at demotion
    time — ``demote_fn(block) -> payload`` — and is installed by the
    engine (it owns the device pool the bytes come from).  Without one,
    an armed DRAM tier never demotes (blocks vanish as in PR 9);
    manager-level tests install trivial fakes.
    """

    def __init__(self, num_blocks: int, block_tokens: int, *,
                 dram_blocks: int = 0,
                 demote_fn: Optional[Callable[[int], object]] = None,
                 summary_ttl_s: Optional[float] = None,
                 clock: Optional[Callable[[], float]] = None):
        if num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
        if block_tokens < 1:
            raise ValueError(
                f"block_tokens must be >= 1, got {block_tokens}"
            )
        if dram_blocks < 0:
            raise ValueError(
                f"dram_blocks must be >= 0, got {dram_blocks}"
            )
        if summary_ttl_s is not None and summary_ttl_s <= 0:
            raise ValueError(
                f"summary_ttl_s must be > 0, got {summary_ttl_s}"
            )
        self.num_blocks = num_blocks
        self.block_tokens = block_tokens
        self.dram_blocks = dram_blocks
        self.demote_fn = demote_fn
        #: TTL on :meth:`hot_prefixes` entries (None = never expire —
        #: byte-identical to the pre-TTL summary).  ``clock`` is the
        #: wall source (monotonic seconds); tests inject fakes.
        self.summary_ttl_s = summary_ttl_s
        self._wall = clock if clock is not None else time.monotonic
        #: Last time each summary key was HIT (acquire / insert /
        #: seed), keyed like ``_summary``.  Entries for keys no longer
        #: in the summary are pruned at each rebuild, so it stays
        #: bounded by the summary's own limit.
        self._last_hit: Dict[int, float] = {}
        self._root = _Node(key=(), block=-1, parent=None)
        self._free: List[int] = list(range(num_blocks))[::-1]
        #: Eviction candidates — nodes that WERE (refs == 0, childless)
        #: at their last transition, one set per tier.  Maintained
        #: incrementally so an allocation under pool pressure scans
        #: candidates, not the whole trie (entries are re-validated at
        #: eviction time, so a stale member is skipped, never wrongly
        #: evicted).
        self._evictable: set = set()
        self._dram_evictable: set = set()
        self._dram_used = 0
        #: Router-facing hot-prefix summary (see :meth:`hot_prefixes`):
        #: rebuilt whole on the scheduler thread, read by reference
        #: from health() callers.  ``_shape_version`` ticks on every
        #: node ADDITION or REMOVAL (tier flips don't change the
        #: summary), so ``_maybe_refresh`` skips the DFS on the
        #: steady hot path — hits, swap-ins, and pure demotions.
        self._summary: Dict[int, int] = {}
        self._shape_version = 0
        self._summary_version = 0
        self._clock = 0
        self._lock = threading.Lock()
        self._stats = {
            "lookups": 0, "hits": 0, "misses": 0, "hit_tokens": 0,
            "acquire_failures": 0, "evictions": 0, "saved_blocks": 0,
            # DRAM-tier counters (all stay 0 with dram_blocks == 0).
            "demotions": 0, "promotions": 0, "dram_evictions": 0,
            "dram_hits": 0, "dram_hit_tokens": 0, "swapin_failures": 0,
            # Save-backs inserted while a pipelined chunk was still in
            # flight (stays 0 at pipeline_depth=1) — see "Save-back
            # ordering under pipelined scheduling" below.
            "deferred_saves": 0,
        }

    def note_deferred_save(self) -> None:
        """Count one save-back that landed while a pipelined chunk was
        still in flight (engine calls this from its scheduler thread;
        the counter is the parity tests' evidence that the deferred
        ordering path actually ran).

        Save-back ordering under pipelined scheduling: with
        ``pipeline_depth=2`` the engine inserts a prompt's trie entry —
        and dispatches the pool write for its new blocks — while the
        PREVIOUS decode chunk is still executing on the device.  Two
        facts keep that safe with zero extra synchronization.  On the
        device, the save program consumes the same donated grid cache
        the in-flight chunk produces, so XLA's dataflow ordering runs
        the pool write strictly AFTER the chunk — the saved rows are
        exactly the post-prefill rows, never a torn snapshot.  On the
        host, the trie entry becomes matchable the moment ``insert``
        returns, but the only thread that can act on a match is the
        scheduler thread itself (match/acquire/copy-in all happen
        there), which by construction has already moved past the save —
        so no request can attach a block whose pool write hasn't been
        enqueued behind everything that could disturb it."""
        with self._lock:
            self._stats["deferred_saves"] += 1

    # -- introspection -----------------------------------------------------

    @property
    def blocks_in_use(self) -> int:
        return self.num_blocks - len(self._free)

    @property
    def dram_blocks_in_use(self) -> int:
        return self._dram_used

    def stats(self) -> dict:
        with self._lock:
            snap = dict(self._stats)
        snap["blocks_in_use"] = self.blocks_in_use
        snap["dram_blocks_in_use"] = self.dram_blocks_in_use
        return snap

    def hot_prefixes(self) -> Dict[int, int]:
        """The router-facing cached-prefix summary: ``{affinity key ->
        deepest cached prefix tokens}`` over the trie's hot roots, both
        tiers (a demoted prefix still serves via swap-in, so it still
        deserves traffic).  Keys are :func:`affinity_key` hashes of
        each cached prefix's leading tokens — the same hash the fleet
        stamps on every request — so ``summary.get(request.affinity_
        key, 0)`` estimates the prefix tokens this replica would serve
        from cache.

        Only prefixes covering at least ``AFFINITY_PREFIX_TOKENS``
        tokens appear (shorter cached paths cannot match any request's
        key — ``_refresh_summary``).  Returns a SNAPSHOT: the summary
        is recomputed on the scheduler thread after every trie-shape
        change and swapped in whole, so ``health()`` callers on router
        threads never walk a trie that is mutating under them.

        With ``summary_ttl_s`` armed, entries whose prefix has not been
        HIT (acquired, re-inserted, or handoff-seeded) within the TTL
        are filtered out of the snapshot: a replica that lost its hot
        tenant stops advertising stale cached-prefix credit to the
        router cost model, even though the blocks may still sit in the
        trie waiting for LRU pressure.  The blocks themselves remain
        servable — a late request still hits; only the ADVERTISEMENT
        ages out."""
        summary = self._summary
        ttl = self.summary_ttl_s
        if ttl is None:
            return dict(summary)
        now = self._wall()
        last = self._last_hit
        return {
            key: depth for key, depth in summary.items()
            if now - last.get(key, now) <= ttl
        }

    def _touch_summary_key(self, lead_tokens: Sequence[int]) -> None:
        """Refresh the TTL clock of the summary entry covering
        ``lead_tokens`` (no-op without a TTL or below the affinity
        span — such paths never appear in the summary at all)."""
        if self.summary_ttl_s is None:
            return
        if len(lead_tokens) < AFFINITY_PREFIX_TOKENS:
            return
        key = hash(tuple(
            int(t) for t in lead_tokens[:AFFINITY_PREFIX_TOKENS]
        ))
        self._last_hit[key] = self._wall()

    def _lead_tokens(self, nodes: Sequence[_Node]) -> List[int]:
        """The leading tokens of a root-down node chain, just enough to
        cover the affinity span."""
        lead: List[int] = []
        for node in nodes:
            lead.extend(node.key)
            if len(lead) >= AFFINITY_PREFIX_TOKENS:
                break
        return lead

    def _maybe_refresh(self) -> None:
        """Rebuild the summary iff the trie's node set changed since
        the last build (scheduler thread only — every caller of
        insert/acquire/evict ends with this)."""
        if self._summary_version != self._shape_version:
            self._refresh_summary()
            self._summary_version = self._shape_version

    def _refresh_summary(self, *, limit: int = 64) -> None:
        """Recompute the hot-prefix summary (scheduler thread only).
        Entries are emitted
        only once a root-down path covers ``AFFINITY_PREFIX_TOKENS``
        tokens (deeper nodes just raise that entry's depth): a
        shallower cached path can never match ANY request's affinity
        key — the cacheable span caps at ``len - 1``, so a request
        able to hit a ``d``-token path hashes at least ``d + 1``
        leading tokens, a strictly longer tuple — and emitting such
        paths would burn the ``limit`` bound on dead keys while a
        genuinely hot long prefix gets dropped.  At most ``limit``
        distinct keys (new keys past the bound are dropped — the
        summary is an estimate, not an index)."""
        out: Dict[int, int] = {}
        stack: List[Tuple[_Node, Tuple[int, ...], int]] = [
            (self._root, (), 0)
        ]
        while stack:
            node, lead, depth = stack.pop()
            for key, child in node.children.items():
                clead = (
                    lead if len(lead) >= AFFINITY_PREFIX_TOKENS
                    else (lead + key)[:AFFINITY_PREFIX_TOKENS]
                )
                cdepth = depth + len(key)
                if len(clead) >= AFFINITY_PREFIX_TOKENS:
                    k = hash(tuple(clead))
                    if k in out:
                        out[k] = max(out[k], cdepth)
                    elif len(out) < limit:
                        out[k] = cdepth
                stack.append((child, clead, cdepth))
        if self.summary_ttl_s is not None:
            # New keys start their TTL clock at first appearance; keys
            # that left the summary drop their clock (bounds the map).
            now = self._wall()
            last = self._last_hit
            self._last_hit = {
                key: last.get(key, now) for key in out
            }
        self._summary = out

    def _count(self, **deltas) -> None:
        with self._lock:
            for key, delta in deltas.items():
                self._stats[key] += delta

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    @staticmethod
    def _has_hbm_child(node: _Node) -> bool:
        return any(c.tier == "hbm" for c in node.children.values())

    def _mark_if_evictable_leaf(self, node: _Node) -> None:
        """(Re)enter ``node`` into its tier's eviction-candidate set.

        HBM candidates are unreferenced nodes with no HBM children — a
        node whose children all demoted to DRAM may itself DEMOTE
        (the trie keeps it, so nothing dangles) but never vanish;
        ``_evict_node`` enforces that split.  DRAM candidates must be
        fully childless: DRAM eviction is removal, and a removed parent
        would orphan its subtree."""
        if node is self._root or not node.live:
            return
        if node.refs != 0:
            return
        if node.tier == "dram":
            if not node.children:
                self._dram_evictable.add(node)
        elif not self._has_hbm_child(node):
            self._evictable.add(node)

    def _unmark_evictable(self, node: _Node) -> None:
        self._evictable.discard(node)
        self._dram_evictable.discard(node)

    # -- lookup / pin ------------------------------------------------------

    def _walk(self, tokens: Sequence[int], max_tokens: int) -> PrefixHit:
        node = self._root
        nodes: List[_Node] = []
        offset = 0
        while offset + self.block_tokens <= max_tokens:
            key = tuple(
                int(t) for t in tokens[offset:offset + self.block_tokens]
            )
            child = node.children.get(key)
            if child is None:
                break
            nodes.append(child)
            node = child
            offset += self.block_tokens
        return PrefixHit(nodes=tuple(nodes), tokens=offset)

    def match(self, tokens: Sequence[int]) -> PrefixHit:
        """Longest cached prefix of ``tokens``, in whole blocks, capped
        at ``len(tokens) - 1`` tokens — at least the prompt's last token
        always prefills, so even a fully cached prompt produces the
        logits its first sampled token needs.  Counts a lookup (and the
        miss, when nothing matched); a HIT is only counted by a
        successful :meth:`acquire` — a match whose blocks evict before
        the pin lands serves cold, and the stats must say so (the same
        verdict the engine's own counters reach).  The walk crosses
        tier boundaries freely: demoted nodes match, and the acquire
        step promotes them."""
        hit = self._walk(tokens, max(len(tokens) - 1, 0))
        self._count(lookups=1, misses=0 if hit.nodes else 1)
        return hit

    def acquire(self, hit: PrefixHit) -> bool:
        """Pin a match's blocks (ref +1 each, LRU bumped).  Returns
        False — pinning NOTHING, counting a miss — if any node was
        evicted since the match: the caller must fall back to a cold
        prefill.  This is the single-tier pin: a hit that walked into
        DRAM-demoted nodes also fails (their bytes are not on the
        device) — a tier-armed engine pins through
        :meth:`acquire_swapin` instead, which promotes them."""
        plan = self.acquire_swapin(hit, promote=False)
        return plan is not None

    def acquire_swapin(
        self, hit: PrefixHit, *, promote: bool = True,
    ) -> Optional[List[Tuple[_Node, int, object]]]:
        """Pin a match's blocks, promoting any DRAM-demoted ones back
        into fresh HBM rows.  Returns the promotion plan — ``[(node,
        new_block, payload), ...]`` root-down, empty when the whole hit
        was already HBM-resident — whose payloads the caller must
        upload into the pool rows BEFORE dispatching the prefix copy.
        Returns ``None`` — pinning nothing, counting a miss (plus
        ``swapin_failures`` when a promotion was needed) — when any
        node was evicted since the match OR the promotion could not
        allocate rows (HBM fully pinned: the swap-in lost the race);
        the caller falls back to a cold prefill either way."""
        if not hit.nodes:
            return None
        if not all(node.live for node in hit.nodes):
            self._count(misses=1, acquire_failures=1)
            return None
        demoted = [n for n in hit.nodes if n.tier == "dram"]
        if demoted and not promote:
            self._count(misses=1, acquire_failures=1)
            return None
        # Pin FIRST: allocation pressure from the promotion below must
        # never evict (or re-demote) the hit's own blocks.
        now = self._tick()
        for node in hit.nodes:
            node.refs += 1
            node.last_used = now
            self._unmark_evictable(node)
        plan: List[Tuple[_Node, int, object]] = []
        if demoted:
            rows: List[int] = []
            for _ in demoted:
                block, _ = self._allocate()
                if block is None:
                    # Lost the race: HBM is fully pinned right now.
                    # Unwind entirely — rows back, pins off — and tell
                    # the caller to serve cold.
                    self._free.extend(rows)
                    self.release(list(hit.nodes))
                    self._count(misses=1, acquire_failures=1,
                                swapin_failures=1)
                    self._maybe_refresh()  # _allocate may have removed
                    return None
                rows.append(block)
            for node, block in zip(demoted, rows):
                plan.append((node, block, node.payload))
                node.payload = None
                node.block = block
                node.tier = "hbm"
                self._dram_used -= 1
            self._count(
                promotions=len(plan), dram_hits=1,
                dram_hit_tokens=len(plan) * self.block_tokens,
            )
            self._maybe_refresh()  # _allocate may have removed
        self._count(hits=1, hit_tokens=hit.tokens)
        self._touch_summary_key(self._lead_tokens(hit.nodes))
        return plan

    def release(self, nodes: Sequence[_Node]) -> None:
        """Drop one reference per node (a retiring slot's held blocks).
        Evicted-while-held nodes still count down safely."""
        for node in nodes:
            if node.refs > 0:
                node.refs -= 1
            self._mark_if_evictable_leaf(node)

    # -- insert / evict ----------------------------------------------------

    def insert(self, tokens: Sequence[int],
               already: PrefixHit,
               ) -> Tuple[List[_Node], List[_Node], int]:
        """Extend the trie with the full blocks of ``tokens`` beyond the
        ``already``-cached prefix (the hit the caller copied in, or an
        empty one).  Allocates pool rows — evicting LRU unreferenced
        leaves as needed — and returns ``(held, created, evicted)``:
        ``held`` is every walked node beyond the prefix (one reference
        taken on each — the caller's slot releases them at retire;
        in-flight siblings may have cached some of them since the
        caller's match), ``created`` the subset whose pool rows are NEW
        and must be written by ``save_prefix_program`` (existing blocks
        are never rewritten — in-flight readers may share them), and
        ``evicted`` how many LRU blocks THIS insert reclaimed (demoted
        to DRAM or dropped).  Stops early, caching less, when the pool
        is fully pinned.  A walk that lands on a DRAM-demoted node
        stops there too — the slot did its own prefill for those
        positions, and extending the trie below bytes the device does
        not hold would hand a later match a hit it cannot copy.  The
        last ``len(tokens) % block_tokens`` tokens never cache (partial
        blocks are not addressable), and like :meth:`match` the
        cacheable span is capped at ``len(tokens) - 1``."""
        max_tokens = max(len(tokens) - 1, 0)
        node = self._root if not already.nodes else already.nodes[-1]
        offset = already.tokens
        now = self._tick()
        held: List[_Node] = []
        created: List[_Node] = []
        evicted = 0
        while offset + self.block_tokens <= max_tokens:
            key = tuple(
                int(t) for t in tokens[offset:offset + self.block_tokens]
            )
            child = node.children.get(key)
            if child is not None and child.tier == "dram":
                break
            if child is None:
                block, from_eviction = self._allocate()
                if block is None:
                    break
                evicted += 1 if from_eviction else 0
                child = _Node(key=key, block=block, parent=node)
                node.children[key] = child
                self._unmark_evictable(node)  # no longer a leaf
                created.append(child)
                self._shape_version += 1
                self._count(saved_blocks=1)
            child.refs += 1
            child.last_used = now
            self._unmark_evictable(child)
            held.append(child)
            node = child
            offset += self.block_tokens
        # Shape-change only: a pure re-walk of already-cached blocks
        # (the steady hot state) must not pay the summary DFS on the
        # scheduler thread — and neither must pure demotions.
        self._maybe_refresh()
        if offset >= AFFINITY_PREFIX_TOKENS:
            self._touch_summary_key(tokens)
        return held, created, evicted

    def seed_blocks(
        self, keys: Sequence[Sequence[int]],
    ) -> Tuple[List[_Node], List[_Node]]:
        """Walk/extend the trie along exactly ``keys`` — one
        ``block_tokens``-long token tuple per block, root-down — the
        handoff-import seam: a decode replica plants the blocks a
        prefill replica exported, so its very next lookup for the same
        prompt is an ordinary prefix hit.

        Allocates pool rows for missing nodes (evicting LRU leaves
        under pressure, exactly like :meth:`insert`) and returns
        ``(held, created)``: one reference taken on EVERY walked node —
        the caller releases them once its own acquire has pinned the
        hit, so allocation pressure in between can never evict the
        seeded chain — and ``created`` the subset whose pool rows must
        be WRITTEN (``upload_prefix_block``) by the caller before any
        copy/attach reads them.  Blocks already cached are never
        rewritten (same tokens, same bytes — the cross-replica dedup
        that makes a re-handoff of a hot prefix nearly free).  Stops
        early — seeding less — when allocation fails or the walk lands
        on a DRAM-demoted node, mirroring :meth:`insert`'s contract:
        the import is an accelerator, never a correctness dependency.
        """
        node = self._root
        now = self._tick()
        held: List[_Node] = []
        created: List[_Node] = []
        for key in keys:
            key = tuple(int(t) for t in key)
            if len(key) != self.block_tokens:
                raise ValueError(
                    f"seed key length {len(key)} != block_tokens "
                    f"{self.block_tokens}"
                )
            child = node.children.get(key)
            if child is not None and child.tier == "dram":
                break
            if child is None:
                block, _ = self._allocate()
                if block is None:
                    break
                child = _Node(key=key, block=block, parent=node)
                node.children[key] = child
                self._unmark_evictable(node)  # no longer a leaf
                created.append(child)
                self._shape_version += 1
                self._count(saved_blocks=1)
            child.refs += 1
            child.last_used = now
            self._unmark_evictable(child)
            held.append(child)
            node = child
        self._maybe_refresh()
        if len(held) * self.block_tokens >= AFFINITY_PREFIX_TOKENS:
            self._touch_summary_key(self._lead_tokens(held))
        return held, created

    def _allocate(self) -> Tuple[Optional[int], bool]:
        """A free pool row, or an evicted one: ``(block | None,
        came_from_eviction)``."""
        if self._free:
            return self._free.pop(), False
        block = self._evict_one()
        return block, block is not None

    def _scan_lru(self, candidates: set, tier: str, *,
                  allow_children: bool) -> Optional[_Node]:
        """The LRU valid eviction candidate of ``candidates`` for
        ``tier`` (dropping stale set members as it goes) — ONE scan
        loop for both tiers' candidate sets.  ``allow_children=False``
        restricts to fully childless nodes — the ones that may VANISH
        (DRAM eviction is always removal, so its callers never relax
        it)."""
        victim: Optional[_Node] = None
        stale = []
        for node in candidates:
            if (not node.live or node.refs > 0 or node.tier != tier
                    or (self._has_hbm_child(node) if tier == "hbm"
                        else bool(node.children))):
                stale.append(node)
                continue
            if node.children and not allow_children:
                continue
            if victim is None or node.last_used < victim.last_used:
                victim = node
        for node in stale:
            candidates.discard(node)
        return victim

    def _scan_evictable(self, *, allow_children: bool) -> Optional[_Node]:
        return self._scan_lru(self._evictable, "hbm",
                              allow_children=allow_children)

    def _evict_one(self) -> Optional[int]:
        """Reclaim the LRU unreferenced LEAF block; None if every block
        is referenced (or an HBM ancestor of one).  Scans the
        incrementally-maintained candidate set — not the trie — and
        re-validates each member (stale entries are dropped), so the
        scheduler-thread cost of an allocation under pool pressure is
        bounded by the evictable population.  With the DRAM tier armed
        the victim's bytes demote instead of vanishing; a victim with
        DRAM children can ONLY demote, so when the tier cannot take it
        the scan falls back to the LRU childless candidate."""
        victim = self._scan_evictable(allow_children=True)
        if victim is None:
            return None
        block = victim.block
        if self._evict_node(victim):
            return block
        # Demotion was required (DRAM children) but impossible: only a
        # childless candidate can vanish instead.
        self._evictable.add(victim)  # still a candidate for next time
        victim = self._scan_evictable(allow_children=False)
        if victim is None:
            return None
        block = victim.block
        if self._evict_node(victim):
            return block
        self._evictable.add(victim)
        return None

    def _evict_node(self, victim: _Node, *,
                    allow_demote: bool = True) -> bool:
        """Take ``victim``'s HBM row back: demote its bytes to the DRAM
        tier when armed (the node stays in the trie, ``tier ==
        "dram"``), else evict it for real.  On success the row is the
        caller's to reuse; False when the victim could neither demote
        (no tier room) nor vanish (it still has DRAM children a removal
        would orphan)."""
        self._evictable.discard(victim)
        if (allow_demote and self.dram_blocks > 0
                and self.demote_fn is not None
                and self._demote_room()):
            victim.payload = self.demote_fn(victim.block)
            victim.tier = "dram"
            victim.block = -1
            self._dram_used += 1
            self._mark_if_evictable_leaf(victim)
            # Its parent may have just lost its last HBM child.
            if victim.parent is not None:
                self._mark_if_evictable_leaf(victim.parent)
            self._count(evictions=1, demotions=1)
            return True
        if victim.children:
            # All-DRAM children (the HBM-child scan excluded the rest):
            # removal would orphan them, and demotion just failed.
            return False
        victim.live = False
        victim.payload = None
        parent = victim.parent
        parent.children.pop(victim.key, None)
        self._mark_if_evictable_leaf(parent)  # now a leaf itself
        self._shape_version += 1
        self._count(evictions=1)
        return True

    def _demote_room(self) -> bool:
        """Make room in the DRAM pool for one more demotion, evicting
        its LRU unreferenced leaf if needed.  False when DRAM is full
        of pinned (or interior) blocks — the caller's victim then
        vanishes instead of demoting."""
        if self._dram_used < self.dram_blocks:
            return True
        victim = self._scan_lru(self._dram_evictable, "dram",
                                allow_children=False)
        if victim is None:
            return False
        self._evict_dram_node(victim)
        return True

    def _evict_dram_node(self, victim: _Node) -> None:
        """Full eviction of a DRAM-tier leaf (the miss-after-demote-
        evict state: a later match that reaches it goes cold)."""
        victim.live = False
        victim.payload = None
        self._dram_used -= 1
        self._dram_evictable.discard(victim)
        parent = victim.parent
        parent.children.pop(victim.key, None)
        self._mark_if_evictable_leaf(parent)
        self._shape_version += 1
        self._count(dram_evictions=1)

    def evict_prefix(self, tokens: Sequence[int], *,
                     allow_demote: bool = False) -> int:
        """Force-evict every cached block along ``tokens``'s prefix that
        is unreferenced and childless, deepest first (a test/ops hook —
        the eviction-between-lookup-and-insert seam).  By default the
        blocks vanish even with the DRAM tier armed (the PR 9
        semantics this hook exists to simulate);
        ``allow_demote=True`` routes them through the tier instead.
        Returns the number of blocks evicted."""
        hit = self._walk(tokens, len(tokens))
        evicted = 0
        for node in reversed(hit.nodes):
            if node.refs > 0 or node.children:
                break
            if node.tier == "dram":
                self._evict_dram_node(node)
            else:
                block = node.block
                if not self._evict_node(node, allow_demote=allow_demote):
                    break
                # Whether the bytes demoted or vanished, the HBM row
                # itself is reclaimed.
                self._free.append(block)
            evicted += 1
        self._maybe_refresh()
        return evicted
