"""Continuous-batching serving engine over the generation path.

``models.generation`` can decode a *batch* of prompts as one compiled
program, but traffic arrives one request at a time; serving economics on
TPU hinge on the gap between those two facts (batched decode occupancy
amortizes the weight reads every decode step re-pays — arxiv 2605.25645,
arxiv 2309.08918).  :class:`ServingEngine` closes the gap in-process,
with two schedulers sharing one submit/future/admission surface:

* **Continuous batching** (``scheduler="continuous"``, the default) —
  iteration-level scheduling over a persistent decode grid: a static
  ``(num_slots, max_len)`` KV cache plus per-slot ``{position,
  remaining, active}`` state lives on the device for the engine's whole
  life.  Decode runs in fixed-size token chunks (ONE compiled
  ``generation.decode_chunk_program`` scanning ``chunk_tokens`` steps
  over every slot); between chunks the scheduler retires finished slots
  — per-request ``max_new_tokens`` exhausted or eos sampled, the slot
  deactivates *mid-chunk* via the active mask — completes their futures
  immediately, and prefills queued requests into the freed slots
  (``generation.insert_slot_program``, one program per prompt bucket,
  at the request's own bucket length).  A short request never rides out
  a long neighbor's decode: occupancy is a steady-state quantity
  instead of the batch-synchronous sawtooth (Orca-style iteration
  scheduling — arxiv 2605.25645).
* **Prefix caching** (``prefix_cache_blocks > 0``, continuous mode) —
  requests sharing a prompt prefix (system prompts, few-shot headers)
  share its KV bytes: a radix/token-trie manager
  (``serving.prefix_cache``) keys a device pool of KV blocks by
  token-id prefixes with ref-counting and LRU leaf eviction; on
  admission the scheduler copies the longest cached prefix into the
  slot row (``generation.copy_prefix_program``) and prefills only the
  uncached suffix, then saves the prompt's new full blocks back.
  Greedy outputs stay token-identical to a cold prefill — a hit moves
  compute, never tokens.  A **host-DRAM second tier**
  (``prefix_dram_blocks > 0``) makes HBM eviction a demotion: the
  block's bytes move to a bounded host-side pool and swap back in
  asynchronously on a later hit (``serve/prefix_swapin``), with the
  match-vs-acquire revalidation extended so a swap-in that loses the
  race falls back to a cold prefill — docs/serving.md "Tiered prefix
  cache".
* **Chunked prefill** (``prefill_chunk_tokens``, continuous mode) —
  prompt prefill splits into bounded chunks
  (``generation.prefill_chunk_program``) the scheduler interleaves
  with decode chunks, one prefill chunk per pass: a long arrival
  stalls in-flight decode by at most one chunk dispatch instead of one
  full prefill (the TTFT/tail-latency knob).  Both knobs default OFF —
  the PR 5 one-shot insert path is the compatibility default.
* **Sharded serving** (``mesh_shape=(tp, sp)`` / ``layout="auto"``) —
  one replica spans a multi-chip slice: the whole slot-grid program
  family runs under a TP(xSP) mesh with params sharded per the rules
  table (heads/mlp/vocab over ``tp``), the slot KV cache and prefix
  block pool sharded by attention head, and logits resharded to
  replicated exactly once per forward, at the sampling boundary
  (spanned host-side as ``serve/reshard``).  The layout comes from
  ``parallel.planner.plan_serve_layout`` under ``layout="auto"``
  (model head count x slice shape x HBM budget — the AMP-style search
  already driving training); ``tp`` must divide ``num_heads`` (typed
  error).  Unset / ``(1, 1)`` keeps the single-chip path
  byte-identical, and greedy outputs on any slice are token-identical
  to single-chip ``generate()`` — docs/serving.md "Sharded serving".
* **Speculative decoding** (``draft=DraftConfig(...)``, continuous
  mode) — draft-and-verify on the slot grid: a small draft model
  proposes a ``spec_k``-token window per active slot
  (``generation.draft_chunk_program`` over the draft's own slot cache),
  and the target model scores every window position in ONE chunked
  dispatch (``generation.verify_chunk_program``), committing the
  greedily-accepted prefix and rewinding past the first mismatch.
  Greedy outputs stay token-identical to the non-speculative engine —
  every committed token is the target's own argmax; the draft only
  decides how many of them one dispatch commits — so the win metric is
  accepted-tokens/sec with target-dispatches-per-token < 1.
  ``draft=None`` (default) is byte-identical to the non-speculative
  path; ``spec_k=1`` is a pure-overhead test knob.  ``health()`` and
  ``stats()`` report a rolling/cumulative acceptance rate.
* **Dynamic batching** (``scheduler="batch"``, the PR 4 path) — the
  scheduler groups waiting requests by prompt-length bucket, pads each
  group to a static ``(bucket_len, batch_size)`` grid point, and
  dispatches prefill + scan-decode as two compiled programs
  (``generation.prefill_program`` / ``generation.decode_program``).  A
  batch forms on a full max-batch or a ``flush_deadline_s`` timeout.
  Kept as the baseline the continuous scheduler is measured against
  (tests assert continuous slot occupancy beats it on churn workloads).
* **AOT warmup** — either grid is enumerable, so ``warmup=True``
  pre-compiles it through ``training.compile_cache`` (the trainer's AOT
  registry + background worker) at engine start: continuous warms one
  insert program per prompt bucket plus the single chunk program;
  batch warms prefill/decode per ``(bucket_len, batch_size)`` cell.
* **Admission control** — the waiting set is bounded by ``max_queue``;
  ``admission="block"`` makes ``submit`` wait for space,
  ``admission="reject"`` raises :class:`QueueFullError` (typed, so a
  caller can shed load).  ``close()`` drains gracefully: admitted
  requests complete (a partially full grid decodes to the last slot),
  later submits raise :class:`EngineClosedError`, and no
  scheduler/warmup thread survives (same thread-hygiene contract as
  ``training.pipeline_io``).
* **Observability** — ``serve/queue_wait`` (recorded cross-thread via
  ``tracing.record_span``), ``serve/prefill`` spans in both modes;
  ``serve/chunk`` spans (with per-dispatch ``active``/``occupancy``
  attributes) in continuous mode, ``serve/batch_form``/``serve/decode``
  in batch mode.  ``serve/qps`` and ``serve/tokens_per_sec``
  windowed-rate gauges, ``serve/slot_occupancy`` /
  ``serve/batch_occupancy`` gauges, slot-churn counters
  (``serve/slot_inserts``, ``serve/slot_retires``,
  ``serve/slot_expired``, ``serve/chunks``) and a
  ``serve/latency_seconds`` distribution.  ``python -m
  cloud_tpu.monitoring.report`` renders the serve spans as a dedicated
  breakdown, with a continuous-batching section when chunk spans are
  present.

Greedy parity is the correctness contract in both modes: for any mix of
prompt lengths, arrival times, and per-request decode budgets, a
request's tokens are identical to a direct per-request
``generation.generate`` call (slot/bucket padding is masked out of
attention, greedy decode is prefix-consistent, and the chunk program
replays generate()'s exact sampling order).  Proven in
tests/unit/test_serving.py and scripts/check_serving.py under slot
churn.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import logging
import os
import threading
import time
from concurrent.futures import Future, InvalidStateError
from typing import Dict, List, Optional, Tuple

import numpy as np

from cloud_tpu.monitoring import metrics, tracing
from cloud_tpu.serving import qos as qos_lib
from cloud_tpu.serving.qos import (
    BrownoutShedError,
    QosConfig,
    TokenStream,
)
from cloud_tpu.utils import faults

logger = logging.getLogger(__name__)

#: Scheduler-thread name (prefix match in tests' thread-leak guards).
SERVE_SCHEDULER_THREAD_NAME = "cloud-tpu-serve-scheduler"

#: Watchdog-supervised dispatch threads (``dispatch_timeout_s`` set);
#: same leak-guard prefix family as the scheduler.
SERVE_DISPATCH_THREAD_NAME = "cloud-tpu-serve-dispatch"


class QueueFullError(RuntimeError):
    """Typed rejection under ``admission="reject"``: the waiting set is at
    ``max_queue`` — shed the request or retry with backoff."""


class EngineClosedError(RuntimeError):
    """The engine is closed (or closing): the request was not admitted."""


class DeadlineExceededError(RuntimeError):
    """The request's ``deadline_s`` expired while it waited in the queue:
    it was shed before occupying a decode slot (serving the tokens late
    would waste capacity the deadline says nobody wants)."""


class DispatchTimeoutError(RuntimeError):
    """A device dispatch exceeded ``dispatch_timeout_s``: the watchdog
    failed the in-flight requests and marked the engine unhealthy
    instead of wedging the scheduler forever."""


@dataclasses.dataclass(frozen=True)
class DraftConfig:
    """The draft half of draft-and-verify speculative decoding.

    ``config`` is any ``models.transformer.TransformerConfig`` —
    typically fewer layers / narrower than the target (its vocabulary
    must match the target's: acceptance compares token ids); ``params``
    the draft model's weights.  ``spec_k`` is the verify-window width:
    the tokens the TARGET consumes — and can commit — per verify
    dispatch; the draft proposes ``spec_k - 1`` of them.  ``spec_k=1``
    degenerates to the non-speculative schedule with the draft as pure
    overhead (the parity/overhead test knob).  Speculation is
    greedy-only: the engine rejects non-zero temperature and
    repetition penalties with typed errors (token-identical non-greedy
    speculation needs rejection resampling, which the grid does not
    do).
    """

    config: object
    #: repr-suppressed: a params pytree in a logged config would dump
    #: whole weight arrays.
    params: object = dataclasses.field(repr=False, default=None)
    spec_k: int = 4

    def __post_init__(self):
        if self.spec_k < 1:
            raise ValueError(f"spec_k must be >= 1, got {self.spec_k}")
        if self.params is None:
            raise ValueError(
                "DraftConfig needs the draft model's params — without "
                "them the first proposal dispatch would die deep in the "
                "scheduler thread instead of here"
            )


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Engine knobs (all static — they define the compiled-program grid).

    ``prompt_buckets`` are the padded prompt lengths the engine compiles
    for (a request lands in the smallest bucket that fits it).  Under
    the default continuous scheduler the compiled grid is one insert
    program per prompt bucket plus ONE chunk program over the
    ``(num_slots, prompt_buckets[-1] + max_new_tokens)`` slot cache;
    ``chunk_tokens`` is the scheduling quantum (admission/retirement
    granularity vs dispatch overhead — docs/serving.md).  Under
    ``scheduler="batch"``, ``batch_buckets`` are the batch sizes (a
    formed group pads up to the smallest batch bucket that fits, so
    occupancy is explicit: 3 requests in a bucket-4 dispatch is 75%),
    the grid is the cross product x {prefill, decode}, and
    ``flush_deadline_s`` bounds how long a request may wait for
    co-batching once it is first in line.  ``max_queue``/``admission``
    are the backpressure contract in both modes (module docstring).
    """

    max_new_tokens: int = 32
    prompt_buckets: Tuple[int, ...] = (32, 128, 512)
    batch_buckets: Tuple[int, ...] = (1, 2, 4, 8)
    flush_deadline_s: float = 0.01
    max_queue: int = 256
    admission: str = "block"
    #: ``"continuous"`` (default) — slot-based in-flight decode over a
    #: persistent grid; ``"batch"`` — the PR 4 batch-synchronous path.
    scheduler: str = "continuous"
    #: Decode-slot count for the continuous grid (None: the largest
    #: batch bucket, so both schedulers size their device footprint the
    #: same way).
    num_slots: Optional[int] = None
    #: Tokens decoded per chunk dispatch in continuous mode.  Small
    #: chunks admit/retire at finer granularity (lower latency under
    #: churn); large chunks amortize host dispatch overhead.
    chunk_tokens: int = 8
    #: Shared-prefix KV cache (continuous mode): pool size in blocks.
    #: 0 (default) disables — the compatibility default.  When set, the
    #: scheduler looks up each arriving prompt's longest cached prefix,
    #: copies its KV into the slot row (``generation.
    #: copy_prefix_program``), and prefills only the uncached suffix;
    #: completed prefills donate their new full blocks back to the
    #: pool.  Greedy outputs stay token-identical either way.
    prefix_cache_blocks: int = 0
    #: Tokens per prefix block — the hit granularity (hits are whole
    #: blocks; a prompt's trailing partial block never caches).
    prefix_block_tokens: int = 16
    #: Host-DRAM second tier for the prefix cache: blocks evicted from
    #: the HBM pool demote to a bounded host-side pool of this many
    #: blocks instead of vanishing, and a hit on a demoted prefix swaps
    #: its blocks back in asynchronously (``serve/prefix_swapin``) —
    #: hot system prompts survive HBM pressure.  0 (default) disables
    #: the tier entirely (byte-identical to the single-tier cache;
    #: the ``prefix_dram_*`` health/stats keys read zero).  Requires
    #: ``prefix_cache_blocks > 0``.
    prefix_dram_blocks: int = 0
    #: Chunked prefill (continuous mode): split prompt prefill into
    #: dispatches of this many tokens, interleaved with decode chunks,
    #: so a long arrival stalls in-flight decode by at most ONE chunk
    #: instead of one full prefill.  None (default) keeps the one-shot
    #: insert prefill — the compatibility default.
    prefill_chunk_tokens: Optional[int] = None
    #: Draft-and-verify speculative decoding (continuous mode): arm with
    #: ``DraftConfig(config=..., params=..., spec_k=...)``.  ``None``
    #: (default) keeps the one-dispatch-per-token decode path
    #: byte-identical.  Greedy-only (module docstring).
    draft: Optional[DraftConfig] = None
    #: Sampling config shared by every request (static: it specializes
    #: the compiled decode program).  Default greedy.
    sample: "SampleConfig" = None  # type: ignore[assignment]
    kv_quant: bool = False
    #: Pre-compile the whole (bucket_len, batch_size) grid at start on a
    #: background worker (``training.compile_cache``).
    warmup: bool = False
    #: Seed for the engine-owned sampling rng chain (non-greedy configs).
    seed: int = 0
    #: Watchdog bound on any single device dispatch (prefill, chunk,
    #: decode).  ``None`` (default) trusts the device; when set, a
    #: dispatch exceeding it fails its requests with
    #: :class:`DispatchTimeoutError` and marks the engine unhealthy
    #: (``health()``) instead of wedging the scheduler forever.  Costs
    #: one short-lived supervision thread per dispatch — serving rigs
    #: that want an SLO on "the device answered at all" opt in.
    dispatch_timeout_s: Optional[float] = None
    #: Tensor-parallel serving slice: the ``(tp, sp)`` chip grid ONE
    #: replica spans.  ``tp`` shards params (heads/mlp/vocab) and the
    #: slot KV cache + prefix block pool by attention head — it must
    #: divide the model's ``num_heads`` (typed error otherwise); ``sp``
    #: is sequence parallelism over activations.  ``None`` or ``(1, 1)``
    #: (the default) keeps the existing single-chip path byte-identical.
    #: Greedy outputs on any slice are token-identical to single-chip
    #: ``generate()`` — sharding moves bytes, never tokens.
    mesh_shape: Optional[Tuple[int, int]] = None
    #: ``"explicit"`` (default) uses ``mesh_shape`` verbatim;
    #: ``"auto"`` asks ``parallel.planner.plan_serve_layout`` to pick
    #: the slice partition from the model's head count, the visible
    #: devices (bounded by ``mesh_shape`` when set), and
    #: ``hbm_bytes_per_chip``.
    layout: str = "explicit"
    #: Per-chip HBM budget for ``layout="auto"`` (bytes).  ``None``
    #: uses the whole slice (widest head-dividing tp) for per-request
    #: speed; a budget picks the NARROWEST tp that fits, leaving chips
    #: for more replicas.
    hbm_bytes_per_chip: Optional[int] = None
    #: Multi-tenant QoS (continuous mode): ``serving.qos.QosConfig``
    #: arms priority classes (slot admission by SLO slack + weighted
    #: fairness debt instead of arrival order) and class-aware brownout
    #: shedding.  ``None`` (default) keeps the FIFO path byte-identical
    #: — priority tags are accepted but never reorder anything, and the
    #: per-class health/stats keys read zero.  Host-side policy only:
    #: the compiled programs are untouched either way.
    qos: Optional[QosConfig] = None
    #: Decode-attention path for the continuous slot grid.  ``"xla"``
    #: (default) keeps today's programs byte-identical — plain
    #: ``_cache_attention`` over the padded slot rows, prefix hits
    #: copied into the row before decode.  ``"pallas"`` routes the
    #: chunk/prefill-chunk/verify programs through
    #: ``ops.paged_attention`` (block-table read-in-place: prefix hits
    #: ATTACH pool blocks to the slot's block table instead of
    #: dispatching ``copy_prefix_program``, and dead pages past each
    #: row's length are skipped) with the Pallas kernel forced on;
    #: ``"auto"`` takes the same paged route but lets the op's measured
    #: crossover pick kernel vs its jnp reference per shape
    #: (docs/KERNELS.md).  Greedy outputs are token-identical on every
    #: setting.  Continuous-scheduler only.
    decode_kernel: str = "xla"
    #: Disaggregated-serving role this engine plays in a fleet:
    #: ``"prefill"`` (serves the prefill leg of split requests),
    #: ``"decode"`` (serves handoff-carrying decode legs), or
    #: ``"both"`` (default — the colocated engine, byte-identical to
    #: today; the ``role``/handoff health keys read ``"both"``/zero).
    #: Routing policy lives in the fleet; the engine only reports the
    #: role and accepts the handoff submit kwargs, which themselves
    #: need the continuous scheduler plus a prefix pool (the handoff IS
    #: cross-replica prefix-cache seeding — docs/fleet.md).  A fleet
    #: replica may override per-replica via :meth:`ServingEngine.
    #: set_role`, so one factory serves mixed-role fleets.
    role: str = "both"
    #: TTL (seconds) on the router-facing ``hot_prefixes()`` summary:
    #: entries for prefixes not HIT within it age out of ``health()``'s
    #: ``cached_prefixes``, so a replica that lost its hot tenant stops
    #: advertising stale cached-prefix credit to the cost-model router.
    #: ``None`` (default) never expires — byte-identical to today.
    prefix_summary_ttl_s: Optional[float] = None
    #: Scheduler pipelining depth.  ``1`` (default) is the strictly
    #: synchronous loop — dispatch a chunk, block on its emissions,
    #: mutate slots, dispatch the next — byte-identical to today.  ``2``
    #: keeps a second chunk in flight: chunk N+1 is dispatched against
    #: the device-resident slot state *before* chunk N's emissions are
    #: synchronized, and N drains (non-blocking device→host copy) while
    #: the device runs N+1, hiding the host scheduling bubble.  Slot
    #: mutations from a drain apply to the *next* dispatch (one pass
    #: stale); the chunk program's active mask keeps a speculatively
    #: dispatched chunk for a just-finished slot emitting only masked
    #: tokens, so greedy outputs are token-identical to depth 1
    #: (docs/serving.md "Pipelined scheduling").  Kill switch:
    #: ``CLOUD_TPU_PIPELINE=0`` forces depth 1 at engine build.
    pipeline_depth: int = 1

    def __post_init__(self):
        from cloud_tpu.models.generation import SampleConfig

        if self.sample is None:
            object.__setattr__(self, "sample",
                               SampleConfig(temperature=0.0))
        if self.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {self.max_new_tokens}"
            )
        for name in ("prompt_buckets", "batch_buckets"):
            buckets = tuple(getattr(self, name))
            object.__setattr__(self, name, buckets)
            if not buckets or any(b < 1 for b in buckets):
                raise ValueError(f"{name} must be non-empty and positive")
            if list(buckets) != sorted(set(buckets)):
                raise ValueError(
                    f"{name} must be strictly increasing, got {buckets}"
                )
        if self.admission not in ("block", "reject"):
            raise ValueError(
                f"admission must be 'block' or 'reject', "
                f"got {self.admission!r}"
            )
        if self.scheduler not in ("continuous", "batch"):
            raise ValueError(
                f"scheduler must be 'continuous' or 'batch', "
                f"got {self.scheduler!r}"
            )
        if self.num_slots is None:
            object.__setattr__(self, "num_slots", self.batch_buckets[-1])
        if self.num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {self.num_slots}")
        if self.chunk_tokens < 1:
            raise ValueError(
                f"chunk_tokens must be >= 1, got {self.chunk_tokens}"
            )
        if self.prefix_cache_blocks < 0:
            raise ValueError(
                f"prefix_cache_blocks must be >= 0, got "
                f"{self.prefix_cache_blocks}"
            )
        if self.prefix_block_tokens < 1:
            raise ValueError(
                f"prefix_block_tokens must be >= 1, got "
                f"{self.prefix_block_tokens}"
            )
        if self.prefix_dram_blocks < 0:
            raise ValueError(
                f"prefix_dram_blocks must be >= 0, got "
                f"{self.prefix_dram_blocks}"
            )
        if self.prefix_dram_blocks and not self.prefix_cache_blocks:
            raise ValueError(
                "prefix_dram_blocks (the host-DRAM tier) needs "
                "prefix_cache_blocks > 0 — there is no HBM pool to "
                "demote from or swap back into"
            )
        if (self.prefill_chunk_tokens is not None
                and self.prefill_chunk_tokens < 1):
            raise ValueError(
                f"prefill_chunk_tokens must be >= 1 or None, got "
                f"{self.prefill_chunk_tokens}"
            )
        if self.scheduler == "batch" and (
            self.prefix_cache_blocks or self.prefill_chunk_tokens is not None
        ):
            raise ValueError(
                "prefix_cache_blocks / prefill_chunk_tokens need the "
                "continuous scheduler (slot-grid prefill); the batch "
                "path has no per-slot cache rows to reuse"
            )
        if self.draft is not None:
            if self.scheduler != "continuous":
                raise ValueError(
                    "draft= (speculative decoding) needs the continuous "
                    "scheduler — the verify program is a slot-grid "
                    "dispatch"
                )
            if self.sample.temperature != 0.0:
                raise ValueError(
                    "draft= (speculative decoding) requires greedy "
                    f"sampling; got temperature={self.sample.temperature}"
                    " (token-identical non-greedy speculation needs "
                    "rejection resampling)"
                )
            if self.sample.repetition_penalty != 1.0:
                raise ValueError(
                    "draft= (speculative decoding) does not compose with "
                    "repetition_penalty: the verify window's emissions "
                    "would each need the penalty state of the emissions "
                    "before them"
                )
        if self.max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {self.max_queue}")
        if self.flush_deadline_s < 0:
            raise ValueError("flush_deadline_s must be >= 0")
        if self.dispatch_timeout_s is not None and self.dispatch_timeout_s <= 0:
            raise ValueError(
                f"dispatch_timeout_s must be > 0 or None, "
                f"got {self.dispatch_timeout_s}"
            )
        if self.qos is not None:
            if not isinstance(self.qos, QosConfig):
                raise ValueError(
                    f"qos must be a serving.qos.QosConfig, got "
                    f"{type(self.qos).__name__}"
                )
            if self.scheduler != "continuous":
                raise ValueError(
                    "qos= (priority scheduling) needs the continuous "
                    "scheduler — slot admission is where the class "
                    "order is enforced; the batch path forms batches "
                    "by bucket, not by request"
                )
        if self.decode_kernel not in ("auto", "pallas", "xla"):
            raise ValueError(
                f"decode_kernel must be 'auto', 'pallas', or 'xla', "
                f"got {self.decode_kernel!r}"
            )
        if self.role not in ("prefill", "decode", "both"):
            raise ValueError(
                f"role must be 'prefill', 'decode', or 'both', "
                f"got {self.role!r}"
            )
        if self.role != "both" and (
            self.scheduler != "continuous" or not self.prefix_cache_blocks
        ):
            raise ValueError(
                "role= (disaggregated serving) needs the continuous "
                "scheduler and prefix_cache_blocks > 0 — the KV handoff "
                "exports/imports prefix-pool blocks"
            )
        if (self.prefix_summary_ttl_s is not None
                and self.prefix_summary_ttl_s <= 0):
            raise ValueError(
                f"prefix_summary_ttl_s must be > 0 or None, got "
                f"{self.prefix_summary_ttl_s}"
            )
        if self.decode_kernel != "xla" and self.scheduler != "continuous":
            raise ValueError(
                "decode_kernel= (paged decode attention) needs the "
                "continuous scheduler — the block table pages slot rows "
                "of the persistent grid; the batch path re-prefills a "
                "fresh cache per batch"
            )
        if self.pipeline_depth not in (1, 2):
            raise ValueError(
                f"pipeline_depth must be 1 or 2, got "
                f"{self.pipeline_depth!r}"
            )
        if self.pipeline_depth > 1 and self.scheduler != "continuous":
            raise ValueError(
                "pipeline_depth=2 (pipelined scheduling) needs the "
                "continuous scheduler — the in-flight ring overlaps "
                "chunk dispatches on the persistent slot grid; the "
                "batch path has no standing state to dispatch against"
            )
        if self.layout not in ("explicit", "auto"):
            raise ValueError(
                f"layout must be 'explicit' or 'auto', got {self.layout!r}"
            )
        if self.mesh_shape is not None:
            shape = tuple(int(v) for v in self.mesh_shape)
            if len(shape) != 2 or any(v < 1 for v in shape):
                raise ValueError(
                    f"mesh_shape must be a (tp, sp) pair of positive "
                    f"ints, got {self.mesh_shape!r}"
                )
            object.__setattr__(self, "mesh_shape", shape)
        if (self.hbm_bytes_per_chip is not None
                and self.hbm_bytes_per_chip < 1):
            raise ValueError(
                f"hbm_bytes_per_chip must be >= 1 or None, got "
                f"{self.hbm_bytes_per_chip}"
            )


@dataclasses.dataclass(frozen=True)
class ServeResult:
    """One resolved request.

    ``tokens`` is the request's generated row, length =
    its ``max_new_tokens`` (eos included where sampled, pad after it) —
    byte-identical to ``generation.generate``'s row for the same prompt.
    ``num_generated`` counts real tokens (eos included).  The batch
    fields record how the request was served (occupancy debugging);
    under the continuous scheduler ``batch_size`` is the grid's
    ``num_slots``.
    """

    tokens: np.ndarray
    num_generated: int
    bucket_len: int
    batch_size: int
    latency_seconds: float
    #: Submit -> first token known.  Under the continuous scheduler the
    #: first token is sampled when the prefill lands, so this isolates
    #: queueing + prefill (what prefix caching and chunked prefill move)
    #: from decode.  The batch scheduler only materializes tokens when
    #: the whole batch decode returns, so there it equals latency.
    ttft_seconds: float = 0.0
    #: Fleet-wide trace id when the request carried a ``TraceContext``
    #: (``tracing.new_trace_context``); None otherwise — the key that
    #: joins this result to its spans in a merged timeline.  Rides
    #: ``dataclasses.replace`` untouched, so the fleet's latency rebase
    #: on failover keeps the identity.
    trace_id: Optional[str] = None
    #: KV handoff payload exported for this request (disaggregated
    #: serving: ``submit(handoff_export=True)`` on a prefill replica) —
    #: the prompt's cached prefix blocks serialized host-side, dict
    #: shape per ``fleet.disagg``.  None everywhere else (the default
    #: fleet never builds one — pinned byte-identical).
    handoff: Optional[dict] = None


#: eq=False: requests are removed from mid-queue by IDENTITY (QoS
#: admission, brownout shed) — a generated __eq__ would compare numpy
#: prompt arrays element-wise and raise on the first non-match.
@dataclasses.dataclass(eq=False)
class _Request:
    prompt: np.ndarray
    prompt_len: int
    max_new_tokens: int
    bucket_len: int
    future: Future
    submitted: float  # perf_counter
    #: Absolute perf_counter time after which the request is shed from
    #: the queue instead of served (None: wait forever).
    deadline: Optional[float] = None
    #: QoS class name (resolved at submit when a QosConfig is armed;
    #: carried-but-inert on the FIFO path).
    priority: Optional[str] = None
    #: Per-token delivery (``submit(stream=True)``): fed from the
    #: emission path as chunks commit, closed by the future's
    #: done-callback.  None for plain futures.
    stream: Optional[TokenStream] = None
    #: Cross-layer per-token hook (the fleet's stream forwarding):
    #: called as ``on_token(index, token)`` from the scheduler thread.
    on_token: Optional[object] = None
    #: Fleet-minted ``tracing.TraceContext`` (None = untraced).  Inert
    #: unless a collector is active: no span gains attributes from it
    #: while tracing is off, so the disabled span set stays
    #: byte-identical.
    trace: Optional[tracing.TraceContext] = None
    #: Disaggregated prefill leg: export the prompt's cached prefix
    #: blocks host-side after prefill (``ServeResult.handoff``).
    handoff_export: bool = False
    #: Disaggregated decode leg: a handoff payload to seed the prefix
    #: cache with BEFORE this request's own prefix lookup, so admission
    #: sees an ordinary hit.  None on every non-handoff request.
    handoff: Optional[dict] = None

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now >= self.deadline

    @property
    def trace_id(self) -> Optional[str]:
        return self.trace.trace_id if self.trace is not None else None


def _trace_attrs(request: _Request, **attrs) -> dict:
    """Span attributes + the request's ``trace_id`` when it carries a
    trace context.  Untraced requests get exactly the attrs passed in,
    so pre-tracing span payloads stay byte-identical."""
    if request.trace is not None:
        attrs["trace_id"] = request.trace.trace_id
    return attrs


@dataclasses.dataclass
class _Slot:
    """Host mirror of one live decode slot (scheduler-thread only):
    which request occupies it and the tokens emitted for it so far.
    The device-side twin is the slot's row of the grid state
    (``generation.init_slot_state``); host and device transition in
    lockstep — both retire a slot exactly when its emission count hits
    the request's ``max_new_tokens`` or the last emission was eos.
    ``prefix_nodes`` are the prefix-cache blocks this slot holds
    references on (copied-in hit + saved-out new blocks), released when
    the slot retires."""

    request: _Request
    tokens: List[int]
    prefix_nodes: List[object] = dataclasses.field(default_factory=list)
    first_token_ts: Optional[float] = None
    #: Tokens already delivered to the request's stream/on_token hook
    #: (prefix of ``tokens``, capped at the request's budget).
    streamed: int = 0
    #: Exported KV handoff payload (``handoff_export`` requests only):
    #: built right after the prefix save, carried to ``_retire_slot``
    #: which rides it out on the result.
    handoff: Optional[dict] = None


@dataclasses.dataclass
class _PrefillTask:
    """A request mid-prefill (chunked prefill and/or a prefix hit): the
    slot is claimed — ``_slot_table`` already holds its host mirror, so
    a crash fails it — but decode has not started.  ``next_pos`` is the
    first prompt position not yet prefilled; the scheduler advances the
    OLDEST task by one ``chunk_width`` dispatch per pass, so in-flight
    decode never waits more than one chunk on a long arrival."""

    request: _Request
    slot: int
    chunk_width: int
    next_pos: int
    #: The acquired prefix hit (its KV was copied in before the first
    #: chunk), or None on a cold prefill.
    hit: Optional[object] = None


@dataclasses.dataclass
class _InflightChunk:
    """One dispatched-but-undrained chunk in the pipelined scheduler's
    in-flight ring (``pipeline_depth=2``; scheduler-thread only).

    Holds the *device-side* emission arrays exactly as the chunk
    program returned them — the drain half materializes them with a
    blocking host copy (``engine._to_host``) one pass later, after the
    NEXT chunk has already been dispatched, so the host-side copy wait
    overlaps device compute.  A slot occupying a row here is never in
    ``_free_slots`` (retirement happens at drain), so an in-flight
    chunk can never describe a slot that was re-assigned under it.
    """

    #: Device array of emitted token ids, ``[num_slots, width]``.
    toks: object
    #: Device bool array — which emissions are live, same shape.
    valid: object
    #: Device int32 ``[emitted_count, active_count]`` summary from the
    #: chunk program (``with_summary=True``) — rides along so callers
    #: that only need occupancy never block on the full emission grid.
    summary: object
    #: Emission width: ``chunk_tokens`` (decode) or ``spec_k`` (verify).
    width: int
    #: ``"chunk"`` or ``"verify"`` — picks the terminal span name and
    #: the stats the drain updates.
    kind: str
    #: ``len(_active_slots)`` at dispatch (the verify drain's
    #: accept-rate denominator).
    active: int
    #: Span attributes captured at dispatch (slots/chunk/active/slice/
    #: traces) — the drain adds tokens/occupancy and records the span
    #: over the full dispatch→drain interval.
    span_attrs: dict
    #: ``time.perf_counter()`` bracketing the dispatch call itself.
    dispatch_start: float
    dispatch_end: float


class _Cell:
    """The compiled-program pair for one (bucket_len, batch_size) point.

    ``AotStep`` wrappers (training.compile_cache): a warmed cell
    dispatches the pre-compiled executable; an un-warmed (or mismatched)
    one falls back to the jitted function, which compiles on first use —
    warmup makes the engine fast, never wrong.
    """

    def __init__(self, engine: "ServingEngine", bucket_len: int,
                 batch_size: int):
        import functools

        import jax

        from cloud_tpu.models import generation
        from cloud_tpu.training import compile_cache

        cfg = engine.serve_config
        self.bucket_len = bucket_len
        self.batch_size = batch_size
        prefill_fn = jax.jit(functools.partial(
            generation.prefill_program,
            config=engine.config, max_new_tokens=cfg.max_new_tokens,
            rules=engine.rules, mesh=engine.mesh, kv_quant=cfg.kv_quant,
        ))

        # Positional-arg wrapper: AotStep (and AOT-compiled executables)
        # dispatch positionally, but decode_program's rng is keyword-only.
        def decode_positional(params, cache, logits0, prompt_lens, rng):
            return generation.decode_program(
                params, cache, logits0, prompt_lens, engine.config,
                max_new_tokens=cfg.max_new_tokens, sample=cfg.sample,
                rng=rng, rules=engine.rules, mesh=engine.mesh,
            )

        decode_fn = jax.jit(decode_positional)
        tag = f"L{bucket_len}_B{batch_size}"
        self.prefill = compile_cache.AotStep(
            prefill_fn, label=f"serve/prefill_{tag}"
        )
        self.decode = compile_cache.AotStep(
            decode_fn, label=f"serve/decode_{tag}"
        )


class _DeferredPayload:
    """A demoted block's host bytes, not yet downloaded.

    Inside a demotion burst (``_demote_burst``), ``_demote_block``
    returns one of these instead of paying a supervised download per
    evicted block; the burst's exit flushes ALL pending downloads as
    one batched dispatch under ONE watchdog window
    (``_flush_demotes``), mirroring how the swap-in side budgets a
    whole plan.  Safe because nothing materializes a demoted payload
    until after the burst scope closes: the save/swap-in programs that
    reuse the evicted rows dispatch strictly AFTER the manager call the
    burst wraps, and ``_dispatch_swapin`` resolves placeholders via
    ``_resolve_payload`` at upload time.  Scheduler-thread only.
    """

    __slots__ = ("value", "filled")

    def __init__(self):
        self.value = None
        self.filled = False


def _resolve_payload(payload):
    """A demoted block's actual host bytes (unwraps a burst-deferred
    placeholder; anything else passes through)."""
    if isinstance(payload, _DeferredPayload):
        if not payload.filled:
            raise RuntimeError(
                "deferred demote payload read before its burst flushed "
                "— demote downloads must complete before row reuse"
            )
        return payload.value
    return payload


class ServingEngine:
    """In-process continuous-batching server over ``generation`` (module
    docstring; ``scheduler="batch"`` selects the batch-synchronous
    path).  Construct, ``submit()`` concurrently from any thread,
    ``close()`` when done (or use as a context manager)."""

    def __init__(
        self,
        params,
        config,
        serve_config: Optional[ServeConfig] = None,
        *,
        rules=None,
        mesh=None,
        start: bool = True,
    ):
        import jax

        from cloud_tpu.models import generation
        from cloud_tpu.parallel import mesh as mesh_lib
        from cloud_tpu.parallel.sharding import DEFAULT_RULES

        self.params = params
        self.config = config
        self.serve_config = serve_config or ServeConfig()
        self.rules = rules if rules is not None else DEFAULT_RULES
        self.mesh = mesh if mesh is not None else mesh_lib.get_global_mesh()
        #: The replica's slice: (tp, sp) and total chips (= tp * sp).
        #: (1, 1)/1 on the single-chip path; a ServeConfig.mesh_shape /
        #: layout="auto" slice builds its own TP(xSP) mesh (flagged so
        #: param placement only happens for engine-owned meshes — a
        #: caller-provided mesh keeps the caller's placement).
        self._built_serving_mesh = False
        self._slice_shape, self._slice_chips = self._resolve_serving_mesh()
        generation.check_inference_supported(
            config, self.rules, self.mesh, "serving"
        )
        if self._built_serving_mesh:
            self._shard_params()
        metrics.gauge_set("serve/slice_chips", self._slice_chips)
        # Engine-owned rng chain: split per batch (carried but
        # unobservable under greedy — one decode signature either way).
        self._rng = jax.random.PRNGKey(self.serve_config.seed)

        self._cond = threading.Condition()
        #: bucket_len -> FIFO of waiting _Requests (guarded by _cond).
        self._pending: Dict[int, collections.deque] = {}
        self._waiting = 0
        self._closed = False
        self._draining = True
        self._thread: Optional[threading.Thread] = None
        self._cells: Dict[Tuple[int, int], _Cell] = {}
        self._warmup_plan = None
        #: Why the engine is unhealthy (watchdog fire, scheduler crash);
        #: None while healthy.  Written by the scheduler, read by
        #: ``health()`` from any thread (str swap — atomic enough).
        self._unhealthy_reason: Optional[str] = None
        #: Watchdog-abandoned dispatch threads, joined (bounded) by
        #: close() so a finite hang never leaks past the engine's life.
        self._orphan_dispatches: List[threading.Thread] = []
        self._last_dispatch_ts: Optional[float] = None
        #: Timeline lane (synthetic Chrome-trace pid) this engine's
        #: scheduler stamps its spans with; None = the real process pid.
        #: Set by the owning fleet replica via :meth:`set_trace_lane`.
        self._trace_lane: Optional[int] = None
        #: Live demotion burst: while a prefix-cache insert/swap-in
        #: reservation runs, demote downloads are DEFERRED into this
        #: list and flushed as one batched dispatch under ONE watchdog
        #: window at burst exit (``_flush_demotes``) — mirroring how
        #: the swap-in side budgets a whole plan, instead of paying a
        #: supervised thread per evicted block.  Scheduler-thread only.
        self._demote_batch: Optional[List[tuple]] = None
        #: This engine's disaggregated-serving role (``"both"`` keeps
        #: the colocated default).  Plain str swap — the owning fleet
        #: replica may restamp it via :meth:`set_role`.
        self._role = self.serve_config.role
        #: Rows of the batch currently on the device (batch scheduler;
        #: the continuous path reads its slot table instead).  Plain int
        #: swap — written by the scheduler, read by ``health()``.
        self._inflight_rows = 0

        self._stats_lock = threading.Lock()
        self._stats = {
            "requests": 0, "completed": 0, "failed": 0, "rejected": 0,
            "batches": 0, "slots": 0, "real_rows": 0,
            "generated_tokens": 0,
            # Token-level decode accounting, comparable across the two
            # schedulers: useful emissions vs dispatched emission slots.
            "decode_slot_steps": 0, "useful_decode_tokens": 0,
            # Continuous-mode churn counters.
            "inserts": 0, "retires": 0, "expired": 0, "chunks": 0,
            # Prefix-cache / chunked-prefill counters (0 when disabled).
            "prefill_chunks": 0, "prefix_hits": 0, "prefix_misses": 0,
            # Paged-attention block-table attaches (0 with
            # decode_kernel="xla" — every hit then goes through the
            # copy program instead).
            "prefix_attaches": 0,
            # Speculative-decoding counters (0 when draft=None):
            # spec_chunks = verify (target) dispatches, spec_emitted =
            # tokens they committed, spec_proposed/accepted = draft
            # tokens offered/committed — acceptance is their quotient.
            "spec_chunks": 0, "spec_emitted": 0,
            "spec_proposed": 0, "spec_accepted": 0, "draft_prefills": 0,
            # Robustness counters: queue-shed deadlines, watchdog fires.
            "shed": 0, "watchdog_timeouts": 0,
            # Requests submitted carrying a TraceContext (0 with
            # tracing off — stable schema either way).
            "traced": 0,
            # QoS brownout sheds (0 unless qos arms a brownout depth).
            "brownout_shed": 0,
            # Disaggregated-serving KV handoff counters (all 0 with
            # role="both" and no handoff submits — stable schema).
            "handoff_exports": 0, "handoff_export_blocks": 0,
            "handoff_imports": 0, "handoff_import_blocks": 0,
        }
        #: QoS state: None keeps the FIFO path byte-identical (every
        #: policy branch below checks this).  The scheduler object owns
        #: the fairness-debt state; per-class counters feed health()/
        #: stats() (zeros when off — stable schema).
        self._qos = self.serve_config.qos
        self._qos_sched = (
            qos_lib.QosScheduler(self._qos) if self._qos else None
        )
        classes = (
            tuple(self._qos.classes) if self._qos
            else qos_lib.DEFAULT_PRIORITIES
        )
        self._class_names = classes
        self._class_completed = {c: 0 for c in classes}
        self._class_shed = {c: 0 for c in classes}
        self._qps = metrics.WindowedRate("serve/qps", window=16)
        self._tokens_rate = metrics.WindowedRate(
            "serve/tokens_per_sec", window=256
        )

        self._continuous = self.serve_config.scheduler == "continuous"
        #: Speculative decoding armed (continuous branch may flip it).
        self._spec = False
        #: Paged decode attention armed (continuous branch may flip it);
        #: ``_block_table`` is its host-side [num_slots, n_pages] mirror
        #: (None on the XLA path and under the batch scheduler).
        self._paged = False
        self._block_table = None
        if self._continuous:
            cfg = self.serve_config
            #: Slot cache rows must fit the largest bucket's prompt plus
            #: the engine-wide decode budget.
            self._max_len = cfg.prompt_buckets[-1] + cfg.max_new_tokens

            def make_grid():
                return generation.init_slot_cache(
                    config, cfg.num_slots, self._max_len, rules=self.rules,
                    mesh=self.mesh, kv_quant=cfg.kv_quant,
                )

            # Under a serving slice the grid is born head-sharded:
            # building it INSIDE jit binds init_slot_cache's logical-
            # axis constraints to the mesh, so every leaf lands
            # [L, slots, S, H/tp, hd] per chip.  Single-chip keeps the
            # eager allocation — byte-identical to the pre-slice path.
            self._grid_cache = (
                jax.jit(make_grid)() if self._slice_chips > 1
                else make_grid()
            )
            self._slot_state = generation.init_slot_state(
                config, cfg.num_slots, sample=cfg.sample
            )
            if self._slice_chips > 1:
                # Per-slot scalars are tiny: replicate them across the
                # slice so every chip samples from the same state.
                from jax.sharding import NamedSharding, PartitionSpec

                self._slot_state = jax.device_put(
                    self._slot_state,
                    NamedSharding(self.mesh, PartitionSpec()),
                )
            #: Scheduler-thread-only slot bookkeeping (the host mirror).
            self._slot_table: List[Optional[_Slot]] = [None] * cfg.num_slots
            self._free_slots = list(range(cfg.num_slots))[::-1]
            self._active_slots: set = set()
            self._insert_cells: Dict[int, "compile_cache.AotStep"] = {}
            #: Requests mid-prefill (chunked prefill / prefix hits):
            #: FIFO, advanced one chunk dispatch per scheduler pass.
            self._prefill_tasks: collections.deque = collections.deque()
            self._chunk_prefill_cells: Dict[int, "compile_cache.AotStep"] = {}
            self._finalize_step = None
            self._copy_cells: Dict[int, "compile_cache.AotStep"] = {}
            self._save_cells: Dict[int, "compile_cache.AotStep"] = {}
            #: The shared-prefix block pool + its host-side radix
            #: bookkeeping (None unless prefix_cache_blocks > 0).
            self._prefix = None
            self._prefix_pool = None
            if cfg.prefix_cache_blocks:
                from cloud_tpu.serving.prefix_cache import PrefixCacheManager

                self._prefix = PrefixCacheManager(
                    cfg.prefix_cache_blocks, cfg.prefix_block_tokens,
                    dram_blocks=cfg.prefix_dram_blocks,
                    demote_fn=(
                        self._demote_block if cfg.prefix_dram_blocks
                        else None
                    ),
                    summary_ttl_s=cfg.prefix_summary_ttl_s,
                )

                def make_pool():
                    return generation.init_prefix_pool(
                        config, cfg.prefix_cache_blocks,
                        cfg.prefix_block_tokens, rules=self.rules,
                        mesh=self.mesh, kv_quant=cfg.kv_quant,
                    )

                # The block pool shards by head exactly like the slot
                # grid (same pytree structure), so pool<->slot copies
                # stay chip-local — no resharding on the hit path.
                self._prefix_pool = (
                    jax.jit(make_pool)() if self._slice_chips > 1
                    else make_pool()
                )
            # Engine device-state lives WITH the params: the init
            # programs above land on the process default device, so on
            # multi-device hosts (a fleet pinning one replica's params
            # per device) the grid, slot state, and pool must be
            # re-committed to the params' device or the first dispatch
            # raises on mixed committed placements.
            if self.mesh is None:
                device = self._params_device()
                if device is not None:
                    self._grid_cache = jax.device_put(
                        self._grid_cache, device
                    )
                    self._slot_state = jax.device_put(
                        self._slot_state, device
                    )
                    if self._prefix_pool is not None:
                        self._prefix_pool = jax.device_put(
                            self._prefix_pool, device
                        )
            #: Paged decode attention (``decode_kernel != "xla"``): the
            #: slot grid's attention reads KV through a per-slot block
            #: table — page p of a row resolves to a prefix-pool block
            #: (entry >= 0) or the slot row itself (-1) — so a prefix
            #: hit ATTACHES pool blocks instead of dispatching the copy
            #: program, and pages past each row's length are skipped.
            #: Page size is ``prefix_block_tokens`` (hits are whole
            #: blocks, so attached pages align by construction).
            self._paged = cfg.decode_kernel != "xla"
            #: "pallas" forces the kernel; "auto" defers to the op's
            #: measured-crossover dispatch (kernel on eligible TPU
            #: shapes, jnp paged reference elsewhere).
            self._paged_use_pallas = (
                True if cfg.decode_kernel == "pallas" else None
            )
            if self._paged:
                n_pages = -(-self._max_len // cfg.prefix_block_tokens)
                self._block_table = np.full(
                    (cfg.num_slots, n_pages), -1, np.int32
                )
            #: Python-trace counters: the retrace guard for "one chunk
            #: compile serves the whole run" (tests/helpers/retrace_guard
            #: idiom — the wrapped body executes only while tracing).
            self._chunk_traces = 0
            self._insert_traces = 0
            self._prefill_chunk_traces = 0
            self._finalize_traces = 0
            self._copy_traces = 0
            self._save_traces = 0
            self._download_traces = 0
            self._swapin_traces = 0
            #: The DRAM-tier block movers (built on demand; one compile
            #: each — block index and payload shapes are static).
            self._download_step = None
            self._swapin_step = None
            self._upload_traces = 0
            self._upload_step = None
            self._export_traces = 0
            self._export_step = None
            self._draft_traces = 0
            self._verify_traces = 0
            self._draft_prefill_traces = 0
            # Donating the grid through each dispatch keeps the cache
            # update in place; CPU ignores donation with a warning, so
            # only ask for it where the backend honors it.
            self._donate = jax.default_backend() != "cpu"
            #: Effective pipelining depth: the config's, unless the
            #: CLOUD_TPU_PIPELINE=0 kill switch forces the synchronous
            #: loop (same env idiom as CLOUD_TPU_TRACE).  Resolved once
            #: at build — flipping the env mid-run does nothing.
            self._pipe_depth = cfg.pipeline_depth
            if os.environ.get("CLOUD_TPU_PIPELINE", "1") == "0":
                self._pipe_depth = 1
            #: Dispatched-but-undrained chunks, oldest first
            #: (scheduler-thread only).  Empty at every pass boundary
            #: at depth 1 — the synchronous loop never grows it, so
            #: the default path stays byte-identical.
            self._inflight: collections.deque = collections.deque()
            #: Rolling dispatch→dispatch host gaps (ms) — the bubble
            #: the pipeline exists to hide.  Tracked at every depth
            #: (host-side bookkeeping only; no spans at depth 1) so
            #: bench probes can compare p50/p99 across arms.
            self._dispatch_gaps: collections.deque = collections.deque(
                maxlen=512
            )
            self._last_chunk_dispatch_end: Optional[float] = None
            self._chunk_step = self._make_chunk_step()
            #: Speculative decoding (None unless ServeConfig.draft):
            #: the draft model's own slot cache + its program cells and
            #: a rolling per-dispatch (accepted, proposed) window for
            #: health()'s acceptance rate.
            self._spec = cfg.draft is not None
            self._draft_cache = None
            self._draft_step = None
            self._verify_step = None
            self._draft_prefill_cells: Dict[int, "compile_cache.AotStep"] = {}
            self._accept_window: collections.deque = collections.deque(
                maxlen=64
            )
            if self._spec:
                self._init_draft()

        if self.serve_config.warmup:
            self._start_warmup()
        if start:
            self.start()

    # -- sharded serving ---------------------------------------------------

    def _resolve_serving_mesh(self) -> Tuple[Tuple[int, int], int]:
        """Build the replica's TP(xSP) serving mesh from ``ServeConfig``.

        Returns ``((tp, sp), chips)``.  With ``mesh_shape`` unset (or
        1x1) and ``layout="explicit"`` this does NOTHING — ``self.mesh``
        stays exactly what the caller passed (usually None), which is
        the byte-identical single-chip default; a caller-provided mesh
        is honored as-is and only described here.  A nontrivial
        ``mesh_shape``/``layout="auto"`` builds a fresh mesh over the
        first ``tp * sp`` visible devices, with the head-divisibility
        contract enforced as a typed error.
        """
        cfg = self.serve_config
        wants = cfg.layout == "auto" or (
            cfg.mesh_shape is not None and cfg.mesh_shape != (1, 1)
        )
        have_mesh = self.mesh is not None and not getattr(
            self.mesh, "empty", False
        )
        if not wants:
            if have_mesh:
                # Caller-provided (or global) mesh: honored as-is — the
                # caller owns param placement, the engine never touches
                # it.  The slice is the mesh's SERVING-parallel extent,
                # tp x sp: a pure dp/fsdp training mesh reads (1, 1)/1
                # and keeps the exact pre-slice engine behavior (no
                # reshard spans, eager grid init).
                shape = dict(self.mesh.shape)
                from cloud_tpu.parallel import mesh as mesh_lib

                tp = int(shape.get(mesh_lib.AXIS_TP, 1))
                sp = int(shape.get(mesh_lib.AXIS_SP, 1))
                return (tp, sp), tp * sp
            return (1, 1), 1
        if have_mesh:
            raise ValueError(
                "pass either an explicit mesh= or "
                "ServeConfig.mesh_shape/layout='auto', not both — the "
                "engine builds its own serving mesh from the config"
            )
        import jax

        from cloud_tpu.parallel import mesh as mesh_lib

        devices = jax.devices()
        bound = len(devices)
        if cfg.mesh_shape is not None:
            want = cfg.mesh_shape[0] * cfg.mesh_shape[1]
            if want > bound:
                raise ValueError(
                    f"mesh_shape={cfg.mesh_shape} needs {want} "
                    f"device(s); only {bound} visible"
                )
        num_heads = int(self.config.num_heads)
        if cfg.layout == "auto":
            from cloud_tpu.parallel import planner
            # Generic array-pytree byte sum (despite the name — it is
            # the repo's one accounting helper for this).
            from cloud_tpu.training.optimizers import optimizer_state_bytes

            draft_bytes = 0
            if cfg.draft is not None:
                # The draft rides every chip (replicated unless its head
                # count happens to divide tp — budget the worst case):
                # params plus its own slot KV grid, no prefix pool.
                draft_bytes = optimizer_state_bytes(cfg.draft.params) + (
                    self._kv_bytes_estimate(
                        cfg.draft.config, include_prefix=False
                    )
                )
            plan = planner.plan_serve_layout(
                num_heads=num_heads,
                num_devices=(
                    cfg.mesh_shape[0] * cfg.mesh_shape[1]
                    if cfg.mesh_shape is not None else bound
                ),
                param_bytes=optimizer_state_bytes(self.params),
                kv_bytes=self._kv_bytes_estimate(),
                draft_bytes=draft_bytes,
                hbm_bytes_per_chip=cfg.hbm_bytes_per_chip,
            )
            tp, sp = plan.tp, plan.sp
            logger.info("serving layout auto-picked: %s", plan.description)
        else:
            tp, sp = cfg.mesh_shape
            if num_heads % tp:
                raise ValueError(
                    f"mesh_shape tp={tp} does not divide "
                    f"num_heads={num_heads}: the slot KV cache shards "
                    "by attention head, so the tensor-parallel degree "
                    "must divide the model's head count"
                )
        chips = tp * sp
        if chips <= 1:
            return (1, 1), 1
        self.mesh = mesh_lib.MeshSpec(
            sizes={mesh_lib.AXIS_SP: sp, mesh_lib.AXIS_TP: tp}
        ).build(devices[:chips])
        self._built_serving_mesh = True
        return (tp, sp), chips

    def _kv_bytes_estimate(self, model_config=None,
                           include_prefix: bool = True) -> int:
        """Total KV bytes the engine will allocate (slot grid + prefix
        pool for the continuous scheduler, the largest batch cell
        otherwise) — the planner's auto-layout input, an estimate, not
        an allocator.  ``model_config`` sizes a different model's cache
        over the same grid (the speculative draft, which gets no
        prefix pool — ``include_prefix=False``)."""
        cfg = self.serve_config
        c = model_config if model_config is not None else self.config
        itemsize = 1 if cfg.kv_quant else np.dtype(c.dtype).itemsize
        # Per cached position: k + v across every layer and head (+ the
        # two f32 scale columns when quantized).
        per_pos = 2 * c.num_layers * c.num_heads * (
            c.head_dim * itemsize + (4 if cfg.kv_quant else 0)
        )
        max_len = cfg.prompt_buckets[-1] + cfg.max_new_tokens
        if cfg.scheduler == "continuous":
            positions = cfg.num_slots * max_len
            if include_prefix:
                positions += (
                    cfg.prefix_cache_blocks * cfg.prefix_block_tokens
                )
        else:
            positions = cfg.batch_buckets[-1] * max_len
        return per_pos * positions

    def _shard_params(self) -> None:
        """Place params per the rules table — heads/mlp/vocab dims over
        ``tp`` (the plan :func:`parallel.planner.plan_serve_layout`
        picked or ``mesh_shape`` pinned), everything else replicated —
        so every generation program lowers against sharded weights."""
        import jax

        from cloud_tpu.models import transformer
        from cloud_tpu.training.train import param_shardings

        axes = transformer.param_logical_axes(self.config)
        self.params = jax.device_put(
            self.params, param_shardings(self.mesh, axes, self.rules)
        )

    # -- speculative decoding ----------------------------------------------

    def _init_draft(self) -> None:
        """Arm draft-and-verify: validate the draft against the target,
        place its params/cache on the slice, and build the program
        cells.  The draft head-shards like the target when ``tp``
        divides its head count; otherwise params and its slot cache
        replicate across the slice (a draft is small — replication
        costs HBM the planner's draft term budgets for, and buys the
        verify path an undisturbed layout)."""
        import jax

        from cloud_tpu.models import generation

        cfg = self.serve_config
        dcfg = cfg.draft.config
        if int(dcfg.vocab_size) != int(self.config.vocab_size):
            raise ValueError(
                f"draft vocab_size={dcfg.vocab_size} != target "
                f"vocab_size={self.config.vocab_size}: acceptance "
                "compares token ids, so the two models must share a "
                "vocabulary"
            )
        generation.check_inference_supported(
            dcfg, self.rules, None, "speculative draft"
        )
        tp = self._slice_shape[0]
        self._draft_sharded = (
            self._slice_chips > 1 and int(dcfg.num_heads) % tp == 0
        )
        #: Mesh the draft programs constrain against: the slice when
        #: head-sharded, None (replicated compute) otherwise.
        self._draft_mesh = self.mesh if self._draft_sharded else None
        self._draft_params = cfg.draft.params

        def make_draft_grid():
            return generation.init_slot_cache(
                dcfg, cfg.num_slots, self._max_len, rules=self.rules,
                mesh=self._draft_mesh, kv_quant=cfg.kv_quant,
            )

        if self._draft_sharded:
            if self._built_serving_mesh:
                from cloud_tpu.models import transformer
                from cloud_tpu.training.train import param_shardings

                axes = transformer.param_logical_axes(dcfg)
                self._draft_params = jax.device_put(
                    cfg.draft.params,
                    param_shardings(self.mesh, axes, self.rules),
                )
            self._draft_cache = jax.jit(make_draft_grid)()
        elif self._slice_chips > 1:
            from jax.sharding import NamedSharding, PartitionSpec

            replicated = NamedSharding(self.mesh, PartitionSpec())
            self._draft_params = jax.device_put(
                cfg.draft.params, replicated
            )
            self._draft_cache = jax.device_put(make_draft_grid(),
                                               replicated)
        else:
            self._draft_cache = make_draft_grid()
        self._draft_step = self._make_draft_step()
        self._verify_step = self._make_verify_step()

    def _make_draft_step(self):
        """The draft-proposal program: ONE compile serves the engine's
        life (static spec_k window over the whole grid)."""
        import jax

        from cloud_tpu.models import generation
        from cloud_tpu.training import compile_cache

        cfg = self.serve_config
        dcfg = cfg.draft.config

        def draft_fn(params, cache, state):
            self._draft_traces += 1
            return generation.draft_chunk_program(
                params, cache, state, dcfg, spec_k=cfg.draft.spec_k,
                rules=self.rules, mesh=self._draft_mesh,
            )

        donate = (1,) if self._donate else ()
        return compile_cache.AotStep(
            jax.jit(draft_fn, donate_argnums=donate),
            label="serve/draft_chunk",
        )

    def _make_verify_step(self):
        """The target's verify program: scores a whole spec_k window per
        slot in one dispatch and commits the accepted prefix.  ONE
        compile serves the engine's life."""
        import jax

        from cloud_tpu.models import generation
        from cloud_tpu.training import compile_cache

        cfg = self.serve_config

        def verify_fn(params, cache, state, window, *extra):
            self._verify_traces += 1
            return generation.verify_chunk_program(
                params, cache, state, window, self.config,
                sample=cfg.sample, rules=self.rules, mesh=self.mesh,
                with_summary=self._pipe_depth > 1,
                **self._paged_kwargs(extra),
            )

        donate = (1, 2) if self._donate else ()
        return compile_cache.AotStep(
            jax.jit(verify_fn, donate_argnums=donate),
            label="serve/verify_chunk",
        )

    def _draft_prefill_cell(self, bucket_len: int):
        """The draft-side prompt prefill for one bucket (one executable
        per bucket, like the insert programs)."""
        cell = self._draft_prefill_cells.get(bucket_len)
        if cell is None:
            import jax

            from cloud_tpu.models import generation
            from cloud_tpu.training import compile_cache

            dcfg = self.serve_config.draft.config

            def draft_prefill_fn(params, cache, tokens, prompt_len, slot):
                self._draft_prefill_traces += 1
                return generation.draft_prefill_slot_program(
                    params, cache, tokens, prompt_len, slot, dcfg,
                    rules=self.rules, mesh=self._draft_mesh,
                )

            donate = (1,) if self._donate else ()
            cell = compile_cache.AotStep(
                jax.jit(draft_prefill_fn, donate_argnums=donate),
                label=f"serve/draft_prefill_L{bucket_len}",
            )
            self._draft_prefill_cells[bucket_len] = cell
        return cell

    def _to_host(self, what: str, *arrays):
        """Materialize device results host-side.  On a sharded slice
        this pull is the sampling boundary's logits/token gather — the
        slice's only cross-chip reshard — and is spanned as
        ``serve/reshard``; single-chip engines skip the span (their
        timeline stays exactly the pre-slice shape)."""
        if self._slice_chips > 1:
            with tracing.span("serve/reshard", what=what,
                              chips=self._slice_chips):
                return tuple(np.asarray(a) for a in arrays)
        return tuple(np.asarray(a) for a in arrays)

    # -- lifecycle ---------------------------------------------------------

    def set_trace_lane(self, lane: Optional[int]) -> None:
        """Adopt a timeline lane (``tracing.register_lane``): the
        scheduler thread stamps its spans with ``pid=lane`` so a merged
        fleet timeline renders this engine as its own labelled process
        row.  Duck-typed — the fleet replica calls it via ``hasattr``
        after building the engine, so non-engine fakes stay valid.
        Thread-safe (int swap); the scheduler re-reads it every pass."""
        self._trace_lane = lane

    def set_role(self, role: str) -> None:
        """Adopt a disaggregated-serving role (``"prefill"``,
        ``"decode"``, or ``"both"``): advertised through ``health()``/
        ``stats()`` so the fleet router can steer legs, and validated
        against the same scheduler requirements as the ctor knob.
        Duck-typed like :meth:`set_trace_lane` — the fleet replica
        calls it via ``hasattr``.  Thread-safe (str swap)."""
        if role not in ("prefill", "decode", "both"):
            raise ValueError(
                f"role must be 'prefill', 'decode' or 'both', got {role!r}"
            )
        if role != "both" and (
                not self._continuous
                or not self.serve_config.prefix_cache_blocks):
            raise ValueError(
                "role= (disaggregated serving) needs the continuous "
                "scheduler and prefix_cache_blocks > 0 — the KV handoff "
                "exports/imports prefix-pool blocks"
            )
        self._role = role

    def start(self) -> "ServingEngine":
        """Launch the scheduler thread (idempotent)."""
        with self._cond:
            if self._closed:
                raise EngineClosedError("engine already closed")
            if self._thread is not None:
                return self
            self._thread = threading.Thread(
                target=self._scheduler_loop, daemon=True,
                name=SERVE_SCHEDULER_THREAD_NAME,
            )
            self._thread.start()
        return self

    def close(self, drain: bool = True, timeout: Optional[float] = None
              ) -> None:
        """Stop the engine: no more admissions, resolve what is owed.

        ``drain=True`` (default) serves every already-admitted request
        before the scheduler exits; ``drain=False`` fails waiting
        requests with :class:`EngineClosedError` immediately.  Joins the
        scheduler and any warmup worker — after ``close()`` returns, the
        engine owns zero live threads.
        """
        with self._cond:
            self._closed = True
            self._draining = drain
            # A never-started engine has no scheduler to drain through:
            # fail what waits rather than strand the futures forever.
            if not drain or self._thread is None:
                self._fail_pending_locked(
                    EngineClosedError("engine closed before dispatch")
                )
            self._cond.notify_all()
            thread = self._thread
        if thread is not None:
            thread.join(timeout)
        if self._warmup_plan is not None:
            self._warmup_plan.wait(timeout=timeout)
        # Watchdog-abandoned dispatches: a finite hang (chaos harness,
        # recovered device) unwinds here so the closed engine owns zero
        # live threads; a truly wedged one is left daemonized after the
        # bounded join (nothing in-process can reclaim it).
        for orphan in self._orphan_dispatches:
            orphan.join(timeout if timeout is not None else 60.0)
        self._orphan_dispatches = [
            t for t in self._orphan_dispatches if t.is_alive()
        ]
        now = time.perf_counter()
        self._qps.flush(now)
        self._tokens_rate.flush(now)

    def __enter__(self) -> "ServingEngine":
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False

    # -- submission --------------------------------------------------------

    @property
    def max_prompt_len(self) -> int:
        return self.serve_config.prompt_buckets[-1]

    def submit(self, prompt, *, max_new_tokens: Optional[int] = None,
               deadline_s: Optional[float] = None,
               priority: Optional[str] = None,
               stream: bool = False,
               on_token=None,
               trace: Optional[tracing.TraceContext] = None,
               handoff_export: bool = False,
               handoff: Optional[dict] = None) -> Future:
        """Enqueue one prompt; returns a Future of :class:`ServeResult`
        (or a :class:`~cloud_tpu.serving.qos.TokenStream` with
        ``stream=True``).

        ``prompt`` is a 1-D int sequence (length 1 ..
        ``prompt_buckets[-1]``).  ``max_new_tokens`` may be below the
        engine-wide ``serve_config.max_new_tokens`` (the row is trimmed —
        greedy decode is prefix-consistent, so this equals a shorter
        direct run); above it is an error.  Thread-safe; blocks or
        raises :class:`QueueFullError` at ``max_queue`` per the
        admission policy.

        ``deadline_s`` bounds the QUEUE WAIT: a request still waiting
        when its deadline passes is shed — its future fails with
        :class:`DeadlineExceededError` — without ever occupying a decode
        slot, so under overload capacity goes to requests whose caller
        is still listening (the load-shedding half of an SLO).  A
        request that reached the device before the deadline runs to
        completion; dispatch is never aborted mid-flight for deadlines
        (that is the watchdog's job, and only for hangs).

        ``priority`` names the request's QoS class: with
        ``ServeConfig.qos`` armed, slot admission orders by (SLO slack,
        weighted fairness debt) over these classes and brownout sheds
        the lowest class first; without it the tag is validated and
        recorded but never reorders anything (FIFO — byte-identical).
        ``stream=True`` returns a :class:`~cloud_tpu.serving.qos.
        TokenStream` fed per emitted token from the chunk-commit path
        (the batch scheduler delivers at completion); iterating yields
        the exact tokens the final result row carries.  ``on_token`` is
        the cross-layer per-token hook the fleet uses to forward a
        stream — called as ``(index, token)`` on the scheduler thread.

        ``trace`` carries the fleet-minted
        :class:`~cloud_tpu.monitoring.tracing.TraceContext` so every
        span this request touches stamps its ``trace_id`` (and the
        result reports it).  Inert while tracing is disabled; None (the
        default) keeps the engine's span set byte-identical to the
        pre-tracing behavior.

        ``handoff_export=True`` marks the request as a disaggregated
        PREFILL leg: right after its prompt blocks land in the prefix
        pool the engine downloads them host-side and rides the payload
        out on ``ServeResult.handoff`` for a decode replica to import.
        ``handoff=<payload>`` marks the DECODE leg: the payload's
        blocks are seeded into this engine's prefix trie before
        admission, so the request's normal prefix lookup hits them
        (ATTACH when paged, copy program otherwise) and decode runs
        token-identical to a colocated ``generate()``.  Both require
        the continuous scheduler with a prefix cache; both default off
        — the engine stays byte-identical without them.
        """
        cfg = self.serve_config
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0, got {deadline_s}")
        if (handoff_export or handoff is not None) and (
                not self._continuous
                or getattr(self, "_prefix", None) is None):
            raise ValueError(
                "handoff_export/handoff need the continuous scheduler "
                "and prefix_cache_blocks > 0 — the KV handoff moves "
                "prefix-pool blocks"
            )
        if self._qos is not None:
            priority = self._qos.resolve_priority(priority)
        else:
            priority = qos_lib.validate_priority(priority)
        prompt = np.asarray(prompt, np.int32)
        if prompt.ndim != 1:
            raise ValueError(
                f"prompt must be 1-D token ids, got shape {prompt.shape}"
            )
        n = int(prompt.shape[0])
        if not 1 <= n <= self.max_prompt_len:
            raise ValueError(
                f"prompt length {n} outside [1, {self.max_prompt_len}] "
                f"(prompt_buckets={cfg.prompt_buckets})"
            )
        m = cfg.max_new_tokens if max_new_tokens is None else int(
            max_new_tokens)
        if not 1 <= m <= cfg.max_new_tokens:
            raise ValueError(
                f"max_new_tokens {m} outside [1, {cfg.max_new_tokens}]"
            )
        bucket_len = next(b for b in cfg.prompt_buckets if b >= n)
        submitted = time.perf_counter()
        token_stream = TokenStream() if stream else None
        request = _Request(
            prompt=prompt, prompt_len=n, max_new_tokens=m,
            bucket_len=bucket_len, future=Future(),
            submitted=submitted,
            deadline=(
                None if deadline_s is None else submitted + deadline_s
            ),
            priority=priority, stream=token_stream, on_token=on_token,
            trace=trace,
            handoff_export=handoff_export, handoff=handoff,
        )
        if token_stream is not None:
            token_stream.trace_id = request.trace_id
            # EVERY resolution path (retire, shed, crash, close) goes
            # through the future; the callback closes the stream with
            # the same result/exception and back-fills any tokens the
            # incremental path did not deliver.
            request.future.add_done_callback(
                token_stream._complete_from_future
            )
        with self._cond:
            if self._closed:
                raise EngineClosedError("engine is closed")
            if self._waiting >= cfg.max_queue:
                if cfg.admission == "reject":
                    with self._stats_lock:
                        self._stats["rejected"] += 1
                    metrics.counter_inc("serve/rejected")
                    raise QueueFullError(
                        f"serving queue full ({cfg.max_queue} waiting); "
                        "retry with backoff or raise max_queue"
                    )
                while self._waiting >= cfg.max_queue and not self._closed:
                    self._cond.wait()
                if self._closed:
                    raise EngineClosedError("engine closed while blocked "
                                            "on admission")
            self._pending.setdefault(
                bucket_len, collections.deque()
            ).append(request)
            self._waiting += 1
            self._cond.notify_all()
        with self._stats_lock:
            self._stats["requests"] += 1
            if trace is not None:
                self._stats["traced"] += 1
        metrics.counter_inc("serve/requests")
        return token_stream if token_stream is not None else request.future

    # -- warmup ------------------------------------------------------------

    def _make_chunk_step(self):
        """The single chunk-decode program: jitted once, optionally
        AOT-warmed; every dispatch carries the same static shapes, so
        one compile serves the engine's whole life (asserted via
        ``_chunk_traces`` in the retrace-guard tests)."""
        import jax

        from cloud_tpu.models import generation
        from cloud_tpu.training import compile_cache

        cfg = self.serve_config

        def chunk_fn(params, cache, state, rng, *extra):
            self._chunk_traces += 1
            return generation.decode_chunk_program(
                params, cache, state, self.config,
                chunk_size=cfg.chunk_tokens, sample=cfg.sample, rng=rng,
                rules=self.rules, mesh=self.mesh,
                with_summary=self._pipe_depth > 1,
                **self._paged_kwargs(extra),
            )

        donate = (1, 2) if self._donate else ()
        return compile_cache.AotStep(
            jax.jit(chunk_fn, donate_argnums=donate),
            label="serve/decode_chunk",
        )

    def _paged_extra(self) -> tuple:
        """The extra traced operands every paged dispatch appends: the
        prefix pool (when one exists — read-only, NEVER donated: the
        attention reads its blocks in place) and the host block table.
        Empty on the XLA path, so those cells' signatures — and their
        compiled programs — stay byte-identical to pre-paged."""
        if not self._paged:
            return ()
        if self._prefix_pool is not None:
            return (self._prefix_pool, self._block_table)
        return (self._block_table,)

    def _paged_kwargs(self, extra: tuple) -> dict:
        """Unpack ``_paged_extra``'s operands into the generation
        programs' paged kwargs (inside a cell trace)."""
        if not self._paged:
            return {}
        if len(extra) == 2:
            return {"pool": extra[0], "block_table": extra[1],
                    "use_pallas": self._paged_use_pallas}
        return {"block_table": extra[0],
                "use_pallas": self._paged_use_pallas}

    def _insert_cell(self, bucket_len: int):
        """The slot-insert program for one prompt bucket (compiled per
        bucket length; ``prompt_len``/``slot``/``max_new_tokens`` are
        traced scalars, so one executable serves every slot)."""
        cell = self._insert_cells.get(bucket_len)
        if cell is None:
            import jax

            from cloud_tpu.models import generation
            from cloud_tpu.training import compile_cache

            cfg = self.serve_config

            def insert_fn(params, cache, state, tokens, prompt_len, slot,
                          max_new, rng):
                self._insert_traces += 1
                return generation.insert_slot_program(
                    params, cache, state, tokens, prompt_len, slot,
                    max_new, self.config, sample=cfg.sample, rng=rng,
                    rules=self.rules, mesh=self.mesh,
                )

            donate = (1, 2) if self._donate else ()
            cell = compile_cache.AotStep(
                jax.jit(insert_fn, donate_argnums=donate),
                label=f"serve/insert_L{bucket_len}",
            )
            self._insert_cells[bucket_len] = cell
        return cell

    def _chunk_prefill_cell(self, width: int):
        """The bounded-prefill program for one chunk width.  With
        ``prefill_chunk_tokens`` set there is exactly one width (ONE
        compile serves every prompt, offset, and slot); with only the
        prefix cache on, suffix-after-hit prefills use the request's
        bucket length as the width — one compile per bucket, like the
        insert programs."""
        cell = self._chunk_prefill_cells.get(width)
        if cell is None:
            import jax

            from cloud_tpu.models import generation
            from cloud_tpu.training import compile_cache

            def chunk_prefill_fn(params, cache, tokens, start, chunk_len,
                                 slot, *extra):
                self._prefill_chunk_traces += 1
                return generation.prefill_chunk_program(
                    params, cache, tokens, start, chunk_len, slot,
                    self.config, rules=self.rules, mesh=self.mesh,
                    **self._paged_kwargs(extra),
                )

            donate = (1,) if self._donate else ()
            cell = compile_cache.AotStep(
                jax.jit(chunk_prefill_fn, donate_argnums=donate),
                label=f"serve/prefill_chunk_W{width}",
            )
            self._chunk_prefill_cells[width] = cell
        return cell

    def _finalize_cell(self):
        """Arm-the-slot program for the final prefill chunk: logits are
        [1, vocab] whatever the bucket, so one compile serves the whole
        engine."""
        if self._finalize_step is None:
            import jax

            from cloud_tpu.models import generation
            from cloud_tpu.training import compile_cache

            cfg = self.serve_config

            def finalize_fn(state, logits, prompt_len, slot, max_new, rng):
                self._finalize_traces += 1
                return generation.finalize_slot_program(
                    state, logits, prompt_len, slot, max_new, self.config,
                    sample=cfg.sample, rng=rng,
                )

            donate = (0,) if self._donate else ()
            self._finalize_step = compile_cache.AotStep(
                jax.jit(finalize_fn, donate_argnums=donate),
                label="serve/finalize_slot",
            )
        return self._finalize_step

    def _copy_cell(self, bucket_len: int):
        """Pool-to-slot prefix copy for one prompt bucket (``n_blocks =
        bucket_len // prefix_block_tokens`` is static per bucket; the
        block-id vector is traced, so one executable serves every hit)."""
        cell = self._copy_cells.get(bucket_len)
        if cell is None:
            import jax

            from cloud_tpu.models import generation
            from cloud_tpu.training import compile_cache

            def copy_fn(cache, pool, block_ids, slot):
                self._copy_traces += 1
                return generation.copy_prefix_program(
                    cache, pool, block_ids, slot
                )

            donate = (0,) if self._donate else ()
            cell = compile_cache.AotStep(
                jax.jit(copy_fn, donate_argnums=donate),
                label=f"serve/prefix_copy_L{bucket_len}",
            )
            self._copy_cells[bucket_len] = cell
        return cell

    def _save_cell(self, bucket_len: int):
        """Slot-to-pool block save for one prompt bucket (SKIP-sentinel
        ids are dropped by the scatter, so already-cached blocks are
        never rewritten)."""
        cell = self._save_cells.get(bucket_len)
        if cell is None:
            import jax

            from cloud_tpu.models import generation
            from cloud_tpu.training import compile_cache

            def save_fn(pool, cache, slot, block_ids):
                self._save_traces += 1
                return generation.save_prefix_program(
                    pool, cache, slot, block_ids
                )

            donate = (0,) if self._donate else ()
            cell = compile_cache.AotStep(
                jax.jit(save_fn, donate_argnums=donate),
                label=f"serve/prefix_save_L{bucket_len}",
            )
            self._save_cells[bucket_len] = cell
        return cell

    def _download_cell(self):
        """Pool-row download for the DRAM tier's demote path (ONE
        compile — the block index is traced).  Reads only: the pool is
        never donated through it."""
        if self._download_step is None:
            import jax

            from cloud_tpu.models import generation
            from cloud_tpu.training import compile_cache

            def download_fn(pool, block):
                self._download_traces += 1
                return generation.download_prefix_block(pool, block)

            self._download_step = compile_cache.AotStep(
                jax.jit(download_fn), label="serve/prefix_download"
            )
        return self._download_step

    def _swapin_cell(self):
        """Pool-row upload for the DRAM tier's promote path (ONE
        compile — block index traced, payload shapes static)."""
        if self._swapin_step is None:
            import jax

            from cloud_tpu.models import generation
            from cloud_tpu.training import compile_cache

            def swapin_fn(pool, payload, block):
                self._swapin_traces += 1
                return generation.upload_prefix_block(pool, payload, block)

            donate = (0,) if self._donate else ()
            self._swapin_step = compile_cache.AotStep(
                jax.jit(swapin_fn, donate_argnums=donate),
                label="serve/prefix_swapin",
            )
        return self._swapin_step

    def _upload_cell(self):
        """Batched pool-row upload for the KV-handoff import seam (jit
        recompiles per padded batch-size bucket; AotStep's fallback
        handles the shape churn)."""
        if self._upload_step is None:
            import jax

            from cloud_tpu.models import generation
            from cloud_tpu.training import compile_cache

            def upload_fn(pool, payloads, blocks):
                self._upload_traces += 1
                return generation.upload_prefix_blocks(
                    pool, payloads, blocks
                )

            donate = (0,) if self._donate else ()
            self._upload_step = compile_cache.AotStep(
                jax.jit(upload_fn, donate_argnums=donate),
                label="serve/kv_handoff",
            )
        return self._upload_step

    def _handoff_batch_blocks(self) -> int:
        """The FIXED batch size every handoff gather/scatter pads to —
        the longest exportable chain the config admits (capped by pool
        capacity).  One shape means ONE executable for every import
        and export, compiled by the first handoff (e.g. a warm-up
        request) instead of a fresh multi-second compile stalling the
        scheduler thread — and every active decode slot with it — the
        first time a dedup'd or truncated payload shows up with a new
        block count."""
        cfg = self.serve_config
        bound = cfg.prefix_cache_blocks
        if cfg.prompt_buckets:
            bound = min(
                bound,
                max(cfg.prompt_buckets) // cfg.prefix_block_tokens,
            )
        return max(1, bound)

    def _params_device(self):
        """The single device the params are committed to (None when
        sharded across several, or on exotic leaves) — the placement
        every piece of engine device-state follows."""
        import jax

        try:
            leaf = jax.tree_util.tree_leaves(self.params)[0]
            devices = leaf.devices()
            if len(devices) == 1:
                return next(iter(devices))
        except Exception:  # pragma: no cover - exotic param leaves
            pass
        return None

    def _pool_device(self):
        """The device the prefix pool is committed to (None when the
        pool is sharded or unallocated — device_put then falls back to
        its default placement).  Host-side payload uploads target this
        so a fleet of replicas pinned to distinct host devices never
        mixes a default-device payload into another device's pool."""
        try:
            leaf = next(iter(self._prefix_pool.values()))
            devices = leaf.devices()
            if len(devices) == 1:
                return next(iter(devices))
        except Exception:  # pragma: no cover - sharded/exotic pools
            pass
        return None

    def _export_cell(self):
        """Batched pool-row download for the KV-handoff export seam
        (pool is read, not donated — the rows stay live for serving)."""
        if self._export_step is None:
            import jax

            from cloud_tpu.models import generation
            from cloud_tpu.training import compile_cache

            def export_fn(pool, blocks):
                self._export_traces += 1
                return generation.download_prefix_blocks(pool, blocks)

            self._export_step = compile_cache.AotStep(
                jax.jit(export_fn), label="serve/kv_handoff",
            )
        return self._export_step

    def _demote_block(self, block: int):
        """The manager's ``demote_fn``: capture one HBM pool row's bytes
        host-side (numpy, outside jit) before the row is reused.  Runs
        on the scheduler thread during allocation, strictly BEFORE the
        save/swap-in dispatch that overwrites the row, so the bytes are
        exactly what the trie says they are.  Inside a burst
        (``_demote_burst``) the download is DEFERRED: the trie keeps a
        :class:`_DeferredPayload` placeholder and the burst's exit
        flushes every pending download as ONE supervised dispatch —
        one watchdog thread per burst, mirroring how the swap-in side
        budgets a whole plan.  Outside a burst the download (and its
        blocking device->host sync) runs under the watchdog like every
        other dispatch: a wedged device fails typed instead of hanging
        the scheduler on ``np.asarray`` forever."""
        import jax

        if self._demote_batch is not None:
            deferred = _DeferredPayload()
            self._demote_batch.append((int(block), deferred))
            metrics.counter_inc("serve/prefix_demotions")
            return deferred

        cell = self._download_cell()

        def dispatch():
            payload = cell(self._prefix_pool, np.int32(block))
            return jax.tree_util.tree_map(np.asarray, payload)

        with tracing.span("serve/prefix_demote", block=int(block)):
            payload = self._supervised("serve/prefix_demote", dispatch)
        metrics.counter_inc("serve/prefix_demotions")
        return payload

    @contextlib.contextmanager
    def _demote_burst(self):
        """Scope one prefix-cache allocation burst: every
        ``_demote_block`` inside defers its download into one batch,
        flushed at scope exit as ONE supervised dispatch (one watchdog
        thread per burst, mirroring how ``_dispatch_swapin`` budgets a
        whole plan) instead of paying a fresh thread per evicted block.
        Safe because the save/swap-in programs that reuse the evicted
        rows dispatch strictly AFTER this scope closes.  No-op when
        already inside a burst."""
        if self._demote_batch is not None:
            yield
            return
        batch: List[tuple] = []
        self._demote_batch = batch
        try:
            yield
        finally:
            self._demote_batch = None
            if batch:
                self._flush_demotes(batch)

    def _flush_demotes(self, batch: List[tuple]) -> None:
        """Download a burst's deferred demotions under ONE supervised
        dispatch, filling their placeholders — strictly before any row
        reuse (the caller's scope exits before the save/swap-in that
        overwrites the rows is dispatched)."""
        import jax

        cell = self._download_cell()

        def dispatch():
            for block, deferred in batch:
                payload = cell(self._prefix_pool, np.int32(block))
                deferred.value = jax.tree_util.tree_map(np.asarray, payload)
                deferred.filled = True

        with tracing.span("serve/prefix_demote", blocks=len(batch)):
            self._supervised("serve/prefix_demote", dispatch)

    def _dispatch_swapin(self, slot: int, plan,
                         trace_id: Optional[str] = None) -> None:
        """Upload a promotion plan's payloads into their fresh pool rows
        (``serve/prefix_swapin`` span — the swap-in stall the report
        attributes).  ``device_put`` is asynchronous: the host enqueues
        the transfers and the subsequent copy dispatch waits on them in
        dataflow order, off the scheduler's critical path."""
        import jax

        cell = self._swapin_cell()
        tokens = len(plan) * self.serve_config.prefix_block_tokens

        def dispatch():
            # One watchdog budget for the WHOLE plan (a fully demoted
            # long prefix can be dozens of blocks — one supervised
            # thread, not one per block); still one executable, one
            # upload dispatch per block.
            pool = self._prefix_pool
            device = self._pool_device()
            for _node, block, payload in plan:
                pool = cell(pool,
                            jax.device_put(_resolve_payload(payload),
                                           device),
                            np.int32(block))
            return pool

        span_attrs = dict(slot=slot, blocks=len(plan), tokens=tokens)
        if trace_id is not None:
            span_attrs["trace_id"] = trace_id
        with tracing.span("serve/prefix_swapin", **span_attrs):
            self._prefix_pool = self._supervised(
                "serve/prefix_swapin", dispatch
            )
        metrics.counter_inc("serve/prefix_swapins")
        metrics.counter_inc("serve/prefix_swapin_blocks", len(plan))

    def _start_warmup(self) -> None:
        """Queue AOT compiles for the whole grid on the compile-ahead
        worker (one background thread, in grid order — smallest programs
        first so early traffic warms soonest)."""
        import jax

        from cloud_tpu.training import compile_cache

        cfg = self.serve_config
        params_avals = compile_cache.abstract_state(self.params)
        context = compile_cache.context_key(mesh=self.mesh, rules=self.rules)
        rng_aval = jax.ShapeDtypeStruct(self._rng.shape, self._rng.dtype)
        if self._continuous:
            cache_avals = compile_cache.abstract_state(self._grid_cache)
            state_avals = compile_cache.abstract_state(self._slot_state)
            scalar = jax.ShapeDtypeStruct((), np.int32)
            use_chunks = cfg.prefill_chunk_tokens is not None
            # Paged cells take the (pool,) table as extra operands —
            # warm with matching avals so the AOT executable is the one
            # traffic dispatches.
            paged_avals: tuple = ()
            if self._paged:
                table_aval = jax.ShapeDtypeStruct(
                    self._block_table.shape, np.int32
                )
                if self._prefix_pool is not None:
                    paged_avals = (
                        compile_cache.abstract_state(self._prefix_pool),
                        table_aval,
                    )
                else:
                    paged_avals = (table_aval,)
            jobs = []
            if not use_chunks:
                # One-shot inserts serve cold prefills (and with
                # chunking on they are never dispatched — skip them).
                for bucket_len in cfg.prompt_buckets:
                    cell = self._insert_cell(bucket_len)
                    tok_aval = jax.ShapeDtypeStruct(
                        (1, bucket_len), np.int32
                    )
                    jobs.append((cell, (
                        params_avals, cache_avals, state_avals, tok_aval,
                        scalar, scalar, scalar, rng_aval,
                    ), context))
            # Chunked-prefill widths: THE chunk width when chunking is
            # on; the per-bucket suffix widths when only the prefix
            # cache drives partial prefills.
            if use_chunks:
                widths = (cfg.prefill_chunk_tokens,)
            elif self._prefix is not None:
                widths = cfg.prompt_buckets
            else:
                widths = ()
            for width in widths:
                cell = self._chunk_prefill_cell(width)
                tok_aval = jax.ShapeDtypeStruct((1, width), np.int32)
                jobs.append((cell, (
                    params_avals, cache_avals, tok_aval, scalar, scalar,
                    scalar, *paged_avals,
                ), context))
            if widths:
                logits_aval = jax.ShapeDtypeStruct(
                    (1, self.config.vocab_size), np.float32
                )
                jobs.append((self._finalize_cell(), (
                    state_avals, logits_aval, scalar, scalar, scalar,
                    rng_aval,
                ), context))
            if self._prefix is not None:
                pool_avals = compile_cache.abstract_state(self._prefix_pool)
                for bucket_len in cfg.prompt_buckets:
                    n_blocks = bucket_len // cfg.prefix_block_tokens
                    if n_blocks < 1:
                        continue
                    ids_aval = jax.ShapeDtypeStruct((n_blocks,), np.int32)
                    if not self._paged:
                        # The paged path NEVER dispatches the copy
                        # program (hits attach); warming it would both
                        # waste a compile and advance _copy_traces,
                        # breaking the zero-copy assertion.
                        jobs.append((self._copy_cell(bucket_len), (
                            cache_avals, pool_avals, ids_aval, scalar,
                        ), context))
                    jobs.append((self._save_cell(bucket_len), (
                        pool_avals, cache_avals, scalar, ids_aval,
                    ), context))
                if cfg.prefix_dram_blocks:
                    # The tier's block movers: one executable each.
                    payload_avals = {
                        name: jax.ShapeDtypeStruct(
                            (leaf.shape[0],) + leaf.shape[2:], leaf.dtype
                        )
                        for name, leaf in self._prefix_pool.items()
                    }
                    jobs.append((self._download_cell(), (
                        pool_avals, scalar,
                    ), context))
                    jobs.append((self._swapin_cell(), (
                        pool_avals, payload_avals, scalar,
                    ), context))
            if self._spec:
                # Speculation replaces the decode chunk wholesale: warm
                # the draft-prefill/draft/verify trio instead (the
                # never-dispatched chunk program is skipped, like the
                # insert programs under chunked prefill).
                draft_params_avals = compile_cache.abstract_state(
                    self._draft_params
                )
                draft_cache_avals = compile_cache.abstract_state(
                    self._draft_cache
                )
                for bucket_len in cfg.prompt_buckets:
                    tok_aval = jax.ShapeDtypeStruct(
                        (1, bucket_len), np.int32
                    )
                    jobs.append((self._draft_prefill_cell(bucket_len), (
                        draft_params_avals, draft_cache_avals, tok_aval,
                        scalar, scalar,
                    ), context))
                jobs.append((self._draft_step, (
                    draft_params_avals, draft_cache_avals, state_avals,
                ), context))
                window_aval = jax.ShapeDtypeStruct(
                    (cfg.num_slots, cfg.draft.spec_k), np.int32
                )
                jobs.append((self._verify_step, (
                    params_avals, cache_avals, state_avals, window_aval,
                    *paged_avals,
                ), context))
            else:
                jobs.append((self._chunk_step, (
                    params_avals, cache_avals, state_avals, rng_aval,
                    *paged_avals,
                ), context))
            self._warmup_plan = compile_cache.start_compile_ahead(jobs)
            return
        jobs = []
        for bucket_len in cfg.prompt_buckets:
            for batch_size in cfg.batch_buckets:
                cell = self._cell(bucket_len, batch_size)
                tok_aval = jax.ShapeDtypeStruct(
                    (batch_size, bucket_len), np.int32
                )
                lens_aval = jax.ShapeDtypeStruct((batch_size,), np.int32)
                prefill_args = (params_avals, tok_aval, lens_aval)
                jobs.append((cell.prefill, prefill_args, context))

                def decode_args(cell=cell, prefill_args=prefill_args):
                    # Resolved on the worker right before the decode
                    # compile: the cache/logits avals come from an
                    # eval_shape of the prefill program (pure tracing).
                    cache_aval, logits_aval = jax.eval_shape(
                        cell.prefill.jitted, *prefill_args
                    )
                    return (params_avals, cache_aval, logits_aval,
                            prefill_args[2], rng_aval)

                jobs.append((cell.decode, decode_args, context))
        self._warmup_plan = compile_cache.start_compile_ahead(jobs)

    def wait_ready(self, timeout: Optional[float] = None) -> None:
        """Block until the warmup grid has finished compiling (no-op
        without ``warmup=True``; compile failures were logged and those
        cells fall back to jit — see ``compile_cache.CompileAhead``)."""
        if self._warmup_plan is not None:
            self._warmup_plan.wait(timeout=timeout)

    # -- scheduler ---------------------------------------------------------

    def _cell(self, bucket_len: int, batch_size: int) -> _Cell:
        key = (bucket_len, batch_size)
        cell = self._cells.get(key)
        if cell is None:
            cell = self._cells[key] = _Cell(self, bucket_len, batch_size)
        return cell

    def _fail_pending_locked(self, exc: BaseException) -> None:
        failed = 0
        for queue_ in self._pending.values():
            while queue_:
                request = queue_.popleft()
                self._waiting -= 1
                failed += 1
                try:
                    request.future.set_exception(exc)
                except InvalidStateError:  # pragma: no cover - cancelled
                    pass
        if failed:
            with self._stats_lock:
                self._stats["failed"] += failed

    def _shed_expired_locked(self, now: float) -> int:
        """Drop queued requests whose deadline passed (caller holds the
        lock).  Runs at every scheduling decision, so a request is shed
        at the first opportunity AFTER expiry — before it can claim a
        slot or a batch row — with a typed failure the caller can
        distinguish from a crash.  Returns the shed count."""
        shed = 0
        shed_classes: List[str] = []
        for queue_ in self._pending.values():
            if not queue_ or not any(r.expired(now) for r in queue_):
                continue
            kept = collections.deque()
            while queue_:
                request = queue_.popleft()
                if not request.expired(now):
                    kept.append(request)
                    continue
                self._waiting -= 1
                shed += 1
                if request.priority is not None:
                    shed_classes.append(request.priority)
                waited = now - request.submitted
                tracing.record_span(
                    "serve/shed", request.submitted, now,
                    **_trace_attrs(request, bucket=request.bucket_len,
                                   reason="deadline"),
                )
                try:
                    request.future.set_exception(DeadlineExceededError(
                        f"request shed after waiting {waited:.3f}s; "
                        f"deadline_s="
                        f"{request.deadline - request.submitted:.3f}"
                    ))
                except InvalidStateError:  # pragma: no cover - cancelled
                    pass
            queue_.extend(kept)
        if shed:
            metrics.counter_inc("serve/deadline_exceeded", shed)
            with self._stats_lock:
                self._stats["shed"] += shed
                if self._qos is not None:
                    for name in shed_classes:
                        self._class_shed[name] += 1
            self._cond.notify_all()  # admission space freed
        return shed

    def _shed_brownout_locked(self, now: float) -> int:
        """Class-aware load shedding (caller holds the lock; no-op
        unless ``qos.brownout_queue_depth`` is armed): while the waiting
        set exceeds the brownout depth, shed from the LOWEST-weight
        class first — newest arrival first within a class, so the
        requests that waited longest keep their place — with a typed
        :class:`BrownoutShedError`.  The class-ordered generalization
        of the deadline shed: batch sheds before interactive."""
        if (self._qos is None
                or self._qos.brownout_queue_depth is None
                or self._waiting <= self._qos.brownout_queue_depth):
            return 0
        waiting_at_trigger = self._waiting
        excess = waiting_at_trigger - self._qos.brownout_queue_depth
        # ONE shed-order definition for both schedulers (qos_lib owns
        # the policy; this method owns the engine's queue mechanics).
        victims = qos_lib.brownout_victims(
            (r for queue_ in self._pending.values() for r in queue_),
            excess, self._qos,
        )
        shed = 0
        shed_classes: List[str] = []
        for request in victims:
            self._pending[request.bucket_len].remove(request)
            self._waiting -= 1
            shed += 1
            shed_classes.append(request.priority)
            tracing.record_span(
                "serve/shed", request.submitted, now,
                **_trace_attrs(request, bucket=request.bucket_len,
                               reason="brownout",
                               priority=request.priority),
            )
            try:
                request.future.set_exception(BrownoutShedError(
                    f"request shed under brownout: {waiting_at_trigger}"
                    f" waiting > brownout_queue_depth="
                    f"{self._qos.brownout_queue_depth} and "
                    f"{request.priority!r} is the lowest class still "
                    "queued"
                ))
            except InvalidStateError:  # pragma: no cover - cancelled
                pass
        if shed:
            metrics.counter_inc("serve/brownout_shed", shed)
            with self._stats_lock:
                self._stats["shed"] += shed
                self._stats["brownout_shed"] += shed
                for name in shed_classes:
                    self._class_shed[name] += 1
            self._cond.notify_all()  # admission space freed
        return shed

    # -- watchdog ----------------------------------------------------------

    def _supervised(self, label: str, fn):
        """Run one device dispatch under the watchdog (no-op without
        ``dispatch_timeout_s``).

        The dispatch runs on a short-lived supervised thread; if it
        does not finish inside the budget the scheduler raises
        :class:`DispatchTimeoutError` — failing the dispatch's requests
        and (via the crash path) the engine — rather than blocking
        forever on a wedged device program.  The abandoned thread is
        remembered and joined by ``close()``: a finite hang (the chaos
        harness's ``hang`` mode, a recovered device) unwinds without a
        leak; a truly wedged program leaves one daemon thread, which is
        the best Python can do short of killing the process.
        """
        timeout = self.serve_config.dispatch_timeout_s
        self._last_dispatch_ts = time.perf_counter()
        if timeout is None:
            return fn()
        box: dict = {}
        done = threading.Event()

        def runner():
            try:
                box["result"] = fn()
            except BaseException as exc:  # noqa: BLE001 — rethrown below
                box["error"] = exc
            finally:
                done.set()

        thread = threading.Thread(
            target=runner, daemon=True, name=SERVE_DISPATCH_THREAD_NAME
        )
        thread.start()
        if not done.wait(timeout):
            self._orphan_dispatches.append(thread)
            self._unhealthy_reason = (
                f"{label} exceeded dispatch_timeout_s={timeout}"
            )
            metrics.counter_inc("serve/watchdog_timeouts")
            with self._stats_lock:
                self._stats["watchdog_timeouts"] += 1
            raise DispatchTimeoutError(self._unhealthy_reason)
        thread.join()
        if "error" in box:
            raise box["error"]
        return box["result"]

    def _pop_batch_locked(self, now: float) -> Optional[List[_Request]]:
        """The batch-formation policy (caller holds the lock).

        Priority: (1) the bucket whose HEAD request has waited past
        ``flush_deadline_s``, oldest head first — the deadline is a real
        bound, never preempted by other buckets' saturation (under
        sustained traffic the saturated bucket's own head is expired
        too, so oldest-first degenerates to FIFO across buckets and a
        minority bucket cannot starve); (2) any bucket with a full
        max-batch — no deadline pressure, so take the occupancy win;
        (3) when draining a closed engine, anything left.  Whichever
        bucket wins, up to a full max-batch is taken from it.
        """
        self._shed_expired_locked(now)
        max_batch = self.serve_config.batch_buckets[-1]
        chosen = None
        for queue_ in self._pending.values():
            if not queue_:
                continue
            head = queue_[0]
            if now - head.submitted >= self.serve_config.flush_deadline_s:
                if chosen is None or head.submitted < chosen[0].submitted:
                    chosen = queue_
        if chosen is None:
            for queue_ in self._pending.values():
                if len(queue_) >= max_batch:
                    chosen = queue_
                    break
        if chosen is None and self._closed and self._draining:
            chosen = next(
                (q for q in self._pending.values() if q), None
            )
        if chosen is None:
            return None
        batch = []
        while chosen and len(batch) < max_batch:
            batch.append(chosen.popleft())
        return batch

    def _earliest_deadline_locked(self) -> Optional[float]:
        """Next instant the batch scheduler must wake: the earliest
        flush deadline OR the earliest request ``deadline_s`` expiry —
        a lone request must be shed when ITS deadline passes, not when
        the (possibly much later) flush deadline happens to wake the
        loop."""
        flush = self.serve_config.flush_deadline_s
        deadlines = []
        for queue_ in self._pending.values():
            if not queue_:
                continue
            deadlines.append(queue_[0].submitted + flush)
            deadlines.extend(
                r.deadline for r in queue_ if r.deadline is not None
            )
        return min(deadlines) if deadlines else None

    def _scheduler_loop(self) -> None:
        try:
            if self._continuous:
                self._continuous_loop()
            else:
                self._batch_loop()
        except BaseException as exc:  # noqa: BLE001 — scheduler must not
            # die silently: fail everything still queued and in flight,
            # and refuse new work.
            logger.exception("serving scheduler crashed")
            if self._unhealthy_reason is None:
                self._unhealthy_reason = f"scheduler crashed: {exc!r}"
            with self._cond:
                self._closed = True
                self._fail_pending_locked(exc)
                self._cond.notify_all()
            if self._continuous:
                self._dispose_inflight()
                self._fail_live_slots(exc)

    def _batch_loop(self) -> None:
        while True:
            if self._trace_lane is not None:
                tracing.set_thread_lane(self._trace_lane)
            with self._cond:
                while True:
                    now = time.perf_counter()
                    batch = self._pop_batch_locked(now)
                    if batch is not None:
                        self._waiting -= len(batch)
                        self._cond.notify_all()  # admission space freed
                        break
                    if self._closed:
                        return
                    deadline = self._earliest_deadline_locked()
                    timeout = (
                        None if deadline is None
                        else max(deadline - now, 1e-4)
                    )
                    self._cond.wait(timeout)
            self._inflight_rows = len(batch)
            try:
                self._dispatch(batch)
            except BaseException as exc:  # noqa: BLE001 — per-batch
                logger.exception("serving dispatch failed")
                metrics.counter_inc("serve/batch_errors")
                with self._stats_lock:
                    self._stats["failed"] += len(batch)
                for request in batch:
                    try:
                        request.future.set_exception(exc)
                    except InvalidStateError:  # pragma: no cover
                        pass
                if isinstance(exc, DispatchTimeoutError):
                    # A wedged device program is not a per-batch blip:
                    # the next dispatch would hang the same way.  Take
                    # the engine down (crash handler fails the queue and
                    # leaves health() unhealthy).
                    raise
            finally:
                self._inflight_rows = 0

    # -- continuous scheduler ----------------------------------------------

    def _continuous_loop(self) -> None:
        """Iteration-level scheduling: fill free slots from the queue,
        advance at most ONE prefill chunk, run one decode chunk, retire
        what finished, repeat.  The one-prefill-chunk bound is the
        chunked-prefill latency contract: however long an arriving
        prompt, in-flight decode waits at most one ``prefill_chunk_
        tokens`` dispatch before its next chunk (without chunking a
        prefill task is a single whole-suffix chunk, so the pass shape
        degenerates to the old insert-then-decode loop).  A dispatch
        failure here is fatal to the grid (the cache/state pytrees may
        be half-donated), so it propagates to the crash handler, which
        fails every queued and in-flight request."""
        while True:
            # Re-assert the timeline lane each pass: the owning replica
            # tags the engine AFTER this thread is already running (and
            # a restarted engine may inherit the replica's lane late).
            if self._trace_lane is not None:
                tracing.set_thread_lane(self._trace_lane)
            inserts: List[Tuple[_Request, int]] = []
            abort = False
            with self._cond:
                while True:
                    if self._closed and not self._draining:
                        abort = True
                        break
                    self._pop_inserts_locked(inserts)
                    if (inserts or self._active_slots
                            or self._prefill_tasks or self._inflight):
                        break
                    if self._closed:
                        return  # draining and nothing left to serve
                    self._cond.wait()
            if abort:
                self._prefill_tasks.clear()
                self._dispose_inflight()
                self._fail_live_slots(EngineClosedError(
                    "engine closed without draining in-flight requests"
                ))
                return
            try:
                for idx, (request, slot) in enumerate(inserts):
                    self._admit_request(request, slot)
            except BaseException as exc:
                # Requests popped from the queue but not yet in the slot
                # table are invisible to the crash handler: fail them
                # here (the in-flight one may already be tabled — its
                # InvalidStateError is suppressed), then let the crash
                # handler take the grid down.
                failed = 0
                for request, _ in inserts[idx:]:
                    try:
                        request.future.set_exception(exc)
                        failed += 1
                    except InvalidStateError:  # pragma: no cover
                        pass
                if failed:
                    with self._stats_lock:
                        self._stats["failed"] += failed
                raise
            if self._prefill_tasks:
                self._advance_prefill()
            if self._active_slots:
                if self._pipe_depth > 1:
                    # Survivor guard: the host knows every slot's budget,
                    # so it can tell — without syncing — when the work
                    # already in flight will exhaust ALL of them.  A
                    # further dispatch would be pure dead rows (the
                    # device active mask has already killed every slot);
                    # skip it and let the drain below run the pass like
                    # depth 1 instead.  Eos only ends a slot EARLIER
                    # than the budget, so the guard can at worst allow
                    # a partially-dead chunk — never block a live one.
                    if self._predict_survivors():
                        if self._spec:
                            self._dispatch_spec_chunk_async()
                        else:
                            self._dispatch_chunk_async()
                elif self._spec:
                    self._dispatch_spec_chunk()
                else:
                    self._dispatch_chunk()
            # Drain half of the pipelined pass (the ring is ALWAYS empty
            # at depth 1 — the synchronous paths above never grow it, so
            # this loop is a no-op and the default flow is unchanged).
            # While any slot can outlive the work in flight, keep
            # depth-1 chunks in the ring; once nothing can (wave end,
            # idle engine), drain dry so every pass boundary — and a
            # graceful close() — sees an empty ring with all emissions
            # committed and futures settled.  The condition is
            # re-evaluated per drain: a drain that retires the last
            # active slot flips the target to zero and flushes the
            # trailing speculative chunk (whose rows are all masked).
            while len(self._inflight) > (
                    self._pipe_depth - 1
                    if self._active_slots and self._predict_survivors()
                    else 0):
                self._drain_inflight()

    def _pop_inserts_locked(self, inserts) -> None:
        """Claim one free slot per waiting request — oldest submit first
        across every bucket (FIFO — a minority bucket cannot starve),
        or, with QoS armed, by (SLO slack, weighted fairness debt)
        over the whole waiting set (``qos.QosScheduler``: earliest
        expiring SLO while slack remains, weighted fair shares once
        saturation blows every SLO).  Caller holds the lock; dispatch
        happens outside it."""
        now = time.perf_counter()
        self._shed_expired_locked(now)
        if self._qos_sched is not None:
            self._shed_brownout_locked(now)
            self._pop_inserts_qos_locked(inserts, now)
            return
        popped = False
        while self._free_slots:
            oldest = None
            oldest_queue = None
            for queue_ in self._pending.values():
                if queue_ and (
                    oldest is None or queue_[0].submitted < oldest.submitted
                ):
                    oldest = queue_[0]
                    oldest_queue = queue_
            if oldest is None:
                break
            oldest_queue.popleft()
            self._waiting -= 1
            popped = True
            inserts.append((oldest, self._free_slots.pop()))
        if popped:
            self._cond.notify_all()  # admission space freed

    def _pop_inserts_qos_locked(self, inserts, now: float) -> None:
        """The QoS admission order: consider EVERY waiting request
        (class order is orthogonal to the bucket queues, which exist
        for compiled-program selection), admit
        ``QosScheduler.select``'s pick per free slot, and charge the
        admitted class its fairness debt."""
        popped = False
        while self._free_slots:
            best = self._qos_sched.select(
                (r for queue_ in self._pending.values() for r in queue_),
                now,
            )
            if best is None:
                break
            self._pending[best.bucket_len].remove(best)
            self._waiting -= 1
            popped = True
            self._qos_sched.charge(
                best.priority,
                self._qos.request_cost(best.prompt_len,
                                       best.max_new_tokens),
            )
            inserts.append((best, self._free_slots.pop()))
        if popped:
            self._cond.notify_all()  # admission space freed

    def _admit_request(self, request: _Request, slot: int) -> None:
        """Route one popped request into its claimed slot.

        With neither prefix caching nor chunked prefill configured this
        IS the PR 5 one-shot insert (``_insert_request``).  Otherwise:
        look up the longest cached prefix (``serve/prefix_lookup``),
        pin its blocks — an acquire that fails because the blocks were
        evicted since the match falls back to a cold prefill, never a
        stale copy — copy the hit's KV into the slot row, and queue a
        :class:`_PrefillTask` for the uncached suffix, which the loop
        advances one chunk per pass."""
        cfg = self.serve_config
        use_chunks = cfg.prefill_chunk_tokens is not None
        if self._block_table is not None:
            # Fresh claim: every page reads the slot row until a hit
            # attaches pool blocks below.
            self._block_table[slot, :] = -1
        # Disaggregated decode leg: seed the handoff payload's blocks
        # into the trie FIRST, so the ordinary lookup below hits them.
        # The seed refs are dropped once the acquire has its own pins.
        seed_held: List[object] = []
        if request.handoff is not None and self._prefix is not None:
            seed_held = self._import_handoff(request)
        hit = None
        held: List[object] = []
        swapin_plan = None
        if self._prefix is not None:
            with tracing.span(
                "serve/prefix_lookup",
                **_trace_attrs(request, bucket=request.bucket_len,
                               slot=slot),
            ) as span:
                candidate = self._prefix.match(request.prompt.tolist())
                faults.fault_point("serve.prefix_acquire")
                if candidate:
                    if cfg.prefix_dram_blocks:
                        # Tiered pin: promote any DRAM-demoted blocks
                        # back into fresh HBM rows.  None = the swap-in
                        # lost the race (blocks evicted since the match,
                        # or HBM fully pinned): fall back to a cold
                        # prefill — the PR 9 revalidation, extended.
                        with self._demote_burst():
                            swapin_plan = self._prefix.acquire_swapin(
                                candidate
                            )
                        if swapin_plan is not None:
                            hit = candidate
                            held.extend(candidate.nodes)
                    elif self._prefix.acquire(candidate):
                        hit = candidate
                        held.extend(candidate.nodes)
                span.set_attribute("hit", hit is not None)
                span.set_attribute(
                    "hit_tokens", hit.tokens if hit is not None else 0
                )
                span.set_attribute("dram", bool(swapin_plan))
            if hit is not None:
                metrics.counter_inc("serve/prefix_hits")
                metrics.counter_inc("serve/prefix_hit_tokens", hit.tokens)
                with self._stats_lock:
                    self._stats["prefix_hits"] += 1
            else:
                metrics.counter_inc("serve/prefix_misses")
                with self._stats_lock:
                    self._stats["prefix_misses"] += 1
        if seed_held:
            # The acquire above pinned what it needs; the seed's
            # bridging references have done their job.
            self._prefix.release(seed_held)
        if hit is None and not use_chunks:
            self._insert_request(request, slot)
            return
        now = time.perf_counter()
        tracing.record_span(
            "serve/queue_wait", request.submitted, now,
            **_trace_attrs(request, bucket=request.bucket_len, slot=slot),
        )
        # Tabled BEFORE any dispatch: a grid crash mid-prefill fails
        # this request along with the live slots.
        self._slot_table[slot] = _Slot(
            request=request, tokens=[], prefix_nodes=held
        )
        if swapin_plan:
            # The promoted rows must hold their bytes before the copy
            # below reads them (dataflow-ordered on device).
            self._dispatch_swapin(slot, swapin_plan,
                                  trace_id=request.trace_id)
        if hit is not None and hit.tokens:
            if self._paged:
                self._attach_prefix(request, slot, hit)
            else:
                self._dispatch_copy(request, slot, hit)
        width = (
            cfg.prefill_chunk_tokens if use_chunks else request.bucket_len
        )
        self._prefill_tasks.append(_PrefillTask(
            request=request, slot=slot, chunk_width=width,
            next_pos=hit.tokens if hit is not None else 0, hit=hit,
        ))

    def _attach_prefix(self, request: _Request, slot: int, hit) -> None:
        """The paged path's whole prefix hit: point the slot's leading
        block-table pages at the hit's pool blocks.  Zero device
        dispatch — the chunk/prefill/verify programs read the pool rows
        in place through the table.  Safe against eviction because the
        hit's blocks are ref-pinned from the acquire in
        ``_admit_request`` until ``_retire_slot`` releases them: a
        pinned pool row is never evicted, demoted, or rewritten (the
        save program's SKIP sentinel drops already-cached blocks), so
        the bytes the table points at are immutable for the slot's
        whole life."""
        blocks = hit.blocks
        with tracing.span(
            "serve/prefix_attach",
            **_trace_attrs(request, slot=slot, blocks=len(blocks),
                           tokens=hit.tokens),
        ):
            self._block_table[slot, :len(blocks)] = np.asarray(
                blocks, np.int32
            )
        metrics.counter_inc("serve/prefix_attached_blocks", len(blocks))
        with self._stats_lock:
            self._stats["prefix_attaches"] += 1

    def _dispatch_copy(self, request: _Request, slot: int, hit) -> None:
        """Copy an acquired hit's pool blocks into the slot row.  The
        id vector pads with the hit's own last block (the gather clamps
        out-of-range reads; padding with a REAL id keeps the copied-
        then-overwritten garbage deterministic)."""
        cfg = self.serve_config
        n_blocks = request.bucket_len // cfg.prefix_block_tokens
        blocks = hit.blocks
        ids = np.full((n_blocks,), blocks[-1], np.int32)
        ids[:len(blocks)] = blocks
        cell = self._copy_cell(request.bucket_len)

        def dispatch():
            return cell(self._grid_cache, self._prefix_pool, ids,
                        np.int32(slot))

        with tracing.span(
            "serve/prefix_copy",
            **_trace_attrs(request, slot=slot, blocks=len(blocks),
                           tokens=hit.tokens),
        ):
            self._grid_cache = self._supervised(
                "serve/prefix_copy", dispatch
            )

    def _advance_prefill(self) -> None:
        """One prefill-chunk dispatch for the OLDEST mid-prefill request
        — at most one per scheduler pass, so the next decode chunk is
        never more than one chunk dispatch away.  The final chunk's
        logits arm the slot (``_finalize_insert``)."""
        task = self._prefill_tasks[0]
        request = task.request
        width = task.chunk_width
        start_pos = task.next_pos
        clen = min(request.prompt_len - start_pos, width)
        tokens = np.zeros((1, width), np.int32)
        tokens[0, :clen] = request.prompt[start_pos:start_pos + clen]
        cell = self._chunk_prefill_cell(width)

        def dispatch():
            faults.fault_point("serve.prefill")
            return cell(
                self.params, self._grid_cache, tokens, np.int32(start_pos),
                np.int32(clen), np.int32(task.slot),
                *self._paged_extra(),
            )

        with tracing.span(
            "serve/prefill_chunk",
            **_trace_attrs(request, bucket=request.bucket_len,
                           slot=task.slot, start=start_pos, tokens=clen),
        ):
            self._grid_cache, logits = self._supervised(
                "serve/prefill_chunk", dispatch
            )
        task.next_pos = start_pos + clen
        metrics.counter_inc("serve/prefill_chunks")
        with self._stats_lock:
            self._stats["prefill_chunks"] += 1
        if task.next_pos >= request.prompt_len:
            self._prefill_tasks.popleft()
            self._finalize_insert(task, logits)

    def _finalize_insert(self, task: _PrefillTask, logits) -> None:
        """Arm a fully-prefilled slot from its last chunk's logits (the
        device twin of what ``insert_slot_program`` does inline), save
        the prompt's new prefix blocks, and activate — or retire, when
        the first token already finishes the request."""
        import jax

        request, slot = task.request, task.slot
        self._rng, fin_rng = jax.random.split(self._rng)
        cell = self._finalize_cell()

        def dispatch():
            return cell(
                self._slot_state, logits, np.int32(request.prompt_len),
                np.int32(slot), np.int32(request.max_new_tokens), fin_rng,
            )

        with tracing.span("serve/prefill_finalize",
                          **_trace_attrs(request, slot=slot)):
            self._slot_state, tok0 = self._supervised(
                "serve/prefill_finalize", dispatch
            )
            tok0 = int(self._to_host("finalize_tok0", tok0)[0])
        entry = self._slot_table[slot]
        entry.tokens = [tok0]
        entry.first_token_ts = time.perf_counter()
        self._feed_entry(entry)
        self._save_prefix_blocks(request, slot, already=task.hit)
        self._export_handoff(request, slot)
        self._activate_or_retire(slot, request, tok0)

    def _save_prefix_blocks(self, request: _Request, slot: int,
                            already=None) -> None:
        """Donate a just-prefilled prompt's new full blocks to the pool
        (no-op without the prefix cache).  The slot holds references on
        everything it walked — copied-in hit and saved-out new blocks —
        until it retires."""
        if self._prefix is None:
            return
        from cloud_tpu.serving.prefix_cache import SKIP_BLOCK, PrefixHit

        cfg = self.serve_config
        if self._inflight:
            # Pipelined scheduling: a chunk dispatched last pass is
            # still in flight, so this save-back's pool writes land
            # AFTER it on the device stream (dataflow through the
            # donated grid cache orders them) — the trie entry created
            # below is deferred in exactly that sense.  Counted so the
            # parity tests can assert the ordering path was exercised
            # (prefix_cache.py "Save-back ordering under pipelined
            # scheduling").
            self._prefix.note_deferred_save()
        if already is None:
            already = PrefixHit(nodes=(), tokens=0)
        with self._demote_burst():
            held, created, evicted = self._prefix.insert(
                request.prompt.tolist(), already
            )
        if evicted:
            metrics.counter_inc("serve/prefix_evictions", evicted)
        entry = self._slot_table[slot]
        entry.prefix_nodes.extend(held)
        if not created:
            return
        n_blocks = request.bucket_len // cfg.prefix_block_tokens
        ids = np.full((n_blocks,), SKIP_BLOCK, np.int32)
        created_set = {id(node) for node in created}
        base = already.tokens // cfg.prefix_block_tokens
        for i, node in enumerate(held):
            if id(node) in created_set:
                ids[base + i] = node.block
        cell = self._save_cell(request.bucket_len)

        def dispatch():
            return cell(self._prefix_pool, self._grid_cache,
                        np.int32(slot), ids)

        with tracing.span("serve/prefix_save", slot=slot,
                          blocks=len(created)):
            self._prefix_pool = self._supervised(
                "serve/prefix_save", dispatch
            )
        metrics.counter_inc("serve/prefix_saved_blocks", len(created))

    def _export_handoff(self, request: _Request, slot: int) -> None:
        """Build a disaggregated-serving handoff payload from a
        just-prefilled slot's prefix-pool blocks (no-op unless the
        request asked via ``handoff_export`` and a prefix cache is
        armed).  Runs right after ``_save_prefix_blocks`` — the slot's
        ``prefix_nodes`` is the prompt's full root-down block chain,
        ref-pinned until retire, so the rows are immutable while the
        batched download (ONE supervised dispatch, like the demote
        flush) captures them via ``download_prefix_block`` — per-leaf
        numpy pytrees, the DRAM tier's exact serialization, so kv_quant
        int8 blocks and their scale leaves ride verbatim.  The payload
        parks on the slot and rides out on ``ServeResult.handoff``."""
        if not request.handoff_export or self._prefix is None:
            return
        import jax

        cfg = self.serve_config
        entry = self._slot_table[slot]
        nodes = list(entry.prefix_nodes)
        payload = {
            "version": 1,
            "block_tokens": cfg.prefix_block_tokens,
            "covered_tokens": len(nodes) * cfg.prefix_block_tokens,
            "keys": [tuple(node.key) for node in nodes],
            "payloads": [],
        }
        if nodes:
            cell = self._export_cell()
            blocks = [int(node.block) for node in nodes]
            # One gather for the whole chain, padded to the config's
            # fixed batch size (clipped pad rows are discarded below)
            # so every export reuses one executable.
            n = len(blocks)
            bucket = max(self._handoff_batch_blocks(), n)
            block_ids = np.asarray(
                blocks + [0] * (bucket - n), np.int32
            )

            def dispatch():
                host = jax.tree_util.tree_map(
                    np.asarray, cell(self._prefix_pool, block_ids)
                )
                # Per-block copies: a payload must not pin the whole
                # stacked gather in host memory once the pool/trie
                # dedups it down to a few blocks.
                return [
                    {name: leaf[i].copy() for name, leaf in host.items()}
                    for i in range(n)
                ]

            with tracing.span(
                "serve/kv_handoff",
                **_trace_attrs(request, direction="export", slot=slot,
                               blocks=len(nodes)),
            ):
                payload["payloads"] = self._supervised(
                    "serve/kv_handoff", dispatch
                )
        entry.handoff = payload
        with self._stats_lock:
            self._stats["handoff_exports"] += 1
            self._stats["handoff_export_blocks"] += len(nodes)
        metrics.counter_inc("serve/handoff_exports")
        metrics.counter_inc("serve/handoff_export_blocks", len(nodes))

    def _import_handoff(self, request: _Request) -> List[object]:
        """Seed this engine's prefix trie with a handoff payload's
        blocks, so the request's ordinary admission lookup (just below
        in ``_admit_request``) sees a plain prefix hit — ATTACH when
        paged, the copy program otherwise.  Uploads only the blocks the
        trie did NOT already hold (the cross-replica dedup), batched
        under ONE supervised dispatch.  Returns the seeded nodes, each
        carrying one reference the caller drops once its own acquire
        has pinned the hit.  Malformed/partial payloads import less —
        the suffix prefill covers the rest, never a correctness
        dependency."""
        import jax

        cfg = self.serve_config
        payload = request.handoff
        if int(payload.get("block_tokens") or 0) != cfg.prefix_block_tokens:
            return []
        keys = list(payload.get("keys") or ())
        payloads = list(payload.get("payloads") or ())
        usable = 0
        for i, key in enumerate(keys):
            if (i < len(payloads) and payloads[i] is not None
                    and len(key) == cfg.prefix_block_tokens):
                usable += 1
            else:
                break
        if not usable:
            return []
        with tracing.span(
            "serve/kv_handoff",
            **_trace_attrs(request, direction="import", blocks=usable),
        ) as span:
            with self._demote_burst():
                held, created = self._prefix.seed_blocks(keys[:usable])
            span.set_attribute("seeded", len(held))
            span.set_attribute("uploaded", len(created))
            if created:
                cell = self._upload_cell()
                created_ids = {id(node) for node in created}
                uploads = [
                    (int(node.block), payloads[i])
                    for i, node in enumerate(held)
                    if id(node) in created_ids
                ]
                # One scatter for the whole batch, padded to the
                # config's fixed batch size so every import reuses one
                # executable; pad rows carry an out-of-range block
                # index and are dropped in-program.
                n = len(uploads)
                bucket = max(self._handoff_batch_blocks(), n)
                pad = bucket - n
                drop = self.serve_config.prefix_cache_blocks
                block_ids = np.asarray(
                    [b for b, _ in uploads] + [drop] * pad, np.int32
                )
                stacked = {}
                for name in uploads[0][1]:
                    arr = np.stack([p[name] for _, p in uploads])
                    if pad:
                        arr = np.concatenate([
                            arr,
                            np.zeros((pad,) + arr.shape[1:], arr.dtype),
                        ])
                    stacked[name] = arr

                def dispatch():
                    # Upload to the pool's own device: on multi-device
                    # hosts (one virtual device per replica) a bare
                    # device_put would land on the process default
                    # device and conflict with the committed pool.
                    return cell(self._prefix_pool,
                                jax.device_put(stacked,
                                               self._pool_device()),
                                block_ids)

                self._prefix_pool = self._supervised(
                    "serve/kv_handoff", dispatch
                )
        with self._stats_lock:
            self._stats["handoff_imports"] += 1
            self._stats["handoff_import_blocks"] += len(held)
        metrics.counter_inc("serve/handoff_imports")
        metrics.counter_inc("serve/handoff_import_blocks", len(held))
        return held

    def _activate_or_retire(self, slot: int, request: _Request,
                            tok0: int) -> None:
        """Post-prefill slot accounting, shared by the one-shot insert
        and the chunked finalize (mirrors the programs' active0 gate)."""
        with self._stats_lock:
            self._stats["inserts"] += 1
            self._stats["decode_slot_steps"] += 1  # the prefill emission
            self._stats["useful_decode_tokens"] += 1
        metrics.counter_inc("serve/slot_inserts")
        eos = self.serve_config.sample.eos_id
        if request.max_new_tokens == 1 or (eos is not None and tok0 == eos):
            # Finished at insert (mirrors the program's active0 gate).
            self._retire_slot(slot)
        else:
            if self._spec:
                # The slot will decode: give the draft its prompt KV
                # before the next proposal round (a retired-at-insert
                # slot never needs one).
                self._dispatch_draft_prefill(request, slot)
            self._active_slots.add(slot)

    def _insert_request(self, request: _Request, slot: int) -> None:
        import jax

        start = time.perf_counter()
        tracing.record_span(
            "serve/queue_wait", request.submitted, start,
            **_trace_attrs(request, bucket=request.bucket_len, slot=slot),
        )
        tokens = np.zeros((1, request.bucket_len), np.int32)
        tokens[0, :request.prompt_len] = request.prompt
        cell = self._insert_cell(request.bucket_len)
        self._rng, insert_rng = jax.random.split(self._rng)

        def dispatch():
            faults.fault_point("serve.prefill")
            return cell(
                self.params, self._grid_cache, self._slot_state, tokens,
                np.int32(request.prompt_len), np.int32(slot),
                np.int32(request.max_new_tokens), insert_rng,
            )

        with tracing.span(
            "serve/prefill",
            **_trace_attrs(request, bucket=request.bucket_len, slot=slot),
        ):
            self._grid_cache, self._slot_state, tok0 = self._supervised(
                "serve/prefill", dispatch
            )
            tok0 = int(self._to_host("insert_tok0", tok0)[0])
        entry = _Slot(
            request=request, tokens=[tok0],
            first_token_ts=time.perf_counter(),
        )
        self._slot_table[slot] = entry
        self._feed_entry(entry)
        self._save_prefix_blocks(request, slot)
        self._export_handoff(request, slot)
        self._activate_or_retire(slot, request, tok0)

    def _active_trace_map(self) -> Optional[Dict[str, str]]:
        """slot -> trace_id for the traced requests a multi-slot dispatch
        serves (chunk/verify spans carry it as the ``traces`` attribute,
        since one dispatch advances MANY requests).  None when tracing is
        off or no active request carries a context — the attribute is
        then omitted entirely, keeping untraced span payloads
        byte-identical.  JSON object keys must be strings, hence
        ``str(slot)``."""
        if not tracing.enabled():
            return None
        traces = {}
        for slot in sorted(self._active_slots):
            entry = self._slot_table[slot]
            if entry is not None and entry.request.trace is not None:
                traces[str(slot)] = entry.request.trace.trace_id
        return traces or None

    def _dispatch_chunk(self) -> None:
        import jax

        cfg = self.serve_config
        num_slots, chunk = cfg.num_slots, cfg.chunk_tokens
        self._rng, chunk_rng = jax.random.split(self._rng)

        def dispatch():
            faults.fault_point("serve.chunk")
            return self._chunk_step(
                self.params, self._grid_cache, self._slot_state, chunk_rng,
                *self._paged_extra(),
            )

        span_attrs = dict(
            slots=num_slots, chunk=chunk, active=len(self._active_slots),
        )
        if self._slice_chips > 1:
            span_attrs["slice"] = (
                f"{self._slice_shape[0]}x{self._slice_shape[1]}"
            )
            span_attrs["slice_chips"] = self._slice_chips
        traces = self._active_trace_map()
        if traces:
            span_attrs["traces"] = traces
        self._note_dispatch_gap(time.perf_counter())
        with tracing.span("serve/chunk", **span_attrs) as chunk_span:
            self._grid_cache, self._slot_state, toks, valid = (
                self._supervised("serve/chunk", dispatch)
            )
            self._last_chunk_dispatch_end = time.perf_counter()
            toks, valid = self._to_host("chunk_tokens", toks, valid)
            emitted = int(valid.sum())
            occupancy = emitted / float(num_slots * chunk)
            chunk_span.set_attribute("tokens", emitted)
            chunk_span.set_attribute("occupancy", round(occupancy, 4))
        metrics.counter_inc("serve/chunks")
        metrics.gauge_set("serve/slot_occupancy", occupancy)
        with self._stats_lock:
            self._stats["chunks"] += 1
            self._stats["decode_slot_steps"] += num_slots * chunk
            self._stats["useful_decode_tokens"] += emitted
        self._commit_emissions(toks, valid, chunk)

    def _feed_entry(self, entry: _Slot) -> None:
        """Deliver a slot's not-yet-streamed emissions to its request's
        stream / ``on_token`` hook (no-op for plain futures — the FIFO
        path pays one attribute check).  Capped at the request's budget
        so the streamed view is exactly the final result row's prefix;
        the future's done-callback closes the stream and back-fills
        anything this path never saw (batch scheduler, crash paths)."""
        request = entry.request
        if request.stream is None and request.on_token is None:
            return
        limit = min(len(entry.tokens), request.max_new_tokens)
        while entry.streamed < limit:
            i = entry.streamed
            token = entry.tokens[i]
            if request.stream is not None:
                request.stream.feed(i, token)
            if request.on_token is not None:
                try:
                    request.on_token(i, token)
                except Exception:  # noqa: BLE001 — a consumer's bug must
                    # not take the scheduler (and every other slot) down.
                    logger.exception("on_token hook failed")
                    request.on_token = None
            entry.streamed = i + 1

    def _commit_emissions(self, toks, valid, width: int) -> None:
        """Mirror one dispatch's [slots, width] emissions into the host
        slot table and retire what finished — shared verbatim by the
        decode-chunk and verify paths (``valid`` is a per-row prefix in
        both).  Streaming requests get each committed token the moment
        it lands here (host-side delivery; the dispatch is unchanged)."""
        eos = self.serve_config.sample.eos_id
        for slot in sorted(self._active_slots):
            entry = self._slot_table[slot]
            for i in range(width):
                if not valid[slot, i]:
                    break
                entry.tokens.append(int(toks[slot, i]))
            self._feed_entry(entry)
            hit_eos = eos is not None and entry.tokens[-1] == eos
            if hit_eos or len(entry.tokens) >= entry.request.max_new_tokens:
                self._retire_slot(slot)

    def _dispatch_spec_chunk(self) -> None:
        """One draft-and-verify round: the draft proposes a ``spec_k``
        window per slot over its own cache (``serve/draft``), then the
        target scores the whole window in ONE dispatch and commits the
        accepted prefix (``serve/verify``).  Host-side emission
        handling is byte-for-byte the chunk path's — only the token
        source changed."""
        cfg = self.serve_config
        num_slots, k = cfg.num_slots, cfg.draft.spec_k
        active_n = len(self._active_slots)
        self._note_dispatch_gap(time.perf_counter())

        def draft_dispatch():
            faults.fault_point("serve.draft")
            return self._draft_step(
                self._draft_params, self._draft_cache, self._slot_state
            )

        with tracing.span("serve/draft", slots=num_slots, spec_k=k,
                          active=active_n):
            self._draft_cache, window = self._supervised(
                "serve/draft", draft_dispatch
            )

        def verify_dispatch():
            faults.fault_point("serve.verify")
            return self._verify_step(
                self.params, self._grid_cache, self._slot_state, window,
                *self._paged_extra(),
            )

        span_attrs = dict(slots=num_slots, spec_k=k, active=active_n)
        if self._slice_chips > 1:
            span_attrs["slice"] = (
                f"{self._slice_shape[0]}x{self._slice_shape[1]}"
            )
            span_attrs["slice_chips"] = self._slice_chips
        traces = self._active_trace_map()
        if traces:
            span_attrs["traces"] = traces
        with tracing.span("serve/verify", **span_attrs) as verify_span:
            self._grid_cache, self._slot_state, toks, valid = (
                self._supervised("serve/verify", verify_dispatch)
            )
            self._last_chunk_dispatch_end = time.perf_counter()
            toks, valid = self._to_host("verify_tokens", toks, valid)
            emitted = int(valid.sum())
            # Every active slot commits >= 1 token (the first-mismatch
            # position's target token); the surplus is accepted drafts.
            accepted = max(emitted - active_n, 0)
            proposed = active_n * (k - 1)
            occupancy = emitted / float(num_slots * k)
            verify_span.set_attribute("tokens", emitted)
            verify_span.set_attribute("accepted", accepted)
            verify_span.set_attribute("proposed", proposed)
            verify_span.set_attribute("occupancy", round(occupancy, 4))
        metrics.counter_inc("serve/spec_chunks")
        metrics.counter_inc("serve/spec_accepted_tokens", accepted)
        metrics.gauge_set("serve/slot_occupancy", occupancy)
        with self._stats_lock:
            self._accept_window.append((accepted, proposed))
            self._stats["spec_chunks"] += 1
            self._stats["spec_emitted"] += emitted
            self._stats["spec_accepted"] += accepted
            self._stats["spec_proposed"] += proposed
            self._stats["decode_slot_steps"] += num_slots * k
            self._stats["useful_decode_tokens"] += emitted
        metrics.gauge_set(
            "serve/spec_accept_rate", self._rolling_acceptance()
        )
        self._commit_emissions(toks, valid, k)

    def _rolling_acceptance(self) -> float:
        """Acceptance over the last <=64 verify dispatches (health()'s
        number; stats() carries the cumulative quotient).  Reads under
        ``_stats_lock``: health() iterates from router threads while
        the scheduler appends, and a deque raises on concurrent
        mutation during iteration."""
        with self._stats_lock:
            accepted = sum(a for a, _ in self._accept_window)
            proposed = sum(p for _, p in self._accept_window)
        return accepted / proposed if proposed else 0.0

    # -- pipelined scheduling (pipeline_depth=2) ---------------------------

    def _note_dispatch_gap(self, start: float) -> None:
        """Record the host gap between the previous chunk dispatch and
        this one — the scheduling bubble the pipeline exists to hide.
        Deque-only at depth 1 (the default path emits no new spans); at
        depth 2 also recorded as a ``serve/dispatch_gap`` span so the
        report's serve breakdown can attribute the residual bubble."""
        last = self._last_chunk_dispatch_end
        if last is None:
            return
        with self._stats_lock:
            # Under the lock: health()/stats() snapshot the deque from
            # router threads while the scheduler appends.
            self._dispatch_gaps.append((start - last) * 1000.0)
        if self._pipe_depth > 1:
            tracing.record_span("serve/dispatch_gap", last, start)

    def _predict_survivors(self) -> bool:
        """Host-side liveness prediction, no device sync: can ANY
        active slot still be decoding after every chunk already in the
        in-flight ring lands?

        The host knows each slot's budget exactly (``max_new_tokens``
        minus tokens committed so far) and each ring entry's maximum
        per-slot progress (its emission ``width``), so budget
        exhaustion is predictable at dispatch time.  Eos is not — but
        eos only retires a slot EARLIER than its budget, so a ``True``
        here can at worst admit a partially-dead chunk (the device
        active mask zeroes those rows, exactly as at depth 1), never
        suppress a live one.  Used by the pipelined pass to stop
        dispatching ahead once the work in flight provably finishes
        every slot — the all-dead trailing chunk a naive
        dispatch-ahead loop would waste at each wave end."""
        pending = sum(rec.width for rec in self._inflight)
        for slot in self._active_slots:
            entry = self._slot_table[slot]
            if entry is None:  # pragma: no cover - retire races
                continue
            if entry.request.max_new_tokens - len(entry.tokens) > pending:
                return True
        return False

    def _start_host_copy(self, *arrays) -> None:
        """Kick off non-blocking device→host copies for a dispatched
        chunk's emission buffers, so the drain's blocking ``_to_host``
        one pass later finds the bytes already (or nearly) resident.
        Best effort: backends/array types without the method simply
        fall back to the blocking copy at drain."""
        for arr in arrays:
            try:
                arr.copy_to_host_async()
            except (AttributeError, RuntimeError):
                return

    def _dispatch_chunk_async(self) -> None:
        """Dispatch half of the pipelined decode pass: enqueue one
        chunk against the current device-resident grid and push its
        *unmaterialized* emission arrays onto the in-flight ring — no
        host sync here.  ``_drain_inflight`` commits them one pass
        later, after the NEXT chunk is already running, so the commit/
        retire/insert host work overlaps device compute.  Metrics and
        stats move to the drain with the emissions: a disposed (never
        drained) chunk is never counted."""
        import jax

        cfg = self.serve_config
        num_slots, chunk = cfg.num_slots, cfg.chunk_tokens
        self._rng, chunk_rng = jax.random.split(self._rng)

        def dispatch():
            faults.fault_point("serve.chunk")
            return self._chunk_step(
                self.params, self._grid_cache, self._slot_state, chunk_rng,
                *self._paged_extra(),
            )

        span_attrs = dict(
            slots=num_slots, chunk=chunk, active=len(self._active_slots),
        )
        if self._slice_chips > 1:
            span_attrs["slice"] = (
                f"{self._slice_shape[0]}x{self._slice_shape[1]}"
            )
            span_attrs["slice_chips"] = self._slice_chips
        traces = self._active_trace_map()
        if traces:
            span_attrs["traces"] = traces
        start = time.perf_counter()
        self._note_dispatch_gap(start)
        self._grid_cache, self._slot_state, toks, valid, summary = (
            self._supervised("serve/chunk", dispatch)
        )
        end = time.perf_counter()
        self._last_chunk_dispatch_end = end
        self._start_host_copy(toks, valid, summary)
        self._inflight.append(_InflightChunk(
            toks=toks, valid=valid, summary=summary, width=chunk,
            kind="chunk", active=len(self._active_slots),
            span_attrs=span_attrs, dispatch_start=start, dispatch_end=end,
        ))

    def _dispatch_spec_chunk_async(self) -> None:
        """Pipelined draft-and-verify round: both dispatches enqueue
        back to back (the verify consumes the draft's window as a
        device operand — no host sync between them) and the verify's
        emissions ride the in-flight ring exactly like a decode
        chunk's.  The ``serve/draft`` span brackets only the enqueue
        here; the ``serve/verify`` span is recorded at drain over the
        full dispatch→drain interval."""
        cfg = self.serve_config
        num_slots, k = cfg.num_slots, cfg.draft.spec_k
        active_n = len(self._active_slots)

        def draft_dispatch():
            faults.fault_point("serve.draft")
            return self._draft_step(
                self._draft_params, self._draft_cache, self._slot_state
            )

        start = time.perf_counter()
        self._note_dispatch_gap(start)
        with tracing.span("serve/draft", slots=num_slots, spec_k=k,
                          active=active_n):
            self._draft_cache, window = self._supervised(
                "serve/draft", draft_dispatch
            )

        def verify_dispatch():
            faults.fault_point("serve.verify")
            return self._verify_step(
                self.params, self._grid_cache, self._slot_state, window,
                *self._paged_extra(),
            )

        span_attrs = dict(slots=num_slots, spec_k=k, active=active_n)
        if self._slice_chips > 1:
            span_attrs["slice"] = (
                f"{self._slice_shape[0]}x{self._slice_shape[1]}"
            )
            span_attrs["slice_chips"] = self._slice_chips
        traces = self._active_trace_map()
        if traces:
            span_attrs["traces"] = traces
        self._grid_cache, self._slot_state, toks, valid, summary = (
            self._supervised("serve/verify", verify_dispatch)
        )
        end = time.perf_counter()
        self._last_chunk_dispatch_end = end
        self._start_host_copy(toks, valid, summary)
        self._inflight.append(_InflightChunk(
            toks=toks, valid=valid, summary=summary, width=k,
            kind="verify", active=active_n,
            span_attrs=span_attrs, dispatch_start=start, dispatch_end=end,
        ))

    def _drain_inflight(self) -> None:
        """Drain half of the pipelined pass: materialize the OLDEST
        in-flight chunk's emissions (the blocking host copy overlaps
        the device running the chunk dispatched after it — the wait
        actually paid is recorded as ``serve/host_bubble``), then run
        the exact metrics/stats/commit sequence of the synchronous
        path.  The terminal ``serve/chunk``/``serve/verify`` span
        covers dispatch→drain, so the report's serve breakdown keeps
        aggregating occupancy the same way at any depth."""
        rec = self._inflight.popleft()
        cfg = self.serve_config
        num_slots = cfg.num_slots
        wait0 = time.perf_counter()
        toks, valid, summary = self._to_host(
            f"{rec.kind}_tokens", rec.toks, rec.valid, rec.summary
        )
        wait1 = time.perf_counter()
        tracing.record_span("serve/host_bubble", wait0, wait1,
                            kind=rec.kind, width=rec.width)
        emitted = int(summary[0])
        occupancy = emitted / float(num_slots * rec.width)
        attrs = dict(rec.span_attrs)
        attrs["tokens"] = emitted
        attrs["occupancy"] = round(occupancy, 4)
        if rec.kind == "verify":
            accepted = max(emitted - rec.active, 0)
            proposed = rec.active * (cfg.draft.spec_k - 1)
            attrs["accepted"] = accepted
            attrs["proposed"] = proposed
            tracing.record_span("serve/verify", rec.dispatch_start,
                                wait1, **attrs)
            metrics.counter_inc("serve/spec_chunks")
            metrics.counter_inc("serve/spec_accepted_tokens", accepted)
            metrics.gauge_set("serve/slot_occupancy", occupancy)
            with self._stats_lock:
                self._accept_window.append((accepted, proposed))
                self._stats["spec_chunks"] += 1
                self._stats["spec_emitted"] += emitted
                self._stats["spec_accepted"] += accepted
                self._stats["spec_proposed"] += proposed
                self._stats["decode_slot_steps"] += num_slots * rec.width
                self._stats["useful_decode_tokens"] += emitted
            metrics.gauge_set(
                "serve/spec_accept_rate", self._rolling_acceptance()
            )
        else:
            tracing.record_span("serve/chunk", rec.dispatch_start,
                                wait1, **attrs)
            metrics.counter_inc("serve/chunks")
            metrics.gauge_set("serve/slot_occupancy", occupancy)
            with self._stats_lock:
                self._stats["chunks"] += 1
                self._stats["decode_slot_steps"] += num_slots * rec.width
                self._stats["useful_decode_tokens"] += emitted
        self._commit_emissions(toks, valid, rec.width)

    def _dispose_inflight(self) -> None:
        """Abandon the in-flight ring without committing (abort/crash
        paths): block until every pending dispatch and its async
        device→host copy actually completed — ``close(drain=False)``
        must never leave a computation or copy running against state
        being torn down — then drop the results.  Errors are logged,
        not raised: disposal must not mask the failure that got us
        here, and the slots' futures are failed by the caller."""
        while self._inflight:
            rec = self._inflight.popleft()
            try:
                self._to_host(f"{rec.kind}_dispose", rec.toks, rec.valid,
                              rec.summary)
            except Exception:  # noqa: BLE001
                logger.exception("disposing in-flight chunk failed")

    def _dispatch_draft_prefill(self, request: _Request, slot: int) -> None:
        """Mirror a just-armed slot's prompt into the draft model's
        cache row so the next proposal round attends over real context
        (one-shot whatever the target side did — prefix hits and
        chunked prefills stay target-only)."""
        tokens = np.zeros((1, request.bucket_len), np.int32)
        tokens[0, :request.prompt_len] = request.prompt
        cell = self._draft_prefill_cell(request.bucket_len)

        def dispatch():
            faults.fault_point("serve.draft_prefill")
            return cell(
                self._draft_params, self._draft_cache, tokens,
                np.int32(request.prompt_len), np.int32(slot),
            )

        with tracing.span("serve/draft_prefill",
                          bucket=request.bucket_len, slot=slot):
            self._draft_cache = self._supervised(
                "serve/draft_prefill", dispatch
            )
        metrics.counter_inc("serve/draft_prefills")
        with self._stats_lock:
            self._stats["draft_prefills"] += 1

    def _retire_slot(self, slot: int, exc: Optional[BaseException] = None
                     ) -> None:
        """Free a slot and resolve its request's future — with the
        result (the emitted row padded to the request's length) or, on
        abort, the given exception."""
        cfg = self.serve_config
        entry = self._slot_table[slot]
        self._slot_table[slot] = None
        self._active_slots.discard(slot)
        if self._block_table is not None:
            # Detach before the pins below release: a stale table row
            # must never outlive the references that made its pool
            # blocks immutable.
            self._block_table[slot, :] = -1
        if entry.prefix_nodes and self._prefix is not None:
            # Drop this slot's references; blocks shared with another
            # in-flight slot stay pinned until IT retires too.
            self._prefix.release(entry.prefix_nodes)
        with self._cond:
            self._free_slots.append(slot)
        request = entry.request
        if exc is not None:
            try:
                request.future.set_exception(exc)
            except InvalidStateError:
                # Already resolved elsewhere (e.g. the insert-failure
                # handler beat us to it, or the caller cancelled): don't
                # double-count the failure.
                return
            with self._stats_lock:
                self._stats["failed"] += 1
            return
        m = request.max_new_tokens
        num = min(len(entry.tokens), m)
        row = np.full((m,), cfg.sample.pad_id, np.int32)
        row[:num] = entry.tokens[:num]
        done = time.perf_counter()
        first = entry.first_token_ts if entry.first_token_ts else done
        result = ServeResult(
            tokens=row,
            num_generated=num,
            bucket_len=request.bucket_len,
            batch_size=cfg.num_slots,
            latency_seconds=done - request.submitted,
            ttft_seconds=first - request.submitted,
            trace_id=request.trace_id,
            handoff=entry.handoff,
        )
        metrics.distribution_record(
            "serve/latency_seconds", result.latency_seconds
        )
        metrics.counter_inc("serve/slot_retires")
        metrics.counter_inc("serve/generated_tokens", num)
        eos = cfg.sample.eos_id
        hit_eos = eos is not None and num > 0 and int(row[num - 1]) == eos
        if not hit_eos:
            # The per-slot max_new_tokens cap (not eos) ended the slot.
            metrics.counter_inc("serve/slot_expired")
        self._qps.add(done, 1)
        self._tokens_rate.add(done, num)
        with self._stats_lock:
            self._stats["retires"] += 1
            if not hit_eos:
                self._stats["expired"] += 1
            self._stats["completed"] += 1
            self._stats["generated_tokens"] += num
            if self._qos is not None:
                self._class_completed[request.priority] += 1
        if self._qos is not None or request.trace is not None:
            # Per-request terminal span — with QoS armed (report.py's
            # per-class TTFT/latency breakdown reads the priority
            # attribute) or when the request carries a trace context
            # (the lifecycle stitch needs a terminal under the
            # trace_id).  A FIFO engine serving untraced requests keeps
            # its exact pre-QoS span set.
            attrs = {"ttft_s": round(result.ttft_seconds, 6),
                     "tokens": num}
            if request.priority is not None:
                attrs["priority"] = request.priority
            tracing.record_span(
                "serve/request", request.submitted, done,
                **_trace_attrs(request, **attrs),
            )
        try:
            request.future.set_result(result)
        except InvalidStateError:  # pragma: no cover - cancelled
            pass

    def _fail_live_slots(self, exc: BaseException) -> None:
        for slot, entry in enumerate(self._slot_table):
            if entry is not None:
                self._retire_slot(slot, exc=exc)

    def _dispatch(self, batch: List[_Request]) -> None:
        import jax

        cfg = self.serve_config
        bucket_len = batch[0].bucket_len
        n = len(batch)
        batch_size = next(b for b in cfg.batch_buckets if b >= n)
        form_start = time.perf_counter()
        for request in batch:
            tracing.record_span(
                "serve/queue_wait", request.submitted, form_start,
                **_trace_attrs(request, bucket=bucket_len),
            )
        with tracing.span("serve/batch_form", bucket=bucket_len,
                          rows=n, batch=batch_size):
            tokens = np.zeros((batch_size, bucket_len), np.int32)
            lens = np.ones((batch_size,), np.int32)
            for i, request in enumerate(batch):
                tokens[i, :request.prompt_len] = request.prompt
                lens[i] = request.prompt_len
        cell = self._cell(bucket_len, batch_size)
        self._rng, batch_rng = jax.random.split(self._rng)

        def prefill():
            faults.fault_point("serve.prefill")
            cache, logits0 = cell.prefill(self.params, tokens, lens)
            jax.block_until_ready(logits0)
            return cache, logits0

        with tracing.span("serve/prefill", bucket=bucket_len,
                          batch=batch_size):
            cache, logits0 = self._supervised("serve/prefill", prefill)

        def decode():
            faults.fault_point("serve.decode")
            out = cell.decode(self.params, cache, logits0, lens, batch_rng)
            return self._to_host(
                "batch_tokens", out["tokens"], out["num_generated"]
            )

        with tracing.span("serve/decode", bucket=bucket_len,
                          batch=batch_size):
            out_tokens, out_nums = self._supervised("serve/decode", decode)
        done = time.perf_counter()

        results = []
        generated = 0
        for i, request in enumerate(batch):
            m = request.max_new_tokens
            num = int(min(out_nums[i], m))
            generated += num
            result = ServeResult(
                tokens=out_tokens[i, :m].copy(),
                num_generated=num,
                bucket_len=bucket_len,
                batch_size=batch_size,
                latency_seconds=done - request.submitted,
                # Batch decode materializes tokens all at once: first
                # token and last arrive together.
                ttft_seconds=done - request.submitted,
                trace_id=request.trace_id,
            )
            metrics.distribution_record(
                "serve/latency_seconds", result.latency_seconds
            )
            if request.trace is not None:
                # Terminal span for the lifecycle stitch (continuous
                # engines emit it in _retire_slot); untraced batch
                # requests keep the pre-tracing span set.
                attrs = {"ttft_s": round(result.ttft_seconds, 6),
                         "tokens": num,
                         "trace_id": request.trace.trace_id}
                if request.priority is not None:
                    attrs["priority"] = request.priority
                tracing.record_span(
                    "serve/request", request.submitted, done, **attrs
                )
            results.append(result)

        # Stats/metrics BEFORE the futures resolve: a caller waking from
        # ``future.result()`` must see this batch already counted.
        metrics.counter_inc("serve/batches")
        metrics.counter_inc("serve/generated_tokens", generated)
        metrics.gauge_set("serve/batch_occupancy", n / batch_size)
        self._qps.add(done, n)
        self._tokens_rate.add(done, generated)
        with self._stats_lock:
            self._stats["batches"] += 1
            self._stats["slots"] += batch_size
            self._stats["real_rows"] += n
            self._stats["completed"] += n
            self._stats["generated_tokens"] += generated
            # Token-level occupancy, comparable with the continuous
            # scheduler: every dispatched row owes max_new_tokens
            # emission slots whether or not a real request (or a short
            # one) occupies it.
            self._stats["decode_slot_steps"] += (
                batch_size * cfg.max_new_tokens
            )
            self._stats["useful_decode_tokens"] += generated
        for request, result in zip(batch, results):
            try:
                request.future.set_result(result)
            except InvalidStateError:  # pragma: no cover - cancelled
                pass

    # -- introspection -----------------------------------------------------

    def health(self) -> dict:
        """Readiness/liveness snapshot (the shape a /healthz endpoint or
        an external supervisor polls; cheap, lock-bounded, any thread).

        ``healthy`` — no watchdog fire, no scheduler crash (a cleanly
        closed engine is still healthy: it stopped, it didn't break).
        ``ready`` — accepting new ``submit()`` calls right now.
        ``live`` — the scheduler thread exists and is running.
        ``reason`` — why ``healthy`` is False, else None.  Plus the
        load signal a fleet router reads per routing decision —
        ``queue_depth`` (waiting requests; same value as the legacy
        ``waiting`` key), ``active_slots`` (OCCUPIED slots / batch rows
        on the device right now — decoding or mid-prefill, both
        schedulers), ``num_slots`` (the engine's slot capacity, so
        occupancy is ``active/num``) — the
        continuous grid's ``free_slots``, orphaned dispatch count, and
        seconds since the last device dispatch (None before the first)
        for staleness alerting.
        """
        with self._cond:
            waiting = self._waiting
            closed = self._closed
            thread = self._thread
            free_slots = (
                len(self._free_slots) if self._continuous else None
            )
            class_backlog = self._class_backlog_locked()
        live = thread is not None and thread.is_alive()
        reason = self._unhealthy_reason
        last = self._last_dispatch_ts
        snap = {
            "healthy": reason is None,
            "ready": live and not closed and reason is None,
            "live": live,
            "reason": reason,
            "closed": closed,
            "waiting": waiting,
            "queue_depth": waiting,
            # OCCUPIED slots, not merely decoding ones: a slot claimed
            # by a mid-prefill task (chunked prefill can hold it for
            # many passes) is load a router must see — it left the
            # queue-depth count the moment it was popped.
            "active_slots": (
                self.serve_config.num_slots - free_slots
                if self._continuous else self._inflight_rows
            ),
            "num_slots": self.serve_config.num_slots,
            # The slice this replica spans: (tp, sp) and total chips.
            # (1, 1)/1 on the single-chip path — stable schema, so a
            # fleet can sum chips without probing.  Router load math
            # deliberately ignores these: load is queued + in-flight
            # REQUESTS, whatever the slice width serving them.
            "slice_shape": self._slice_shape,
            "slice_chips": self._slice_chips,
            "orphaned_dispatches": len(self._orphan_dispatches),
            "last_dispatch_age_s": (
                None if last is None else time.perf_counter() - last
            ),
            # Speculative decoding (stable schema — zeros when off):
            # the rolling acceptance over recent verify dispatches, and
            # the armed window width.
            "spec_acceptance_rate": (
                self._rolling_acceptance() if self._spec else 0.0
            ),
            "spec_k": (
                self.serve_config.draft.spec_k if self._spec else 0
            ),
            # Per-class queued requests (QoS): all-zeros when qos=None
            # (requests are classless on the FIFO path) — stable
            # schema, so the fleet's per-class backlog aggregation and
            # the autoscaler's class signal read without probing.
            "class_backlog": class_backlog,
            # The armed decode-attention path ("xla" default; stable
            # schema — the batch scheduler only ever reports "xla").
            "decode_kernel": self.serve_config.decode_kernel,
            # Disaggregated serving (stable schema — "both" and zeros
            # with roles off): the role the fleet router steers legs
            # by, plus the KV handoff counters.
            "role": self._role,
            # Pipelined scheduling (stable schema — depth 1 / 0.0 on
            # the batch scheduler and before the first two chunks):
            # the effective depth and the rolling mean host gap
            # between consecutive chunk dispatches, the bubble depth 2
            # exists to hide — a supervisor alert on it regressing is
            # the cheapest "pipelining stopped helping" signal.
            "pipeline_depth": (
                self._pipe_depth if self._continuous else 1
            ),
            "dispatch_gap_ms": self._dispatch_gap_mean(),
        }
        with self._stats_lock:
            snap["handoff_exports"] = self._stats["handoff_exports"]
            snap["handoff_export_blocks"] = (
                self._stats["handoff_export_blocks"]
            )
            snap["handoff_imports"] = self._stats["handoff_imports"]
            snap["handoff_import_blocks"] = (
                self._stats["handoff_import_blocks"]
            )
        snap.update(self._prefix_snapshot())
        if self._continuous:
            snap["free_slots"] = free_slots
        return snap

    def _class_backlog_locked(self) -> Dict[str, int]:
        """Queued requests per QoS class (caller holds ``_cond``).
        Zeros for every class when QoS is off — the FIFO path never
        classes its queue."""
        backlog = {name: 0 for name in self._class_names}
        if self._qos is not None:
            for queue_ in self._pending.values():
                for request in queue_:
                    backlog[request.priority] += 1
        return backlog

    def _prefix_snapshot(self) -> dict:
        """The prefix-cache keys ``health()`` and ``stats()`` both
        carry (ONE spelling — the fleet router pins the schema): zeros
        when the cache is off, so callers read a stable shape.  The
        ``prefix_dram_*`` keys are the host-DRAM tier's (zeros with
        ``prefix_dram_blocks`` unset), and ``cached_prefixes`` is the
        router-facing hot-prefix summary ({} when off) the cost-model
        router scores candidates by."""
        prefix = (
            self._prefix.stats()
            if self._continuous and self._prefix is not None else None
        )
        return {
            "prefix_cache_blocks": (
                prefix["blocks_in_use"] if prefix else 0
            ),
            "prefix_hit_tokens": prefix["hit_tokens"] if prefix else 0,
            "evictions": prefix["evictions"] if prefix else 0,
            "prefix_dram_blocks": (
                prefix["dram_blocks_in_use"] if prefix else 0
            ),
            "prefix_dram_hits": prefix["dram_hits"] if prefix else 0,
            "prefix_dram_hit_tokens": (
                prefix["dram_hit_tokens"] if prefix else 0
            ),
            "prefix_dram_demotions": prefix["demotions"] if prefix else 0,
            "prefix_dram_evictions": (
                prefix["dram_evictions"] if prefix else 0
            ),
            "prefix_dram_swapin_failures": (
                prefix["swapin_failures"] if prefix else 0
            ),
            # Pipelined save-backs (0 at pipeline_depth=1): the parity
            # tests assert the deferred-ordering path was exercised.
            "prefix_deferred_saves": (
                prefix["deferred_saves"] if prefix else 0
            ),
            "cached_prefixes": (
                self._prefix.hot_prefixes()
                if self._continuous and self._prefix is not None else {}
            ),
        }

    def stats(self) -> dict:
        """Counters snapshot plus the two occupancy quotients.

        ``mean_batch_occupancy`` — real rows / dispatched rows (the PR 4
        batch-formation number; 0.0 under the continuous scheduler).
        ``mean_slot_occupancy`` — useful emitted tokens / dispatched
        token slots, comparable ACROSS schedulers: it charges a batch
        row for the full engine decode length and a continuous chunk
        for every slot lane, so it is the number iteration-level
        scheduling is judged by.
        """
        with self._stats_lock:
            snap = dict(self._stats)
            # Per-class service accounting (QoS): zeros when qos=None —
            # stable schema next to brownout_shed above.
            snap["class_completed"] = dict(self._class_completed)
            snap["class_shed"] = dict(self._class_shed)
        snap["role"] = self._role
        with self._cond:
            snap["class_backlog"] = self._class_backlog_locked()
        snap["mean_batch_occupancy"] = (
            snap["real_rows"] / snap["slots"] if snap["slots"] else 0.0
        )
        snap["mean_slot_occupancy"] = (
            snap["useful_decode_tokens"] / snap["decode_slot_steps"]
            if snap["decode_slot_steps"] else 0.0
        )
        snap["slice_shape"] = self._slice_shape
        snap["slice_chips"] = self._slice_chips
        # Cumulative acceptance (health() carries the rolling one);
        # 0.0 with draft=None — stable schema.
        snap["spec_acceptance_rate"] = (
            snap["spec_accepted"] / snap["spec_proposed"]
            if snap["spec_proposed"] else 0.0
        )
        # Pipelined scheduling (stable schema — depth 1 / 0.0 on the
        # batch scheduler): dispatch-gap percentiles over the rolling
        # window, the per-arm numbers the serving_pipeline bench probe
        # reports.
        snap["pipeline_depth"] = (
            self._pipe_depth if self._continuous else 1
        )
        gaps = self._dispatch_gap_window()
        snap["dispatch_gap_ms_p50"] = (
            float(np.percentile(gaps, 50)) if gaps else 0.0
        )
        snap["dispatch_gap_ms_p99"] = (
            float(np.percentile(gaps, 99)) if gaps else 0.0
        )
        snap.update(self._prefix_snapshot())
        return snap

    def _dispatch_gap_window(self) -> List[float]:
        """Snapshot of the rolling dispatch-gap window (ms), empty on
        the batch scheduler and before the first two chunk dispatches."""
        if not self._continuous:
            return []
        with self._stats_lock:
            return list(self._dispatch_gaps)

    def _dispatch_gap_mean(self) -> float:
        """health()'s rolling mean dispatch gap in ms (0.0 when the
        window is empty)."""
        gaps = self._dispatch_gap_window()
        return float(sum(gaps) / len(gaps)) if gaps else 0.0

    @property
    def chunk_traces(self) -> int:
        """Python-trace count of the chunk program (continuous mode): 1
        after any amount of traffic == one compile served the run."""
        return self._chunk_traces if self._continuous else 0

    @property
    def verify_traces(self) -> int:
        """Python-trace count of the speculative verify program: 1
        after any amount of traffic == one compile served the run (0
        with ``draft=None``)."""
        return self._verify_traces if self._continuous else 0
