"""Dynamic-batched serving engine over the generation path.

``models.generation`` can decode a *batch* of prompts as one compiled
program, but traffic arrives one request at a time; serving economics on
TPU hinge on the gap between those two facts (batched decode occupancy
amortizes the weight reads every decode step re-pays — arxiv 2605.25645,
arxiv 2309.08918).  :class:`ServingEngine` closes the gap in-process:

* **Dynamic batching** — ``submit()`` enqueues a request and returns a
  ``concurrent.futures.Future``; a scheduler thread groups waiting
  requests by *prompt-length bucket*, pads each group to its bucket
  shape, and dispatches prefill + scan-decode as two compiled programs
  (``generation.prefill_program`` / ``generation.decode_program``),
  demultiplexing per-row results back onto the futures.  A batch forms
  when a bucket fills to the largest batch bucket or when its oldest
  request has waited ``flush_deadline_s`` — a lone request is never
  stranded behind an unfillable batch.
* **Bucketed AOT warmup** — shapes are quantized to a static
  ``(bucket_len, batch_size)`` grid, so the full set of executables the
  engine can ever dispatch is enumerable; ``warmup=True`` pre-compiles
  the grid through ``training.compile_cache`` (the same AOT registry +
  background worker the trainer's compile-ahead uses) at engine start,
  making first-request latency an engineered quantity like PR 3 did for
  first-step latency.
* **Admission control** — the waiting set is bounded by ``max_queue``;
  ``admission="block"`` makes ``submit`` wait for space,
  ``admission="reject"`` raises :class:`QueueFullError` (typed, so a
  caller can shed load).  ``close()`` drains gracefully: admitted
  requests complete, later submits raise :class:`EngineClosedError`, and
  no scheduler/warmup thread survives (same thread-hygiene contract as
  ``training.pipeline_io``).
* **Observability** — ``serve/queue_wait`` (recorded cross-thread via
  ``tracing.record_span``), ``serve/batch_form``, ``serve/prefill`` and
  ``serve/decode`` spans; ``serve/qps`` and ``serve/tokens_per_sec``
  windowed-rate gauges, a ``serve/batch_occupancy`` gauge and a
  ``serve/latency_seconds`` distribution.  ``python -m
  cloud_tpu.monitoring.report`` renders the serve spans as a dedicated
  queue-wait vs prefill vs decode breakdown.

Greedy parity is the correctness contract: for any mix of prompt
lengths, a request's tokens are identical to a direct per-request
``generation.generate`` call (padding rows and bucket tails are masked
out of attention, and greedy decode is prefix-consistent, so per-request
``max_new_tokens`` is served by trimming the engine-wide decode length).
Proven in tests/unit/test_serving.py and scripts/check_serving.py.
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import threading
import time
from concurrent.futures import Future, InvalidStateError
from typing import Dict, List, Optional, Tuple

import numpy as np

from cloud_tpu.monitoring import metrics, tracing

logger = logging.getLogger(__name__)

#: Scheduler-thread name (prefix match in tests' thread-leak guards).
SERVE_SCHEDULER_THREAD_NAME = "cloud-tpu-serve-scheduler"


class QueueFullError(RuntimeError):
    """Typed rejection under ``admission="reject"``: the waiting set is at
    ``max_queue`` — shed the request or retry with backoff."""


class EngineClosedError(RuntimeError):
    """The engine is closed (or closing): the request was not admitted."""


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Engine knobs (all static — they define the compiled-program grid).

    ``prompt_buckets`` are the padded prompt lengths the engine compiles
    for (a request lands in the smallest bucket that fits it);
    ``batch_buckets`` are the batch sizes (a formed group pads up to the
    smallest batch bucket that fits, so occupancy is explicit: 3 requests
    in a bucket-4 dispatch is 75%).  The compiled grid is their cross
    product x {prefill, decode}.  ``flush_deadline_s`` bounds how long a
    request may wait for co-batching once it is first in line;
    ``max_queue``/``admission`` are the backpressure contract
    (module docstring).
    """

    max_new_tokens: int = 32
    prompt_buckets: Tuple[int, ...] = (32, 128, 512)
    batch_buckets: Tuple[int, ...] = (1, 2, 4, 8)
    flush_deadline_s: float = 0.01
    max_queue: int = 256
    admission: str = "block"
    #: Sampling config shared by every request (static: it specializes
    #: the compiled decode program).  Default greedy.
    sample: "SampleConfig" = None  # type: ignore[assignment]
    kv_quant: bool = False
    #: Pre-compile the whole (bucket_len, batch_size) grid at start on a
    #: background worker (``training.compile_cache``).
    warmup: bool = False
    #: Seed for the engine-owned sampling rng chain (non-greedy configs).
    seed: int = 0

    def __post_init__(self):
        from cloud_tpu.models.generation import SampleConfig

        if self.sample is None:
            object.__setattr__(self, "sample",
                               SampleConfig(temperature=0.0))
        if self.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {self.max_new_tokens}"
            )
        for name in ("prompt_buckets", "batch_buckets"):
            buckets = tuple(getattr(self, name))
            object.__setattr__(self, name, buckets)
            if not buckets or any(b < 1 for b in buckets):
                raise ValueError(f"{name} must be non-empty and positive")
            if list(buckets) != sorted(set(buckets)):
                raise ValueError(
                    f"{name} must be strictly increasing, got {buckets}"
                )
        if self.admission not in ("block", "reject"):
            raise ValueError(
                f"admission must be 'block' or 'reject', "
                f"got {self.admission!r}"
            )
        if self.max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {self.max_queue}")
        if self.flush_deadline_s < 0:
            raise ValueError("flush_deadline_s must be >= 0")


@dataclasses.dataclass(frozen=True)
class ServeResult:
    """One resolved request.

    ``tokens`` is the request's generated row, length =
    its ``max_new_tokens`` (eos included where sampled, pad after it) —
    byte-identical to ``generation.generate``'s row for the same prompt.
    ``num_generated`` counts real tokens (eos included).  The batch
    fields record how the request was served (occupancy debugging).
    """

    tokens: np.ndarray
    num_generated: int
    bucket_len: int
    batch_size: int
    latency_seconds: float


@dataclasses.dataclass
class _Request:
    prompt: np.ndarray
    prompt_len: int
    max_new_tokens: int
    bucket_len: int
    future: Future
    submitted: float  # perf_counter


class _Cell:
    """The compiled-program pair for one (bucket_len, batch_size) point.

    ``AotStep`` wrappers (training.compile_cache): a warmed cell
    dispatches the pre-compiled executable; an un-warmed (or mismatched)
    one falls back to the jitted function, which compiles on first use —
    warmup makes the engine fast, never wrong.
    """

    def __init__(self, engine: "ServingEngine", bucket_len: int,
                 batch_size: int):
        import functools

        import jax

        from cloud_tpu.models import generation
        from cloud_tpu.training import compile_cache

        cfg = engine.serve_config
        self.bucket_len = bucket_len
        self.batch_size = batch_size
        prefill_fn = jax.jit(functools.partial(
            generation.prefill_program,
            config=engine.config, max_new_tokens=cfg.max_new_tokens,
            rules=engine.rules, mesh=engine.mesh, kv_quant=cfg.kv_quant,
        ))

        # Positional-arg wrapper: AotStep (and AOT-compiled executables)
        # dispatch positionally, but decode_program's rng is keyword-only.
        def decode_positional(params, cache, logits0, prompt_lens, rng):
            return generation.decode_program(
                params, cache, logits0, prompt_lens, engine.config,
                max_new_tokens=cfg.max_new_tokens, sample=cfg.sample,
                rng=rng, rules=engine.rules, mesh=engine.mesh,
            )

        decode_fn = jax.jit(decode_positional)
        tag = f"L{bucket_len}_B{batch_size}"
        self.prefill = compile_cache.AotStep(
            prefill_fn, label=f"serve/prefill_{tag}"
        )
        self.decode = compile_cache.AotStep(
            decode_fn, label=f"serve/decode_{tag}"
        )


class ServingEngine:
    """In-process dynamic-batching server over ``generation`` (module
    docstring).  Construct, ``submit()`` concurrently from any thread,
    ``close()`` when done (or use as a context manager)."""

    def __init__(
        self,
        params,
        config,
        serve_config: Optional[ServeConfig] = None,
        *,
        rules=None,
        mesh=None,
        start: bool = True,
    ):
        import jax

        from cloud_tpu.models import generation
        from cloud_tpu.parallel import mesh as mesh_lib
        from cloud_tpu.parallel.sharding import DEFAULT_RULES

        self.params = params
        self.config = config
        self.serve_config = serve_config or ServeConfig()
        self.rules = rules if rules is not None else DEFAULT_RULES
        self.mesh = mesh if mesh is not None else mesh_lib.get_global_mesh()
        generation.check_inference_supported(
            config, self.rules, self.mesh, "serving"
        )
        # Engine-owned rng chain: split per batch (carried but
        # unobservable under greedy — one decode signature either way).
        self._rng = jax.random.PRNGKey(self.serve_config.seed)

        self._cond = threading.Condition()
        #: bucket_len -> FIFO of waiting _Requests (guarded by _cond).
        self._pending: Dict[int, collections.deque] = {}
        self._waiting = 0
        self._closed = False
        self._draining = True
        self._thread: Optional[threading.Thread] = None
        self._cells: Dict[Tuple[int, int], _Cell] = {}
        self._warmup_plan = None

        self._stats_lock = threading.Lock()
        self._stats = {
            "requests": 0, "completed": 0, "failed": 0, "rejected": 0,
            "batches": 0, "slots": 0, "real_rows": 0,
            "generated_tokens": 0,
        }
        self._qps = metrics.WindowedRate("serve/qps", window=16)
        self._tokens_rate = metrics.WindowedRate(
            "serve/tokens_per_sec", window=256
        )

        if self.serve_config.warmup:
            self._start_warmup()
        if start:
            self.start()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ServingEngine":
        """Launch the scheduler thread (idempotent)."""
        with self._cond:
            if self._closed:
                raise EngineClosedError("engine already closed")
            if self._thread is not None:
                return self
            self._thread = threading.Thread(
                target=self._scheduler_loop, daemon=True,
                name=SERVE_SCHEDULER_THREAD_NAME,
            )
            self._thread.start()
        return self

    def close(self, drain: bool = True, timeout: Optional[float] = None
              ) -> None:
        """Stop the engine: no more admissions, resolve what is owed.

        ``drain=True`` (default) serves every already-admitted request
        before the scheduler exits; ``drain=False`` fails waiting
        requests with :class:`EngineClosedError` immediately.  Joins the
        scheduler and any warmup worker — after ``close()`` returns, the
        engine owns zero live threads.
        """
        with self._cond:
            self._closed = True
            self._draining = drain
            # A never-started engine has no scheduler to drain through:
            # fail what waits rather than strand the futures forever.
            if not drain or self._thread is None:
                self._fail_pending_locked(
                    EngineClosedError("engine closed before dispatch")
                )
            self._cond.notify_all()
            thread = self._thread
        if thread is not None:
            thread.join(timeout)
        if self._warmup_plan is not None:
            self._warmup_plan.wait(timeout=timeout)
        now = time.perf_counter()
        self._qps.flush(now)
        self._tokens_rate.flush(now)

    def __enter__(self) -> "ServingEngine":
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False

    # -- submission --------------------------------------------------------

    @property
    def max_prompt_len(self) -> int:
        return self.serve_config.prompt_buckets[-1]

    def submit(self, prompt, *, max_new_tokens: Optional[int] = None
               ) -> Future:
        """Enqueue one prompt; returns a Future of :class:`ServeResult`.

        ``prompt`` is a 1-D int sequence (length 1 ..
        ``prompt_buckets[-1]``).  ``max_new_tokens`` may be below the
        engine-wide ``serve_config.max_new_tokens`` (the row is trimmed —
        greedy decode is prefix-consistent, so this equals a shorter
        direct run); above it is an error.  Thread-safe; blocks or
        raises :class:`QueueFullError` at ``max_queue`` per the
        admission policy.
        """
        cfg = self.serve_config
        prompt = np.asarray(prompt, np.int32)
        if prompt.ndim != 1:
            raise ValueError(
                f"prompt must be 1-D token ids, got shape {prompt.shape}"
            )
        n = int(prompt.shape[0])
        if not 1 <= n <= self.max_prompt_len:
            raise ValueError(
                f"prompt length {n} outside [1, {self.max_prompt_len}] "
                f"(prompt_buckets={cfg.prompt_buckets})"
            )
        m = cfg.max_new_tokens if max_new_tokens is None else int(
            max_new_tokens)
        if not 1 <= m <= cfg.max_new_tokens:
            raise ValueError(
                f"max_new_tokens {m} outside [1, {cfg.max_new_tokens}]"
            )
        bucket_len = next(b for b in cfg.prompt_buckets if b >= n)
        request = _Request(
            prompt=prompt, prompt_len=n, max_new_tokens=m,
            bucket_len=bucket_len, future=Future(),
            submitted=time.perf_counter(),
        )
        with self._cond:
            if self._closed:
                raise EngineClosedError("engine is closed")
            if self._waiting >= cfg.max_queue:
                if cfg.admission == "reject":
                    with self._stats_lock:
                        self._stats["rejected"] += 1
                    metrics.counter_inc("serve/rejected")
                    raise QueueFullError(
                        f"serving queue full ({cfg.max_queue} waiting); "
                        "retry with backoff or raise max_queue"
                    )
                while self._waiting >= cfg.max_queue and not self._closed:
                    self._cond.wait()
                if self._closed:
                    raise EngineClosedError("engine closed while blocked "
                                            "on admission")
            self._pending.setdefault(
                bucket_len, collections.deque()
            ).append(request)
            self._waiting += 1
            self._cond.notify_all()
        with self._stats_lock:
            self._stats["requests"] += 1
        metrics.counter_inc("serve/requests")
        return request.future

    # -- warmup ------------------------------------------------------------

    def _start_warmup(self) -> None:
        """Queue AOT compiles for the whole grid on the compile-ahead
        worker (one background thread, in grid order — smallest programs
        first so early traffic warms soonest)."""
        import jax

        from cloud_tpu.training import compile_cache

        cfg = self.serve_config
        params_avals = compile_cache.abstract_state(self.params)
        context = compile_cache.context_key(mesh=self.mesh, rules=self.rules)
        rng_aval = jax.ShapeDtypeStruct(self._rng.shape, self._rng.dtype)
        jobs = []
        for bucket_len in cfg.prompt_buckets:
            for batch_size in cfg.batch_buckets:
                cell = self._cell(bucket_len, batch_size)
                tok_aval = jax.ShapeDtypeStruct(
                    (batch_size, bucket_len), np.int32
                )
                lens_aval = jax.ShapeDtypeStruct((batch_size,), np.int32)
                prefill_args = (params_avals, tok_aval, lens_aval)
                jobs.append((cell.prefill, prefill_args, context))

                def decode_args(cell=cell, prefill_args=prefill_args):
                    # Resolved on the worker right before the decode
                    # compile: the cache/logits avals come from an
                    # eval_shape of the prefill program (pure tracing).
                    cache_aval, logits_aval = jax.eval_shape(
                        cell.prefill.jitted, *prefill_args
                    )
                    return (params_avals, cache_aval, logits_aval,
                            prefill_args[2], rng_aval)

                jobs.append((cell.decode, decode_args, context))
        self._warmup_plan = compile_cache.start_compile_ahead(jobs)

    def wait_ready(self, timeout: Optional[float] = None) -> None:
        """Block until the warmup grid has finished compiling (no-op
        without ``warmup=True``; compile failures were logged and those
        cells fall back to jit — see ``compile_cache.CompileAhead``)."""
        if self._warmup_plan is not None:
            self._warmup_plan.wait(timeout=timeout)

    # -- scheduler ---------------------------------------------------------

    def _cell(self, bucket_len: int, batch_size: int) -> _Cell:
        key = (bucket_len, batch_size)
        cell = self._cells.get(key)
        if cell is None:
            cell = self._cells[key] = _Cell(self, bucket_len, batch_size)
        return cell

    def _fail_pending_locked(self, exc: BaseException) -> None:
        failed = 0
        for queue_ in self._pending.values():
            while queue_:
                request = queue_.popleft()
                self._waiting -= 1
                failed += 1
                try:
                    request.future.set_exception(exc)
                except InvalidStateError:  # pragma: no cover - cancelled
                    pass
        if failed:
            with self._stats_lock:
                self._stats["failed"] += failed

    def _pop_batch_locked(self, now: float) -> Optional[List[_Request]]:
        """The batch-formation policy (caller holds the lock).

        Priority: (1) the bucket whose HEAD request has waited past
        ``flush_deadline_s``, oldest head first — the deadline is a real
        bound, never preempted by other buckets' saturation (under
        sustained traffic the saturated bucket's own head is expired
        too, so oldest-first degenerates to FIFO across buckets and a
        minority bucket cannot starve); (2) any bucket with a full
        max-batch — no deadline pressure, so take the occupancy win;
        (3) when draining a closed engine, anything left.  Whichever
        bucket wins, up to a full max-batch is taken from it.
        """
        max_batch = self.serve_config.batch_buckets[-1]
        chosen = None
        for queue_ in self._pending.values():
            if not queue_:
                continue
            head = queue_[0]
            if now - head.submitted >= self.serve_config.flush_deadline_s:
                if chosen is None or head.submitted < chosen[0].submitted:
                    chosen = queue_
        if chosen is None:
            for queue_ in self._pending.values():
                if len(queue_) >= max_batch:
                    chosen = queue_
                    break
        if chosen is None and self._closed and self._draining:
            chosen = next(
                (q for q in self._pending.values() if q), None
            )
        if chosen is None:
            return None
        batch = []
        while chosen and len(batch) < max_batch:
            batch.append(chosen.popleft())
        return batch

    def _earliest_deadline_locked(self) -> Optional[float]:
        heads = [q[0].submitted for q in self._pending.values() if q]
        if not heads:
            return None
        return min(heads) + self.serve_config.flush_deadline_s

    def _scheduler_loop(self) -> None:
        try:
            while True:
                with self._cond:
                    while True:
                        now = time.perf_counter()
                        batch = self._pop_batch_locked(now)
                        if batch is not None:
                            self._waiting -= len(batch)
                            self._cond.notify_all()  # admission space freed
                            break
                        if self._closed:
                            return
                        deadline = self._earliest_deadline_locked()
                        timeout = (
                            None if deadline is None
                            else max(deadline - now, 1e-4)
                        )
                        self._cond.wait(timeout)
                try:
                    self._dispatch(batch)
                except BaseException as exc:  # noqa: BLE001 — per-batch
                    logger.exception("serving dispatch failed")
                    metrics.counter_inc("serve/batch_errors")
                    with self._stats_lock:
                        self._stats["failed"] += len(batch)
                    for request in batch:
                        try:
                            request.future.set_exception(exc)
                        except InvalidStateError:  # pragma: no cover
                            pass
        except BaseException as exc:  # noqa: BLE001 — scheduler must not
            # die silently: fail everything still queued and refuse new work.
            logger.exception("serving scheduler crashed")
            with self._cond:
                self._closed = True
                self._fail_pending_locked(exc)
                self._cond.notify_all()

    def _dispatch(self, batch: List[_Request]) -> None:
        import jax

        cfg = self.serve_config
        bucket_len = batch[0].bucket_len
        n = len(batch)
        batch_size = next(b for b in cfg.batch_buckets if b >= n)
        form_start = time.perf_counter()
        for request in batch:
            tracing.record_span(
                "serve/queue_wait", request.submitted, form_start,
                bucket=bucket_len,
            )
        with tracing.span("serve/batch_form", bucket=bucket_len,
                          rows=n, batch=batch_size):
            tokens = np.zeros((batch_size, bucket_len), np.int32)
            lens = np.ones((batch_size,), np.int32)
            for i, request in enumerate(batch):
                tokens[i, :request.prompt_len] = request.prompt
                lens[i] = request.prompt_len
        cell = self._cell(bucket_len, batch_size)
        self._rng, batch_rng = jax.random.split(self._rng)
        with tracing.span("serve/prefill", bucket=bucket_len,
                          batch=batch_size):
            cache, logits0 = cell.prefill(self.params, tokens, lens)
            jax.block_until_ready(logits0)
        with tracing.span("serve/decode", bucket=bucket_len,
                          batch=batch_size):
            out = cell.decode(self.params, cache, logits0, lens, batch_rng)
            out_tokens = np.asarray(out["tokens"])
            out_nums = np.asarray(out["num_generated"])
        done = time.perf_counter()

        results = []
        generated = 0
        for i, request in enumerate(batch):
            m = request.max_new_tokens
            num = int(min(out_nums[i], m))
            generated += num
            result = ServeResult(
                tokens=out_tokens[i, :m].copy(),
                num_generated=num,
                bucket_len=bucket_len,
                batch_size=batch_size,
                latency_seconds=done - request.submitted,
            )
            metrics.distribution_record(
                "serve/latency_seconds", result.latency_seconds
            )
            results.append(result)

        # Stats/metrics BEFORE the futures resolve: a caller waking from
        # ``future.result()`` must see this batch already counted.
        metrics.counter_inc("serve/batches")
        metrics.counter_inc("serve/generated_tokens", generated)
        metrics.gauge_set("serve/batch_occupancy", n / batch_size)
        self._qps.add(done, n)
        self._tokens_rate.add(done, generated)
        with self._stats_lock:
            self._stats["batches"] += 1
            self._stats["slots"] += batch_size
            self._stats["real_rows"] += n
            self._stats["completed"] += n
            self._stats["generated_tokens"] += generated
        for request, result in zip(batch, results):
            try:
                request.future.set_result(result)
            except InvalidStateError:  # pragma: no cover - cancelled
                pass

    # -- introspection -----------------------------------------------------

    def stats(self) -> dict:
        """Counters snapshot + mean batch occupancy (real rows / dispatched
        slots — the number the dynamic batcher is judged by)."""
        with self._stats_lock:
            snap = dict(self._stats)
        snap["mean_batch_occupancy"] = (
            snap["real_rows"] / snap["slots"] if snap["slots"] else 0.0
        )
        return snap
