"""cloud-tpu: a TPU-native launch-and-scale framework built on JAX/XLA.

One ``run()`` call takes a local training script or notebook, validates a
declarative TPU slice config, plans a ``jax.sharding.Mesh`` parallelism
layout, containerizes the code, and launches it on Cloud TPU — plus a
Vizier-backed hyperparameter tuner, an in-memory remote-fit path, and a
native metrics exporter.

Public surface parity with the reference package root
(``tensorflow_cloud/__init__.py:17-27``): run, remote, MachineConfig,
AcceleratorType, COMMON_MACHINE_CONFIGS, CloudTuner, CloudOracle, cloud_fit.
"""

from cloud_tpu.version import __version__

from cloud_tpu.core.machine_config import (
    AcceleratorType,
    COMMON_MACHINE_CONFIGS,
    MachineConfig,
    TpuTopology,
    TPU_SLICE_CATALOG,
    is_tpu_config,
)

__all__ = [
    "__version__",
    "AcceleratorType",
    "COMMON_MACHINE_CONFIGS",
    "MachineConfig",
    "TpuTopology",
    "TPU_SLICE_CATALOG",
    "is_tpu_config",
]


def __getattr__(name):
    # Lazy re-exports: keep `import cloud_tpu` light (no jax/tuner import cost
    # until used).  Mirrors the reference's flat package-root API.
    try:
        if name in ("run", "remote", "RunReport"):
            from cloud_tpu.core import run as _run

            return getattr(_run, name)
        if name in ("CloudTuner", "CloudOracle"):
            from cloud_tpu import tuner as _tuner

            return getattr(_tuner, name)
        if name == "cloud_fit":
            from cloud_tpu.cloud_fit import client as _client

            return _client.cloud_fit
    except ImportError as e:
        raise AttributeError(
            f"cloud_tpu.{name} is unavailable: {e}"
        ) from e
    raise AttributeError(f"module 'cloud_tpu' has no attribute {name!r}")
