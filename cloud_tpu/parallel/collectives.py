"""Named-axis collective algorithms for use inside ``shard_map``.

XLA inserts collectives automatically for pjit-sharded code; this module
is for the explicitly-scheduled paths.  Two kinds of content:

* thin named wrappers over ``lax`` collectives (readability at the ring
  attention / pipeline call sites, and the seam where a future backend
  tweak lands once);
* real algorithms XLA does NOT produce on its own: the bandwidth-optimal
  two-level all-reduce for multi-slice meshes
  (:func:`hierarchical_all_reduce_sum`), precision-safe gradient
  synchronization (:func:`grad_sync`), and the sequence<->head
  re-sharding all-to-all (:func:`all_to_all_seq_heads`).

All take mesh axis names, never device ids — the TPU-native replacement
for the reference's NCCL/gRPC CollectiveOps backends (SURVEY.md §2.6),
which lived inside tf.distribute.
"""

from __future__ import annotations

from typing import Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

from cloud_tpu.monitoring import tracing

AxisNames = Union[str, Sequence[str]]


def _payload_bytes(x):
    """Stored bytes of a pytree (works on tracers: avals carry shape/dtype)."""
    try:
        return sum(
            leaf.size * leaf.dtype.itemsize
            for leaf in jax.tree_util.tree_leaves(x)
            if hasattr(leaf, "size") and hasattr(leaf, "dtype")
        )
    except Exception:  # noqa: BLE001 — attribution only, never fail the op
        return None


def _span(name: str, x, axis):
    """Collective span carrying payload size + axis.

    These fire at TRACE time (collectives run inside jit), so they
    attribute host-side tracing/lowering cost and record per-collective
    payload sizes — the bytes the compiled program will move.  The
    payload walk is skipped entirely when tracing is disabled.
    """
    if not tracing.enabled():
        return tracing.span(name)
    return tracing.span(
        name, payload_bytes=_payload_bytes(x), axis=str(axis)
    )


def all_reduce_sum(x, axis: AxisNames):
    with _span("collective/all_reduce_sum", x, axis):
        return lax.psum(x, axis)


def all_reduce_mean(x, axis: AxisNames):
    with _span("collective/all_reduce_mean", x, axis):
        return lax.pmean(x, axis)


def all_gather(x, axis: str, *, gather_dim: int = 0, tiled: bool = True):
    with _span("collective/all_gather", x, axis):
        return lax.all_gather(x, axis, axis=gather_dim, tiled=tiled)


def reduce_scatter_sum(x, axis: str, *, scatter_dim: int = 0):
    with _span("collective/reduce_scatter_sum", x, axis):
        return lax.psum_scatter(
            x, axis, scatter_dimension=scatter_dim, tiled=True
        )


def ring_permute(x, axis: str, *, shift: int = 1):
    """Send this shard to the neighbour ``shift`` positions along ``axis``.

    On TPU the resulting ``ppermute`` rides nearest-neighbour ICI links,
    which is what makes ring attention and pipeline transfers overlap with
    compute.
    """
    with _span("collective/ring_permute", x, axis):
        n = lax.axis_size(axis)
        perm = [(i, (i + shift) % n) for i in range(n)]
        return lax.ppermute(x, axis, perm)


def axis_index(axis: str):
    return lax.axis_index(axis)


def axis_size(axis: str):
    return lax.axis_size(axis)


def barrier(axis: AxisNames):
    """Cross-device synchronization point (a trivial psum)."""
    return lax.psum(jnp.zeros((), jnp.int32), axis)


def broadcast_from(x, axis: str, *, root: int = 0):
    """Every member of ``axis`` gets root's value."""
    with _span("collective/broadcast_from", x, axis):
        idx = lax.axis_index(axis)
        zero = jnp.where(idx == root, x, jnp.zeros_like(x))
        return lax.psum(zero, axis)


def host_local_mean(tree):
    """jnp mean of a pytree across all devices outside shard_map (jit-level)."""
    return jax.tree_util.tree_map(jnp.mean, tree)


def hierarchical_all_reduce_sum(x, *, ici_axis: str, dcn_axis: str,
                                scatter_dim: int = 0):
    """Two-level all-reduce for multi-slice meshes: reduce-scatter over
    the fast in-slice links, all-reduce the 1/n_ici-sized shard across
    slices, then all-gather back over ICI.

    A flat ``psum`` over both axes moves the FULL tensor across DCN; this
    decomposition moves ``1/ici_size`` of it — the standard bandwidth-
    optimal schedule when the outer network is the bottleneck (each DCN
    link carries only the shard its ICI group owns).  Use for gradient
    sync on ``dcn_sizes``-split meshes (``MeshSpec.dcn_axes``); for
    single-slice meshes plain :func:`all_reduce_sum` is simpler and XLA
    already schedules it well.

    ``scatter_dim`` must divide evenly by the ICI axis size.
    """
    with _span(
        "collective/hierarchical_all_reduce_sum", x, (ici_axis, dcn_axis)
    ):
        n = lax.axis_size(ici_axis)
        if x.shape[scatter_dim] % n:
            # Indivisible shapes can't scatter; correctness beats bandwidth.
            return lax.psum(x, (ici_axis, dcn_axis))
        shard = lax.psum_scatter(
            x, ici_axis, scatter_dimension=scatter_dim, tiled=True
        )
        shard = lax.psum(shard, dcn_axis)
        return lax.all_gather(shard, ici_axis, axis=scatter_dim, tiled=True)


def grad_sync(grads, axis: AxisNames, *, mean: bool = True,
              accum_dtype=jnp.float32):
    """Synchronize a gradient pytree across ``axis`` with precision-safe
    accumulation: bf16/fp16 leaves are upcast to ``accum_dtype`` for the
    reduction and cast back after.

    On large rings a bf16 psum loses low-order bits at every add (the
    reduction runs in the wire dtype); mixed-precision recipes therefore
    accumulate in f32.  Leaves already >= ``accum_dtype`` wide pass
    through unchanged.
    """
    reduce = lax.pmean if mean else lax.psum

    def sync_leaf(g):
        dtype = g.dtype
        if jnp.issubdtype(dtype, jnp.floating) and (
            jnp.finfo(dtype).bits < jnp.finfo(accum_dtype).bits
        ):
            return reduce(g.astype(accum_dtype), axis).astype(dtype)
        return reduce(g, axis)

    with _span("collective/grad_sync", grads, axis):
        return jax.tree_util.tree_map(sync_leaf, grads)


def all_to_all_seq_heads(x, axis: str, *, seq_dim: int = 1,
                         heads_dim: int = 2, to_heads: bool = True):
    """Re-shard attention activations between sequence-parallel and
    head-parallel layouts with one all-to-all (the Ulysses pattern).

    With ``to_heads=True`` an input sharded over sequence
    (``[B, T/n, H, D]`` per rank) becomes sharded over heads
    (``[B, T, H/n, D]``): each rank keeps every position for its own
    head group, which lets attention run WITHOUT ring hops; the inverse
    (``to_heads=False``) restores sequence sharding for the surrounding
    feed-forward.  Requires the global head count to divide by the axis
    size (ring attention covers the indivisible cases).
    """
    if to_heads:
        split, concat = heads_dim, seq_dim
    else:
        split, concat = seq_dim, heads_dim
    n = lax.axis_size(axis)
    if x.shape[split] % n:
        raise ValueError(
            f"all_to_all split dim {split} (size {x.shape[split]}) must "
            f"divide by axis {axis!r} size {n}"
        )
    with _span("collective/all_to_all_seq_heads", x, axis):
        return lax.all_to_all(
            x, axis, split_axis=split, concat_axis=concat, tiled=True
        )
