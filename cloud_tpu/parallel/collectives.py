"""Thin named-axis collective helpers for use inside ``shard_map``.

XLA inserts collectives automatically for pjit-sharded code; these wrappers
exist for the explicitly-scheduled paths (ring attention, pipeline) and for
readability at call sites.  All take mesh axis names, never device ids —
the TPU-native replacement for the reference's NCCL/gRPC CollectiveOps
backends (SURVEY.md §2.6), which lived inside tf.distribute.
"""

from __future__ import annotations

from typing import Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

AxisNames = Union[str, Sequence[str]]


def all_reduce_sum(x, axis: AxisNames):
    return lax.psum(x, axis)


def all_reduce_mean(x, axis: AxisNames):
    return lax.pmean(x, axis)


def all_gather(x, axis: str, *, gather_dim: int = 0, tiled: bool = True):
    return lax.all_gather(x, axis, axis=gather_dim, tiled=tiled)


def reduce_scatter_sum(x, axis: str, *, scatter_dim: int = 0):
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_dim, tiled=True)


def ring_permute(x, axis: str, *, shift: int = 1):
    """Send this shard to the neighbour ``shift`` positions along ``axis``.

    On TPU the resulting ``ppermute`` rides nearest-neighbour ICI links,
    which is what makes ring attention and pipeline transfers overlap with
    compute.
    """
    n = lax.axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)


def axis_index(axis: str):
    return lax.axis_index(axis)


def axis_size(axis: str):
    return lax.axis_size(axis)


def barrier(axis: AxisNames):
    """Cross-device synchronization point (a trivial psum)."""
    return lax.psum(jnp.zeros((), jnp.int32), axis)


def broadcast_from(x, axis: str, *, root: int = 0):
    """Every member of ``axis`` gets root's value."""
    idx = lax.axis_index(axis)
    zero = jnp.where(idx == root, x, jnp.zeros_like(x))
    return lax.psum(zero, axis)


def host_local_mean(tree):
    """jnp mean of a pytree across all devices outside shard_map (jit-level)."""
    return jax.tree_util.tree_map(jnp.mean, tree)
