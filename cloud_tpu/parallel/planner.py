"""Mesh planner: declarative machine config -> parallelism layout.

The TPU-native replacement for the reference's auto-strategy picker
(preprocess.py:124-149), which chose among OneDevice/Mirrored/MWMS/TPU
strategies by *generating source text*.  Here the decision produces a
:class:`MeshPlan` — a named-axis mesh layout plus sharding rules — that the
bootstrap runner materializes on every host before user code runs.

Mapping from the reference's decision table:

=============================  ========================================
reference strategy             mesh plan
=============================  ========================================
OneDeviceStrategy              1 device, all axes 1
MirroredStrategy               single slice: ``dp`` = chips (replicated
                               params, ICI all-reduce)
MultiWorkerMirroredStrategy    multi-host slice: ``fsdp`` = chips
                               (ZeRO-style sharded DP over ICI)
TPUStrategy                    any TPU slice (same as above; SPMD is
                               the only mode here)
multi-slice (worker_count>0)   ``dp`` across slices on DCN x ``fsdp``
                               within each slice on ICI
=============================  ========================================

Hints let users express what the reference never could: tensor, pipeline,
sequence and expert parallelism as explicit axis sizes.
"""

from __future__ import annotations

import dataclasses
import json
import math
from typing import Dict, Optional

from cloud_tpu.core import machine_config as mc_lib
from cloud_tpu.parallel import mesh as mesh_lib
from cloud_tpu.parallel.mesh import MeshSpec

#: Model-parallel axes a user can pin via hints.
_HINT_AXES = (
    mesh_lib.AXIS_TP,
    mesh_lib.AXIS_SP,
    mesh_lib.AXIS_PP,
    mesh_lib.AXIS_EP,
    mesh_lib.AXIS_FSDP,
    mesh_lib.AXIS_DP,
)


@dataclasses.dataclass(frozen=True)
class ParallelismHints:
    """Optional user pins for mesh axis sizes.

    Unset axes are planned automatically; set axes are honored or rejected
    (never silently adjusted).  ``prefer_fsdp`` switches the leftover
    data-parallel capacity between replicated ``dp`` and sharded ``fsdp``.
    """

    tp: Optional[int] = None
    sp: Optional[int] = None
    pp: Optional[int] = None
    ep: Optional[int] = None
    fsdp: Optional[int] = None
    dp: Optional[int] = None
    prefer_fsdp: bool = True

    def pinned(self) -> Dict[str, int]:
        out = {}
        for axis in _HINT_AXES:
            val = getattr(self, axis)
            if val is not None:
                if val < 1:
                    raise ValueError(f"Hint {axis}={val} must be >= 1")
                out[axis] = val
        return out


@dataclasses.dataclass(frozen=True)
class MeshPlan:
    """A fully-determined parallelism layout for one job."""

    spec: MeshSpec
    num_slices: int
    chips_per_slice: int
    hosts_per_slice: int
    description: str

    @property
    def total_chips(self) -> int:
        return self.num_slices * self.chips_per_slice

    @property
    def total_hosts(self) -> int:
        return self.num_slices * self.hosts_per_slice

    def build(self, devices=None):
        return self.spec.build(devices)

    def to_json(self) -> str:
        return json.dumps(
            {
                "sizes": self.spec.sizes,
                "dcn_sizes": self.spec.dcn_sizes,
                "num_slices": self.num_slices,
                "chips_per_slice": self.chips_per_slice,
                "hosts_per_slice": self.hosts_per_slice,
                "description": self.description,
            }
        )

    @classmethod
    def from_json(cls, data: str) -> "MeshPlan":
        obj = json.loads(data)
        return cls(
            spec=MeshSpec(
                sizes=obj["sizes"], dcn_sizes=obj.get("dcn_sizes", {})
            ),
            num_slices=obj["num_slices"],
            chips_per_slice=obj["chips_per_slice"],
            hosts_per_slice=obj["hosts_per_slice"],
            description=obj["description"],
        )


@dataclasses.dataclass(frozen=True)
class ServeLayout:
    """A fully-determined serving partition for one replica slice.

    Serving replicas shard the *generation* path — params by head/mlp/
    vocab, the slot KV cache (and prefix-cache block pool) by attention
    head — over a ``tp`` (x ``sp``) mesh, so one replica spans a
    multi-chip slice instead of one chip.  ``tp`` must divide the
    model's head count (head-granular KV sharding); ``sp`` is sequence
    parallelism over activations and defaults to 1.  The per-chip byte
    fields are planning *estimates* (params and KV divide by ``tp``;
    replicated norm scales are negligible), good enough to pick a
    layout against an HBM budget, not an allocator.
    """

    tp: int
    sp: int
    description: str
    param_bytes_per_chip: int = 0
    kv_bytes_per_chip: int = 0
    #: Speculative-decoding draft footprint (params + draft slot KV),
    #: budgeted REPLICATED per chip — the conservative bound: the engine
    #: head-shards the draft only when ``tp`` divides its head count.
    draft_bytes_per_chip: int = 0

    @property
    def num_chips(self) -> int:
        return self.tp * self.sp

    @property
    def shape(self) -> tuple:
        return (self.tp, self.sp)

    def mesh_spec(self) -> MeshSpec:
        return MeshSpec(sizes={
            mesh_lib.AXIS_SP: self.sp, mesh_lib.AXIS_TP: self.tp,
        })


def plan_serve_layout(
    *,
    num_heads: int,
    num_devices: int,
    param_bytes: int = 0,
    kv_bytes: int = 0,
    draft_bytes: int = 0,
    hbm_bytes_per_chip: Optional[int] = None,
    sp: int = 1,
) -> ServeLayout:
    """Pick the tensor-parallel serving partition for one replica slice.

    The serving analogue of :func:`plan_mesh` (AMP-style layout search,
    PAPERS.md): from the model's head count, the slice's chip count, and
    an optional per-chip HBM budget, choose the ``tp`` degree a
    ``ServingEngine`` replica shards its generation programs over.

    Candidates are every ``tp`` that divides ``num_heads`` (the KV cache
    shards by head — a non-dividing degree would split a head) and fits
    the slice (``tp * sp <= num_devices``).  Without a budget the
    largest candidate wins: use the whole slice for per-request speed.
    With ``hbm_bytes_per_chip``, the SMALLEST candidate whose estimated
    per-chip bytes (params + KV, both ~1/tp, plus the whole
    ``draft_bytes`` term — a speculative-decoding draft model's params +
    draft slot KV, budgeted replicated since the engine only head-shards
    a draft whose head count ``tp`` divides) fit wins — sharding no
    wider than memory requires leaves the remaining chips for more
    replicas, which is the fleet's business, not the slice's.  Raises
    ``ValueError`` (naming every number involved) when even the widest
    candidate busts the budget.
    """
    if num_heads < 1:
        raise ValueError(f"num_heads must be >= 1, got {num_heads}")
    if num_devices < 1:
        raise ValueError(f"num_devices must be >= 1, got {num_devices}")
    if sp < 1:
        raise ValueError(f"sp must be >= 1, got {sp}")
    if sp > num_devices:
        raise ValueError(
            f"sp={sp} exceeds the slice's {num_devices} device(s)"
        )
    candidates = [
        t for t in range(1, num_devices // sp + 1) if num_heads % t == 0
    ]

    def per_chip(tp: int) -> tuple:
        return (param_bytes + tp - 1) // tp, (kv_bytes + tp - 1) // tp

    if hbm_bytes_per_chip is None:
        tp = candidates[-1]
    else:
        fitting = [
            t for t in candidates
            if sum(per_chip(t)) + draft_bytes <= hbm_bytes_per_chip
        ]
        if not fitting:
            widest = candidates[-1]
            raise ValueError(
                f"No serving layout fits hbm_bytes_per_chip="
                f"{hbm_bytes_per_chip}: even tp={widest} (the widest "
                f"divisor of num_heads={num_heads} within "
                f"{num_devices} device(s), sp={sp}) needs "
                f"{sum(per_chip(widest)) + draft_bytes} bytes/chip "
                f"(params {param_bytes} + kv {kv_bytes} total"
                + (
                    f" + draft {draft_bytes} replicated"
                    if draft_bytes else ""
                )
                + "). Shrink the model/cache/draft or grow the slice."
            )
        tp = fitting[0]
    p_chip, k_chip = per_chip(tp)
    description = (
        f"serve slice {tp * sp} chip(s): tp={tp}"
        + (f" x sp={sp}" if sp > 1 else "")
        + f" ({num_heads} heads -> {num_heads // tp}/chip"
        + (
            f", ~{(p_chip + k_chip + draft_bytes) >> 20} MiB/chip"
            if param_bytes or kv_bytes or draft_bytes else ""
        )
        + (f", draft ~{draft_bytes >> 20} MiB replicated"
           if draft_bytes else "")
        + ")"
    )
    return ServeLayout(
        tp=tp, sp=sp, description=description,
        param_bytes_per_chip=p_chip, kv_bytes_per_chip=k_chip,
        draft_bytes_per_chip=draft_bytes,
    )


def plan_mesh(
    chief_config: Optional[mc_lib.MachineConfig] = None,
    worker_count: int = 0,
    hints: Optional[ParallelismHints] = None,
    num_devices: Optional[int] = None,
) -> MeshPlan:
    """Plan the mesh for a job.

    ``chief_config`` describes the TPU slice every worker runs (reference
    semantics: ``worker_count`` *additional* replicas of the slice, so the
    job spans ``worker_count + 1`` slices).  ``num_devices`` overrides the
    chip count for local/virtual runs (tests, CPU dry-runs) where no
    MachineConfig exists; combined with ``worker_count`` it plans a
    multi-slice job over virtual devices (``num_devices`` total chips
    split evenly into ``worker_count + 1`` slices), so the dp-over-DCN
    rule below is exercisable without TPU hardware.
    """
    hints = hints or ParallelismHints()

    if num_devices is not None:
        num_slices = worker_count + 1
        if num_devices % num_slices:
            raise ValueError(
                f"num_devices={num_devices} cannot be split into "
                f"worker_count + 1 = {num_slices} equal virtual slices "
                f"(worker_count={worker_count}); num_devices must be a "
                f"multiple of {num_slices}"
            )
        chips_per_slice = num_devices // num_slices
        hosts_per_slice = 1
    elif chief_config is not None and chief_config.is_tpu():
        topo = chief_config.tpu_topology()
        chips_per_slice = topo.chips
        hosts_per_slice = topo.hosts
        num_slices = worker_count + 1
    else:
        # CPU-only role: a single process, single "device" plan.
        chips_per_slice = 1
        hosts_per_slice = 1
        num_slices = 1

    total = chips_per_slice * num_slices
    pinned = hints.pinned()

    model_parallel = math.prod(
        pinned.get(a, 1)
        for a in (mesh_lib.AXIS_TP, mesh_lib.AXIS_SP, mesh_lib.AXIS_PP, mesh_lib.AXIS_EP)
    )
    if total % model_parallel:
        raise ValueError(
            f"Model-parallel axes (tp x sp x pp x ep = {model_parallel}) do not "
            f"divide the total chip count {total} "
            f"({num_slices} slice(s) x {chips_per_slice} chips)."
        )
    data_capacity = total // model_parallel

    dp = pinned.get(mesh_lib.AXIS_DP)
    fsdp = pinned.get(mesh_lib.AXIS_FSDP)
    if dp is None and fsdp is None:
        if num_slices > 1:
            # DCN-friendly default: replicate across slices, shard within.
            if data_capacity % num_slices:
                raise ValueError(
                    f"Data-parallel capacity {data_capacity} not divisible by "
                    f"{num_slices} slices; pin dp/fsdp explicitly."
                )
            dp, fsdp = num_slices, data_capacity // num_slices
        elif hosts_per_slice > 1 or hints.prefer_fsdp:
            # Multi-host (or large-model preference): shard params over ICI.
            dp, fsdp = 1, data_capacity
        else:
            dp, fsdp = data_capacity, 1
    elif dp is None:
        if data_capacity % fsdp:
            raise ValueError(
                f"fsdp={fsdp} does not divide data capacity {data_capacity}"
            )
        dp = data_capacity // fsdp
    elif fsdp is None:
        if data_capacity % dp:
            raise ValueError(
                f"dp={dp} does not divide data capacity {data_capacity}"
            )
        fsdp = data_capacity // dp
    elif dp * fsdp != data_capacity:
        raise ValueError(
            f"dp={dp} x fsdp={fsdp} != data capacity {data_capacity} "
            f"(total {total} / model-parallel {model_parallel})"
        )

    sizes = {
        mesh_lib.AXIS_DP: dp,
        mesh_lib.AXIS_PP: pinned.get(mesh_lib.AXIS_PP, 1),
        mesh_lib.AXIS_FSDP: fsdp,
        mesh_lib.AXIS_EP: pinned.get(mesh_lib.AXIS_EP, 1),
        mesh_lib.AXIS_SP: pinned.get(mesh_lib.AXIS_SP, 1),
        mesh_lib.AXIS_TP: pinned.get(mesh_lib.AXIS_TP, 1),
    }
    dcn_sizes = {}
    if num_slices > 1:
        # Slice boundaries are crossed by the dp axis only (the lone
        # per-step collective tolerant of DCN latency).  A plan whose dp
        # cannot absorb the slice count would force another axis onto DCN —
        # reject it rather than silently build a layout whose ICI-hungry
        # collectives ride the slow links.
        if dp % num_slices:
            raise ValueError(
                f"Multi-slice plan needs dp divisible by the slice count: "
                f"dp={dp}, slices={num_slices}. Pin dp to a multiple of "
                f"{num_slices} (or leave dp/fsdp unpinned)."
            )
        dcn_sizes = {mesh_lib.AXIS_DP: num_slices}
    spec = MeshSpec(sizes=sizes, dcn_sizes=dcn_sizes)

    nontrivial = {a: s for a, s in sizes.items() if s > 1} or {"dp": 1}
    description = (
        f"{num_slices} slice(s) x {chips_per_slice} chips: "
        + " x ".join(f"{a}={s}" for a, s in nontrivial.items())
        + (" (dp over DCN)" if dcn_sizes else "")
    )
    return MeshPlan(
        spec=spec,
        num_slices=num_slices,
        chips_per_slice=chips_per_slice,
        hosts_per_slice=hosts_per_slice,
        description=description,
    )
