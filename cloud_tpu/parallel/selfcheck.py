"""Distributed-runtime self-check CLI: run one on every host of a job.

``python -m cloud_tpu.parallel.selfcheck`` initializes the multi-process
runtime from the ``CLOUD_TPU_*`` env contract (parallel/distributed.py),
then proves the job is actually wired: a cross-process global reduction
and one real sharded train step, reported as a single JSON line.

This is the executable answer to SURVEY.md §7's hard part 2 — "failure
modes are hangs, not errors": ``initialize_from_env`` runs with a bounded
``timeout_seconds`` so a mis-wired coordinator fails loudly, and every
phase is stamped into the JSON so a partial wedge is attributable.  The
reference's analogue is the TF_CONFIG cluster-faking rig
(cloud_fit/tests/unit/remote_test.py:76-82) — but executed here as real
processes over real collectives, not an env-var simulation.

Env knobs: ``CLOUD_TPU_SELFCHECK_FORCE_CPU=1`` pins the CPU platform
(the local rig), ``CLOUD_TPU_SELFCHECK_TIMEOUT`` bounds the distributed
init (default 60 s).
"""

from __future__ import annotations

import json
import os
import sys


def run_selfcheck() -> dict:
    import jax

    if os.environ.get("CLOUD_TPU_SELFCHECK_FORCE_CPU"):
        jax.config.update("jax_platforms", "cpu")

    from cloud_tpu.parallel import distributed

    report = {"phase": "init"}
    timeout = int(os.environ.get("CLOUD_TPU_SELFCHECK_TIMEOUT", "60"))
    report["distributed"] = distributed.initialize_from_env(
        timeout_seconds=timeout
    )
    report.update(
        process_index=jax.process_index(),
        process_count=jax.process_count(),
        device_count=jax.device_count(),
        local_device_count=jax.local_device_count(),
        platform=jax.devices()[0].platform,
    )

    import functools

    import jax.numpy as jnp
    import numpy as np
    import optax

    from cloud_tpu import parallel
    from cloud_tpu.models import mnist
    from cloud_tpu.training import train as train_lib

    # Phase 1: cross-process global reduction.  Every process contributes
    # rank+1 on each of its local rows; the jit-computed global sum proves
    # the collectives span all processes, not just this host.
    report["phase"] = "psum"
    mesh = parallel.MeshSpec({"dp": jax.device_count()}).build()
    local = np.full(
        (jax.local_device_count(), 4), float(jax.process_index() + 1),
        np.float32,
    )
    arr = train_lib.shard_batch({"x": local}, mesh)["x"]
    report["global_sum"] = float(jax.jit(jnp.sum)(arr))
    report["expected_sum"] = float(
        sum(
            (r + 1) * jax.local_device_count() * 4
            for r in range(jax.process_count())
        )
    )

    # Phase 2: one real sharded train step on per-host data.
    report["phase"] = "train_step"
    cfg = mnist.MnistConfig(hidden_dim=16)
    logical_axes = mnist.param_logical_axes(cfg)
    with parallel.use_mesh(mesh):
        state = train_lib.create_sharded_state(
            jax.random.PRNGKey(0),
            functools.partial(mnist.init, config=cfg),
            optax.sgd(0.1),
            mesh,
            logical_axes=logical_axes,
        )
        step = train_lib.make_train_step(
            functools.partial(mnist.loss_fn, config=cfg),
            optax.sgd(0.1),
            logical_axes=logical_axes,
            mesh=mesh,
        )
        rng = np.random.default_rng(jax.process_index())
        local_batch = {
            "image": rng.normal(
                size=(2 * jax.local_device_count(), 784)
            ).astype(np.float32),
            "label": rng.integers(0, 10, 2 * jax.local_device_count()),
        }
        batch = train_lib.shard_batch(local_batch, mesh)
        state, metrics = step(state, batch)
        report["loss"] = float(metrics["loss"])

    report["phase"] = "done"
    report["ok"] = bool(
        abs(report["global_sum"] - report["expected_sum"]) < 1e-3
        and np.isfinite(report["loss"])
    )
    return report


def main() -> int:
    try:
        report = run_selfcheck()
    except Exception as exc:  # noqa: BLE001 — the JSON line IS the report
        print(
            json.dumps(
                {"ok": False, "error": f"{type(exc).__name__}: {exc}"[:1000]}
            ),
            flush=True,
        )
        return 1
    print(json.dumps(report), flush=True)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
