"""Distributed-runtime self-check CLI: run one on every host of a job.

``python -m cloud_tpu.parallel.selfcheck`` initializes the multi-process
runtime from the ``CLOUD_TPU_*`` env contract (parallel/distributed.py),
then proves the job is actually wired: a cross-process global reduction
and one real sharded train step, reported as a single JSON line.

This is the executable answer to SURVEY.md §7's hard part 2 — "failure
modes are hangs, not errors": ``initialize_from_env`` runs with a bounded
``timeout_seconds`` so a mis-wired coordinator fails loudly, and every
phase is stamped into the JSON so a partial wedge is attributable.  The
reference's analogue is the TF_CONFIG cluster-faking rig
(cloud_fit/tests/unit/remote_test.py:76-82) — but executed here as real
processes over real collectives, not an env-var simulation.

Env knobs: ``CLOUD_TPU_SELFCHECK_FORCE_CPU=1`` pins the CPU platform
(the local rig), ``CLOUD_TPU_SELFCHECK_TIMEOUT`` bounds the distributed
init (default 60 s), and ``CLOUD_TPU_SELFCHECK_MODE`` picks the check:

- ``basic`` (default): dp-only mesh, cross-process psum + dense-MNIST step.
- ``transformer``: an fsdp x tp mesh whose fsdp axis CROSSES process
  boundaries, one CloudLM train step — the model-parallel layout SURVEY §7
  warns hangs (not errors) when mis-wired.
- ``pp``: a pp x tp mesh whose pp axis spans processes, so the pipeline's
  ppermute shift register rides cross-process links.
- ``tp``: an fsdp x tp mesh where the TP axis itself spans processes
  (tp size > local device count; tp is the innermost canonical axis, so
  a 4-wide tp over 2-device processes straddles the boundary) — the
  activation all-reduces after every projection ride cross-process links.
- ``sp``: an sp x tp mesh whose sp axis places NEIGHBORING ring ranks in
  different processes, so ring attention's ppermute hops (fwd and bwd)
  are real cross-process sends.
- ``ulysses``: the same sp x tp mesh with ``ulysses_sp`` — the
  sequence<->head all-to-alls cross the process boundary instead of
  ring hops.
- ``records``: every process streams its shard of a shared record dir
  (``CLOUD_TPU_SELFCHECK_RECORDS_DIR``) and reports the example ids it saw
  (the caller asserts the shards are disjoint and complete).
"""

from __future__ import annotations

import json
import os
import sys


def _check_transformer(report, mesh_sizes, *, pipeline: bool,
                       ulysses: bool = False) -> None:
    """One CloudLM train step on a model-parallel mesh; loss into report."""
    import functools

    import jax
    import numpy as np
    import optax

    from cloud_tpu import parallel
    from cloud_tpu.models import transformer
    from cloud_tpu.training import train as train_lib

    rules = (
        parallel.DEFAULT_RULES.extended(layers="pp")
        if pipeline
        else parallel.DEFAULT_RULES
    )
    cfg = transformer.TINY
    if ulysses:
        cfg = cfg.scaled(ulysses_sp=True)
    mesh = parallel.MeshSpec(mesh_sizes).build()
    report["mesh"] = {k: v for k, v in mesh.shape.items() if v > 1}
    if ulysses:
        # This mode exists to prove the all-to-all path; an ineligible
        # mesh would silently run the ring instead (ADVICE r4).
        from cloud_tpu.models import layers as layers_lib

        report["ulysses_eligible"] = layers_lib.ulysses_eligible(
            cfg.num_heads, mesh, rules
        )
        if not report["ulysses_eligible"]:
            raise RuntimeError(
                f"ulysses mode mesh {mesh_sizes} is not Ulysses-eligible "
                f"for {cfg.num_heads} heads — it would test the ring "
                "fallback, not the all-to-all path"
            )
    logical_axes = transformer.param_logical_axes(cfg)

    # Batch rows shard over the "batch" logical axes (dp x fsdp).  Each
    # process feeds only its own rows; ranks on a batch-replicated layout
    # (the pp mesh) all feed the same global batch.
    batch_axes = set(
        a for a in (rules.rules.get("batch") or ()) if a
    )
    shard_procs = 1
    for axis in batch_axes:
        shard_procs *= mesh_sizes.get(axis, 1)
    shard_procs = min(shard_procs, jax.process_count())
    global_batch, t = 8, 32
    local_rows = global_batch // shard_procs
    seed = jax.process_index() if shard_procs > 1 else 0
    rng = np.random.default_rng(seed)
    local_batch = {
        "tokens": rng.integers(
            0, cfg.vocab_size, (local_rows, t)
        ).astype(np.int32)
    }

    with parallel.use_mesh(mesh):
        state = train_lib.create_sharded_state(
            jax.random.PRNGKey(0),
            functools.partial(transformer.init, config=cfg),
            optax.sgd(0.1),
            mesh,
            logical_axes=logical_axes,
            rules=rules,
        )
        step = train_lib.make_train_step(
            functools.partial(transformer.loss_fn, config=cfg, rules=rules,
                              mesh=mesh),
            optax.sgd(0.1),
            logical_axes=logical_axes,
            rules=rules,
            mesh=mesh,
        )
        batch = train_lib.shard_batch(local_batch, mesh, rules)
        state, metrics = step(state, batch)
        state, metrics = step(state, batch)  # step 2 proves params updated
        report["loss"] = float(metrics["loss"])

    import numpy as _np

    report["ok"] = bool(_np.isfinite(report["loss"]))


def _check_records(report) -> None:
    """Stream this process's shard of a shared record dir; report ids."""
    import jax

    from cloud_tpu.training import records

    data_dir = os.environ["CLOUD_TPU_SELFCHECK_RECORDS_DIR"]
    ds = records.RecordDataset(
        os.path.join(data_dir, "*.rec"), batch_size=2,
        drop_remainder=False,
    )
    seen = []
    for batch in ds():
        seen.extend(int(x) for x in batch["x"][:, 0])
    report.update(
        shard_files=[os.path.basename(p) for p in ds.shard_files],
        example_ids=sorted(seen),
        loss=0.0,
        ok=True,
    )


def run_selfcheck() -> dict:
    import jax

    if os.environ.get("CLOUD_TPU_SELFCHECK_FORCE_CPU"):
        jax.config.update("jax_platforms", "cpu")

    from cloud_tpu.parallel import distributed

    report = {"phase": "init"}
    timeout = int(os.environ.get("CLOUD_TPU_SELFCHECK_TIMEOUT", "60"))
    report["distributed"] = distributed.initialize_from_env(
        timeout_seconds=timeout
    )
    report.update(
        process_index=jax.process_index(),
        process_count=jax.process_count(),
        device_count=jax.device_count(),
        local_device_count=jax.local_device_count(),
        platform=jax.devices()[0].platform,
    )

    mode = os.environ.get("CLOUD_TPU_SELFCHECK_MODE", "basic")
    # Model-parallel modes and their mesh from the job's device count.
    # 'ulysses': sp is PINNED to 2 (not dc//2): TINY has 4 heads, tp=2 ->
    # 2 local heads, and Ulysses requires local_heads % sp == 0 — sp=4
    # would silently take the ring fallback, the exact trap ADVICE r4
    # found in the unit test.  tp is innermost and sp next, so on
    # 2-device processes the sp=2 partners (device stride 2) still live
    # in different processes: both all-to-alls cross the boundary.
    model_parallel_meshes = {
        "transformer": {"fsdp": jax.device_count() // 2, "tp": 2},
        "pp": {"pp": jax.device_count() // 2, "tp": 2},
        "tp": {"fsdp": jax.device_count() // 4, "tp": 4},
        "sp": {"sp": jax.device_count() // 2, "tp": 2},
        "ulysses": {"fsdp": jax.device_count() // 4, "sp": 2, "tp": 2},
    }
    if mode in model_parallel_meshes:
        sizes = model_parallel_meshes[mode]
        if min(sizes.values()) < 1:
            # These modes are env-selected and may be pointed at a rig too
            # small for their mesh; report that clearly instead of letting
            # MeshSpec.build die on a zero-size axis (ADVICE r4).
            report["phase"] = "mesh_too_small"
            report["ok"] = False
            report["error"] = (
                f"mode {mode!r} computed mesh {sizes} from "
                f"device_count={jax.device_count()}: every axis must be "
                ">= 1; run this mode on a rig with more devices"
            )
            return report
        report["phase"] = f"{mode}_step"
        _check_transformer(
            report, sizes,
            pipeline=(mode == "pp"), ulysses=(mode == "ulysses"),
        )
        report["phase"] = "done"
        return report
    if mode == "records":
        report["phase"] = "records"
        _check_records(report)
        report["phase"] = "done"
        return report

    import functools

    import jax.numpy as jnp
    import numpy as np
    import optax

    from cloud_tpu import parallel
    from cloud_tpu.models import mnist
    from cloud_tpu.training import train as train_lib

    # Phase 1: cross-process global reduction.  Every process contributes
    # rank+1 on each of its local rows; the jit-computed global sum proves
    # the collectives span all processes, not just this host.
    report["phase"] = "psum"
    mesh = parallel.MeshSpec({"dp": jax.device_count()}).build()
    local = np.full(
        (jax.local_device_count(), 4), float(jax.process_index() + 1),
        np.float32,
    )
    arr = train_lib.shard_batch({"x": local}, mesh)["x"]
    report["global_sum"] = float(jax.jit(jnp.sum)(arr))
    report["expected_sum"] = float(
        sum(
            (r + 1) * jax.local_device_count() * 4
            for r in range(jax.process_count())
        )
    )

    # Phase 2: one real sharded train step on per-host data.
    report["phase"] = "train_step"
    cfg = mnist.MnistConfig(hidden_dim=16)
    logical_axes = mnist.param_logical_axes(cfg)
    with parallel.use_mesh(mesh):
        state = train_lib.create_sharded_state(
            jax.random.PRNGKey(0),
            functools.partial(mnist.init, config=cfg),
            optax.sgd(0.1),
            mesh,
            logical_axes=logical_axes,
        )
        step = train_lib.make_train_step(
            functools.partial(mnist.loss_fn, config=cfg),
            optax.sgd(0.1),
            logical_axes=logical_axes,
            mesh=mesh,
        )
        rng = np.random.default_rng(jax.process_index())
        local_batch = {
            "image": rng.normal(
                size=(2 * jax.local_device_count(), 784)
            ).astype(np.float32),
            "label": rng.integers(0, 10, 2 * jax.local_device_count()),
        }
        batch = train_lib.shard_batch(local_batch, mesh)
        state, metrics = step(state, batch)
        report["loss"] = float(metrics["loss"])

    report["phase"] = "done"
    report["ok"] = bool(
        abs(report["global_sum"] - report["expected_sum"]) < 1e-3
        and np.isfinite(report["loss"])
    )
    return report


def main() -> int:
    try:
        report = run_selfcheck()
    except Exception as exc:  # noqa: BLE001 — the JSON line IS the report
        print(
            json.dumps(
                {"ok": False, "error": f"{type(exc).__name__}: {exc}"[:1000]}
            ),
            flush=True,
        )
        return 1
    print(json.dumps(report), flush=True)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
