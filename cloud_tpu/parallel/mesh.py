"""Named device meshes: the framework's parallelism substrate.

Every parallel strategy in this framework is a :class:`jax.sharding.Mesh`
with canonical axis names; models and the trainer consult sharding *rules*
(``sharding.py``), never device lists.  Axis conventions:

==========  =====================================================
``dp``      pure data parallelism — params replicated; maps to the
            slowest links (DCN across slices) because its only
            collective is one gradient all-reduce per step
``pp``      pipeline stages (GPipe-style microbatching, pipeline.py)
``fsdp``    data parallelism with params/optimizer sharded
            (ZeRO-3); wants intra-slice ICI for its all-gathers
``ep``      expert parallelism for MoE layers
``sp``      sequence/context parallelism (ring attention)
``tp``      tensor parallelism (heads/mlp sharding); innermost —
            its collectives are on the hot path of every matmul
==========  =====================================================

The canonical order sorts axes by collective latency tolerance, so the
device mesh puts ``tp`` neighbours on directly-wired ICI links.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import logging
import math
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

logger = logging.getLogger(__name__)

AXIS_DP = "dp"
AXIS_PP = "pp"
AXIS_FSDP = "fsdp"
AXIS_EP = "ep"
AXIS_SP = "sp"
AXIS_TP = "tp"

#: Outermost (DCN-tolerant) to innermost (ICI-hungry).
CANONICAL_AXES: Tuple[str, ...] = (
    AXIS_DP,
    AXIS_PP,
    AXIS_FSDP,
    AXIS_EP,
    AXIS_SP,
    AXIS_TP,
)


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Sizes for each canonical axis (missing axes default to 1).

    ``dcn_sizes`` gives, per axis, how much of that axis spans slice
    boundaries (data-center network) rather than ICI; an axis of size 8
    with ``dcn_sizes={"dp": 2}`` is 2 slice-granules x 4 within-slice.
    The planner fills it for multi-slice jobs.
    """

    sizes: Dict[str, int]
    dcn_sizes: Dict[str, int] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        for axis in self.sizes:
            if axis not in CANONICAL_AXES:
                raise ValueError(
                    f"Unknown mesh axis {axis!r}; canonical axes are "
                    f"{CANONICAL_AXES}"
                )
            if self.sizes[axis] < 1:
                raise ValueError(f"Axis {axis!r} must have size >= 1")
        for axis, dcn in self.dcn_sizes.items():
            if axis not in CANONICAL_AXES:
                raise ValueError(f"Unknown DCN axis {axis!r}")
            if dcn < 1 or self.size(axis) % dcn:
                raise ValueError(
                    f"DCN granule {dcn} must divide axis {axis!r} size "
                    f"{self.size(axis)}"
                )

    @property
    def dcn_axes(self) -> Tuple[str, ...]:
        return tuple(a for a in CANONICAL_AXES if self.dcn_sizes.get(a, 1) > 1)

    def size(self, axis: str) -> int:
        return self.sizes.get(axis, 1)

    @property
    def num_devices(self) -> int:
        return math.prod(self.sizes.values()) if self.sizes else 1

    def axis_names(self) -> Tuple[str, ...]:
        return CANONICAL_AXES

    def shape(self) -> Tuple[int, ...]:
        return tuple(self.size(a) for a in CANONICAL_AXES)

    def nontrivial_axes(self) -> List[str]:
        return [a for a in CANONICAL_AXES if self.size(a) > 1]

    def build(self, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
        """Materialize a Mesh over ``devices`` (default: all devices).

        Uses ``mesh_utils.create_device_mesh`` so the ICI topology is
        respected on real TPU slices (nearest-neighbour axes get wired
        links); on CPU/virtual platforms it degrades to a reshape.  For
        multi-slice specs (``dcn_axes`` non-empty) the hybrid helper lays
        DCN axes across slice granules.
        """
        if devices is None:
            devices = jax.devices()
        devices = list(devices)
        if len(devices) != self.num_devices:
            raise ValueError(
                f"MeshSpec wants {self.num_devices} devices "
                f"(sizes={self.sizes}), got {len(devices)}"
            )
        shape = self.shape()
        from jax.experimental import mesh_utils

        try:
            if self.dcn_axes:
                dcn_shape = tuple(
                    self.dcn_sizes.get(a, 1) for a in CANONICAL_AXES
                )
                ici_shape = tuple(
                    s // d for s, d in zip(shape, dcn_shape)
                )
                arr = mesh_utils.create_hybrid_device_mesh(
                    ici_shape, dcn_shape, devices=devices
                )
            else:
                arr = mesh_utils.create_device_mesh(shape, devices=devices)
        except Exception as e:
            # mesh_utils needs real TPU topology metadata; on CPU/virtual
            # platforms a plain reshape is equivalent.  On real TPU a
            # failure here means the plan doesn't fit the hardware — never
            # silently degrade the layout there.
            if any(d.platform != "cpu" for d in devices):
                raise
            logger.debug("mesh_utils unavailable (%s); reshaping devices", e)
            arr = np.asarray(devices).reshape(shape)
        return Mesh(arr, CANONICAL_AXES)

    # --- wire format (job specs carry the plan into the container) ---

    def to_json(self) -> str:
        return json.dumps({"sizes": self.sizes, "dcn_sizes": self.dcn_sizes})

    @classmethod
    def from_json(cls, data: str) -> "MeshSpec":
        obj = json.loads(data)
        return cls(sizes=obj["sizes"], dcn_sizes=obj.get("dcn_sizes", {}))


# --- global mesh registry -------------------------------------------------
#
# The bootstrap runner (core/bootstrap.py) plans and installs the mesh before
# the user script runs; user code retrieves it here.  This is the analogue of
# the reference setting the global tf.distribute strategy via
# `experimental_set_strategy` in the generated prologue (preprocess.py:148).

_GLOBAL_MESH: Optional[Mesh] = None


def set_global_mesh(mesh: Optional[Mesh]) -> None:
    global _GLOBAL_MESH
    _GLOBAL_MESH = mesh


def get_global_mesh() -> Optional[Mesh]:
    return _GLOBAL_MESH


@contextlib.contextmanager
def use_mesh(mesh: Mesh):
    """Install ``mesh`` as the global mesh for the duration of the block.

    Enters via ``jax.set_mesh`` (the sharding-in-types context), not the
    legacy ``with mesh:`` block: under the legacy context the GSPMD
    partitioner CHECK-fails on custom_partitioning calls inside a
    partial-manual region (spmd_partitioner_util.cc "num_devices_per_group"
    — the pipelined flash-attention path), while the modern context
    partitions them correctly.
    """
    prev = get_global_mesh()
    set_global_mesh(mesh)
    try:
        set_mesh = getattr(jax, "set_mesh", None) or getattr(
            jax.sharding, "use_mesh", None
        )
        # Older jax (< 0.5) has neither entry point; the legacy
        # ``with mesh:`` context is the only option there, and the
        # custom_partitioning CHECK-failure above doesn't apply to it.
        with (set_mesh(mesh) if set_mesh is not None else mesh):
            yield mesh
    finally:
        set_global_mesh(prev)
