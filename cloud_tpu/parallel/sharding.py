"""Logical-axis sharding rules: how tensors map onto the mesh.

Models annotate tensors with *logical* axis names (``batch``, ``embed``,
``heads`` ...).  A :class:`ShardingRules` table translates those to mesh
axes, producing ``PartitionSpec``/``NamedSharding``.  Changing the
parallelism layout of a model = swapping the rules table — model code never
mentions mesh axes directly.

This replaces the reference's strategy dichotomy (Mirrored vs MWMS vs
TPUStrategy, preprocess.py:124-149): one rules table expresses DP, FSDP, TP,
SP and EP simultaneously as an assignment of logical axes to mesh axes.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from cloud_tpu.parallel import mesh as mesh_lib

#: A logical axis maps to one mesh axis, a tuple of mesh axes (the tensor
#: dimension is sharded over their product), or None (replicated).
MeshAxisAssignment = Union[None, str, Tuple[str, ...]]


@dataclasses.dataclass(frozen=True)
class ShardingRules:
    rules: Dict[str, MeshAxisAssignment]

    def assignment(self, logical_axis: Optional[str]) -> MeshAxisAssignment:
        if logical_axis is None:
            return None
        if logical_axis not in self.rules:
            raise KeyError(
                f"No sharding rule for logical axis {logical_axis!r}; "
                f"known axes: {sorted(self.rules)}"
            )
        return self.rules[logical_axis]

    def spec(self, *logical_axes: Optional[str]) -> PartitionSpec:
        """PartitionSpec for a tensor whose dims carry these logical axes."""
        return PartitionSpec(*(self.assignment(a) for a in logical_axes))

    def extended(self, **overrides: MeshAxisAssignment) -> "ShardingRules":
        merged = dict(self.rules)
        merged.update(overrides)
        return ShardingRules(merged)


#: Default logical-axis table.  ``batch`` shards over every data-parallel
#: mesh axis; parameters shard their ``embed`` dim over fsdp (ZeRO-3) and
#: their head/mlp dims over tp; ``seq`` is the ring-attention axis.
DEFAULT_RULES = ShardingRules(
    {
        "batch": (mesh_lib.AXIS_DP, mesh_lib.AXIS_FSDP),
        "expert_batch": (mesh_lib.AXIS_DP, mesh_lib.AXIS_FSDP, mesh_lib.AXIS_EP),
        "seq": mesh_lib.AXIS_SP,
        "embed": mesh_lib.AXIS_FSDP,
        # Activations shard on batch, never on the param-sharding axis —
        # constraining an activation's feature dim with "embed" would reuse
        # fsdp twice in one spec.
        "act_embed": None,
        "heads": mesh_lib.AXIS_TP,
        "kv": None,
        "mlp": mesh_lib.AXIS_TP,
        "vocab": mesh_lib.AXIS_TP,
        "expert": mesh_lib.AXIS_EP,
        "layers": None,
        "stage": mesh_lib.AXIS_PP,
    }
)


def logical_to_mesh_axes(
    logical_axes: Tuple[Optional[str], ...],
    rules: ShardingRules = DEFAULT_RULES,
) -> PartitionSpec:
    return rules.spec(*logical_axes)


def named_sharding(
    mesh: Mesh,
    *logical_axes: Optional[str],
    rules: ShardingRules = DEFAULT_RULES,
) -> NamedSharding:
    return NamedSharding(mesh, rules.spec(*logical_axes))


def manual_context_mesh():
    """The enclosing partial-manual shard_map's abstract mesh, or None.

    Inside a partial-manual region (e.g. the pipeline's ``pp``-manual body,
    parallel/pipeline.py) every sharding construct must be built against the
    *abstract* context mesh — a concrete Mesh there raises a mesh-mismatch
    error from XLA's sharding checks.
    """
    get_abstract_mesh = getattr(jax.sharding, "get_abstract_mesh", None)
    if get_abstract_mesh is None:
        # Older jax (< 0.5) has no abstract-mesh tracking (and no
        # AxisType): there is no partial-manual context to detect.
        return None
    am = get_abstract_mesh()
    if am is not None and not am.empty and any(
        t == jax.sharding.AxisType.Manual for t in am.axis_types
    ):
        return am
    return None


def pad_batch(batch, pad_to: int, *, axis: int = 0):
    """Zero-pad every leaf of a HOST batch along ``axis``; returns
    ``(padded, valid)``.

    ``valid`` is a float32 ``[pad_to]`` mask with 1.0 marking real rows —
    the per-example (or, for a stacked super-batch, per-step) validity
    that the masked train-step paths consume.  Padding with zeros keeps
    every leaf's dtype and the downstream compiled shapes fixed, so a
    short tail reuses an already-compiled executable instead of tracing
    a fresh one (`training.train.make_multi_step`'s ``valid`` argument
    skips the padded slots entirely, so garbage-in never reaches the
    optimizer).

    Host-side by design: the windowing pipelines pad BEFORE placement
    (device arrays passed here are pulled back to host first).

    Leaves without the padded axis (scalars, lower-rank side data) pass
    through untouched; leaves that HAVE the axis must agree on its length
    — disagreement is ambiguous (which one defines "the batch"?) and
    raises instead of silently padding to inconsistent sizes.
    """
    if pad_to < 1:
        raise ValueError(f"pad_to must be >= 1, got {pad_to}")
    import numpy as np

    leaves = jax.tree_util.tree_leaves(batch)
    lengths = {
        int(np.shape(leaf)[axis])
        for leaf in leaves
        if len(np.shape(leaf)) > axis
    }
    if not lengths:
        raise ValueError(
            f"pad_batch: no leaf has axis {axis} to pad (leaf shapes: "
            f"{[np.shape(leaf) for leaf in leaves]})"
        )
    if len(lengths) > 1:
        raise ValueError(
            f"pad_batch: leaves disagree on axis {axis} length "
            f"({sorted(lengths)}); a consistent batch axis is required "
            "to pad unambiguously"
        )
    n = lengths.pop()
    if n > pad_to:
        raise ValueError(
            f"batch axis {axis} has {n} rows, more than pad_to={pad_to}"
        )
    valid = np.zeros((pad_to,), np.float32)
    valid[:n] = 1.0
    if n == pad_to:
        return batch, valid

    def pad(x):
        x = np.asarray(x)
        if x.ndim <= axis:
            return x  # no batch axis: side data rides along unpadded
        widths = [(0, 0)] * x.ndim
        widths[axis] = (0, pad_to - n)
        return np.pad(x, widths)

    return jax.tree_util.tree_map(pad, batch), valid


def shard_constraint(
    x,
    *logical_axes: Optional[str],
    rules: ShardingRules = DEFAULT_RULES,
    mesh: Optional[Mesh] = None,
):
    """``with_sharding_constraint`` by logical axes, inside jit.

    No-op when no mesh is active (single-device eager use), so model code is
    unconditional.  Inside a partial-manual shard_map region the constraint
    binds to the abstract context mesh (specs there may only name its Auto
    axes; the rules tables never route activations onto ``pp``, the one
    manual axis in practice).
    """
    mesh = mesh or mesh_lib.get_global_mesh()
    if mesh is None or mesh.empty:
        return x
    spec = rules.spec(*logical_axes)
    am = manual_context_mesh()
    if am is not None:
        return jax.lax.with_sharding_constraint(x, NamedSharding(am, spec))
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
