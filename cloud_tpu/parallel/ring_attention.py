"""Ring attention: exact long-context attention over the ``sp`` mesh axis.

Each device holds one sequence block of Q, K, V.  K/V blocks rotate around
the ring via ``ppermute`` (nearest-neighbour ICI links on TPU) while every
device folds the incoming block into an online-softmax accumulator — the
blockwise log-sum-exp trick from flash attention, distributed.  After
``sp`` hops every query block has attended to every key block, with peak
memory O(T/sp) per device and communication overlapped with the block
matmuls by XLA's async collective scheduling.

Each per-block fold runs through the Pallas flash kernel when eligible
(``flash_attention_with_lse`` — out + lse, differentiable in both, so the
lse-based merge backpropagates exactly), falling back to the jnp reference
otherwise.  Block-level causality is exact for equal block sizes: blocks
strictly in the past attend fully, the diagonal block applies the in-block
causal mask, and future blocks are folded with weight zero.

No reference counterpart exists (SURVEY.md §5: sequence parallelism absent);
this is the capability the TPU-native build adds for long-context scale.

Call under ``shard_map`` with the sequence dim of q/k/v sharded over
``axis``; batch/head dims may be sharded over other axes — the computation
is independent along them.

Causal imbalance: with contiguous blocks, device i folds i+1 real blocks
and skips the rest, so every ppermute-synchronized hop runs at the busiest
rank's pace (~2x the balanced cost).  :func:`ring_attention_balanced`
fixes this with zig-zag ("striped") block assignment — device i holds
chunks i and 2n-1-i of a 2n-chunk split, making the per-hop causal work
IDENTICAL across ranks (3 sub-blocks on the diagonal hop, exactly 2 on
every other hop).  Inputs must be laid out with :func:`zigzag_indices`
before sharding; outputs invert with ``inverse=True``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from cloud_tpu.ops.flash_attention import flash_attention_with_lse

NEG_INF = -1e30  # large-but-finite: keeps fully-masked rows NaN-free


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis: str,
    *,
    causal: bool = True,
    mask: Optional[jnp.ndarray] = None,
    use_pallas: Optional[bool] = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Exact attention over sequence blocks distributed along ``axis``.

    Args:
      q, k, v: local blocks ``[B, T_local, H, D]`` (sequence dim sharded
        over ``axis``; block i holds global positions
        ``[i*T_local, (i+1)*T_local)``).
      axis: mesh axis name carrying the sequence shards.
      causal: apply a causal mask in *global* positions.
      mask: optional [B, T_local] KEY-side padding mask (nonzero = attend),
        the local shard of the global [B, T] mask, sharded like k's
        sequence dim.  It rides the ring with its K/V block, so every
        query block sees every key's mask bit exactly once.  Query-side
        semantics match the kernels: fully-masked query rows produce
        uniform garbage the caller's loss mask must drop.
      use_pallas: per-block kernel dispatch — None auto-detects (TPU +
        tileable local block), True forces the kernel, False forces jnp.
      interpret: run the kernels in the Pallas interpreter (CPU tests).

    Returns:
      Local attention output block ``[B, T_local, H, D]`` in q's dtype.
    """
    n = lax.axis_size(axis)
    my_idx = lax.axis_index(axis)
    b, t, h, d = q.shape

    def block_attention(k_blk, v_blk, m_blk, block_causal: bool):
        out, lse = flash_attention_with_lse(
            q, k_blk, v_blk, causal=block_causal, mask=m_blk,
            use_pallas=use_pallas, interpret=interpret,
        )
        return out.astype(jnp.float32), lse  # [B,T,H,D] f32, [B,H,T] f32

    def fold_block(carry, k_blk, v_blk, m_blk, src_idx):
        o_acc, lse_acc = carry
        if causal:
            # Exact block-level causality (equal block sizes): past blocks
            # attend fully, the diagonal applies the in-block mask, and
            # future blocks SKIP the kernel entirely (lax.cond executes one
            # branch) and merge with weight exp(NEG_INF - lse) = 0.
            def skip():
                return (
                    jnp.zeros((b, t, h, d), jnp.float32),
                    jnp.full((b, h, t), NEG_INF, jnp.float32),
                )

            out_blk, lse_blk = lax.cond(
                src_idx > my_idx,
                skip,
                lambda: lax.cond(
                    src_idx == my_idx,
                    lambda: block_attention(k_blk, v_blk, m_blk, True),
                    lambda: block_attention(k_blk, v_blk, m_blk, False),
                ),
            )
        else:
            out_blk, lse_blk = block_attention(k_blk, v_blk, m_blk, False)
        return _merge_partials(o_acc, lse_acc, out_blk, lse_blk)

    def body(i, carry):
        o_acc, lse_acc, k_cur, v_cur, m_cur = carry
        # Block currently held originated at rank (my_idx - i) mod n.
        src_idx = jax.lax.rem(my_idx - i + n, n)
        o_acc, lse_acc = fold_block(
            (o_acc, lse_acc), k_cur, v_cur,
            None if mask is None else m_cur, src_idx,
        )
        k_nxt = _rotate(k_cur, axis, n)
        v_nxt = _rotate(v_cur, axis, n)
        m_nxt = m_cur if mask is None else _rotate(m_cur, axis, n)
        return o_acc, lse_acc, k_nxt, v_nxt, m_nxt

    o0 = jnp.zeros((b, t, h, d), jnp.float32)
    lse0 = jnp.full((b, h, t), NEG_INF, jnp.float32)
    # The mask slot carries a dummy scalar when unused so the fori_loop
    # carry structure stays static.
    m0 = jnp.zeros((), jnp.int32) if mask is None else mask.astype(jnp.int32)
    # Loop runs n-1 hops (each fold + rotate); the final block is folded
    # outside so no dead K/V rotation ships on the last hop (a fori_loop
    # body is compiled once — XLA cannot trim it per-iteration).
    o, lse, k_last, v_last, m_last = lax.fori_loop(
        0, n - 1, body, (o0, lse0, k, v, m0)
    )
    o, lse = fold_block(
        (o, lse), k_last, v_last,
        None if mask is None else m_last,
        jax.lax.rem(my_idx - (n - 1) + n, n),
    )
    return o.astype(q.dtype)


def _rotate(x, axis, n):
    perm = [(i, (i + 1) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)


def _merge_partials(o_acc, lse_acc, o_new, lse_new):
    """Online-softmax merge of two attention partials.

    ``o_*`` are [B, T, H, D] f32 UN-normalized-by-each-other outputs (each
    already normalized within its own partial), ``lse_*`` their [B, H, T]
    log-sum-exps.  Shared by both ring variants — the numerically delicate
    piece lives once.
    """
    lse = jnp.logaddexp(lse_acc, lse_new)
    w_acc = jnp.exp(lse_acc - lse).transpose(0, 2, 1)[..., None]
    w_new = jnp.exp(lse_new - lse).transpose(0, 2, 1)[..., None]
    return o_acc * w_acc + o_new * w_new, lse


# ---------------------------------------------------------------------------
# Load-balanced causal ring (zig-zag block assignment)
# ---------------------------------------------------------------------------


def zigzag_indices(seq_len: int, n: int, *, inverse: bool = False):
    """Gather indices for the zig-zag sequence layout over ``n`` ring ranks.

    The sequence splits into 2n chunks; rank i holds chunks (i, 2n-1-i).
    ``x_zz = x[:, zigzag_indices(T, n)]`` produces the layout
    ``ring_attention_balanced`` expects once sharded contiguously over the
    ring axis; ``inverse=True`` gives the indices that undo it on outputs.
    Positions fed to RoPE etc. must be permuted the same way (the tokens
    keep their ORIGINAL global positions).
    """
    if seq_len % (2 * n):
        raise ValueError(
            f"zig-zag layout needs seq_len divisible by 2*n; got "
            f"T={seq_len}, n={n}"
        )
    chunk = seq_len // (2 * n)
    order = []
    for i in range(n):
        order.append(i)
        order.append(2 * n - 1 - i)
    forward = jnp.concatenate(
        [jnp.arange(c * chunk, (c + 1) * chunk) for c in order]
    )
    if not inverse:
        return forward
    inv = jnp.zeros((seq_len,), jnp.int32)
    inv = inv.at[forward].set(jnp.arange(seq_len, dtype=jnp.int32))
    return inv


def ring_attention_balanced(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis: str,
    *,
    use_pallas: Optional[bool] = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """CAUSAL ring attention with per-hop load balance (zig-zag layout).

    Args/returns as :func:`ring_attention`, except the local [B, T_local,
    H, D] blocks must hold zig-zag chunks (``zigzag_indices``): rank i's
    first half is global chunk i, its second half global chunk 2n-1-i.
    Per hop every rank folds the same causal mass, so the ppermute
    barrier no longer waits on the busiest rank — ~2x the throughput of
    the contiguous causal ring at larger n.  Non-causal attention has no
    imbalance; use :func:`ring_attention` for it.
    """
    n = lax.axis_size(axis)
    my_idx = lax.axis_index(axis)
    b, t_local, h, d = q.shape
    if t_local % 2:
        raise ValueError("zig-zag local block must hold two equal chunks")
    c = t_local // 2
    q_lo, q_hi = q[:, :c], q[:, c:]  # global chunks my_idx, 2n-1-my_idx

    def attn(q_, k_, v_, causal_):
        out, lse = flash_attention_with_lse(
            q_, k_, v_, causal=causal_, use_pallas=use_pallas,
            interpret=interpret,
        )
        return out.astype(jnp.float32), lse

    def fold(carry, k_cur, v_cur, src):
        (o_lo, l_lo, o_hi, l_hi) = carry
        k_lo_b, k_hi_b = k_cur[:, :c], k_cur[:, c:]  # chunks src, 2n-1-src
        v_lo_b, v_hi_b = v_cur[:, :c], v_cur[:, c:]

        # Every sub-attention is a SQUARE c x c call, so each is
        # kernel-eligible and the rectangular-dispatch hazard never
        # arises; q_hi's two partials combine through the same lse merge
        # as the hop accumulators.

        def diagonal():
            # Own chunks: q_lo diag vs chunk i; q_hi sees chunk i fully
            # (i < 2n-1-i always) and its own chunk diagonally.
            oa, la = attn(q_lo, k_lo_b, v_lo_b, True)
            ob, lb = _merge_partials(
                *attn(q_hi, k_lo_b, v_lo_b, False),
                *attn(q_hi, k_hi_b, v_hi_b, True),
            )
            return oa, la, ob, lb

        def past():  # src < my_idx: chunk src is past BOTH local q chunks
            # (chunk 2n-1-src is future for both: 2n-1-src > 2n-1-my_idx).
            oa, la = attn(q_lo, k_lo_b, v_lo_b, False)
            ob, lb = attn(q_hi, k_lo_b, v_lo_b, False)
            return oa, la, ob, lb

        def future():
            # src in (my_idx, n): chunks src and 2n-1-src are both
            # > my_idx and both < 2n-1-my_idx — q_hi attends both fully,
            # q_lo attends neither.
            ob, lb = _merge_partials(
                *attn(q_hi, k_lo_b, v_lo_b, False),
                *attn(q_hi, k_hi_b, v_hi_b, False),
            )
            oa = jnp.zeros((b, c, h, d), jnp.float32)
            la = jnp.full((b, h, c), NEG_INF, jnp.float32)
            return oa, la, ob, lb

        case = jnp.where(src == my_idx, 0, jnp.where(src < my_idx, 1, 2))
        oa, la, ob, lb = lax.switch(case, (diagonal, past, future))
        o_lo, l_lo = _merge_partials(o_lo, l_lo, oa, la)
        o_hi, l_hi = _merge_partials(o_hi, l_hi, ob, lb)
        return o_lo, l_lo, o_hi, l_hi

    def body(j, carry):
        o_lo, l_lo, o_hi, l_hi, k_cur, v_cur = carry
        src = jax.lax.rem(my_idx - j + n, n)
        o_lo, l_lo, o_hi, l_hi = fold(
            (o_lo, l_lo, o_hi, l_hi), k_cur, v_cur, src
        )
        return (
            o_lo, l_lo, o_hi, l_hi,
            _rotate(k_cur, axis, n), _rotate(v_cur, axis, n),
        )

    o_lo = jnp.zeros((b, c, h, d), jnp.float32)
    l_lo = jnp.full((b, h, c), NEG_INF, jnp.float32)
    o_hi = jnp.zeros((b, c, h, d), jnp.float32)
    l_hi = jnp.full((b, h, c), NEG_INF, jnp.float32)
    o_lo, l_lo, o_hi, l_hi, k_last, v_last = lax.fori_loop(
        0, n - 1, body, (o_lo, l_lo, o_hi, l_hi, k, v)
    )
    o_lo, l_lo, o_hi, l_hi = fold(
        (o_lo, l_lo, o_hi, l_hi), k_last, v_last,
        jax.lax.rem(my_idx - (n - 1) + n, n),
    )
    return jnp.concatenate([o_lo, o_hi], axis=1).astype(q.dtype)
