"""Ring attention: exact long-context attention over the ``sp`` mesh axis.

Each device holds one sequence block of Q, K, V.  K/V blocks rotate around
the ring via ``ppermute`` (nearest-neighbour ICI links on TPU) while every
device folds the incoming block into an online-softmax accumulator — the
blockwise log-sum-exp trick from flash attention, distributed.  After
``sp`` hops every query block has attended to every key block, with peak
memory O(T/sp) per device and communication overlapped with the block
matmuls by XLA's async collective scheduling.

Each per-block fold runs through the Pallas flash kernel when eligible
(``flash_attention_with_lse`` — out + lse, differentiable in both, so the
lse-based merge backpropagates exactly), falling back to the jnp reference
otherwise.  Block-level causality is exact for equal block sizes: blocks
strictly in the past attend fully, the diagonal block applies the in-block
causal mask, and future blocks are folded with weight zero.

No reference counterpart exists (SURVEY.md §5: sequence parallelism absent);
this is the capability the TPU-native build adds for long-context scale.

Call under ``shard_map`` with the sequence dim of q/k/v sharded over
``axis``; batch/head dims may be sharded over other axes — the computation
is independent along them.

Known causal imbalance (future work): device i folds i+1 real blocks and
skips the rest, so late ring ranks do ~2x the work of rank 0 and the step
runs at the slowest rank's pace.  The fix is striped ("zig-zag") block
assignment — each device holds stripes i and 2n-1-i so every rank folds
the same causal mass; requires re-deriving the src-block bookkeeping and
a gather at the output.  Not implemented: single-chip hardware here can't
measure the multi-chip balance win to justify the extra index complexity.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from cloud_tpu.ops.flash_attention import flash_attention_with_lse

NEG_INF = -1e30  # large-but-finite: keeps fully-masked rows NaN-free


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis: str,
    *,
    causal: bool = True,
    use_pallas: Optional[bool] = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Exact attention over sequence blocks distributed along ``axis``.

    Args:
      q, k, v: local blocks ``[B, T_local, H, D]`` (sequence dim sharded
        over ``axis``; block i holds global positions
        ``[i*T_local, (i+1)*T_local)``).
      axis: mesh axis name carrying the sequence shards.
      causal: apply a causal mask in *global* positions.
      use_pallas: per-block kernel dispatch — None auto-detects (TPU +
        tileable local block), True forces the kernel, False forces jnp.
      interpret: run the kernels in the Pallas interpreter (CPU tests).

    Returns:
      Local attention output block ``[B, T_local, H, D]`` in q's dtype.
    """
    n = lax.axis_size(axis)
    my_idx = lax.axis_index(axis)
    b, t, h, d = q.shape

    def block_attention(k_blk, v_blk, block_causal: bool):
        out, lse = flash_attention_with_lse(
            q, k_blk, v_blk, causal=block_causal,
            use_pallas=use_pallas, interpret=interpret,
        )
        return out.astype(jnp.float32), lse  # [B,T,H,D] f32, [B,H,T] f32

    def fold_block(carry, k_blk, v_blk, src_idx):
        o_acc, lse_acc = carry
        if causal:
            # Exact block-level causality (equal block sizes): past blocks
            # attend fully, the diagonal applies the in-block mask, and
            # future blocks SKIP the kernel entirely (lax.cond executes one
            # branch) and merge with weight exp(NEG_INF - lse) = 0.
            def skip():
                return (
                    jnp.zeros((b, t, h, d), jnp.float32),
                    jnp.full((b, h, t), NEG_INF, jnp.float32),
                )

            out_blk, lse_blk = lax.cond(
                src_idx > my_idx,
                skip,
                lambda: lax.cond(
                    src_idx == my_idx,
                    lambda: block_attention(k_blk, v_blk, True),
                    lambda: block_attention(k_blk, v_blk, False),
                ),
            )
        else:
            out_blk, lse_blk = block_attention(k_blk, v_blk, False)
        lse_new = jnp.logaddexp(lse_acc, lse_blk)  # [B, H, T]
        w_acc = jnp.exp(lse_acc - lse_new).transpose(0, 2, 1)[..., None]
        w_blk = jnp.exp(lse_blk - lse_new).transpose(0, 2, 1)[..., None]
        return o_acc * w_acc + out_blk * w_blk, lse_new

    def body(i, carry):
        o_acc, lse_acc, k_cur, v_cur = carry
        # Block currently held originated at rank (my_idx - i) mod n.
        src_idx = jax.lax.rem(my_idx - i + n, n)
        o_acc, lse_acc = fold_block((o_acc, lse_acc), k_cur, v_cur, src_idx)
        k_nxt = _rotate(k_cur, axis, n)
        v_nxt = _rotate(v_cur, axis, n)
        return o_acc, lse_acc, k_nxt, v_nxt

    o0 = jnp.zeros((b, t, h, d), jnp.float32)
    lse0 = jnp.full((b, h, t), NEG_INF, jnp.float32)
    # Loop runs n-1 hops (each fold + rotate); the final block is folded
    # outside so no dead K/V rotation ships on the last hop (a fori_loop
    # body is compiled once — XLA cannot trim it per-iteration).
    o, lse, k_last, v_last = lax.fori_loop(0, n - 1, body, (o0, lse0, k, v))
    o, lse = fold_block(
        (o, lse), k_last, v_last, jax.lax.rem(my_idx - (n - 1) + n, n)
    )
    return o.astype(q.dtype)


def _rotate(x, axis, n):
    perm = [(i, (i + 1) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)
