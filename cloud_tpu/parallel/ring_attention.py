"""Ring attention: exact long-context attention over the ``sp`` mesh axis.

Each device holds one sequence block of Q, K, V.  K/V blocks rotate around
the ring via ``ppermute`` (nearest-neighbour ICI links on TPU) while every
device folds the incoming block into an online-softmax accumulator — the
blockwise log-sum-exp trick from flash attention, distributed.  After
``sp`` hops every query block has attended to every key block, with peak
memory O(T/sp) per device and communication overlapped with the block
matmuls by XLA's async collective scheduling.

No reference counterpart exists (SURVEY.md §5: sequence parallelism absent);
this is the capability the TPU-native build adds for long-context scale.

Call under ``shard_map`` with the sequence dim of q/k/v sharded over
``axis``; batch/head dims may be sharded over other axes — the computation
is independent along them.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30  # large-but-finite: keeps fully-masked rows NaN-free


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis: str,
    *,
    causal: bool = True,
) -> jnp.ndarray:
    """Exact attention over sequence blocks distributed along ``axis``.

    Args:
      q, k, v: local blocks ``[B, T_local, H, D]`` (sequence dim sharded
        over ``axis``; block i holds global positions
        ``[i*T_local, (i+1)*T_local)``).
      axis: mesh axis name carrying the sequence shards.
      causal: apply a causal mask in *global* positions.

    Returns:
      Local attention output block ``[B, T_local, H, D]`` in q's dtype.
    """
    n = lax.axis_size(axis)
    my_idx = lax.axis_index(axis)
    b, t, h, d = q.shape
    scale = 1.0 / math.sqrt(d)

    q_pos = my_idx * t + jnp.arange(t)  # global positions of local queries

    def fold_block(carry, _i, k_blk, v_blk, src_idx):
        m_acc, l_acc, o_acc = carry
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk).astype(jnp.float32) * scale
        if causal:
            k_pos = src_idx * t + jnp.arange(t)
            mask = q_pos[:, None] >= k_pos[None, :]  # [T_q, T_k]
            s = jnp.where(mask[None, None, :, :], s, NEG_INF)
        m_blk = jnp.max(s, axis=-1)  # [B, H, T_q]
        m_new = jnp.maximum(m_acc, m_blk)
        # renormalize previous accumulator to the new max
        correction = jnp.exp(m_acc - m_new)
        p = jnp.exp(s - m_new[..., None])  # [B, H, T_q, T_k]
        l_new = l_acc * correction + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(q.dtype), v_blk)
        o_new = o_acc * correction.transpose(0, 2, 1)[..., None] + pv.astype(
            jnp.float32
        )
        return m_new, l_new, o_new

    def body(i, carry):
        m_acc, l_acc, o_acc, k_cur, v_cur = carry
        # Block currently held originated at rank (my_idx - i) mod n.
        src_idx = jax.lax.rem(my_idx - i + n, n)
        m_acc, l_acc, o_acc = fold_block(
            (m_acc, l_acc, o_acc), i, k_cur, v_cur, src_idx
        )
        k_nxt = _rotate(k_cur, axis, n)
        v_nxt = _rotate(v_cur, axis, n)
        return m_acc, l_acc, o_acc, k_nxt, v_nxt

    m0 = jnp.full((b, h, t), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, t), jnp.float32)
    o0 = jnp.zeros((b, t, h, d), jnp.float32)
    # Loop runs n-1 hops (each fold + rotate); the final block is folded
    # outside so no dead K/V rotation ships on the last hop (a fori_loop
    # body is compiled once — XLA cannot trim it per-iteration).
    m, l, o, k_last, v_last = lax.fori_loop(0, n - 1, body, (m0, l0, o0, k, v))
    m, l, o = fold_block(
        (m, l, o), n - 1, k_last, v_last, jax.lax.rem(my_idx - (n - 1) + n, n)
    )

    # l==0 only for globally-masked rows (cannot happen with causal=True);
    # guard anyway so padding-only rows return zeros, not NaN.
    l_t = l.transpose(0, 2, 1)[..., None]  # [B, T, H, 1]
    out = o / jnp.where(l_t == 0.0, 1.0, l_t)
    return out.astype(q.dtype)


def _rotate(x, axis, n):
    perm = [(i, (i + 1) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)
