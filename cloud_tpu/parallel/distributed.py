"""Multi-host runtime initialization: the cross-process contract.

Replaces the reference's generated TPU resolver-wait prologue
(preprocess.py:215-262, a 40 x 10 s poll for the ``TPU_CONFIG`` env var) and
its reliance on CAIP-injected ``TF_CONFIG``.  On Cloud TPU VMs,
``jax.distributed.initialize()`` auto-discovers the coordinator from TPU
metadata; off-TPU (tests, CPU fleets) the ``CLOUD_TPU_COORDINATOR`` /
``CLOUD_TPU_NUM_PROCESSES`` / ``CLOUD_TPU_PROCESS_ID`` env vars carry the
topology — set by our deploy layer's startup script (core/deploy.py).

Env contract (every variable optional on TPU VMs):

- ``CLOUD_TPU_COORDINATOR``    host:port of process 0
- ``CLOUD_TPU_NUM_PROCESSES``  total process count
- ``CLOUD_TPU_PROCESS_ID``     this process's rank
"""

from __future__ import annotations

import logging
import os
from typing import Optional

logger = logging.getLogger(__name__)

ENV_COORDINATOR = "CLOUD_TPU_COORDINATOR"
ENV_NUM_PROCESSES = "CLOUD_TPU_NUM_PROCESSES"
ENV_PROCESS_ID = "CLOUD_TPU_PROCESS_ID"

_INITIALIZED = False


def initialize_from_env(timeout_seconds: Optional[int] = None) -> bool:
    """Initialize jax.distributed if this is a multi-process job.

    Returns True when distributed init ran (or already had), False for
    single-process jobs.  Idempotent — safe to call from both the bootstrap
    runner and user code (mirroring the reference's re-entrant ``remote()``
    guard philosophy, run.py:31-33).
    """
    global _INITIALIZED
    if _INITIALIZED:
        return True

    import jax

    coordinator = os.environ.get(ENV_COORDINATOR)
    num_processes = os.environ.get(ENV_NUM_PROCESSES)
    process_id = os.environ.get(ENV_PROCESS_ID)

    if coordinator:
        kwargs = dict(
            coordinator_address=coordinator,
            num_processes=int(num_processes) if num_processes else None,
            process_id=int(process_id) if process_id else None,
        )
        if timeout_seconds is not None:
            kwargs["initialization_timeout"] = timeout_seconds
        logger.info("jax.distributed.initialize(%s)", kwargs)
        jax.distributed.initialize(**kwargs)
        _INITIALIZED = True
        return True

    if _on_tpu_vm_pod():
        # TPU metadata supplies coordinator/topology automatically.
        logger.info("jax.distributed.initialize() via TPU metadata")
        jax.distributed.initialize()
        _INITIALIZED = True
        return True

    logger.debug("single-process run; skipping jax.distributed")
    return False


def _on_tpu_vm_pod() -> bool:
    """True when running on a TPU VM that is part of a multi-host slice."""
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    return len([h for h in hostnames.split(",") if h]) > 1


def process_index() -> int:
    import jax

    return jax.process_index()


def process_count() -> int:
    import jax

    return jax.process_count()


def is_chief() -> bool:
    """Process 0 is the chief (checkpoint writer, log owner).

    Analogue of the reference's ``TF_CONFIG``-derived chief detection
    (cloud_fit/remote.py:148-156).
    """
    return process_index() == 0
