"""GPipe-style pipeline parallelism over the ``pp`` mesh axis.

The layer stack is divided into ``pp`` contiguous stages (the stacked
params' leading dim shards over ``pp``); the batch is divided into M
microbatches that flow through the stages as a shift register: at tick t,
stage s runs microbatch ``t - s`` and hands its activations to stage s+1
over ICI (``ppermute``).  Total ticks = M + pp - 1, so the pipeline bubble
is ``(pp - 1) / (M + pp - 1)`` of the step — raise ``num_microbatches`` to
amortize it.

Implementation: a *partial-manual* ``shard_map`` — manual over ``pp`` only
(``axis_names={"pp"}``), while dp/fsdp/tp/sp/ep stay under automatic GSPMD
partitioning.  The stage body is therefore the ordinary model layer code:
its einsums still shard over tp/ep, its attention still runs its own inner
``shard_map`` (over the remaining auto axes via the context's abstract
mesh), and batch dims stay sharded over dp×fsdp.  Gradients flow through
the schedule because every schedule op (``ppermute``, dynamic slices,
``psum``) is differentiable — the backward pass is the mirrored pipeline.

The reference has no pipeline analogue (SURVEY.md §2.6: it tops out at data
parallelism); this is TPU-native capability the rebuild adds, fulfilling
the ``pp`` axis contract declared in ``parallel/mesh.py``.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

from cloud_tpu.parallel import mesh as mesh_lib


def _tree_where(pred, on_true, on_false):
    return jax.tree_util.tree_map(
        lambda a, b: jnp.where(pred, a, b), on_true, on_false
    )


def _psum_f32(x, axis: str):
    """psum that dodges an XLA crash: all-reduce over a partially-manual
    axis CHECK-fails on sub-f32 dtypes ("Invalid binary instruction opcode
    copy", hlo_instruction.cc) — reduce in f32 and cast back."""
    if x.dtype in (jnp.bfloat16, jnp.float16):
        return jax.lax.psum(x.astype(jnp.float32), axis).astype(x.dtype)
    return jax.lax.psum(x, axis)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _pvary_safe(x, axis: str):
    """``pcast``-to-varying whose transpose reduces via :func:`_psum_f32`
    (the default transpose emits a raw psum, hitting the same sub-f32 XLA
    crash)."""
    return jax.lax.pcast(x, (axis,), to="varying")


def _pvary_safe_fwd(x, axis):
    return _pvary_safe(x, axis), None


def _pvary_safe_bwd(axis, _, g):
    return (_psum_f32(g, axis),)


_pvary_safe.defvjp(_pvary_safe_fwd, _pvary_safe_bwd)


def num_stages(mesh, axis: str = mesh_lib.AXIS_PP) -> int:
    if mesh is None:
        return 1
    return dict(mesh.shape).get(axis, 1)


def pipeline(
    layer_fn: Callable[[Any, Any], Any],
    stacked_params,
    microbatches,
    *,
    mesh,
    axis: str = mesh_lib.AXIS_PP,
):
    """Run microbatches through a pipelined layer stack.

    Args:
      layer_fn: ``layer_fn(one_layer_params, carry) -> carry`` — applies a
        single layer to one microbatch's carry pytree.
      stacked_params: pytree whose leaves have leading dim L (the layer
        count, divisible by the ``pp`` size); sharded over ``axis`` on that
        dim, so each stage holds L/pp contiguous layers.
      microbatches: pytree whose leaves have leading dim M (the microbatch
        count); leaf [m] is microbatch m's slice of the carry.
      mesh: the active Mesh (must contain ``axis``).

    Returns:
      A pytree congruent with ``microbatches``: each microbatch's carry
      after all L layers.
    """
    pp = num_stages(mesh, axis)
    if pp <= 1:
        return _sequential(layer_fn, stacked_params, microbatches)

    m = jax.tree_util.tree_leaves(microbatches)[0].shape[0]
    for leaf in jax.tree_util.tree_leaves(stacked_params):
        if leaf.shape[0] % pp:
            raise ValueError(
                f"Layer count {leaf.shape[0]} not divisible by pp={pp}"
            )

    def body(params, mbs):
        stage = jax.lax.axis_index(axis)
        nticks = m + pp - 1
        # Everything entering the tick loop must already be pp-varying so
        # the fori_loop carry keeps a consistent varying-manual-axes type.
        mbs = jax.tree_util.tree_map(lambda x: _pvary_safe(x, axis), mbs)

        def one_stage(carry):
            def scan_body(c, p):
                return layer_fn(p, c), None

            out, _ = jax.lax.scan(scan_body, carry, params)
            return out

        def mb_at(t):
            # Clamped read: ticks >= M re-read the last microbatch; their
            # results land past the output window (the scratch row).
            idx = jnp.minimum(t, m - 1)
            return jax.tree_util.tree_map(
                lambda x: jax.lax.dynamic_index_in_dim(
                    x, idx, 0, keepdims=False
                ),
                mbs,
            )

        carry0 = jax.tree_util.tree_map(
            lambda x: _pvary_safe(jnp.zeros(x.shape[1:], x.dtype), axis),
            mbs,
        )
        # Output buffer with one scratch row (index M): bubble-tick writes
        # are routed there instead of guarding with a whole-buffer select.
        out0 = jax.tree_util.tree_map(
            lambda x: _pvary_safe(jnp.zeros((m + 1,) + x.shape[1:], x.dtype), axis),
            mbs,
        )

        def tick(t, state):
            carry, outputs = state
            inp = _tree_where(stage == 0, mb_at(t), carry)
            y = one_stage(inp)
            out_idx = t - (pp - 1)
            store = jnp.where((out_idx >= 0) & (out_idx < m), out_idx, m)
            outputs = jax.tree_util.tree_map(
                lambda buf, v: jax.lax.dynamic_update_index_in_dim(
                    buf, v, store, 0
                ),
                outputs,
                y,
            )
            carry = jax.tree_util.tree_map(
                lambda v: jax.lax.ppermute(
                    v, axis, [(i, (i + 1) % pp) for i in range(pp)]
                ),
                y,
            )
            return carry, outputs

        _, outputs = jax.lax.fori_loop(0, nticks, tick, (carry0, out0))
        outputs = jax.tree_util.tree_map(lambda x: x[:m], outputs)
        # Only the final stage holds real results; zero the rest and
        # all-reduce so every stage returns the same (replicated) value.
        outputs = _tree_where(
            stage == pp - 1,
            outputs,
            jax.tree_util.tree_map(jnp.zeros_like, outputs),
        )

        return jax.tree_util.tree_map(
            lambda x: _psum_f32(x, axis), outputs
        )

    return jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(PartitionSpec(axis), PartitionSpec()),
        out_specs=PartitionSpec(),
        axis_names={axis},
    )(stacked_params, microbatches)


def _sequential(layer_fn, stacked_params, microbatches):
    """pp=1 degenerate case: one traced layer-stack scan, mapped over the
    microbatch dim (lax.map keeps the trace single, unlike a Python loop
    which would compile the stack M times)."""

    def scan_body(carry, p):
        return layer_fn(p, carry), None

    def run_one(mb):
        out, _ = jax.lax.scan(scan_body, mb, stacked_params)
        return out

    return jax.lax.map(run_one, microbatches)
