"""TPU parallelism layer: meshes, sharding rules, planner, collectives.

This package replaces the reference's strategy-selection mechanism (generated
``tf.distribute`` prologue text, preprocess.py:124-149) with a real library:
a :class:`MeshSpec` describes named parallelism axes over the device mesh, a
planner maps a declarative machine config to a mesh layout, and sharding
rules translate logical tensor axes to mesh axes.
"""

from cloud_tpu.utils import jax_compat as _jax_compat  # noqa: F401  (shims)
from cloud_tpu.parallel.mesh import (
    AXIS_DP,
    AXIS_EP,
    AXIS_FSDP,
    AXIS_PP,
    AXIS_SP,
    AXIS_TP,
    CANONICAL_AXES,
    MeshSpec,
    get_global_mesh,
    set_global_mesh,
    use_mesh,
)
from cloud_tpu.parallel.planner import (
    MeshPlan,
    ParallelismHints,
    ServeLayout,
    plan_mesh,
    plan_serve_layout,
)
from cloud_tpu.parallel.sharding import (
    ShardingRules,
    DEFAULT_RULES,
    logical_to_mesh_axes,
    named_sharding,
    shard_constraint,
)

__all__ = [
    "AXIS_DP",
    "AXIS_EP",
    "AXIS_FSDP",
    "AXIS_PP",
    "AXIS_SP",
    "AXIS_TP",
    "CANONICAL_AXES",
    "MeshSpec",
    "MeshPlan",
    "ParallelismHints",
    "ShardingRules",
    "DEFAULT_RULES",
    "get_global_mesh",
    "set_global_mesh",
    "use_mesh",
    "logical_to_mesh_axes",
    "named_sharding",
    "plan_mesh",
    "plan_serve_layout",
    "ServeLayout",
    "shard_constraint",
]
