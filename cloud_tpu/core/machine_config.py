"""TPU-first machine/slice resource model.

This is the declarative resource layer of the framework: every job a user
launches is described by a :class:`MachineConfig` (what one *role* of the job
runs on) and, for TPU roles, a :class:`TpuTopology` (the shape of the slice).

Reference analogue: ``src/python/tensorflow_cloud/core/machine_config.py``
(AcceleratorType enum :25-55, MachineConfig :58-93, COMMON_MACHINE_CONFIGS
:97-176, is_tpu_config :179-185).  Differences, by design:

* TPU generations are first-class (v2..v6e) and carry *slice topology*
  (``2x4``, ``4x4`` ...), because on Cloud TPU the slice shape — not a GPU
  count — is the unit of scale.  The reference only knew ``TPU_V2/V3 x 8``.
* GPU accelerator types from the reference are kept as *migration aliases* so
  existing configs still parse; the TPU deploy path rejects them with a
  pointer to the nearest TPU config (see :func:`gpu_migration_hint`).
* A config knows how many hosts its slice spans — the mesh planner
  (``cloud_tpu/parallel/planner.py``) turns that into DCN x ICI mesh axes.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Dict, Optional


class AcceleratorType(enum.Enum):
    """Accelerator families a job role can request.

    TPU generations are the native path.  The NVIDIA entries mirror the
    reference enum (machine_config.py:25-55) so that configs written against
    the reference keep parsing; they are rejected at deploy time with a
    migration hint.
    """

    NO_ACCELERATOR = "CPU"
    TPU_V2 = "TPU_V2"
    TPU_V3 = "TPU_V3"
    TPU_V4 = "TPU_V4"
    TPU_V5E = "TPU_V5E"
    TPU_V5P = "TPU_V5P"
    TPU_V6E = "TPU_V6E"
    # --- migration aliases (reference GPU catalog) ---
    NVIDIA_TESLA_K80 = "K80"
    NVIDIA_TESLA_P100 = "P100"
    NVIDIA_TESLA_V100 = "V100"
    NVIDIA_TESLA_P4 = "P4"
    NVIDIA_TESLA_T4 = "T4"


#: TPU generations, newest last.
TPU_ACCELERATORS = (
    AcceleratorType.TPU_V2,
    AcceleratorType.TPU_V3,
    AcceleratorType.TPU_V4,
    AcceleratorType.TPU_V5E,
    AcceleratorType.TPU_V5P,
    AcceleratorType.TPU_V6E,
)

GPU_ACCELERATORS = (
    AcceleratorType.NVIDIA_TESLA_K80,
    AcceleratorType.NVIDIA_TESLA_P100,
    AcceleratorType.NVIDIA_TESLA_V100,
    AcceleratorType.NVIDIA_TESLA_P4,
    AcceleratorType.NVIDIA_TESLA_T4,
)


@dataclasses.dataclass(frozen=True)
class TpuTopology:
    """Shape of one TPU slice.

    ``chips`` is the number of physical chips; ``hosts`` the number of TPU-VM
    hosts the slice spans; ``topology`` the ICI wiring string as used by the
    Cloud TPU API (``2x4``, ``4x4x4`` ...); ``accelerator_type`` the API name
    (``v5litepod-8`` ...).  ``cores_per_chip`` distinguishes the two-TensorCore
    generations (v2-v4, v5p) from the single-core inference-optimised ones
    (v5e, v6e).
    """

    generation: AcceleratorType
    accelerator_type: str
    topology: str
    chips: int
    hosts: int
    cores_per_chip: int

    @property
    def chips_per_host(self) -> int:
        return self.chips // self.hosts

    @property
    def cores(self) -> int:
        return self.chips * self.cores_per_chip


def _topo(gen, name, topology, chips, hosts, cores_per_chip) -> TpuTopology:
    return TpuTopology(gen, name, topology, chips, hosts, cores_per_chip)


#: Legal slice shapes per generation, keyed by Cloud TPU API accelerator-type
#: string.  This is the TPU-native analogue of the reference's ~200-row
#: (cpu, mem, accelerator, count) whitelist (gcp.py:123-406): deploy requests
#: are validated against this table before anything touches the network.
TPU_SLICE_CATALOG: Dict[str, TpuTopology] = {
    t.accelerator_type: t
    for t in [
        # v2 / v3 (the only generations the reference knew; gcp.py:78-90)
        _topo(AcceleratorType.TPU_V2, "v2-8", "2x2", 4, 1, 2),
        _topo(AcceleratorType.TPU_V2, "v2-32", "4x4", 16, 4, 2),
        _topo(AcceleratorType.TPU_V3, "v3-8", "2x2", 4, 1, 2),
        _topo(AcceleratorType.TPU_V3, "v3-32", "4x4", 16, 4, 2),
        # v4: 3D torus, 4 chips/host
        _topo(AcceleratorType.TPU_V4, "v4-8", "2x2x1", 4, 1, 2),
        _topo(AcceleratorType.TPU_V4, "v4-16", "2x2x2", 8, 2, 2),
        _topo(AcceleratorType.TPU_V4, "v4-32", "2x2x4", 16, 4, 2),
        _topo(AcceleratorType.TPU_V4, "v4-64", "2x4x4", 32, 8, 2),
        _topo(AcceleratorType.TPU_V4, "v4-128", "4x4x4", 64, 16, 2),
        # v5e: 2D mesh, single host up to 8 chips, 4 chips/host beyond
        _topo(AcceleratorType.TPU_V5E, "v5litepod-1", "1x1", 1, 1, 1),
        _topo(AcceleratorType.TPU_V5E, "v5litepod-4", "2x2", 4, 1, 1),
        _topo(AcceleratorType.TPU_V5E, "v5litepod-8", "2x4", 8, 1, 1),
        _topo(AcceleratorType.TPU_V5E, "v5litepod-16", "4x4", 16, 4, 1),
        _topo(AcceleratorType.TPU_V5E, "v5litepod-32", "4x8", 32, 8, 1),
        _topo(AcceleratorType.TPU_V5E, "v5litepod-64", "8x8", 64, 16, 1),
        _topo(AcceleratorType.TPU_V5E, "v5litepod-128", "8x16", 128, 32, 1),
        _topo(AcceleratorType.TPU_V5E, "v5litepod-256", "16x16", 256, 64, 1),
        # v5p: 3D torus, 4 chips/host
        _topo(AcceleratorType.TPU_V5P, "v5p-8", "2x2x1", 4, 1, 2),
        _topo(AcceleratorType.TPU_V5P, "v5p-16", "2x2x2", 8, 2, 2),
        _topo(AcceleratorType.TPU_V5P, "v5p-32", "2x2x4", 16, 4, 2),
        _topo(AcceleratorType.TPU_V5P, "v5p-128", "4x4x4", 64, 16, 2),
        # v6e (Trillium): 2D mesh like v5e
        _topo(AcceleratorType.TPU_V6E, "v6e-1", "1x1", 1, 1, 1),
        _topo(AcceleratorType.TPU_V6E, "v6e-4", "2x2", 4, 1, 1),
        _topo(AcceleratorType.TPU_V6E, "v6e-8", "2x4", 8, 1, 1),
        _topo(AcceleratorType.TPU_V6E, "v6e-16", "4x4", 16, 4, 1),
        _topo(AcceleratorType.TPU_V6E, "v6e-32", "4x8", 32, 8, 1),
        _topo(AcceleratorType.TPU_V6E, "v6e-64", "8x8", 64, 16, 1),
        _topo(AcceleratorType.TPU_V6E, "v6e-128", "8x16", 128, 32, 1),
        _topo(AcceleratorType.TPU_V6E, "v6e-256", "16x16", 256, 64, 1),
    ]
}


def find_topology(
    generation: AcceleratorType, chips: int, topology: Optional[str] = None
) -> TpuTopology:
    """Resolve (generation, chip count[, topology string]) to a catalog entry."""
    matches = [
        t
        for t in TPU_SLICE_CATALOG.values()
        if t.generation == generation
        and t.chips == chips
        and (topology is None or t.topology == topology)
    ]
    if not matches:
        legal = sorted(
            t.chips for t in TPU_SLICE_CATALOG.values() if t.generation == generation
        )
        raise ValueError(
            f"No legal {generation.name} slice with {chips} chips"
            + (f" and topology {topology!r}" if topology else "")
            + f". Legal chip counts for {generation.name}: {legal}."
        )
    return matches[0]


@dataclasses.dataclass(frozen=True)
class MachineConfig:
    """Declarative spec for one job role (chief / worker).

    For TPU configs ``accelerator_count`` counts *chips* — note Google's
    v2/v3/v4/v5p accelerator-type names count TensorCores instead, so
    ``TPU_V4 x 32`` chips resolves to API name ``v4-64`` — and ``topology``
    may pin the slice wiring; ``cpu_cores``/``memory`` describe the host VM and
    may be ``None`` (TPU-VM machine shape is implied by the slice, mirroring
    the reference's TPU rows ``(None, None, TPU_V*, 8)``, gcp.py:123-406).
    """

    cpu_cores: Optional[int] = 8
    memory: Optional[int] = 30
    accelerator_type: AcceleratorType = AcceleratorType.NO_ACCELERATOR
    accelerator_count: int = 0
    topology: Optional[str] = None

    def __post_init__(self):
        if not isinstance(self.accelerator_type, AcceleratorType):
            raise ValueError(
                "accelerator_type must be an AcceleratorType, got "
                f"{self.accelerator_type!r}"
            )
        if self.accelerator_type is AcceleratorType.NO_ACCELERATOR:
            if self.accelerator_count:
                raise ValueError(
                    "accelerator_count must be 0 for NO_ACCELERATOR, got "
                    f"{self.accelerator_count}"
                )
        elif self.accelerator_count < 1:
            raise ValueError(
                f"accelerator_count must be >= 1 for {self.accelerator_type.name}"
            )
        if self.is_tpu():
            # Resolves or raises with the legal-shape table.
            find_topology(self.accelerator_type, self.accelerator_count, self.topology)

    def is_tpu(self) -> bool:
        return self.accelerator_type in TPU_ACCELERATORS

    def is_gpu(self) -> bool:
        return self.accelerator_type in GPU_ACCELERATORS

    def tpu_topology(self) -> TpuTopology:
        if not self.is_tpu():
            raise ValueError(f"{self.accelerator_type.name} is not a TPU config")
        return find_topology(
            self.accelerator_type, self.accelerator_count, self.topology
        )


def is_tpu_config(config: Optional[MachineConfig]) -> bool:
    """Reference parity: machine_config.py:179-185."""
    return config is not None and config.is_tpu()


def gpu_migration_hint(config: MachineConfig) -> str:
    """The TPU config a reference GPU config should move to.

    Used by validate/deploy to produce actionable errors instead of silently
    launching GPU fleets from a TPU-native framework.
    """
    n = max(1, config.accelerator_count)
    if n <= 1:
        name = "v5litepod-1"
    elif n <= 4:
        name = "v5litepod-4"
    else:
        name = "v5litepod-8"
    return (
        f"{config.accelerator_type.name} x{config.accelerator_count} is a GPU "
        f"config from tensorflow-cloud; this framework launches TPU jobs. "
        f"Nearest TPU equivalent: COMMON_MACHINE_CONFIGS['TPU_V5E_{TPU_SLICE_CATALOG[name].chips}'] "
        f"({name})."
    )


def _tpu_config(name: str) -> MachineConfig:
    t = TPU_SLICE_CATALOG[name]
    return MachineConfig(
        cpu_cores=None,
        memory=None,
        accelerator_type=t.generation,
        accelerator_count=t.chips,
        topology=t.topology,
    )


#: Named presets.  Mirrors the reference's 14-entry catalog
#: (machine_config.py:97-176) but TPU-first: 'TPU' now means a current-
#: generation v5e-8 slice (the BASELINE.json north-star target), and every
#: TPU generation gets entries.  The GPU presets stay for migration parsing.
COMMON_MACHINE_CONFIGS: Dict[str, MachineConfig] = {
    "CPU": MachineConfig(cpu_cores=4, memory=15),
    "CPU_LARGE": MachineConfig(cpu_cores=32, memory=120),
    # TPU presets — the native path.
    "TPU": _tpu_config("v5litepod-8"),
    "TPU_V2": _tpu_config("v2-8"),
    "TPU_V3": _tpu_config("v3-8"),
    "TPU_V4_8": _tpu_config("v4-8"),
    "TPU_V4_32": _tpu_config("v4-32"),
    "TPU_V5E_1": _tpu_config("v5litepod-1"),
    "TPU_V5E_4": _tpu_config("v5litepod-4"),
    "TPU_V5E_8": _tpu_config("v5litepod-8"),
    "TPU_V5E_16": _tpu_config("v5litepod-16"),
    "TPU_V5E_32": _tpu_config("v5litepod-32"),
    "TPU_V5E_64": _tpu_config("v5litepod-64"),
    "TPU_V5E_128": _tpu_config("v5litepod-128"),
    "TPU_V5E_256": _tpu_config("v5litepod-256"),
    "TPU_V5P_8": _tpu_config("v5p-8"),
    "TPU_V6E_8": _tpu_config("v6e-8"),
    "TPU_V6E_32": _tpu_config("v6e-32"),
    "TPU_V6E_256": _tpu_config("v6e-256"),
    # Migration aliases (reference catalog names; deploy rejects with hint).
    "K80_1X": MachineConfig(8, 30, AcceleratorType.NVIDIA_TESLA_K80, 1),
    "K80_4X": MachineConfig(16, 60, AcceleratorType.NVIDIA_TESLA_K80, 4),
    "K80_8X": MachineConfig(32, 120, AcceleratorType.NVIDIA_TESLA_K80, 8),
    "P100_1X": MachineConfig(8, 30, AcceleratorType.NVIDIA_TESLA_P100, 1),
    "P100_4X": MachineConfig(16, 60, AcceleratorType.NVIDIA_TESLA_P100, 4),
    "P4_1X": MachineConfig(8, 30, AcceleratorType.NVIDIA_TESLA_P4, 1),
    "P4_4X": MachineConfig(16, 60, AcceleratorType.NVIDIA_TESLA_P4, 4),
    "V100_1X": MachineConfig(8, 30, AcceleratorType.NVIDIA_TESLA_V100, 1),
    "V100_4X": MachineConfig(16, 60, AcceleratorType.NVIDIA_TESLA_V100, 4),
    "T4_1X": MachineConfig(8, 30, AcceleratorType.NVIDIA_TESLA_T4, 1),
    "T4_4X": MachineConfig(16, 60, AcceleratorType.NVIDIA_TESLA_T4, 4),
}
