"""The front door: ``run()`` scales a local script to a TPU pod.

Reference analogue: ``src/python/tensorflow_cloud/core/run.py`` — the
pipeline (guard -> defaults -> validate -> preprocess -> containerize ->
deploy -> exit, :36-246) carries over; the mechanisms are TPU-native:

* default configs target a v5e-8 slice, not a T4 GPU (reference :154-157)
* strategy selection becomes a MeshPlan (parallel/planner.py) serialized
  into the container ENTRYPOINT, not generated source text
* deployment creates Cloud TPU VM nodes, not a CAIP GPU cluster

``remote()`` is the re-entry contract (reference run.py:31-33): the same
script calls run() locally (submits and stops) and trains when re-executed
inside the container (bootstrap sets CLOUD_TPU_RUNNING_REMOTELY).
"""

from __future__ import annotations

import logging
import os
import shutil
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Union

from cloud_tpu.core import containerize, deploy, gcp, machine_config, notebook
from cloud_tpu.core import validate as validate_lib
from cloud_tpu.core.bootstrap import ENV_RUNNING_REMOTELY
from cloud_tpu.monitoring import tracing
from cloud_tpu.parallel import planner

logger = logging.getLogger(__name__)


def remote() -> bool:
    """True inside the cloud container (reference run.py:31-33)."""
    return bool(os.environ.get(ENV_RUNNING_REMOTELY))


@dataclass
class RunReport:
    """Everything run() decided and produced — inspectable in dry runs."""

    job_id: Optional[str] = None
    console_url: Optional[str] = None
    image_uri: Optional[str] = None
    mesh_plan: Optional[planner.MeshPlan] = None
    dockerfile: Optional[str] = None
    node_requests: Dict[str, dict] = field(default_factory=dict)
    submitted: bool = False


def run(
    entry_point: Optional[str] = None,
    requirements_txt: Optional[str] = None,
    distribution_strategy: Optional[str] = "auto",
    docker_config: Optional[containerize.DockerConfig] = None,
    chief_config: Union[str, machine_config.MachineConfig] = "auto",
    worker_config: Union[str, machine_config.MachineConfig] = "auto",
    worker_count: int = 0,
    entry_point_args: Optional[List[str]] = None,
    stream_logs: bool = False,
    job_labels: Optional[Dict[str, str]] = None,
    service_account: Optional[str] = None,
    parallelism_hints: Optional[planner.ParallelismHints] = None,
    dry_run: bool = False,
    max_restarts: int = 0,
    monitoring: bool = True,
    profiler_port: Optional[int] = None,
    _session=None,
    _builder=None,
    **kwargs,
) -> RunReport:
    """Validate, plan, containerize and launch a training job on Cloud TPU.

    Args mirror the reference ``run()`` (run.py:36-131) plus
    ``parallelism_hints`` (mesh axis pins — capability the reference's
    strategy picker couldn't express), ``dry_run`` (produce every
    artifact, submit nothing), and ``max_restarts`` (> 0: stay alive
    after submission supervising the job — preempted nodes are recreated
    up to this many times and training resumes from the latest
    checkpoint; the reference delegated this to CAIP job restarts.
    Blocking, like ``stream_logs``; if both are set, log streaming wins
    and supervision never starts).  ``monitoring`` (default True) makes
    every deployed host export runtime metrics to Cloud Monitoring with
    zero user code — the job spec carries the exporter's env gate, the
    reference's stackdriver_exporter.cc:31-36 contract;
    ``profiler_port`` additionally starts the on-demand profiler server
    on each host.  ``_session``/``_builder`` are test seams.

    Returns a RunReport.  In script mode (entry_point=None, run() called
    from the training script itself) the local process exits after
    submission, mirroring reference run.py:243-246.
    """
    if remote():
        # Inside the container: fall through to the caller's training code.
        return RunReport(submitted=False)

    if kwargs:
        # Strict kwargs for forward compatibility (reference run.py:137-145).
        raise TypeError(f"Unknown arguments to run(): {sorted(kwargs)}")

    # Arm the submit-to-first-step composite: the trainer's first completed
    # step (local smoke runs) or the in-container re-entry (via the
    # CLOUD_TPU_SUBMIT_TS env below) publishes the gauge.
    submit_ts = time.time()
    tracing.mark_submit()

    try:
        called_from_notebook = notebook.called_from_notebook()

        if chief_config == "auto":
            chief_config = machine_config.COMMON_MACHINE_CONFIGS["TPU"]
        if worker_config == "auto":
            worker_config = chief_config if worker_count > 0 else None

        docker_config = docker_config or containerize.DockerConfig()

        with tracing.span("run/validate"):
            validate_lib.validate(
                entry_point=entry_point,
                requirements_txt=requirements_txt,
                distribution_strategy=distribution_strategy,
                chief_config=chief_config,
                worker_config=worker_config,
                worker_count=worker_count,
                entry_point_args=entry_point_args,
                stream_logs=stream_logs,
                docker_image_build_bucket=docker_config.image_build_bucket,
                called_from_notebook=called_from_notebook,
                job_labels=job_labels,
                service_account=service_account,
            )

        # --- plan the mesh (replaces strategy-code generation) ---
        plan = None
        if distribution_strategy == "auto":
            with tracing.span("run/plan"):
                plan = planner.plan_mesh(
                    chief_config=chief_config,
                    worker_count=worker_count,
                    hints=parallelism_hints,
                )
            logger.info("mesh plan: %s", plan.description)

        # --- resolve the entry point ---
        script_mode = entry_point is None
        resolved_entry = entry_point
        temp_dirs = []
        if called_from_notebook and entry_point is None:
            # Colab: the live notebook is fetched over the kernel RPC — it
            # need not exist on disk (reference preprocess.py:196-212).
            try:
                resolved_entry = notebook.fetch_live_notebook_script()
            except (RuntimeError, KeyError, TypeError) as exc:
                # RuntimeError: not a Colab runtime / frontend returned None;
                # KeyError/TypeError: malformed RPC response shape.  All get
                # the same actionable guidance instead of a raw traceback.
                raise ValueError(
                    "In this notebook environment the live-notebook fetch is "
                    f"unavailable ({exc!r}); pass entry_point= (the .ipynb or "
                    ".py to run)."
                ) from exc
            temp_dirs.append(os.path.dirname(resolved_entry))
        if resolved_entry is not None and resolved_entry.endswith(".ipynb"):
            resolved_entry = notebook.notebook_to_script(resolved_entry)
            temp_dirs.append(os.path.dirname(resolved_entry))
        if script_mode and not called_from_notebook:
            # run() was called from inside the training script: ship that script.
            resolved_entry = os.path.abspath(sys.argv[0])

        # --- containerize ---
        project = None
        image_uri = docker_config.image
        if image_uri is None:
            project = gcp.get_project_name()
            image_uri = containerize.default_image_uri(project)
        dockerfile = containerize.make_dockerfile(
            os.path.basename(resolved_entry),
            chief_config,
            requirements_name=(
                os.path.basename(requirements_txt) if requirements_txt else None
            ),
            parent_image=docker_config.parent_image,
            jax_version=docker_config.jax_version,
            mesh_plan_json=plan.to_json() if plan else None,
            distribution_strategy="auto" if distribution_strategy == "auto" else "none",
            entry_point_args=entry_point_args,
        )

        deploy_plan = plan or planner.plan_mesh(
            chief_config=chief_config, worker_count=worker_count
        )
        # Built exactly once: the report's node requests ARE the submitted ones.
        job_request = deploy.build_job_request(
            image_uri, chief_config, worker_count, deploy_plan,
            job_labels=job_labels, service_account=service_account,
            monitoring=monitoring, profiler_port=profiler_port,
            submit_ts=submit_ts,
        )
        report = RunReport(
            image_uri=image_uri, mesh_plan=plan, dockerfile=dockerfile,
            job_id=job_request["job_id"], node_requests=job_request["nodes"],
        )

        try:
            if dry_run:
                return report

            with tracing.span("run/containerize"):
                context_dir = containerize.build_context(
                    dockerfile, resolved_entry, requirements_txt
                )
                temp_dirs.append(context_dir)
                if _builder is not None:
                    builder = _builder
                elif docker_config.image_build_bucket:
                    builder = containerize.CloudContainerBuilder(
                        image_uri, context_dir,
                        project=project or gcp.get_project_name(),
                        bucket=docker_config.image_build_bucket,
                        session=_session,
                    )
                else:
                    builder = containerize.LocalContainerBuilder(
                        image_uri, context_dir, cache_from=docker_config.cache_from
                    )
                report.image_uri = builder.get_docker_image()
            if report.image_uri != image_uri:
                # Builder renamed the image: regenerate node bodies so their
                # startup scripts pull the image that actually exists.
                job_request = deploy.build_job_request(
                    report.image_uri, chief_config, worker_count, deploy_plan,
                    job_id=job_request["job_id"],
                    job_labels=job_labels, service_account=service_account,
                    monitoring=monitoring, profiler_port=profiler_port,
                    submit_ts=submit_ts,
                )
                report.node_requests = job_request["nodes"]

            # --- deploy ---
            with tracing.span("run/deploy"):
                job_info = deploy.deploy_job(
                    report.image_uri,
                    chief_config,
                    worker_count,
                    deploy_plan,
                    job_labels=job_labels,
                    service_account=service_account,
                    session=_session,
                    stream_logs=stream_logs,
                    request=job_request,
                )
            report.job_id = job_info["job_id"]
            report.console_url = job_info["console_url"]
            report.submitted = True
        finally:
            for d in temp_dirs:
                shutil.rmtree(d, ignore_errors=True)

        if max_restarts > 0 and not stream_logs:
            # After cleanup: supervision may run for the job's whole life and
            # needs none of the build artifacts.  Returns when the job's
            # nodes are torn down (delete_job/console) or raises when the
            # restart budget is exhausted.  Not after stream_logs: the only
            # way out of the log tail is Ctrl-C, and that interrupt means
            # "stop run()", not "enter a second blocking loop".
            deploy.supervise_job(
                job_info, job_request, session=_session,
                max_restarts=max_restarts,
            )

        if script_mode and not called_from_notebook:
            # Stop local execution of the training script after submitting
            # (reference run.py:243-246).
            sys.exit(0)
        return report
    except Exception:
        # A run() that raised before submitting must not leave a
        # pending submit mark for a later unrelated fit() in this
        # process to consume as its submit-to-first-step origin.
        tracing.clear_submit()
        raise
