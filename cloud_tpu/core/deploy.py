"""Deployment: emit and submit Cloud TPU VM job specs.

Reference analogue: ``src/python/tensorflow_cloud/core/deploy.py`` (CAIP
trainingInput :109-161, submit :82-88, console URL :170-184, log streaming
:187-211, job id :214-218).  The TPU-native job is not a CAIP GPU cluster:
each worker role becomes a **TPU VM node** (tpu.googleapis.com v2) whose
startup script launches the training container on every host of the slice
with the ``jax.distributed`` env contract filled in — replacing both CAIP's
``TF_CONFIG`` injection and the reference's ``cloud_tpu`` sidecar worker.
"""

from __future__ import annotations

import logging
import subprocess
import uuid
from typing import Dict, List, Optional

from cloud_tpu.core import gcp, machine_config
from cloud_tpu.parallel import planner
from cloud_tpu.utils import api_client

logger = logging.getLogger(__name__)

_TPU_API = "https://tpu.googleapis.com/v2"


def _job_id() -> str:
    """cloud_tpu_train_<uuid> (reference deploy.py:214-218)."""
    return f"cloud-tpu-train-{uuid.uuid4().hex[:8]}"


def startup_script(
    image_uri: str,
    *,
    coordinator_address: str,
    num_processes: int,
    process_id_base: int,
) -> str:
    """TPU-VM startup script: pull + run the training container on each host.

    ``process_id_base`` is the rank of this node's host 0; TPU VM metadata
    exposes the within-node worker index as ``agent-worker-number``, so the
    global rank is base + local index.  This replaces the reference's
    resolver-wait prologue (preprocess.py:215-262) — topology is fully
    determined before boot.
    """
    return "\n".join(
        [
            "#! /bin/bash",
            "set -ex",
            'LOCAL_ID=$(curl -sf -H "Metadata-Flavor: Google" '
            '"http://metadata.google.internal/computeMetadata/v1/instance/'
            'attributes/agent-worker-number" || echo 0)',
            f"docker pull {image_uri}",
            "docker run --privileged --net=host \\",
            f"  -e CLOUD_TPU_COORDINATOR={coordinator_address} \\",
            f"  -e CLOUD_TPU_NUM_PROCESSES={num_processes} \\",
            f"  -e CLOUD_TPU_PROCESS_ID=$(({process_id_base} + LOCAL_ID)) \\",
            f"  {image_uri}",
        ]
    )


def build_node_request(
    image_uri: str,
    config: machine_config.MachineConfig,
    *,
    coordinator_address: str,
    num_processes: int,
    process_id_base: int,
    job_labels: Optional[Dict[str, str]] = None,
    service_account: Optional[str] = None,
) -> dict:
    """The TPU v2 API Node body for one slice (golden-tested)."""
    topo = config.tpu_topology()
    node: dict = {
        "acceleratorType": topo.accelerator_type,
        "runtimeVersion": gcp.TPU_RUNTIME_VERSIONS[config.accelerator_type],
        "metadata": {
            "startup-script": startup_script(
                image_uri,
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id_base=process_id_base,
            )
        },
        "labels": dict(job_labels or {}),
    }
    if service_account:
        node["serviceAccount"] = {"email": service_account}
    return node


def build_job_request(
    image_uri: str,
    chief_config: machine_config.MachineConfig,
    worker_count: int,
    plan: planner.MeshPlan,
    *,
    job_id: Optional[str] = None,
    job_labels: Optional[Dict[str, str]] = None,
    service_account: Optional[str] = None,
) -> dict:
    """All node bodies for a (multi-)slice job, keyed by node id.

    Slice i's hosts get ranks [i * hosts_per_slice, (i+1) * hosts_per_slice);
    the coordinator is slice 0 host 0, reachable by node DNS name.
    """
    job_id = job_id or _job_id()
    num_slices = worker_count + 1
    hosts_per_slice = plan.hosts_per_slice
    num_processes = num_slices * hosts_per_slice
    coordinator = f"{job_id}-0-w0:8476"
    nodes = {}
    for i in range(num_slices):
        nodes[f"{job_id}-{i}"] = build_node_request(
            image_uri,
            chief_config,
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id_base=i * hosts_per_slice,
            job_labels={**(job_labels or {}), "cloud_tpu_job": job_id},
            service_account=service_account,
        )
    return {"job_id": job_id, "nodes": nodes}


def deploy_job(
    image_uri: str,
    chief_config: machine_config.MachineConfig,
    worker_count: int,
    plan: planner.MeshPlan,
    *,
    project: Optional[str] = None,
    zone: Optional[str] = None,
    job_labels: Optional[Dict[str, str]] = None,
    service_account: Optional[str] = None,
    session: Optional[api_client.GcpApiSession] = None,
    stream_logs: bool = False,
    request: Optional[dict] = None,
) -> dict:
    """Create the TPU nodes for the job; returns job info incl. console URL.

    ``request`` may carry a prebuilt ``build_job_request`` result (run()
    builds one for its report; passing it here guarantees the submitted
    nodes are exactly the reported ones).
    """
    if not chief_config.is_tpu():
        raise NotImplementedError(
            "deploy_job launches Cloud TPU jobs; CPU-only/chief-off-slice "
            "jobs are not yet supported. "
            + (
                machine_config.gpu_migration_hint(chief_config)
                if chief_config.is_gpu()
                else ""
            )
        )
    project = project or gcp.get_project_name()
    zone = zone or gcp.get_zone(chief_config)
    session = session or api_client.default_session()
    if request is None:
        request = build_job_request(
            image_uri, chief_config, worker_count, plan,
            job_labels=job_labels, service_account=service_account,
        )
    parent = f"projects/{project}/locations/{zone}"
    for node_id, body in request["nodes"].items():
        session.post(
            f"{_TPU_API}/{parent}/nodes", body=body, params={"nodeId": node_id}
        )
        logger.info("created TPU node %s (%s)", node_id, body["acceleratorType"])
    job_id = request["job_id"]
    console_url = (
        f"https://console.cloud.google.com/compute/tpus?project={project}"
    )
    print(f"Job submitted: {job_id}")
    print(f"Your TPU nodes are visible at: {console_url}")
    if stream_logs:
        _stream_logs(job_id, project, zone)
    return {
        "job_id": job_id,
        "nodes": list(request["nodes"]),
        "project": project,
        "zone": zone,
        "console_url": console_url,
    }


def delete_job(job_info: dict,
               session: Optional[api_client.GcpApiSession] = None) -> None:
    """Tear the job's TPU nodes down (the lifecycle the reference delegated
    to CAIP — SURVEY.md §7 hard parts)."""
    session = session or api_client.default_session()
    parent = f"projects/{job_info['project']}/locations/{job_info['zone']}"
    for node_id in job_info["nodes"]:
        session.delete(f"{_TPU_API}/{parent}/nodes/{node_id}")
        logger.info("deleted TPU node %s", node_id)


def _stream_logs(job_id: str, project: str, zone: str) -> None:
    """Stream node logs via gcloud (reference shelled out the same way,
    deploy.py:187-211)."""
    argv = [
        "gcloud", "logging", "read",
        f'resource.type="tpu_worker" AND labels.cloud_tpu_job="{job_id}"',
        "--project", project, "--format", "value(textPayload)",
    ]
    try:
        subprocess.run(argv, check=False)
    except FileNotFoundError:
        logger.warning("gcloud not installed; skipping log streaming")
