"""Deployment: emit and submit Cloud TPU VM job specs.

Reference analogue: ``src/python/tensorflow_cloud/core/deploy.py`` (CAIP
trainingInput :109-161, submit :82-88, console URL :170-184, log streaming
:187-211, job id :214-218).  The TPU-native job is not a CAIP GPU cluster:
each worker role becomes a **TPU VM node** (tpu.googleapis.com v2) whose
startup script launches the training container on every host of the slice
with the ``jax.distributed`` env contract filled in — replacing both CAIP's
``TF_CONFIG`` injection and the reference's ``cloud_tpu`` sidecar worker.
"""

from __future__ import annotations

import logging
import os
import time
import uuid
from typing import Callable, Dict, List, Optional, Sequence

from cloud_tpu.core import gcp, machine_config
from cloud_tpu.parallel import planner
from cloud_tpu.utils import api_client, retries

logger = logging.getLogger(__name__)

_TPU_API = "https://tpu.googleapis.com/v2"

#: Node-create LRO poll budget (creation request acknowledged).
_LRO_POLL_INTERVAL_SECONDS = 5
_LRO_POLL_ATTEMPTS = 60
#: Node READY-state poll budget — the analogue of the reference's TPU
#: provisioning wait, 40 x 10 s (preprocess.py:238-261).
_READY_POLL_INTERVAL_SECONDS = 10
_READY_POLL_ATTEMPTS = 40


def _poll_sleep(sleep: Callable[[float], None], seconds: float) -> None:
    """All fixed-interval poll waits go through here, ±20% jittered:
    recreated multi-node jobs boot near-simultaneously, and without
    jitter their supervisors/awaits poll the API in lockstep forever.
    The injectable ``sleep`` seam is preserved (tests stay instant and
    can assert the base interval from the jittered value)."""
    sleep(retries.jittered(seconds))


def _deploy_retry_policy(sleep: Callable[[float], None]) -> retries.RetryPolicy:
    """Deploy-layer policy over the SESSION's own retries: coarser
    backoff for polls that may legitimately run for minutes, threaded
    through the same injectable ``sleep`` the poll loops use."""
    return retries.default_api_policy(
        max_attempts=5, initial_backoff_s=1.0, max_backoff_s=20.0,
        max_elapsed_s=120.0, sleep=sleep,
    )


class ProvisioningError(RuntimeError):
    """A TPU node failed to provision; partial slices were rolled back."""


def _job_id() -> str:
    """cloud_tpu_train_<uuid> (reference deploy.py:214-218)."""
    return f"cloud-tpu-train-{uuid.uuid4().hex[:8]}"


def startup_script(
    image_uri: str,
    *,
    coordinator_address: str,
    num_processes: int,
    process_id_base: int,
    monitoring: bool = True,
    profiler_port: Optional[int] = None,
    submit_ts: Optional[float] = None,
    compile_cache: Optional[str] = None,
) -> str:
    """TPU-VM startup script: pull + run the training container on each host.

    ``process_id_base`` is the rank of this node's host 0; TPU VM metadata
    exposes the within-node worker index as ``agent-worker-number``, so the
    global rank is base + local index.  This replaces the reference's
    resolver-wait prologue (preprocess.py:215-262) — topology is fully
    determined before boot.

    ``monitoring=True`` (default) passes the exporter's enabling env pair
    into the container so every deployed job exports runtime metrics with
    zero user code — the reference registered its exporter into the
    runtime and had the job spec set the env gate
    (stackdriver_exporter.cc:31-36,128).  The project id is read from the
    VM metadata server at boot (the node's own project is where its time
    series belong), so building this script needs no ADC locally.
    ``profiler_port`` additionally gates the on-demand profiler server
    (bootstrap reads CLOUD_TPU_PROFILER_PORT; --net=host exposes it).
    ``submit_ts`` (wall-clock unix seconds of run()'s submission) rides
    into the container as CLOUD_TPU_SUBMIT_TS so the remote trainer's
    first completed step can publish the true end-to-end
    ``run/submit_to_first_step_seconds`` gauge (monitoring.tracing).
    ``compile_cache`` (default: the submitting process's
    ``CLOUD_TPU_COMPILE_CACHE``) forwards the persistent-compile-cache
    directory into the container — a container-local path, where the
    bootstrap's safety probe decides whether to actually enable it
    (training.compile_cache); pass ``""`` to suppress forwarding.
    """
    if compile_cache is None:
        compile_cache = os.environ.get("CLOUD_TPU_COMPILE_CACHE", "")
    lines = [
        "#! /bin/bash",
        "set -ex",
        'LOCAL_ID=$(curl -sf -H "Metadata-Flavor: Google" '
        '"http://metadata.google.internal/computeMetadata/v1/instance/'
        'attributes/agent-worker-number" || echo 0)',
    ]
    if monitoring:
        lines.append(
            'PROJECT_ID=$(curl -sf -H "Metadata-Flavor: Google" '
            '"http://metadata.google.internal/computeMetadata/v1/project/'
            'project-id" || echo "")'
        )
    lines += [
        f"docker pull {image_uri}",
        "docker run --privileged --net=host \\",
        f"  -e CLOUD_TPU_COORDINATOR={coordinator_address} \\",
        f"  -e CLOUD_TPU_NUM_PROCESSES={num_processes} \\",
        f"  -e CLOUD_TPU_PROCESS_ID=$(({process_id_base} + LOCAL_ID)) \\",
    ]
    if monitoring:
        lines += [
            "  -e CLOUD_TPU_MONITORING_ENABLED=1 \\",
            "  -e CLOUD_TPU_MONITORING_PROJECT_ID=$PROJECT_ID \\",
        ]
    if profiler_port:
        lines.append(f"  -e CLOUD_TPU_PROFILER_PORT={int(profiler_port)} \\")
    if submit_ts is not None:
        lines.append(f"  -e CLOUD_TPU_SUBMIT_TS={submit_ts!r} \\")
    if compile_cache:
        import shlex

        # First arbitrary user-environment string baked into this root
        # startup script: quote it, or a space truncates the docker line
        # and shell metacharacters execute on the TPU VM.
        lines.append(
            "  -e "
            + shlex.quote(f"CLOUD_TPU_COMPILE_CACHE={compile_cache}")
            + " \\"
        )
    lines.append(f"  {image_uri}")
    return "\n".join(lines)


def build_node_request(
    image_uri: str,
    config: machine_config.MachineConfig,
    *,
    coordinator_address: str,
    num_processes: int,
    process_id_base: int,
    job_labels: Optional[Dict[str, str]] = None,
    service_account: Optional[str] = None,
    monitoring: bool = True,
    profiler_port: Optional[int] = None,
    submit_ts: Optional[float] = None,
    compile_cache: Optional[str] = None,
) -> dict:
    """The TPU v2 API Node body for one slice (golden-tested)."""
    topo = config.tpu_topology()
    node: dict = {
        "acceleratorType": topo.accelerator_type,
        "runtimeVersion": gcp.TPU_RUNTIME_VERSIONS[config.accelerator_type],
        "metadata": {
            "startup-script": startup_script(
                image_uri,
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id_base=process_id_base,
                monitoring=monitoring,
                profiler_port=profiler_port,
                submit_ts=submit_ts,
                compile_cache=compile_cache,
            )
        },
        "labels": dict(job_labels or {}),
    }
    if service_account:
        node["serviceAccount"] = {"email": service_account}
    return node


def build_job_request(
    image_uri: str,
    chief_config: machine_config.MachineConfig,
    worker_count: int,
    plan: planner.MeshPlan,
    *,
    job_id: Optional[str] = None,
    job_labels: Optional[Dict[str, str]] = None,
    service_account: Optional[str] = None,
    monitoring: bool = True,
    profiler_port: Optional[int] = None,
    submit_ts: Optional[float] = None,
    compile_cache: Optional[str] = None,
) -> dict:
    """All node bodies for a (multi-)slice job, keyed by node id.

    Slice i's hosts get ranks [i * hosts_per_slice, (i+1) * hosts_per_slice);
    the coordinator is slice 0 host 0, reachable by node DNS name.
    """
    job_id = job_id or _job_id()
    num_slices = worker_count + 1
    hosts_per_slice = plan.hosts_per_slice
    num_processes = num_slices * hosts_per_slice
    coordinator = f"{job_id}-0-w0:8476"
    nodes = {}
    for i in range(num_slices):
        nodes[f"{job_id}-{i}"] = build_node_request(
            image_uri,
            chief_config,
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id_base=i * hosts_per_slice,
            job_labels={**(job_labels or {}), "cloud_tpu_job": job_id},
            service_account=service_account,
            monitoring=monitoring,
            profiler_port=profiler_port,
            submit_ts=submit_ts,
            compile_cache=compile_cache,
        )
    return {"job_id": job_id, "nodes": nodes}


def build_serve_fleet_request(
    image_uri: str,
    replica_config: machine_config.MachineConfig,
    num_replicas: int,
    plan: planner.MeshPlan,
    *,
    job_id: Optional[str] = None,
    job_labels: Optional[Dict[str, str]] = None,
    service_account: Optional[str] = None,
    monitoring: bool = True,
    profiler_port: Optional[int] = None,
    submit_ts: Optional[float] = None,
    compile_cache: Optional[str] = None,
    roles: Optional[Sequence[str]] = None,
) -> dict:
    """Node bodies for a serve FLEET: N independent single-slice replicas.

    The topology deliberately inverts :func:`build_job_request`'s.  A
    training job is ONE jax_graft process group — every slice dials the
    same coordinator, so losing any slice stalls the whole job.  A serve
    fleet is N *separate* process groups: replica i's coordinator is its
    own host 0 (``<node>-w0``), process ids restart at 0 per replica, so
    replicas boot, fail, restart, and scale independently — exactly the
    unit ``cloud_tpu.fleet.Fleet`` routes over and its supervisor
    recreates.  Node ids are ``<job_id>-r<i>`` and every node carries
    ``cloud_tpu_role: serve-replica`` plus its ``cloud_tpu_replica``
    index, so a fronting router (or ``supervise_job``-style tooling) can
    enumerate the fleet by label.  The same request shape deploys through
    :func:`deploy_job` (each replica is just a node create).

    Since sharded serving (one replica = one multi-chip slice) the wire
    format also records the SLICE TOPOLOGY explicitly: each replica node
    is a ``workers_per_replica``-host jax_graft process group over
    ``chips_per_replica`` chips, with its own coordinator (host 0 of its
    own slice) — the ``slice_topology`` block carries worker count, chip
    count, and the per-replica coordinator map, so fleet tooling can
    size health checks and dial slices without parsing startup scripts.
    A single-chip fleet degenerates to ``workers_per_replica=1`` with
    the same schema.

    ``roles`` is the disaggregated prefill/decode assignment, one of
    ``"prefill" | "decode" | "both"`` per replica index (padded with
    ``"both"`` when shorter than the fleet; validated by
    ``fleet.disagg.validate_roles`` — a split fleet must keep at least
    one replica on each side).  The ``slice_topology`` block grows a
    ``roles`` axis (node id -> role) and each node carries its role as
    the ``cloud_tpu_serve_role`` label, so a fronting router can
    enumerate the prefill and decode pools by label alone.  ``None``
    (the default) records every replica as ``"both"`` — the colocated
    fleet, same schema.
    """
    from cloud_tpu.fleet import disagg

    if num_replicas < 1:
        raise ValueError(f"num_replicas must be >= 1, got {num_replicas}")
    if roles is not None and len(roles) > num_replicas:
        raise ValueError(
            f"roles has {len(roles)} entries for {num_replicas} replicas"
        )
    padded = list(roles or ())
    padded += ["both"] * (num_replicas - len(padded))
    padded = list(disagg.validate_roles(padded))
    job_id = job_id or _job_id()
    hosts = plan.hosts_per_slice
    nodes = {}
    coordinators = {}
    node_roles = {}
    for i in range(num_replicas):
        node_id = f"{job_id}-r{i}"
        coordinators[node_id] = f"{node_id}-w0:8476"
        node_roles[node_id] = padded[i]
        nodes[node_id] = build_node_request(
            image_uri,
            replica_config,
            coordinator_address=coordinators[node_id],
            num_processes=hosts,
            process_id_base=0,
            job_labels={
                **(job_labels or {}),
                "cloud_tpu_job": job_id,
                "cloud_tpu_role": "serve-replica",
                "cloud_tpu_replica": str(i),
                "cloud_tpu_serve_role": padded[i],
            },
            service_account=service_account,
            monitoring=monitoring,
            profiler_port=profiler_port,
            submit_ts=submit_ts,
            compile_cache=compile_cache,
        )
    return {
        "job_id": job_id,
        "nodes": nodes,
        "role": "serve-fleet",
        "slice_topology": {
            "workers_per_replica": hosts,
            "chips_per_replica": plan.chips_per_slice,
            "coordinators": coordinators,
            "roles": node_roles,
        },
    }


def deploy_job(
    image_uri: str,
    chief_config: machine_config.MachineConfig,
    worker_count: int,
    plan: planner.MeshPlan,
    *,
    project: Optional[str] = None,
    zone: Optional[str] = None,
    job_labels: Optional[Dict[str, str]] = None,
    service_account: Optional[str] = None,
    monitoring: bool = True,
    profiler_port: Optional[int] = None,
    session: Optional[api_client.GcpApiSession] = None,
    stream_logs: bool = False,
    request: Optional[dict] = None,
    wait_for_ready: bool = True,
    sleep: Callable[[float], None] = time.sleep,
    retry: Optional[retries.RetryPolicy] = None,
) -> dict:
    """Create the TPU nodes for the job; returns job info incl. console URL.

    ``request`` may carry a prebuilt ``build_job_request`` result (run()
    builds one for its report; passing it here guarantees the submitted
    nodes are exactly the reported ones).

    ``retry`` (default: a deploy-grade :class:`retries.RetryPolicy`)
    absorbs transient API failures — 429/5xx/transport, surfaced as
    typed :class:`api_client.ApiTransientError` — around every submit
    POST and status poll, on top of whatever the session itself retries;
    permanent 4xx still fails (and rolls back) on the first attempt.

    Lifecycle (the part the reference delegated to CAIP's managed
    ``cloud_tpu`` worker — SURVEY.md §7 hard parts): each create's LRO is
    polled to completion, then the node is awaited READY under the
    reference's 40 x 10 s provisioning budget (preprocess.py:238-261).  If
    any slice fails, every already-created slice is deleted before the
    error propagates — a multi-slice job never leaks stray paid-for nodes.
    ``wait_for_ready=False`` degrades to fire-and-forget submission.
    """
    if not chief_config.is_tpu():
        raise NotImplementedError(
            "deploy_job launches Cloud TPU jobs; CPU-only/chief-off-slice "
            "jobs are not yet supported. "
            + (
                machine_config.gpu_migration_hint(chief_config)
                if chief_config.is_gpu()
                else ""
            )
        )
    project = project or gcp.get_project_name()
    zone = zone or gcp.get_zone(chief_config)
    session = session or api_client.default_session()
    if request is None:
        request = build_job_request(
            image_uri, chief_config, worker_count, plan,
            job_labels=job_labels, service_account=service_account,
            monitoring=monitoring, profiler_port=profiler_port,
        )
    retry = retry if retry is not None else _deploy_retry_policy(sleep)
    parent = f"projects/{project}/locations/{zone}"
    created: List[str] = []
    try:
        operations = {}
        for node_id, body in request["nodes"].items():
            # Appended BEFORE the POST: if the request reaches the API
            # but the response is lost (ambiguous transient), the node
            # may exist server-side — rollback must try to delete it
            # (a 404 for a never-created node is best-effort-swallowed).
            created.append(node_id)
            op = _create_node(session, parent, node_id, body, retry)
            operations[node_id] = op
            logger.info(
                "creating TPU node %s (%s)", node_id, body["acceleratorType"]
            )
        if wait_for_ready:
            for node_id, op in operations.items():
                _await_operation(session, op, node_id, sleep=sleep,
                                 retry=retry)
                _await_node_ready(
                    session, parent, node_id, sleep=sleep, retry=retry
                )
    except Exception as exc:
        logger.error("provisioning failed (%s); rolling back %d node(s)",
                     exc, len(created))
        _rollback_nodes(session, parent, created)
        if isinstance(exc, (ProvisioningError, api_client.ApiError)):
            raise
        raise ProvisioningError(str(exc)) from exc
    job_id = request["job_id"]
    console_url = (
        f"https://console.cloud.google.com/compute/tpus?project={project}"
    )
    print(f"Job submitted: {job_id}")
    print(f"Your TPU nodes are visible at: {console_url}")
    if stream_logs:
        _stream_logs(job_id, project, session=session)
    return {
        "job_id": job_id,
        "nodes": list(request["nodes"]),
        "project": project,
        "zone": zone,
        "console_url": console_url,
    }


def _create_node(session, parent: str, node_id: str, body: dict,
                 retry: retries.RetryPolicy) -> dict:
    """One node-create, retried under ``retry`` and 409-tolerant AFTER a
    transient.

    Node creation is not idempotent: if an attempt's request reached the
    API before its response was lost, the retry gets 409 ALREADY_EXISTS
    — which would classify as a permanent failure and (in deploy_job)
    roll back healthy slices, or (in supervise_job) burn a restart for a
    node that exists.  A 409 is treated as created ONLY when an earlier
    attempt of THIS call failed transiently — a first-attempt 409 (a
    stale node from a caller-supplied job id) still raises and rolls
    back, because adopting a READY node running the OLD workload would
    report success for a job that never started.  The empty op
    short-circuits ``_await_operation``; the READY await then validates
    the node for real.
    """
    saw_transient: List[BaseException] = []

    def attempt() -> dict:
        try:
            return session.post(
                f"{_TPU_API}/{parent}/nodes", body=body,
                params={"nodeId": node_id},
            )
        except api_client.ApiTransientError:
            saw_transient.append(True)
            raise
        except api_client.ApiError as exc:
            if exc.status == 409 and saw_transient:
                logger.info(
                    "node %s already exists after a retried create (the "
                    "lost attempt landed); proceeding to READY await",
                    node_id,
                )
                return {}
            raise

    return retry.call(attempt, name="node_create")


def _await_operation(
    session, op: dict, node_id: str, *, sleep: Callable[[float], None],
    retry: Optional[retries.RetryPolicy] = None,
) -> dict:
    """Poll a TPU v2 long-running operation until done (bounded).

    A transient failure of one status GET retries under ``retry``
    instead of aborting provisioning (and rolling back healthy slices)
    over a blip; the poll-interval sleeps are jittered so concurrent
    awaits don't hit the API in lockstep.
    """
    name = op.get("name")
    if not name:
        # Some fakes/environments return the node body directly.
        return op
    retry = retry if retry is not None else _deploy_retry_policy(sleep)
    for _ in range(_LRO_POLL_ATTEMPTS):
        if op.get("done"):
            if "error" in op:
                raise ProvisioningError(
                    f"node {node_id} create operation failed: {op['error']}"
                )
            return op
        _poll_sleep(sleep, _LRO_POLL_INTERVAL_SECONDS)
        op = retry.call(
            lambda: session.get(f"{_TPU_API}/{name}"), name="operation_poll"
        )
    raise ProvisioningError(
        f"node {node_id} create operation {name!r} not done after "
        f"{_LRO_POLL_ATTEMPTS * _LRO_POLL_INTERVAL_SECONDS}s"
    )


def _await_node_ready(
    session, parent: str, node_id: str, *, sleep: Callable[[float], None],
    retry: Optional[retries.RetryPolicy] = None,
) -> dict:
    """Poll the node until state == READY (reference budget 40 x 10 s)."""
    node = {}
    retry = retry if retry is not None else _deploy_retry_policy(sleep)
    for attempt in range(_READY_POLL_ATTEMPTS):
        node = retry.call(
            lambda: session.get(f"{_TPU_API}/{parent}/nodes/{node_id}"),
            name="node_ready_poll",
        )
        state = node.get("state")
        if state == "READY":
            logger.info("TPU node %s READY", node_id)
            return node
        if state in ("PREEMPTED", "TERMINATED"):
            raise ProvisioningError(
                f"node {node_id} entered terminal state {state}"
            )
        if attempt + 1 < _READY_POLL_ATTEMPTS:
            _poll_sleep(sleep, _READY_POLL_INTERVAL_SECONDS)
    raise ProvisioningError(
        f"node {node_id} not READY after "
        f"{_READY_POLL_ATTEMPTS * _READY_POLL_INTERVAL_SECONDS}s "
        f"(last state: {node.get('state')!r})"
    )


def _rollback_nodes(session, parent: str, node_ids: List[str]) -> None:
    """Best-effort deletion of partially-provisioned slices."""
    for node_id in node_ids:
        try:
            session.delete(f"{_TPU_API}/{parent}/nodes/{node_id}")
            logger.info("rolled back TPU node %s", node_id)
        except Exception:  # noqa: BLE001 — rollback must visit every node
            logger.exception("rollback of node %s failed", node_id)


def supervise_job(
    job_info: dict,
    request: dict,
    *,
    session: Optional[api_client.GcpApiSession] = None,
    poll_seconds: float = 30.0,
    max_restarts: int = 3,
    should_stop: Optional[Callable[[], bool]] = None,
    sleep: Callable[[float], None] = time.sleep,
    retry: Optional[retries.RetryPolicy] = None,
) -> dict:
    """Watch a running job's nodes and recreate any that get preempted.

    The reference's recovery story was CAIP job restarts (SURVEY.md §5
    "recovery is delegated to CAIP job restarts"); this framework owns
    the node lifecycle, so it owns the restart: a node observed in
    PREEMPTED/TERMINATED after the job started is deleted (best-effort)
    and re-created from its original body in ``request`` (the
    ``build_job_request`` result that deploy_job submitted), then awaited
    READY again.  The recreated node boots the same startup script, the
    container re-enters bootstrap, and training resumes from the latest
    checkpoint (``CheckpointCallback(resume=True)`` / cloud_fit's
    ``_maybe_restore``) — compute is lost back to the last save, nothing
    more.

    ``max_restarts`` bounds TOTAL restarts across all nodes; exceeding it
    raises :class:`ProvisioningError` (the job is likely being preempted
    faster than it can checkpoint).  Runs until ``should_stop()`` returns
    True — or until every node has been deleted out from under it
    (``delete_job`` from anywhere, console teardown), which is the normal
    end-of-job signal; returns ``{"restarts": {node_id: count}}``.
    Transient API errors on the state poll retry inline under ``retry``
    (typed classification — the ``utils.retries`` policy), and even an
    exhausted retry budget only skips to the next round, never fatal —
    this loop may run for days.  Poll sleeps are jittered (±20%) so the
    supervisors of a recreated multi-node job don't poll in lockstep.
    """
    session = session or api_client.default_session()
    retry = retry if retry is not None else _deploy_retry_policy(sleep)
    parent = f"projects/{job_info['project']}/locations/{job_info['zone']}"
    restarts: Dict[str, int] = {}
    watching = list(job_info["nodes"])
    # Nodes whose last recreate FAILED don't exist in the API; a 404 for
    # them means "retry the recreate", while a 404 for a healthy node
    # means someone tore it down (job finished) — stop watching it.
    recreate_pending: set = set()

    def _recreate(node_id: str, why: str) -> None:
        total = sum(restarts.values())
        if total >= max_restarts:
            raise ProvisioningError(
                f"node {node_id} {why}; restart budget ({max_restarts}) "
                "exhausted — preemption is outpacing checkpointing"
            )
        logger.warning("node %s %s; recreating (restart %d/%d)",
                       node_id, why, total + 1, max_restarts)
        restarts[node_id] = restarts.get(node_id, 0) + 1
        recreate_pending.add(node_id)
        try:
            # nodes.delete is an LRO: creating the replacement before the
            # old node is fully gone gets 409 ALREADY_EXISTS.
            del_op = session.delete(f"{_TPU_API}/{parent}/nodes/{node_id}")
            if isinstance(del_op, dict):
                _await_operation(session, del_op, node_id, sleep=sleep)
        except (api_client.ApiError, ProvisioningError):
            logger.info("delete of %s failed (already gone?)", node_id)
        try:
            # Same ambiguity handling as deploy_job's creates: a 409
            # after a transient means the lost recreate landed — await
            # it READY instead of burning another restart on it.
            op = _create_node(
                session, parent, node_id, request["nodes"][node_id], retry
            )
            _await_operation(session, op, node_id, sleep=sleep, retry=retry)
            _await_node_ready(session, parent, node_id, sleep=sleep,
                              retry=retry)
            recreate_pending.discard(node_id)
        except Exception:  # noqa: BLE001 — the budget raise is earlier
            # The replacement died too (preempted while provisioning,
            # capacity, transient API/transport failure).  The restart is
            # spent; the next round retries until the budget runs out.
            logger.warning(
                "recreated node %s failed to reach READY; retrying",
                node_id, exc_info=True,
            )

    while not (should_stop and should_stop()):
        for node_id in list(watching):
            if should_stop and should_stop():
                break
            try:
                node = retry.call(
                    lambda: session.get(
                        f"{_TPU_API}/{parent}/nodes/{node_id}"
                    ),
                    name="supervise_poll",
                )
            except api_client.ApiError as exc:
                if exc.status == 404:
                    if node_id in recreate_pending:
                        _recreate(node_id, "missing after failed recreate")
                    else:
                        logger.info(
                            "node %s deleted externally; done watching it",
                            node_id,
                        )
                        watching.remove(node_id)
                else:
                    logger.warning("state poll of %s failed (%s); will "
                                   "retry", node_id, exc)
                continue
            except Exception as exc:  # noqa: BLE001 — days-long loop:
                # transport errors (connection reset, auth refresh
                # hiccup) are not ApiErrors but are just as transient.
                logger.warning("state poll of %s failed (%s); will retry",
                               node_id, exc)
                continue
            # The node exists: any earlier failed-recreate bookkeeping is
            # obsolete (e.g. the await timed out but creation finished),
            # and a future 404 must mean external teardown, not retry.
            recreate_pending.discard(node_id)
            state = node.get("state")
            if state in ("PREEMPTED", "TERMINATED"):
                _recreate(node_id, state)
        if not watching:
            logger.info("all nodes gone; supervision complete")
            break
        if should_stop and should_stop():
            break
        _poll_sleep(sleep, poll_seconds)
    return {"restarts": restarts}


def delete_job(job_info: dict,
               session: Optional[api_client.GcpApiSession] = None) -> None:
    """Tear the job's TPU nodes down (the lifecycle the reference delegated
    to CAIP — SURVEY.md §7 hard parts)."""
    session = session or api_client.default_session()
    parent = f"projects/{job_info['project']}/locations/{job_info['zone']}"
    for node_id in job_info["nodes"]:
        session.delete(f"{_TPU_API}/{parent}/nodes/{node_id}")
        logger.info("deleted TPU node %s", node_id)


_LOGGING_API = "https://logging.googleapis.com/v2"


def stream_logs(
    job_id: str,
    project: str,
    *,
    session: Optional[api_client.GcpApiSession] = None,
    poll_seconds: float = 10.0,
    should_stop: Optional[Callable[[], bool]] = None,
    sleep: Callable[[float], None] = time.sleep,
    out: Callable[[str], None] = print,
    retry: Optional[retries.RetryPolicy] = None,
) -> int:
    """Continuously stream the job's TPU-worker logs (Cloud Logging REST).

    Reference analogue: ``deploy.py:187-211`` shelled out to ``gcloud
    ai-platform jobs stream-logs`` (blocking follow).  Here the follow loop
    is framework-owned: poll ``entries:list`` with a timestamp cursor so
    each round prints only new entries, forever until ``should_stop`` says
    otherwise (or Ctrl-C).  A transient Logging-API failure retries under
    ``retry`` (the cursor is untouched, so nothing is skipped or
    reprinted).  Returns the number of entries printed.
    """
    session = session or api_client.default_session()
    retry = retry if retry is not None else _deploy_retry_policy(sleep)
    base_filter = (
        f'resource.type="tpu_worker" AND labels.cloud_tpu_job="{job_id}"'
    )
    cursor: Optional[str] = None
    printed = 0
    try:
        while True:
            log_filter = base_filter + (
                f' AND timestamp>"{cursor}"' if cursor else ""
            )
            resp = retry.call(
                lambda log_filter=log_filter: session.post(
                    f"{_LOGGING_API}/entries:list",
                    body={
                        "resourceNames": [f"projects/{project}"],
                        "filter": log_filter,
                        "orderBy": "timestamp asc",
                        "pageSize": 1000,
                    },
                ),
                name="log_poll",
            )
            for entry in resp.get("entries", []):
                payload = entry.get("textPayload")
                if payload is None:
                    import json

                    payload = json.dumps(entry.get("jsonPayload", {}))
                out(payload)
                printed += 1
                cursor = entry.get("timestamp", cursor)
            if should_stop is not None and should_stop():
                return printed
            _poll_sleep(sleep, poll_seconds)
    except KeyboardInterrupt:
        logger.info("log streaming interrupted")
        return printed


#: deploy_job's ``stream_logs`` kwarg shadows the function inside its body;
#: the alias keeps the call site unambiguous.
_stream_logs = stream_logs
