"""Notebook handling: detection and .ipynb -> .py conversion.

Reference analogue: ``run.py:249-263`` (_called_from_notebook IPython
probe) and ``preprocess.py:169-187`` (nbconvert + magic-stripping).
"""

from __future__ import annotations

import os
import re
import tempfile

#: Shell escapes / magics / comments — nbconvert rewrites ``!cmd`` and
#: ``%magic`` into ``get_ipython().…`` calls, so both raw and converted
#: forms are stripped (reference preprocess.py:181-187 stripped the raw
#: forms only because it converted by hand).
_MAGIC_LINE = re.compile(r"^\s*(!|%|#|get_ipython\(\))")


def called_from_notebook() -> bool:
    """True when the current process is an IPython/Colab kernel."""
    try:
        import IPython

        shell = IPython.get_ipython()
        if shell is None:
            return False
        return shell.__class__.__name__ in (
            "ZMQInteractiveShell",  # jupyter
            "Shell",  # colab
        )
    except ImportError:
        return False


def fetch_live_notebook_script(
    output_dir: str | None = None,
    *,
    timeout_sec: int = 200,
    _request=None,
) -> str:
    """Fetch the RUNNING Colab notebook over the kernel RPC and write it
    out as a runnable .py; returns the script path.

    Reference analogue: ``preprocess.py:196-212`` — a blocking
    ``get_ipynb`` request to the Colab frontend (the notebook need not
    exist on disk; Colab keeps it in the browser session), code cells
    concatenated, shell/magic/comment lines stripped.  ``_request`` is the
    test seam for the RPC (the reference's tests mocked the same call).
    """
    request = _request
    if request is None:
        try:
            from google.colab import _message
        except ImportError as exc:
            raise RuntimeError(
                "Live-notebook fetch needs the Colab runtime "
                "(google.colab is not importable)."
            ) from exc

        def request(method, request_body):
            return _message.blocking_request(
                method, request=request_body, timeout_sec=timeout_sec
            )

    response = request("get_ipynb", "")
    if response is None:
        # Same failure contract as the reference (preprocess.py:199-201).
        raise RuntimeError("Unable to get the notebook contents.")
    lines: list[str] = []
    for cell in response["ipynb"]["cells"]:
        if cell.get("cell_type") != "code":
            continue
        source = cell.get("source", [])
        if isinstance(source, str):
            source = source.splitlines()
        for raw in source:
            line = raw.rstrip("\n")
            if not _MAGIC_LINE.match(line):
                lines.append(line)
    output_dir = output_dir or tempfile.mkdtemp(prefix="cloud_tpu_colab_")
    script_path = os.path.join(output_dir, "colab_notebook.py")
    with open(script_path, "w") as f:
        f.write("\n".join(lines) + "\n")
    return script_path


def notebook_to_script(notebook_path: str, output_dir: str | None = None) -> str:
    """Convert an .ipynb to a runnable .py, stripping shell/magic/comment
    lines (reference preprocess.py:181-187), and return the script path."""
    from nbconvert import PythonExporter

    exporter = PythonExporter()
    source, _ = exporter.from_filename(notebook_path)
    lines = [ln for ln in source.splitlines() if not _MAGIC_LINE.match(ln)]
    output_dir = output_dir or tempfile.mkdtemp(prefix="cloud_tpu_nb_")
    base = os.path.splitext(os.path.basename(notebook_path))[0]
    script_path = os.path.join(output_dir, base + ".py")
    with open(script_path, "w") as f:
        f.write("\n".join(lines) + "\n")
    return script_path
