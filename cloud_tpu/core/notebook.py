"""Notebook handling: detection and .ipynb -> .py conversion.

Reference analogue: ``run.py:249-263`` (_called_from_notebook IPython
probe) and ``preprocess.py:169-187`` (nbconvert + magic-stripping).
"""

from __future__ import annotations

import os
import re
import tempfile

#: Shell escapes / magics / comments — nbconvert rewrites ``!cmd`` and
#: ``%magic`` into ``get_ipython().…`` calls, so both raw and converted
#: forms are stripped (reference preprocess.py:181-187 stripped the raw
#: forms only because it converted by hand).
_MAGIC_LINE = re.compile(r"^\s*(!|%|#|get_ipython\(\))")


def called_from_notebook() -> bool:
    """True when the current process is an IPython/Colab kernel."""
    try:
        import IPython

        shell = IPython.get_ipython()
        if shell is None:
            return False
        return shell.__class__.__name__ in (
            "ZMQInteractiveShell",  # jupyter
            "Shell",  # colab
        )
    except ImportError:
        return False


def notebook_to_script(notebook_path: str, output_dir: str | None = None) -> str:
    """Convert an .ipynb to a runnable .py, stripping shell/magic/comment
    lines (reference preprocess.py:181-187), and return the script path."""
    from nbconvert import PythonExporter

    exporter = PythonExporter()
    source, _ = exporter.from_filename(notebook_path)
    lines = [ln for ln in source.splitlines() if not _MAGIC_LINE.match(ln)]
    output_dir = output_dir or tempfile.mkdtemp(prefix="cloud_tpu_nb_")
    base = os.path.splitext(os.path.basename(notebook_path))[0]
    script_path = os.path.join(output_dir, base + ".py")
    with open(script_path, "w") as f:
        f.write("\n".join(lines) + "\n")
    return script_path
