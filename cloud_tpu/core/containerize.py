"""Containerization: TPU-ready Dockerfile synthesis + image builders.

Reference analogue: ``src/python/tensorflow_cloud/core/containerize.py``
(Dockerfile synthesis :134-228, build-context tar :124-132/:235-277,
LocalContainerBuilder :304-383, CloudContainerBuilder :386-507).

TPU-native differences:

* Base images are plain Python (TPU VMs need no CUDA base): the Dockerfile
  installs ``jax[tpu]`` from the libtpu release index instead of choosing
  ``-gpu`` tags (reference :134-158's DockerHub probing disappears).
* The ENTRYPOINT is the bootstrap runtime
  (``python -m cloud_tpu.core.bootstrap``), not a preprocessed script.
* The docker SDK dependency is replaced by the docker CLI via subprocess
  (injectable for tests), and Cloud Build is driven through the plain REST
  session from ``utils/api_client.py``.
"""

from __future__ import annotations

import abc
import io
import json
import logging
import os
import shutil
import subprocess
import tarfile
import tempfile
import time
import uuid
from dataclasses import dataclass
from typing import Callable, List, Optional

from cloud_tpu.core import gcp, machine_config
from cloud_tpu.utils import api_client

logger = logging.getLogger(__name__)

LIBTPU_INDEX = "https://storage.googleapis.com/jax-releases/libtpu_releases.html"


def default_base_image() -> str:
    """``python:<local major.minor>-slim``.

    Derived from the SUBMITTING interpreter the way the reference derived
    its base image from the local TF version (containerize.py:134-158) —
    cloud_fit ships cloudpickled closures whose bytecode only loads on the
    same Python minor, so client and container must match by construction.
    """
    import sys

    return f"python:{sys.version_info.major}.{sys.version_info.minor}-slim"


def default_jax_pin() -> Optional[str]:
    """``jax==<local jax.__version__>`` — client/container version lock.

    The reference pinned the container's TF to the local TF (its whole
    base-image selection, :134-158, existed for this); SURVEY §7 step 4
    says "pin libtpu/JAX versions".  An unpinned ``jax[tpu]`` would make
    the pod run whatever shipped that day, and serialized artifacts
    (cloud_fit closures, mesh-plan JSON, checkpoints) are exactly what
    breaks under skew.  jax's libtpu requirement is itself pinned by the
    jax wheel, so pinning jax pins libtpu transitively.

    Returns None (=> install unpinned, with a warning) when the local jax
    is a dev/source build whose version has no PyPI release to pin to —
    the reference's nightly fallback (:160-185) for the same situation.

    When jax is already imported, its ``__version__`` is the truth (an
    editable/source checkout shadowing an installed wheel must not be
    pinned to the stale dist-info).  Otherwise read the distribution
    metadata rather than importing: a cold ``import jax`` costs ~1.5-2 s,
    which would triple run()'s submit-artifacts latency (the north-star
    half BASELINE.md tracks) just to learn a version string.
    """
    import sys

    version = getattr(sys.modules.get("jax"), "__version__", None)
    if version is None:
        try:
            import importlib.metadata

            version = importlib.metadata.version("jax")
        except Exception:  # noqa: BLE001 — source trees without dist-info
            import jax

            version = jax.__version__
    if "dev" in version or "+" in version:
        logger.warning(
            "local jax %s is a dev/source build with no released wheel; "
            "container installs UNPINNED jax — set "
            "DockerConfig(jax_version=...) to pin explicitly",
            version,
        )
        return None
    return f"jax=={version}"
_CLOUD_BUILD_POLL_INTERVAL_SECONDS = 30
_CLOUD_BUILD_POLL_ATTEMPTS = 20  # reference budget: 20 x 30s (:390,432-453)


@dataclass
class DockerConfig:
    """User knobs for image naming and building (reference run.py docker_config)."""

    image: Optional[str] = None  # full target URI; default gcr.io/<proj>/...
    parent_image: Optional[str] = None  # overrides default_base_image()
    cache_from: Optional[str] = None  # warm-layer source image
    image_build_bucket: Optional[str] = None  # GCS bucket => Cloud Build
    jax_version: Optional[str] = None  # e.g. "0.9.1"; default = local jax


def make_dockerfile(
    entry_point_name: str,
    chief_config: machine_config.MachineConfig,
    *,
    requirements_name: Optional[str] = None,
    parent_image: Optional[str] = None,
    mesh_plan_json: Optional[str] = None,
    distribution_strategy: str = "auto",
    entry_point_args: Optional[List[str]] = None,
    jax_version: Optional[str] = None,
) -> str:
    """Render the Dockerfile text (golden-tested, like reference :134-228).

    ``jax_version`` overrides the container's jax pin (a bare version
    string like "0.9.1"); default pins to the submitting client's local
    jax so local and remote provably match (see :func:`default_jax_pin`).
    """
    pin = f"jax=={jax_version}" if jax_version else default_jax_pin()
    lines = [f"FROM {parent_image or default_base_image()}", "WORKDIR /app"]
    if machine_config.is_tpu_config(chief_config):
        spec = (
            pin.replace("jax==", "jax[tpu]==", 1) if pin else "jax[tpu]"
        )
        lines.append(f"RUN pip install --no-cache-dir '{spec}' -f {LIBTPU_INDEX}")
    else:
        lines.append(f"RUN pip install --no-cache-dir '{pin or 'jax'}'")
    if requirements_name:
        lines.append(f"COPY {requirements_name} /app/{requirements_name}")
        lines.append(
            f"RUN pip install --no-cache-dir -r /app/{requirements_name}"
        )
    # The build context vendors the framework tree (the reference pip-
    # installed tensorflow-cloud, :208-209; vendoring pins the image to the
    # submitting client's exact version).
    lines.append("COPY . /app")
    lines.append('ENV PYTHONPATH="/app:${PYTHONPATH}"')
    entrypoint = [
        "python",
        "-m",
        "cloud_tpu.core.bootstrap",
        f"--entry-point={entry_point_name}",
        f"--distribution-strategy={distribution_strategy}",
    ]
    if mesh_plan_json:
        entrypoint.append(f"--mesh-plan={mesh_plan_json}")
    if entry_point_args:
        entrypoint.append("--")  # bootstrap passes the rest to the script
        entrypoint.extend(entry_point_args)
    # json.dumps produces the exec-form array with correct escaping — the
    # mesh-plan JSON contains quotes that naive formatting would corrupt
    # (Docker would silently fall back to shell form).
    lines.append(f"ENTRYPOINT {json.dumps(entrypoint)}")
    return "\n".join(lines) + "\n"


def default_image_uri(project: str) -> str:
    """gcr.io/<project>/cloud_tpu_train:<uuid> (reference :279-285)."""
    return f"gcr.io/{project}/cloud_tpu_train:{uuid.uuid4().hex[:12]}"


def build_context(
    dockerfile_text: str,
    entry_point: Optional[str],
    requirements_txt: Optional[str],
    dst_dir: Optional[str] = None,
) -> str:
    """Assemble the docker build context directory.

    Contents: Dockerfile, the entry point's whole directory (multi-file
    projects work, reference tests/examples/multi_file_example), optional
    requirements, and the cloud_tpu framework tree.
    """
    if dst_dir is None:
        dst_dir = tempfile.mkdtemp(prefix="cloud_tpu_ctx_")
    os.makedirs(dst_dir, exist_ok=True)
    with open(os.path.join(dst_dir, "Dockerfile"), "w") as f:
        f.write(dockerfile_text)
    if entry_point is not None:
        src_dir = os.path.dirname(os.path.abspath(entry_point)) or "."
        for name in os.listdir(src_dir):
            src = os.path.join(src_dir, name)
            dst = os.path.join(dst_dir, name)
            if name in ("Dockerfile", "cloud_tpu") or name.startswith("."):
                continue
            if os.path.isdir(src):
                if not os.path.exists(dst):
                    shutil.copytree(src, dst)
            else:
                shutil.copy2(src, dst)
    if requirements_txt is not None:
        shutil.copy2(
            requirements_txt,
            os.path.join(dst_dir, os.path.basename(requirements_txt)),
        )
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pkg_dst = os.path.join(dst_dir, "cloud_tpu")
    if not os.path.exists(pkg_dst):
        shutil.copytree(
            pkg_root, pkg_dst,
            ignore=shutil.ignore_patterns("__pycache__", "*.pyc", "*.so"),
        )
    return dst_dir


class ContainerBuilder(abc.ABC):
    """Build + publish an image, returning its URI (reference :44-301)."""

    def __init__(self, image_uri: str, context_dir: str):
        self.image_uri = image_uri
        self.context_dir = context_dir

    @abc.abstractmethod
    def get_docker_image(self) -> str: ...


class LocalContainerBuilder(ContainerBuilder):
    """docker CLI build + push (reference drove the docker SDK, :304-383).

    ``runner`` is injectable: signature ``(argv: List[str]) -> None``; tests
    substitute a recorder.
    """

    def __init__(self, image_uri, context_dir, *,
                 cache_from: Optional[str] = None,
                 runner: Optional[Callable[[List[str]], None]] = None):
        super().__init__(image_uri, context_dir)
        self.cache_from = cache_from
        self._runner = runner or self._run_streaming

    @staticmethod
    def _run_streaming(argv: List[str]) -> None:
        logger.info("$ %s", " ".join(argv))
        proc = subprocess.Popen(
            argv, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True
        )
        assert proc.stdout is not None
        for line in proc.stdout:
            logger.info("%s", line.rstrip())
        if proc.wait() != 0:
            raise RuntimeError(f"Command failed ({proc.returncode}): {argv}")

    def get_docker_image(self) -> str:
        build = ["docker", "build", "-t", self.image_uri]
        if self.cache_from:
            build += ["--cache-from", self.cache_from]
        build.append(self.context_dir)
        self._runner(build)
        self._runner(["docker", "push", self.image_uri])
        return self.image_uri


class CloudContainerBuilder(ContainerBuilder):
    """GCS-upload + Cloud Build (reference :386-507), REST via the
    injectable session."""

    def __init__(self, image_uri, context_dir, *, project: str, bucket: str,
                 session: Optional[api_client.GcpApiSession] = None,
                 storage_client=None,
                 sleeper: Callable[[float], None] = time.sleep):
        super().__init__(image_uri, context_dir)
        self.project = project
        self.bucket = bucket
        self._session = session
        self._storage_client = storage_client
        self._sleep = sleeper

    def _tarball(self) -> bytes:
        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w:gz") as tar:
            tar.add(self.context_dir, arcname=".")
        return buf.getvalue()

    def _upload_context(self) -> str:
        object_name = f"cloud_tpu_build/{uuid.uuid4().hex}.tgz"
        client = self._storage_client
        if client is None:
            from google.cloud import storage

            client = storage.Client(project=self.project)
        blob = client.bucket(self.bucket).blob(object_name)
        blob.upload_from_string(self._tarball(), content_type="application/gzip")
        return object_name

    def build_request(self, object_name: str) -> dict:
        """The Cloud Build request body (golden-tested, reference :481-507)."""
        return {
            "source": {
                "storageSource": {
                    "bucket": self.bucket,
                    "object": object_name,
                }
            },
            "steps": [
                {
                    "name": "gcr.io/cloud-builders/docker",
                    "args": ["build", "-t", self.image_uri, "."],
                }
            ],
            "images": [self.image_uri],
        }

    def get_docker_image(self) -> str:
        session = self._session or api_client.default_session()
        object_name = self._upload_context()
        url = f"https://cloudbuild.googleapis.com/v1/projects/{self.project}/builds"
        op = session.post(url, body=self.build_request(object_name))
        build_id = op.get("metadata", {}).get("build", {}).get("id")
        if not build_id:
            raise RuntimeError(f"Cloud Build returned no build id: {op}")
        status_url = (
            f"https://cloudbuild.googleapis.com/v1/projects/{self.project}"
            f"/builds/{build_id}"
        )
        for _ in range(_CLOUD_BUILD_POLL_ATTEMPTS):
            build = session.get(status_url)
            status = build.get("status")
            if status == "SUCCESS":
                return self.image_uri
            if status in ("FAILURE", "INTERNAL_ERROR", "TIMEOUT", "CANCELLED"):
                raise RuntimeError(f"Cloud Build {build_id} failed: {status}")
            self._sleep(_CLOUD_BUILD_POLL_INTERVAL_SECONDS)
        raise TimeoutError(
            f"Cloud Build {build_id} did not finish within "
            f"{_CLOUD_BUILD_POLL_ATTEMPTS * _CLOUD_BUILD_POLL_INTERVAL_SECONDS}s"
        )
