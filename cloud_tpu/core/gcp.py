"""GCP substrate: project/region discovery, TPU placement tables, validation.

Reference analogue: ``src/python/tensorflow_cloud/core/gcp.py`` (project from
ADC :25-32, hardcoded region :73-75, accelerator-name map :78-90, machine-type
map :93-116, valid-config whitelist :123-406, job-label validator :409-481).

TPU-native differences:

* Region/zone selection is TPU-generation-aware (each generation is only
  offered in certain zones) instead of a single hardcoded ``us-central1``.
* The machine-type table maps *TPU generations* to TPU-VM machine types
  (``ct5lp-hightpu-4t`` ...); CPU-only roles keep an ``n1-*``-style table.
* Configuration validity is the slice catalog in ``machine_config.py``
  (legal topologies per generation) rather than a flat 200-row whitelist.
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Optional

from . import machine_config

AcceleratorType = machine_config.AcceleratorType


def get_project_name() -> str:
    """Project id from env, falling back to Application Default Credentials.

    Reference: gcp.py:25-32 (ADC only).  Env first keeps offline/test paths
    hermetic.
    """
    for var in ("GOOGLE_CLOUD_PROJECT", "CLOUD_TPU_PROJECT", "PROJECT_ID"):
        if os.environ.get(var):
            return os.environ[var]
    try:
        import google.auth  # deferred: not needed in offline paths

        _, project = google.auth.default()
    except Exception:
        project = None
    if not project:
        raise RuntimeError(
            "Could not determine the GCP project id. Set GOOGLE_CLOUD_PROJECT "
            "or configure application default credentials "
            "(gcloud auth application-default login)."
        )
    return project


#: Zones offering each TPU generation (first entry = default).  The TPU-aware
#: replacement for the reference's hardcoded region (gcp.py:73-75).
TPU_ZONES: Dict[AcceleratorType, List[str]] = {
    AcceleratorType.TPU_V2: ["us-central1-b", "europe-west4-a"],
    AcceleratorType.TPU_V3: ["us-central1-a", "europe-west4-a"],
    AcceleratorType.TPU_V4: ["us-central2-b"],
    AcceleratorType.TPU_V5E: ["us-west4-a", "us-east1-c", "europe-west4-b"],
    AcceleratorType.TPU_V5P: ["us-east5-a", "us-central1-a"],
    AcceleratorType.TPU_V6E: ["us-east5-b", "europe-west4-a", "asia-northeast1-b"],
}

_DEFAULT_ZONE = "us-central1-b"


def get_zone(config: Optional[machine_config.MachineConfig] = None) -> str:
    """Zone from env CLOUD_TPU_ZONE, else the default zone for the generation."""
    if os.environ.get("CLOUD_TPU_ZONE"):
        return os.environ["CLOUD_TPU_ZONE"]
    if config is not None and config.is_tpu():
        return TPU_ZONES[config.accelerator_type][0]
    return _DEFAULT_ZONE


def get_region(config: Optional[machine_config.MachineConfig] = None) -> str:
    """Region = zone minus the trailing letter. Reference: gcp.py:73-75."""
    zone = get_zone(config)
    return zone.rsplit("-", 1)[0]


#: TPU generation -> Cloud TPU VM machine-type family.  The per-host chip
#: count (the ``-Nt`` suffix) varies with the slice shape for v5e/v6e
#: (single-host slices pack 1/4/8 chips on one host), so the full machine
#: type is derived in :func:`get_machine_type` from the slice topology.
TPU_VM_MACHINE_FAMILIES: Dict[AcceleratorType, str] = {
    AcceleratorType.TPU_V4: "ct4p-hightpu",
    AcceleratorType.TPU_V5E: "ct5lp-hightpu",
    AcceleratorType.TPU_V5P: "ct5p-hightpu",
    AcceleratorType.TPU_V6E: "ct6e-standard",
}

#: TPU generation -> default TPU-VM runtime (software) version.  The
#: TPU-native analogue of the reference's ``tpuTfVersion: "2.1"`` pin
#: (deploy.py:152-153) and its supported-versions gate (gcp.py:119-120).
TPU_RUNTIME_VERSIONS: Dict[AcceleratorType, str] = {
    AcceleratorType.TPU_V2: "tpu-ubuntu2204-base",
    AcceleratorType.TPU_V3: "tpu-ubuntu2204-base",
    AcceleratorType.TPU_V4: "tpu-ubuntu2204-base",
    AcceleratorType.TPU_V5E: "v2-alpha-tpuv5-lite",
    AcceleratorType.TPU_V5P: "v2-alpha-tpuv5",
    AcceleratorType.TPU_V6E: "v2-alpha-tpuv6e",
}

#: (cpu_cores, memory_gb) -> machine type for CPU-only roles.
#: Reference parity: gcp.py:93-116.
CPU_MACHINE_TYPES: Dict[tuple, str] = {
    (4, 15): "n1-standard-4",
    (8, 30): "n1-standard-8",
    (16, 60): "n1-standard-16",
    (32, 120): "n1-standard-32",
    (64, 240): "n1-standard-64",
    (96, 360): "n1-standard-96",
    (2, 13): "n1-highmem-2",
    (4, 26): "n1-highmem-4",
    (8, 52): "n1-highmem-8",
    (16, 104): "n1-highmem-16",
    (32, 208): "n1-highmem-32",
    (64, 416): "n1-highmem-64",
    (96, 624): "n1-highmem-96",
}


def get_machine_type(config: machine_config.MachineConfig) -> str:
    """Machine type string for a role. Reference: gcp.py:93-116."""
    if config.is_tpu():
        topo = config.tpu_topology()
        if config.accelerator_type in (
            AcceleratorType.TPU_V2,
            AcceleratorType.TPU_V3,
        ):
            return "n1-standard-96"  # v2/v3 TPU-VM hosts
        family = TPU_VM_MACHINE_FAMILIES[config.accelerator_type]
        return f"{family}-{topo.chips_per_host}t"
    key = (config.cpu_cores, config.memory)
    if key not in CPU_MACHINE_TYPES:
        legal = sorted(CPU_MACHINE_TYPES)
        raise ValueError(
            f"Invalid (cpu_cores, memory) = {key}. Legal combinations: {legal}"
        )
    return CPU_MACHINE_TYPES[key]


def get_accelerator_type(config: machine_config.MachineConfig) -> str:
    """Cloud TPU API accelerator-type string (e.g. 'v5litepod-8').

    Reference: gcp.py:78-90 mapped enum -> CAIP accelerator names; here the
    slice catalog already carries the API name.
    """
    if config.accelerator_type is AcceleratorType.NO_ACCELERATOR:
        return "ACCELERATOR_TYPE_UNSPECIFIED"
    if config.is_gpu():
        raise ValueError(machine_config.gpu_migration_hint(config))
    return config.tpu_topology().accelerator_type


def validate_machine_configuration(
    cpu_cores: Optional[int],
    memory: Optional[int],
    accelerator_type: AcceleratorType,
    accelerator_count: int,
    topology: Optional[str] = None,
) -> None:
    """Raise ValueError unless the combination is launchable.

    Reference: gcp.py:35-70 checked against the flat whitelist; here TPU
    validity is the slice catalog and CPU validity is the machine-type table.
    """
    config = machine_config.MachineConfig(
        cpu_cores=cpu_cores,
        memory=memory,
        accelerator_type=accelerator_type,
        accelerator_count=accelerator_count,
        topology=topology,
    )
    if config.is_gpu():
        raise ValueError(machine_config.gpu_migration_hint(config))
    if not config.is_tpu():
        get_machine_type(config)  # raises on bad (cpu, memory)


_LABEL_KEY_RE = re.compile(r"^[a-z][a-z0-9_-]{0,62}$")
_LABEL_VALUE_RE = re.compile(r"^[a-z0-9_-]{0,63}$")
_MAX_LABELS = 64
_RESERVED_LABEL_PREFIXES = ("goog",)


def validate_job_labels(labels: Optional[Dict[str, str]]) -> None:
    """GCP resource-label rules. Reference parity: gcp.py:409-481.

    <=64 labels; keys start with a lowercase letter, <=63 chars of
    [a-z0-9_-]; values <=63 chars of [a-z0-9_-]; 'goog'-prefixed keys are
    reserved.
    """
    if not labels:
        return
    if len(labels) > _MAX_LABELS:
        raise ValueError(
            f"Too many job labels: {len(labels)} > {_MAX_LABELS} allowed."
        )
    for key, value in labels.items():
        if any(key.startswith(p) for p in _RESERVED_LABEL_PREFIXES):
            raise ValueError(
                f"Invalid job label key {key!r}: the 'goog' prefix is reserved."
            )
        if not _LABEL_KEY_RE.fullmatch(key):
            raise ValueError(
                f"Invalid job label key {key!r}: must start with a lowercase "
                "letter and contain <=63 chars of [a-z0-9_-]."
            )
        if not _LABEL_VALUE_RE.fullmatch(value):
            raise ValueError(
                f"Invalid value {value!r} for job label {key!r}: must contain "
                "<=63 chars of [a-z0-9_-]."
            )
