"""Input validation for the run() pipeline.

Reference analogue: ``src/python/tensorflow_cloud/core/validate.py``
(entry-point checks :87-114, strategy whitelist :117-124, cluster rules
:153-176, labels :179-181, notebook bucket :209-218).  TPU-native rule
changes:

* The chief **may** be (and by default is) a TPU slice — the reference
  forbade TPU chiefs because CAIP's ``cloud_tpu`` worker was a sidecar
  machine; on Cloud TPU VMs the training process runs *on* the slice.
* ``worker_count`` counts additional identical slices (multi-slice data
  parallelism over DCN), so TPU jobs are no longer capped at one worker.
* GPU configs are rejected with a migration hint instead of being the
  default path.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

from cloud_tpu.core import gcp, machine_config

VALID_DISTRIBUTION_STRATEGIES = ("auto", None)
_ENTRY_POINT_SUFFIXES = (".py", ".ipynb")


def validate(
    entry_point: Optional[str],
    requirements_txt: Optional[str],
    distribution_strategy: Optional[str],
    chief_config: machine_config.MachineConfig,
    worker_config: Optional[machine_config.MachineConfig],
    worker_count: int,
    entry_point_args: Optional[List[str]],
    stream_logs: bool,
    docker_image_build_bucket: Optional[str],
    called_from_notebook: bool,
    job_labels: Optional[Dict[str, str]] = None,
    service_account: Optional[str] = None,
) -> None:
    """Raise ValueError/NotImplementedError unless the job spec is launchable."""
    _validate_files(entry_point, requirements_txt, called_from_notebook)
    _validate_strategy(distribution_strategy)
    _validate_cluster(chief_config, worker_config, worker_count)
    gcp.validate_job_labels(job_labels)
    _validate_misc(entry_point_args, stream_logs, service_account)
    if called_from_notebook and not docker_image_build_bucket:
        # Notebook kernels have no local docker daemon worth assuming;
        # Cloud Build needs a bucket (reference validate.py:209-218).
        raise ValueError(
            "Called from a notebook: docker_image_build_bucket is required "
            "so the container can be built with Cloud Build."
        )


def _validate_files(entry_point, requirements_txt, called_from_notebook):
    if entry_point is None and not called_from_notebook:
        # Allowed: run() invoked from within the training script itself
        # (reference run.py:79-83 'script mode').
        return
    if entry_point is not None:
        if not os.path.isfile(entry_point):
            raise ValueError(f"entry_point not found: {entry_point!r}")
        if not entry_point.endswith(_ENTRY_POINT_SUFFIXES):
            raise ValueError(
                f"entry_point must be one of {_ENTRY_POINT_SUFFIXES}, got "
                f"{entry_point!r}"
            )
    if requirements_txt is not None and not os.path.isfile(requirements_txt):
        raise ValueError(f"requirements_txt not found: {requirements_txt!r}")


def _validate_strategy(distribution_strategy):
    if distribution_strategy not in VALID_DISTRIBUTION_STRATEGIES:
        raise ValueError(
            "distribution_strategy must be 'auto' (framework plans the "
            "mesh) or None (user script owns its mesh); got "
            f"{distribution_strategy!r}"
        )


def _validate_cluster(chief_config, worker_config, worker_count):
    if not isinstance(chief_config, machine_config.MachineConfig):
        raise ValueError(
            f"chief_config must be a MachineConfig, got {chief_config!r}"
        )
    if not isinstance(worker_count, int) or worker_count < 0:
        raise ValueError(f"worker_count must be an int >= 0, got {worker_count!r}")
    if chief_config.is_gpu():
        raise NotImplementedError(machine_config.gpu_migration_hint(chief_config))
    if worker_count > 0:
        if worker_config is None:
            raise ValueError("worker_count > 0 requires a worker_config")
        if not isinstance(worker_config, machine_config.MachineConfig):
            raise ValueError(
                f"worker_config must be a MachineConfig, got {worker_config!r}"
            )
        if worker_config.is_gpu():
            raise NotImplementedError(
                machine_config.gpu_migration_hint(worker_config)
            )
        if chief_config.is_tpu() and worker_config != chief_config:
            # Multi-slice jobs are homogeneous: DCN data parallelism needs
            # identical per-slice meshes.  (A CPU chief with TPU workers is
            # allowed — the single worker_config keeps slices homogeneous.)
            raise ValueError(
                "Multi-slice TPU jobs must be homogeneous: worker_config "
                f"({worker_config}) must equal chief_config ({chief_config})."
            )


def _validate_misc(entry_point_args, stream_logs, service_account):
    if entry_point_args is not None:
        if not isinstance(entry_point_args, list) or not all(
            isinstance(a, str) for a in entry_point_args
        ):
            raise ValueError(
                f"entry_point_args must be a list of str, got {entry_point_args!r}"
            )
    if not isinstance(stream_logs, bool):
        raise ValueError(f"stream_logs must be a bool, got {stream_logs!r}")
    if service_account is not None and "@" not in service_account:
        raise ValueError(
            f"service_account must be an email, got {service_account!r}"
        )
